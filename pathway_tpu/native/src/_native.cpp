// Native runtime core for pathway_tpu.
//
// Parity role: the reference implements its value model, key derivation and
// snapshot serialization in Rust (/root/reference/src/engine/value.rs:207-228
// "HashInto" key hashing, bincode snapshot encoding in
// src/persistence/input_snapshot.rs).  This is the TPU build's native
// equivalent: a CPython extension implementing
//
//   * blake2b-128 (RFC 7693) — the stable key-derivation hash,
//     bit-identical to hashlib.blake2b(digest_size=16),
//   * the value-hash serialization of engine/types.py:_ser_value,
//   * the PWT1 row codec of engine/codec.py (encode_row/decode_row),
//
// with fast inline paths for the scalar types that dominate row traffic and
// delegation to registered Python helpers for the long tail (ndarray, Json,
// datetime, pickled objects), so the wire format stays defined in exactly
// one place per type.
//
// Built with plain g++ (no pybind11 in this environment); loaded lazily by
// pathway_tpu/native/__init__.py with a pure-Python fallback.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

// ---------------------------------------------------------------------------
// blake2b (RFC 7693), single-shot, no key
// ---------------------------------------------------------------------------

static const uint64_t B2B_IV[8] = {
    0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL, 0x3c6ef372fe94f82bULL,
    0xa54ff53a5f1d36f1ULL, 0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
    0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL};

static const uint8_t B2B_SIGMA[12][16] = {
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3},
    {11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4},
    {7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8},
    {9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13},
    {2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9},
    {12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11},
    {13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10},
    {6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5},
    {10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0},
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3}};

static inline uint64_t rotr64(uint64_t x, int n) {
  return (x >> n) | (x << (64 - n));
}

static inline uint64_t load64(const uint8_t *p) {
  uint64_t v;
  std::memcpy(&v, p, 8);  // little-endian hosts only (x86/ARM)
  return v;
}

static void b2b_compress(uint64_t h[8], const uint8_t block[128], uint64_t t0,
                         uint64_t t1, bool last) {
  uint64_t v[16], m[16];
  for (int i = 0; i < 8; i++) v[i] = h[i];
  for (int i = 0; i < 8; i++) v[i + 8] = B2B_IV[i];
  v[12] ^= t0;
  v[13] ^= t1;
  if (last) v[14] = ~v[14];
  for (int i = 0; i < 16; i++) m[i] = load64(block + 8 * i);

#define G(a, b, c, d, x, y)       \
  v[a] = v[a] + v[b] + (x);       \
  v[d] = rotr64(v[d] ^ v[a], 32); \
  v[c] = v[c] + v[d];             \
  v[b] = rotr64(v[b] ^ v[c], 24); \
  v[a] = v[a] + v[b] + (y);       \
  v[d] = rotr64(v[d] ^ v[a], 16); \
  v[c] = v[c] + v[d];             \
  v[b] = rotr64(v[b] ^ v[c], 63);

  for (int r = 0; r < 12; r++) {
    const uint8_t *s = B2B_SIGMA[r];
    G(0, 4, 8, 12, m[s[0]], m[s[1]]);
    G(1, 5, 9, 13, m[s[2]], m[s[3]]);
    G(2, 6, 10, 14, m[s[4]], m[s[5]]);
    G(3, 7, 11, 15, m[s[6]], m[s[7]]);
    G(0, 5, 10, 15, m[s[8]], m[s[9]]);
    G(1, 6, 11, 12, m[s[10]], m[s[11]]);
    G(2, 7, 8, 13, m[s[12]], m[s[13]]);
    G(3, 4, 9, 14, m[s[14]], m[s[15]]);
  }
#undef G
  for (int i = 0; i < 8; i++) h[i] ^= v[i] ^ v[i + 8];
}

static void blake2b_hash(uint8_t *out, size_t outlen, const uint8_t *in,
                         size_t inlen) {
  uint64_t h[8];
  for (int i = 0; i < 8; i++) h[i] = B2B_IV[i];
  h[0] ^= 0x01010000ULL ^ (uint64_t)outlen;  // param: digest len, fanout=depth=1

  uint64_t t = 0;
  uint8_t block[128];
  while (inlen > 128) {
    t += 128;
    b2b_compress(h, in, t, 0, false);
    in += 128;
    inlen -= 128;
  }
  t += inlen;
  std::memset(block, 0, 128);
  if (inlen) std::memcpy(block, in, inlen);
  b2b_compress(h, block, t, 0, true);

  uint8_t full[64];
  for (int i = 0; i < 8; i++) std::memcpy(full + 8 * i, &h[i], 8);
  std::memcpy(out, full, outlen);
}

// ---------------------------------------------------------------------------
// registered Python classes & helpers (set once via _native.setup(...))
// ---------------------------------------------------------------------------

static PyObject *g_pointer_cls = nullptr;      // engine.types.Pointer
static PyObject *g_json_cls = nullptr;         // engine.types.Json
static PyObject *g_pyobj_cls = nullptr;        // engine.types.PyObjectWrapper
static PyObject *g_ndarray_cls = nullptr;      // numpy.ndarray
static PyObject *g_error_obj = nullptr;        // engine.types.ERROR singleton
static PyObject *g_encode_slow = nullptr;      // value -> bytes (PWT1)
static PyObject *g_decode_slow = nullptr;      // (tag, memoryview, pos) -> (value, pos)
static PyObject *g_ser_slow = nullptr;         // value -> bytes (hash ser)

// value tags shared with engine/codec.py
enum {
  T_NONE = 0, T_FALSE = 1, T_TRUE = 2, T_INT = 3, T_BIGINT = 4, T_FLOAT = 5,
  T_STR = 6, T_BYTES = 7, T_POINTER = 8, T_TUPLE = 9, T_NDARRAY = 10,
  T_JSON = 11, T_DT_NAIVE = 12, T_DT_UTC = 13, T_DURATION = 14, T_ERROR = 15,
  T_PYOBJECT = 16, T_DATE = 17,
};

struct Buf {
  std::vector<uint8_t> d;
  void u8(uint8_t b) { d.push_back(b); }
  void raw(const void *p, size_t n) {
    const uint8_t *q = (const uint8_t *)p;
    d.insert(d.end(), q, q + n);
  }
  void u64(uint64_t v) { raw(&v, 8); }
  void i64(int64_t v) { raw(&v, 8); }
  void f64(double v) { raw(&v, 8); }
};

// append a Python int as 16-byte signed little-endian; returns false+sets
// error on overflow (matching int.to_bytes(16, 'little', signed=True))
static bool append_i128(Buf &out, PyObject *v) {
  int overflow = 0;
  long long ll = PyLong_AsLongLongAndOverflow(v, &overflow);
  if (!overflow) {
    if (ll == -1 && PyErr_Occurred()) return false;
    uint8_t bytes[16];
    std::memcpy(bytes, &ll, 8);
    std::memset(bytes + 8, ll < 0 ? 0xFF : 0x00, 8);
    out.raw(bytes, 16);
    return true;
  }
  // v.to_bytes(16, 'little', signed=True); OverflowError propagates, as in
  // the Python serializer
  PyObject *meth = PyObject_GetAttrString(v, "to_bytes");
  if (!meth) return false;
  PyObject *args = Py_BuildValue("(is)", 16, "little");
  PyObject *kwargs = Py_BuildValue("{s:O}", "signed", Py_True);
  PyObject *res = PyObject_Call(meth, args, kwargs);
  Py_DECREF(meth);
  Py_DECREF(args);
  Py_DECREF(kwargs);
  if (!res) return false;  // OverflowError propagates, as in Python
  out.raw(PyBytes_AS_STRING(res), PyBytes_GET_SIZE(res));
  Py_DECREF(res);
  return true;
}

// append Pointer.value as 16-byte unsigned little-endian
static bool append_u128_attr(Buf &out, PyObject *ptr) {
  PyObject *val = PyObject_GetAttrString(ptr, "value");
  if (!val) return false;
  uint64_t lo = 0, hi = 0;
  PyObject *shifted = nullptr;
  lo = PyLong_AsUnsignedLongLongMask(val);
  PyObject *sixtyfour = PyLong_FromLong(64);
  shifted = PyNumber_Rshift(val, sixtyfour);
  Py_DECREF(sixtyfour);
  Py_DECREF(val);
  if (!shifted) return false;
  hi = PyLong_AsUnsignedLongLongMask(shifted);
  Py_DECREF(shifted);
  out.raw(&lo, 8);
  out.raw(&hi, 8);
  return true;
}

// ---------------------------------------------------------------------------
// hash serialization (mirror of engine/types.py:_ser_value)
// ---------------------------------------------------------------------------

static bool ser_value(PyObject *v, Buf &out) {
  if (v == Py_None) {
    out.u8(0x00);
    return true;
  }
  if (v == Py_True) {
    out.u8(0x01);
    out.u8(0x01);
    return true;
  }
  if (v == Py_False) {
    out.u8(0x01);
    out.u8(0x00);
    return true;
  }
  if (PyLong_Check(v)) {
    out.u8(0x02);
    return append_i128(out, v);
  }
  if (PyFloat_Check(v)) {
    out.u8(0x03);
    out.f64(PyFloat_AS_DOUBLE(v));
    return true;
  }
  if (PyUnicode_Check(v)) {
    Py_ssize_t n;
    const char *s = PyUnicode_AsUTF8AndSize(v, &n);
    if (!s) return false;
    out.u8(0x04);
    out.u64((uint64_t)n);
    out.raw(s, n);
    return true;
  }
  if (PyBytes_Check(v)) {
    out.u8(0x05);
    out.u64((uint64_t)PyBytes_GET_SIZE(v));
    out.raw(PyBytes_AS_STRING(v), PyBytes_GET_SIZE(v));
    return true;
  }
  int is_ptr = PyObject_IsInstance(v, g_pointer_cls);
  if (is_ptr < 0) return false;
  if (is_ptr) {
    out.u8(0x06);
    return append_u128_attr(out, v);
  }
  if (PyTuple_Check(v)) {
    out.u8(0x07);
    Py_ssize_t n = PyTuple_GET_SIZE(v);
    out.u64((uint64_t)n);
    for (Py_ssize_t i = 0; i < n; i++) {
      if (!ser_value(PyTuple_GET_ITEM(v, i), out)) return false;
    }
    return true;
  }
  // long tail (ndarray, Json, PyObjectWrapper, repr fallback): Python helper
  PyObject *b = PyObject_CallFunctionObjArgs(g_ser_slow, v, nullptr);
  if (!b) return false;
  out.raw(PyBytes_AS_STRING(b), PyBytes_GET_SIZE(b));
  Py_DECREF(b);
  return true;
}

// hash_values(iterable) -> 128-bit int
static PyObject *py_hash_values(PyObject *, PyObject *arg) {
  PyObject *seq = PySequence_Fast(arg, "hash_values expects a sequence");
  if (!seq) return nullptr;
  Buf out;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  for (Py_ssize_t i = 0; i < n; i++) {
    if (!ser_value(PySequence_Fast_GET_ITEM(seq, i), out)) {
      Py_DECREF(seq);
      return nullptr;
    }
  }
  Py_DECREF(seq);
  uint8_t digest[16];
  blake2b_hash(digest, 16, out.d.data(), out.d.size());
  // int.from_bytes(digest, 'little')
  uint64_t lo, hi;
  std::memcpy(&lo, digest, 8);
  std::memcpy(&hi, digest + 8, 8);
  PyObject *plo = PyLong_FromUnsignedLongLong(lo);
  PyObject *phi = PyLong_FromUnsignedLongLong(hi);
  PyObject *sixtyfour = PyLong_FromLong(64);
  PyObject *shifted = PyNumber_Lshift(phi, sixtyfour);
  PyObject *res = PyNumber_Or(shifted, plo);
  Py_DECREF(plo);
  Py_DECREF(phi);
  Py_DECREF(sixtyfour);
  Py_DECREF(shifted);
  return res;
}

// sequential_keys(salt: bytes, start16: bytes, count: int) -> list[int]
// Bulk form of engine/types.py sequential_key: key_i =
// blake2b16(salt + le16(start + i)).  start16 is the 16-byte little-endian
// two's-complement of the starting sequence number; the counter increments
// at byte level so arbitrary (worker-salted, > 2^64) starts stay exact.
static PyObject *py_sequential_keys(PyObject *, PyObject *args) {
  const char *salt;
  Py_ssize_t salt_len;
  const char *start16;
  Py_ssize_t start_len;
  Py_ssize_t count;
  if (!PyArg_ParseTuple(args, "y#y#n", &salt, &salt_len, &start16, &start_len,
                        &count))
    return nullptr;
  if (start_len != 16) {
    PyErr_SetString(PyExc_ValueError, "start must be 16 bytes");
    return nullptr;
  }
  PyObject *out = PyList_New(count);
  if (!out) return nullptr;
  std::vector<uint8_t> buf(static_cast<size_t>(salt_len) + 16);
  std::memcpy(buf.data(), salt, salt_len);
  uint8_t ctr[16];
  std::memcpy(ctr, start16, 16);
  PyObject *sixtyfour = PyLong_FromLong(64);
  for (Py_ssize_t i = 0; i < count; i++) {
    std::memcpy(buf.data() + salt_len, ctr, 16);
    uint8_t digest[16];
    blake2b_hash(digest, 16, buf.data(), buf.size());
    uint64_t lo, hi;
    std::memcpy(&lo, digest, 8);
    std::memcpy(&hi, digest + 8, 8);
    PyObject *key;
    if (hi == 0) {
      key = PyLong_FromUnsignedLongLong(lo);
    } else {
      PyObject *plo = PyLong_FromUnsignedLongLong(lo);
      PyObject *phi = PyLong_FromUnsignedLongLong(hi);
      PyObject *shifted = phi ? PyNumber_Lshift(phi, sixtyfour) : nullptr;
      key = (plo && shifted) ? PyNumber_Or(shifted, plo) : nullptr;
      Py_XDECREF(plo);
      Py_XDECREF(phi);
      Py_XDECREF(shifted);
    }
    if (!key) {
      Py_DECREF(out);
      Py_DECREF(sixtyfour);
      return nullptr;
    }
    PyList_SET_ITEM(out, i, key);
    // little-endian increment with carry
    for (int b = 0; b < 16; b++) {
      if (++ctr[b] != 0) break;
    }
  }
  Py_DECREF(sixtyfour);
  return out;
}

// blake2b_128(data: bytes) -> bytes   (for tests / reuse)
static PyObject *py_blake2b_128(PyObject *, PyObject *arg) {
  Py_buffer view;
  if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0) return nullptr;
  uint8_t digest[16];
  blake2b_hash(digest, 16, (const uint8_t *)view.buf, view.len);
  PyBuffer_Release(&view);
  return PyBytes_FromStringAndSize((const char *)digest, 16);
}

// ---------------------------------------------------------------------------
// PWT1 codec (mirror of engine/codec.py)
// ---------------------------------------------------------------------------

static bool encode_value(PyObject *v, Buf &out) {
  if (v == Py_None) {
    out.u8(T_NONE);
    return true;
  }
  if (v == Py_True) {
    out.u8(T_TRUE);
    return true;
  }
  if (v == Py_False) {
    out.u8(T_FALSE);
    return true;
  }
  if (PyLong_Check(v)) {
    int overflow = 0;
    long long ll = PyLong_AsLongLongAndOverflow(v, &overflow);
    if (!overflow) {
      if (ll == -1 && PyErr_Occurred()) return false;
      out.u8(T_INT);
      out.i64(ll);
      return true;
    }
    // big int: length-prefixed signed little-endian, like codec.py
    PyObject *nbits_obj = PyObject_CallMethod(v, "bit_length", nullptr);
    if (!nbits_obj) return false;
    size_t nbits = (size_t)PyLong_AsSize_t(nbits_obj);
    Py_DECREF(nbits_obj);
    if (nbits == (size_t)-1 && PyErr_Occurred()) return false;
    size_t nbytes = (nbits + 8) / 8 + 1;  // (bit_length + 8) // 8 + 1
    PyObject *meth = PyObject_GetAttrString(v, "to_bytes");
    if (!meth) return false;
    PyObject *args = Py_BuildValue("(ns)", (Py_ssize_t)nbytes, "little");
    PyObject *kwargs = Py_BuildValue("{s:O}", "signed", Py_True);
    PyObject *res = PyObject_Call(meth, args, kwargs);
    Py_DECREF(meth);
    Py_DECREF(args);
    Py_DECREF(kwargs);
    if (!res) return false;
    out.u8(T_BIGINT);
    out.u64((uint64_t)PyBytes_GET_SIZE(res));
    out.raw(PyBytes_AS_STRING(res), PyBytes_GET_SIZE(res));
    Py_DECREF(res);
    return true;
  }
  if (PyFloat_Check(v)) {
    out.u8(T_FLOAT);
    out.f64(PyFloat_AS_DOUBLE(v));
    return true;
  }
  if (PyUnicode_Check(v)) {
    Py_ssize_t n;
    const char *s = PyUnicode_AsUTF8AndSize(v, &n);
    if (!s) return false;
    out.u8(T_STR);
    out.u64((uint64_t)n);
    out.raw(s, n);
    return true;
  }
  if (PyBytes_Check(v)) {
    out.u8(T_BYTES);
    out.u64((uint64_t)PyBytes_GET_SIZE(v));
    out.raw(PyBytes_AS_STRING(v), PyBytes_GET_SIZE(v));
    return true;
  }
  int is_ptr = PyObject_IsInstance(v, g_pointer_cls);
  if (is_ptr < 0) return false;
  if (is_ptr) {
    out.u8(T_POINTER);
    return append_u128_attr(out, v);
  }
  if (PyTuple_Check(v)) {
    out.u8(T_TUPLE);
    Py_ssize_t n = PyTuple_GET_SIZE(v);
    out.u64((uint64_t)n);
    for (Py_ssize_t i = 0; i < n; i++) {
      if (!encode_value(PyTuple_GET_ITEM(v, i), out)) return false;
    }
    return true;
  }
  if (v == g_error_obj) {
    out.u8(T_ERROR);
    return true;
  }
  // long tail: delegate to Python (ndarray/Json/datetime/pickle)
  PyObject *b = PyObject_CallFunctionObjArgs(g_encode_slow, v, nullptr);
  if (!b) return false;
  out.raw(PyBytes_AS_STRING(b), PyBytes_GET_SIZE(b));
  Py_DECREF(b);
  return true;
}

// ---------------------------------------------------------------------------
// CRC-32C (Castagnoli) — hardware SSE4.2 when -march=native provides it,
// slicing-free byte table otherwise.  Releases the GIL: the checkpoint
// writer pool frames chunks concurrently with the epoch loop, and a
// GIL-holding checksum would serialize them right back.
// ---------------------------------------------------------------------------

#if defined(__SSE4_2__)
#include <nmmintrin.h>
#endif

static uint32_t crc32c_table_[256];
static bool crc32c_table_ready_ = false;

static void crc32c_build_table() {
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t crc = i;
    for (int j = 0; j < 8; j++)
      crc = (crc & 1) ? (crc >> 1) ^ 0x82F63B78u : crc >> 1;
    crc32c_table_[i] = crc;
  }
  crc32c_table_ready_ = true;
}

static uint32_t crc32c_update(uint32_t state, const uint8_t *p, size_t n) {
#if defined(__SSE4_2__)
  while (n >= 8) {
    uint64_t v;
    std::memcpy(&v, p, 8);
    state = (uint32_t)_mm_crc32_u64((uint64_t)state, v);
    p += 8;
    n -= 8;
  }
  while (n--) state = _mm_crc32_u8(state, *p++);
  return state;
#else
  while (n--) state = crc32c_table_[(state ^ *p++) & 0xFF] ^ (state >> 8);
  return state;
#endif
}

// crc32c(bytes_like, crc=0) -> int  (chainable, like codec.crc32c)
static PyObject *py_crc32c(PyObject *, PyObject *args) {
  Py_buffer view;
  unsigned long crc = 0;
  if (!PyArg_ParseTuple(args, "y*|k", &view, &crc)) return nullptr;
  // build the table fallback WHILE STILL HOLDING the GIL: crc32c_update
  // runs GIL-released, and a lazy build there would be a C++ data race
  // between writer-pool threads
  if (!crc32c_table_ready_) crc32c_build_table();
  uint32_t state = ~(uint32_t)crc;
  const uint8_t *p = (const uint8_t *)view.buf;
  size_t n = (size_t)view.len;
  Py_BEGIN_ALLOW_THREADS;
  state = crc32c_update(state, p, n);
  Py_END_ALLOW_THREADS;
  PyBuffer_Release(&view);
  return PyLong_FromUnsignedLong(~state & 0xFFFFFFFFu);
}

// encode_row(tuple_or_seq) -> bytes
static PyObject *py_encode_row(PyObject *, PyObject *arg) {
  PyObject *seq = PySequence_Fast(arg, "encode_row expects a sequence");
  if (!seq) return nullptr;
  Buf out;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  out.u64((uint64_t)n);
  for (Py_ssize_t i = 0; i < n; i++) {
    if (!encode_value(PySequence_Fast_GET_ITEM(seq, i), out)) {
      Py_DECREF(seq);
      return nullptr;
    }
  }
  Py_DECREF(seq);
  return PyBytes_FromStringAndSize((const char *)out.d.data(), out.d.size());
}

// append a Python int's low 128 bits as unsigned little-endian (the
// `key & ((1 << 128) - 1)` masking of codec.encode_event)
static bool append_u128_long(Buf &out, PyObject *val) {
  uint64_t lo = PyLong_AsUnsignedLongLongMask(val);
  if (lo == (uint64_t)-1 && PyErr_Occurred()) return false;
  PyObject *sixtyfour = PyLong_FromLong(64);
  PyObject *shifted = PyNumber_Rshift(val, sixtyfour);
  Py_DECREF(sixtyfour);
  if (!shifted) return false;
  uint64_t hi = PyLong_AsUnsignedLongLongMask(shifted);
  Py_DECREF(shifted);
  if (hi == (uint64_t)-1 && PyErr_Occurred()) return false;
  out.raw(&lo, 8);
  out.raw(&hi, 8);
  return true;
}

// encode_events(seq of (kind, key, row, time)) -> bytes
// Batched codec.encode_event: one buffer per snapshot chunk, the row
// payload length patched in place — no per-event allocations.  The
// checkpoint writer pool encodes whole raw-event batches here so the
// epoch loop never pays the per-row serializer (input_snapshot.rs
// serialization analog).
static PyObject *py_encode_events(PyObject *, PyObject *arg) {
  PyObject *seq = PySequence_Fast(arg, "encode_events expects a sequence");
  if (!seq) return nullptr;
  Buf out;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *evseq = PySequence_Fast(
        PySequence_Fast_GET_ITEM(seq, i),
        "encode_events: event must be a sequence");
    if (!evseq) {
      Py_DECREF(seq);
      return nullptr;
    }
    bool ok = PySequence_Fast_GET_SIZE(evseq) == 4;
    if (!ok) {
      PyErr_SetString(PyExc_ValueError,
                      "encode_events: expected (kind, key, row, time)");
    } else {
      long kind = PyLong_AsLong(PySequence_Fast_GET_ITEM(evseq, 0));
      ok = !(kind == -1 && PyErr_Occurred());
      if (ok) {
        out.u8((uint8_t)kind);
        if (kind == 1 || kind == 2) {  // EV_INSERT / EV_DELETE
          ok = append_u128_long(out, PySequence_Fast_GET_ITEM(evseq, 1));
          if (ok) {
            size_t len_at = out.d.size();
            out.u64(0);  // payload length, patched below
            size_t start = out.d.size();
            PyObject *rowseq = PySequence_Fast(
                PySequence_Fast_GET_ITEM(evseq, 2),
                "encode_events: row must be a sequence");
            ok = rowseq != nullptr;
            if (ok) {
              Py_ssize_t rn = PySequence_Fast_GET_SIZE(rowseq);
              out.u64((uint64_t)rn);
              for (Py_ssize_t j = 0; ok && j < rn; j++) {
                ok = encode_value(PySequence_Fast_GET_ITEM(rowseq, j), out);
              }
              Py_DECREF(rowseq);
            }
            if (ok) {
              uint64_t plen = (uint64_t)(out.d.size() - start);
              std::memcpy(out.d.data() + len_at, &plen, 8);
            }
          }
        } else if (kind == 3) {  // EV_ADVANCE_TIME
          uint64_t t = PyLong_AsUnsignedLongLongMask(
              PySequence_Fast_GET_ITEM(evseq, 3));
          ok = !(t == (uint64_t)-1 && PyErr_Occurred());
          if (ok) out.u64(t);
        }  // EV_FINISHED and others: kind byte only, like encode_event
      }
    }
    Py_DECREF(evseq);
    if (!ok) {
      Py_DECREF(seq);
      return nullptr;
    }
  }
  Py_DECREF(seq);
  return PyBytes_FromStringAndSize((const char *)out.d.data(), out.d.size());
}

struct Cursor {
  const uint8_t *p;
  size_t len;
  size_t pos;
  bool need(size_t n) {
    // subtraction form: `pos + n` can wrap for corrupted length fields
    if (pos > len || n > len - pos) {
      PyErr_SetString(PyExc_ValueError, "codec: truncated buffer");
      return false;
    }
    return true;
  }
  bool r_u64(uint64_t *v) {
    if (!need(8)) return false;
    std::memcpy(v, p + pos, 8);
    pos += 8;
    return true;
  }
};

static PyObject *decode_value(Cursor &c, PyObject *view);

static PyObject *decode_slow(Cursor &c, PyObject *view, uint8_t tag) {
  // delegate to Python: (tag, view, pos_before_tag_payload) -> (value, new_pos)
  PyObject *res = PyObject_CallFunction(g_decode_slow, "iOn", (int)tag, view,
                                        (Py_ssize_t)c.pos);
  if (!res) return nullptr;
  PyObject *value = PyTuple_GetItem(res, 0);
  PyObject *newpos = PyTuple_GetItem(res, 1);
  if (!value || !newpos) {
    Py_DECREF(res);
    return nullptr;
  }
  c.pos = (size_t)PyLong_AsSsize_t(newpos);
  Py_INCREF(value);
  Py_DECREF(res);
  return value;
}

static PyObject *decode_value(Cursor &c, PyObject *view) {
  if (!c.need(1)) return nullptr;
  uint8_t tag = c.p[c.pos++];
  switch (tag) {
    case T_NONE:
      Py_RETURN_NONE;
    case T_TRUE:
      Py_RETURN_TRUE;
    case T_FALSE:
      Py_RETURN_FALSE;
    case T_INT: {
      if (!c.need(8)) return nullptr;
      int64_t v;
      std::memcpy(&v, c.p + c.pos, 8);
      c.pos += 8;
      return PyLong_FromLongLong(v);
    }
    case T_FLOAT: {
      if (!c.need(8)) return nullptr;
      double v;
      std::memcpy(&v, c.p + c.pos, 8);
      c.pos += 8;
      return PyFloat_FromDouble(v);
    }
    case T_STR: {
      uint64_t n;
      if (!c.r_u64(&n) || !c.need(n)) return nullptr;
      PyObject *s = PyUnicode_DecodeUTF8((const char *)c.p + c.pos, n, nullptr);
      c.pos += n;
      return s;
    }
    case T_BYTES: {
      uint64_t n;
      if (!c.r_u64(&n) || !c.need(n)) return nullptr;
      PyObject *b = PyBytes_FromStringAndSize((const char *)c.p + c.pos, n);
      c.pos += n;
      return b;
    }
    case T_POINTER: {
      if (!c.need(16)) return nullptr;
      uint64_t lo, hi;
      std::memcpy(&lo, c.p + c.pos, 8);
      std::memcpy(&hi, c.p + c.pos + 8, 8);
      c.pos += 16;
      PyObject *plo = PyLong_FromUnsignedLongLong(lo);
      PyObject *phi = PyLong_FromUnsignedLongLong(hi);
      PyObject *sf = PyLong_FromLong(64);
      PyObject *shifted = PyNumber_Lshift(phi, sf);
      PyObject *key = PyNumber_Or(shifted, plo);
      Py_DECREF(plo);
      Py_DECREF(phi);
      Py_DECREF(sf);
      Py_DECREF(shifted);
      if (!key) return nullptr;
      PyObject *ptr = PyObject_CallFunctionObjArgs(g_pointer_cls, key, nullptr);
      Py_DECREF(key);
      return ptr;
    }
    case T_TUPLE: {
      uint64_t n;
      if (!c.r_u64(&n)) return nullptr;
      // every element takes >=1 byte, so a length beyond the remaining
      // buffer is corruption — reject before PyTuple_New sees a bogus
      // (possibly negative-after-cast) size
      if (n > c.len - c.pos) {
        PyErr_SetString(PyExc_ValueError, "codec: corrupt buffer (tuple length)");
        return nullptr;
      }
      PyObject *t = PyTuple_New((Py_ssize_t)n);
      if (!t) return nullptr;
      for (uint64_t i = 0; i < n; i++) {
        PyObject *item = decode_value(c, view);
        if (!item) {
          Py_DECREF(t);
          return nullptr;
        }
        PyTuple_SET_ITEM(t, (Py_ssize_t)i, item);
      }
      return t;
    }
    case T_ERROR:
      Py_INCREF(g_error_obj);
      return g_error_obj;
    default:
      // BIGINT, NDARRAY, JSON, datetimes, DATE, DURATION, PYOBJECT
      return decode_slow(c, view, tag);
  }
}

// decode_row(buffer, pos=0) -> (tuple, new_pos)
static PyObject *py_decode_row(PyObject *, PyObject *args) {
  PyObject *obj;
  Py_ssize_t pos = 0;
  if (!PyArg_ParseTuple(args, "O|n", &obj, &pos)) return nullptr;
  Py_buffer view;
  if (PyObject_GetBuffer(obj, &view, PyBUF_SIMPLE) < 0) return nullptr;
  PyObject *mview = PyMemoryView_FromBuffer(&view);  // for slow-path calls
  if (!mview) {
    PyBuffer_Release(&view);
    return nullptr;
  }
  Cursor c{(const uint8_t *)view.buf, (size_t)view.len, (size_t)pos};
  uint64_t n = 0;
  PyObject *result = nullptr;
  // each row value takes >=1 byte, so a count beyond the remaining buffer
  // is corruption — reject before PyTuple_New sees a bogus size
  if (c.r_u64(&n) && n <= c.len - c.pos) {
    PyObject *t = PyTuple_New((Py_ssize_t)n);
    if (t) {
      bool ok = true;
      for (uint64_t i = 0; i < n; i++) {
        PyObject *item = decode_value(c, mview);
        if (!item) {
          ok = false;
          break;
        }
        PyTuple_SET_ITEM(t, (Py_ssize_t)i, item);
      }
      if (ok) {
        result = Py_BuildValue("(Nn)", t, (Py_ssize_t)c.pos);
      } else {
        Py_DECREF(t);
      }
    }
  }
  if (!result && !PyErr_Occurred()) {
    PyErr_SetString(PyExc_ValueError, "codec: corrupt buffer (row length)");
  }
  Py_DECREF(mview);
  PyBuffer_Release(&view);
  return result;
}

// ---------------------------------------------------------------------------
// consolidate_dirty: the accumulation half of the engine's per-node delta
// normalization (dataflow.py consolidate).  PRECONDITION: the caller has
// already proven the batch dirty with its (faster, CPython-set) clean scan —
// clean batches must never reach here.  Returns a NEW list of
// (key, row, summed_diff != 0), retractions before insertions in stable
// first-seen order — exactly the Python Counter path's semantics — or
// Py_None when a diff exceeds int64 range (the caller falls back to the
// arbitrary-precision Python path).
// ---------------------------------------------------------------------------

static PyObject *py_consolidate_dirty(PyObject *, PyObject *arg) {
  // private copy: __hash__/__eq__ of engine values run arbitrary Python
  // code that could otherwise mutate the caller's list under our borrowed
  // pointers (the copy holds its own refs to every delta tuple)
  PyObject *seq = PySequence_List(arg);
  if (!seq) return nullptr;
  Py_ssize_t n = PyList_GET_SIZE(seq);

  struct Entry {
    PyObject *key;
    PyObject *row;
    long long acc;
  };
  std::vector<Entry> entries;
  entries.reserve(static_cast<size_t>(n));
  std::unordered_map<Py_hash_t, std::vector<size_t>> index;
  index.reserve(static_cast<size_t>(n) * 2 + 8);
  // keeps every PySequence_Fast result alive until the end: for non-tuple
  // deltas the fast object OWNS the key/row items the entries point at
  std::vector<PyObject *> fast_holds;
  fast_holds.reserve(static_cast<size_t>(n));
  auto cleanup = [&]() {
    for (Entry &e : entries) {
      Py_DECREF(e.key);
      Py_DECREF(e.row);
    }
    for (PyObject *f : fast_holds) Py_DECREF(f);
    Py_DECREF(seq);
  };
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *d = PyList_GET_ITEM(seq, i);
    // same contract as the Python `key, row, diff = d` unpack: any
    // 3-element sequence; wrong length -> ValueError
    PyObject *fast = PySequence_Fast(d, "delta must be (key, row, diff)");
    if (!fast) {
      cleanup();
      return nullptr;
    }
    fast_holds.push_back(fast);
    if (PySequence_Fast_GET_SIZE(fast) != 3) {
      cleanup();
      PyErr_SetString(PyExc_ValueError,
                      "delta must have exactly 3 fields (key, row, diff)");
      return nullptr;
    }
    PyObject *key = PySequence_Fast_GET_ITEM(fast, 0);
    PyObject *row = PySequence_Fast_GET_ITEM(fast, 1);
    // own references BEFORE any __hash__/__eq__ runs: even this delta's
    // own key hash may mutate a list-shaped delta and free the borrowed
    // row pointer (reviewer-reproduced segfault)
    Py_INCREF(key);
    Py_INCREF(row);
    auto drop_kr = [&]() {
      Py_DECREF(key);
      Py_DECREF(row);
    };
    long long dv = PyLong_AsLongLong(PySequence_Fast_GET_ITEM(fast, 2));
    if (dv == -1 && PyErr_Occurred()) {
      drop_kr();
      if (PyErr_ExceptionMatches(PyExc_OverflowError)) {
        // beyond int64: let the arbitrary-precision Python path handle it
        PyErr_Clear();
        cleanup();
        Py_RETURN_NONE;
      }
      cleanup();
      return nullptr;
    }
    Py_hash_t hk = PyObject_Hash(key);
    if (hk == -1) {
      drop_kr();
      cleanup();
      return nullptr;
    }
    Py_hash_t hr = PyObject_Hash(row);
    if (hr == -1) {
      drop_kr();
      cleanup();
      return nullptr;
    }
    Py_hash_t combined =
        static_cast<Py_hash_t>(static_cast<uint64_t>(hk) * 1000003ull ^
                               static_cast<uint64_t>(hr));
    auto &bucket = index[combined];
    bool merged = false;
    for (size_t idx : bucket) {
      Entry &e = entries[idx];
      int eqk = PyObject_RichCompareBool(e.key, key, Py_EQ);
      if (eqk < 0) {
        drop_kr();
        cleanup();
        return nullptr;
      }
      if (!eqk) continue;
      int eqr = PyObject_RichCompareBool(e.row, row, Py_EQ);
      if (eqr < 0) {
        drop_kr();
        cleanup();
        return nullptr;
      }
      if (eqr) {
        long long sum;
        if (__builtin_add_overflow(e.acc, dv, &sum)) {
          drop_kr();
          cleanup();
          Py_RETURN_NONE;  // int64 overflow: Python fallback
        }
        e.acc = sum;
        merged = true;
        break;
      }
    }
    if (merged) {
      drop_kr();
    } else {
      bucket.push_back(entries.size());
      entries.push_back(Entry{key, row, dv});  // refs owned above
    }
  }
  PyObject *out = PyList_New(0);
  if (!out) {
    cleanup();
    return nullptr;
  }
  for (int pass = 0; pass < 2; pass++) {
    for (const Entry &e : entries) {
      if (e.acc == 0) continue;
      // pass 0 emits retractions (acc < 0), pass 1 the insertions
      if ((e.acc > 0) == (pass == 0)) continue;
      PyObject *diff = PyLong_FromLongLong(e.acc);
      if (!diff) {
        Py_DECREF(out);
        cleanup();
        return nullptr;
      }
      PyObject *t = PyTuple_Pack(3, e.key, e.row, diff);
      Py_DECREF(diff);
      if (!t || PyList_Append(out, t) < 0) {
        Py_XDECREF(t);
        Py_DECREF(out);
        cleanup();
        return nullptr;
      }
      Py_DECREF(t);
    }
  }
  cleanup();
  return out;
}

// ---------------------------------------------------------------------------
// setup & module def
// ---------------------------------------------------------------------------

// ---------------------------------------------------------------------------
// upsert_chain: the per-row half of InputNode.emit_time's upsert session
// (dataflow.py).  For each (key, row, diff): retract the key's previous
// value — this epoch's staged overlay first, then committed state — and
// (diff > 0) insert the new row.  Keys are engine 128-bit ints (PyLong),
// so the dict lookups cannot re-enter Python.  Returns the new delta list
// (retraction-before-insert per key, in arrival order).
// ---------------------------------------------------------------------------

static PyObject *py_upsert_chain(PyObject *, PyObject *args) {
  PyObject *deltas, *state;
  if (!PyArg_ParseTuple(args, "OO", &deltas, &state)) return nullptr;
  if (!PyDict_Check(state)) {
    PyErr_SetString(PyExc_TypeError, "state must be a dict");
    return nullptr;
  }
  PyObject *seq = PySequence_List(deltas);
  if (!seq) return nullptr;
  PyObject *seen = PyDict_New();
  PyObject *out = PyList_New(0);
  PyObject *one = PyLong_FromLong(1);
  PyObject *neg_one = PyLong_FromLong(-1);
  auto fail = [&]() -> PyObject * {
    Py_XDECREF(seen);
    Py_XDECREF(out);
    Py_XDECREF(one);
    Py_XDECREF(neg_one);
    Py_DECREF(seq);
    return nullptr;
  };
  if (!seen || !out || !one || !neg_one) return fail();
  Py_ssize_t n = PyList_GET_SIZE(seq);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *fast = PySequence_Fast(PyList_GET_ITEM(seq, i),
                                     "delta must be (key, row, diff)");
    if (!fast) return fail();
    if (PySequence_Fast_GET_SIZE(fast) != 3) {
      Py_DECREF(fast);
      PyErr_SetString(PyExc_ValueError,
                      "delta must have exactly 3 fields (key, row, diff)");
      return fail();
    }
    PyObject *key = PySequence_Fast_GET_ITEM(fast, 0);
    PyObject *row = PySequence_Fast_GET_ITEM(fast, 1);
    long long dv = PyLong_AsLongLong(PySequence_Fast_GET_ITEM(fast, 2));
    if (dv == -1 && PyErr_Occurred()) {
      Py_DECREF(fast);
      return fail();
    }
    PyObject *prev = PyDict_GetItemWithError(seen, key);  // borrowed
    if (!prev) {
      if (PyErr_Occurred()) {
        Py_DECREF(fast);
        return fail();
      }
      prev = PyDict_GetItemWithError(state, key);  // borrowed
      if (!prev && PyErr_Occurred()) {
        Py_DECREF(fast);
        return fail();
      }
    }
    if (prev && prev != Py_None) {
      PyObject *t = PyTuple_Pack(3, key, prev, neg_one);
      int rc = t ? PyList_Append(out, t) : -1;
      Py_XDECREF(t);
      if (rc < 0) {
        Py_DECREF(fast);
        return fail();
      }
    }
    if (dv > 0) {
      PyObject *t = PyTuple_Pack(3, key, row, one);
      int rc = t ? PyList_Append(out, t) : -1;
      Py_XDECREF(t);
      if (rc < 0 || PyDict_SetItem(seen, key, row) < 0) {
        Py_DECREF(fast);
        return fail();
      }
    } else if (PyDict_SetItem(seen, key, Py_None) < 0) {
      Py_DECREF(fast);
      return fail();
    }
    Py_DECREF(fast);
  }
  Py_DECREF(seen);
  Py_DECREF(one);
  Py_DECREF(neg_one);
  Py_DECREF(seq);
  return out;
}


static PyObject *py_setup(PyObject *, PyObject *args) {
  PyObject *pointer_cls, *json_cls, *pyobj_cls, *ndarray_cls, *error_obj,
      *encode_slow, *decode_slow_fn, *ser_slow;
  if (!PyArg_ParseTuple(args, "OOOOOOOO", &pointer_cls, &json_cls, &pyobj_cls,
                        &ndarray_cls, &error_obj, &encode_slow, &decode_slow_fn,
                        &ser_slow))
    return nullptr;
#define SETG(g, v) \
  Py_XDECREF(g);   \
  Py_INCREF(v);    \
  g = v;
  SETG(g_pointer_cls, pointer_cls);
  SETG(g_json_cls, json_cls);
  SETG(g_pyobj_cls, pyobj_cls);
  SETG(g_ndarray_cls, ndarray_cls);
  SETG(g_error_obj, error_obj);
  SETG(g_encode_slow, encode_slow);
  SETG(g_decode_slow, decode_slow_fn);
  SETG(g_ser_slow, ser_slow);
#undef SETG
  Py_RETURN_NONE;
}

// ---------------------------------------------------------------------------
// Columnar batch materialization (the §7.3 "columnar batches instead of row
// tuples" hot path).  The Python columnar evaluator's cost at 1M rows was
// dominated by per-column list comprehensions, per-value type() scans and
// tuple rebuilds; these two functions do each in one C pass.  Semantics
// mirror vector_compiler.materialize_columns exactly: uniform EXACT Python
// types per column (bool/int/float/str), int64 range (INT64_MIN rejected —
// negation would wrap), bail -> None so the row interpreter takes over.
// ---------------------------------------------------------------------------

// materialize_delta_columns(deltas | rows, needed: tuple[int], from_deltas)
//   -> dict {idx: ("q"|"d"|"?", bytearray) | ("U", list)} | None (bail)
static PyObject *py_materialize_columns(PyObject *, PyObject *args) {
  PyObject *items, *needed;
  int from_deltas;
  if (!PyArg_ParseTuple(args, "OO!p", &items, &PyTuple_Type, &needed,
                        &from_deltas))
    return nullptr;
  if (!PyList_Check(items)) Py_RETURN_NONE;
  Py_ssize_t n = PyList_GET_SIZE(items);
  if (n == 0) Py_RETURN_NONE;
  Py_ssize_t n_cols = PyTuple_GET_SIZE(needed);

  PyObject *result = PyDict_New();
  if (!result) return nullptr;

  for (Py_ssize_t c = 0; c < n_cols; c++) {
    PyObject *idx_obj = PyTuple_GET_ITEM(needed, c);
    Py_ssize_t idx = PyLong_AsSsize_t(idx_obj);
    if (idx < 0 && PyErr_Occurred()) {
      Py_DECREF(result);
      return nullptr;
    }
    // pick the column kind from the first row
    PyObject *first = PyList_GET_ITEM(items, 0);
    if (from_deltas) {
      if (!PyTuple_Check(first) || PyTuple_GET_SIZE(first) != 3) goto bail;
      first = PyTuple_GET_ITEM(first, 1);
    }
    if (!PyTuple_Check(first) || idx >= PyTuple_GET_SIZE(first)) goto bail;
    {
      PyObject *v0 = PyTuple_GET_ITEM(first, idx);
      char kind;
      if (PyBool_Check(v0)) kind = '?';
      else if (PyLong_CheckExact(v0)) kind = 'q';
      else if (PyFloat_CheckExact(v0)) kind = 'd';
      else if (PyUnicode_CheckExact(v0)) kind = 'U';
      else goto bail;

      if (kind == 'U') {
        PyObject *lst = PyList_New(n);
        if (!lst) goto err;
        for (Py_ssize_t i = 0; i < n; i++) {
          PyObject *row = PyList_GET_ITEM(items, i);
          if (from_deltas) {
            // every element must be shape-checked, not just the first —
            // GET_ITEM on a short tuple is out-of-bounds, not an error
            if (!PyTuple_Check(row) || PyTuple_GET_SIZE(row) != 3) {
              Py_DECREF(lst);
              goto bail;
            }
            row = PyTuple_GET_ITEM(row, 1);
          }
          if (!PyTuple_Check(row) || idx >= PyTuple_GET_SIZE(row)) {
            Py_DECREF(lst);
            goto bail;
          }
          PyObject *v = PyTuple_GET_ITEM(row, idx);
          if (!PyUnicode_CheckExact(v)) {
            Py_DECREF(lst);
            goto bail;
          }
          Py_INCREF(v);
          PyList_SET_ITEM(lst, i, v);
        }
        PyObject *entry = Py_BuildValue("(sN)", "U", lst);
        if (!entry || PyDict_SetItem(result, idx_obj, entry) != 0) {
          Py_XDECREF(entry);
          goto err;
        }
        Py_DECREF(entry);
        continue;
      }

      Py_ssize_t itemsize = kind == '?' ? 1 : 8;
      PyObject *buf = PyByteArray_FromStringAndSize(nullptr, n * itemsize);
      if (!buf) goto err;
      char *data = PyByteArray_AS_STRING(buf);
      bool ok = true;
      int64_t min_seen = 0;
      for (Py_ssize_t i = 0; i < n && ok; i++) {
        PyObject *row = PyList_GET_ITEM(items, i);
        if (from_deltas) {
          if (!PyTuple_Check(row) || PyTuple_GET_SIZE(row) != 3) { ok = false; break; }
          row = PyTuple_GET_ITEM(row, 1);
        }
        if (!PyTuple_Check(row) || idx >= PyTuple_GET_SIZE(row)) { ok = false; break; }
        PyObject *v = PyTuple_GET_ITEM(row, idx);
        switch (kind) {
          case '?':
            if (!PyBool_Check(v)) { ok = false; break; }
            data[i] = (v == Py_True) ? 1 : 0;
            break;
          case 'q': {
            if (!PyLong_CheckExact(v)) { ok = false; break; }
            int overflow = 0;
            long long x = PyLong_AsLongLongAndOverflow(v, &overflow);
            if (overflow != 0) { ok = false; break; }
            if (x < min_seen) min_seen = x;
            ((int64_t *)data)[i] = (int64_t)x;
            break;
          }
          case 'd':
            if (!PyFloat_CheckExact(v)) { ok = false; break; }
            ((double *)data)[i] = PyFloat_AS_DOUBLE(v);
            break;
        }
      }
      if (!ok || (kind == 'q' && min_seen == INT64_MIN)) {
        Py_DECREF(buf);
        goto bail;
      }
      char kind_str[2] = {kind, 0};
      PyObject *entry = Py_BuildValue("(sN)", kind_str, buf);
      if (!entry || PyDict_SetItem(result, idx_obj, entry) != 0) {
        Py_XDECREF(entry);
        goto err;
      }
      Py_DECREF(entry);
    }
  }
  return result;
bail:
  Py_DECREF(result);
  Py_RETURN_NONE;
err:
  Py_DECREF(result);
  return nullptr;
}

// group_indices(values: list) -> (uniques list, int64 inverse bytearray)
// Hash-grouping replacement for np.unique(return_inverse=True) on object
// columns: one pass, no sort, insertion-ordered uniques.  Used by the
// groupby columnar path for string/object group keys where building a
// numpy U-array then sorting it dominated the epoch.
static PyObject *py_group_indices(PyObject *, PyObject *arg) {
  if (!PyList_Check(arg)) {
    PyErr_SetString(PyExc_TypeError, "group_indices expects a list");
    return nullptr;
  }
  Py_ssize_t n = PyList_GET_SIZE(arg);
  PyObject *uniques = PyList_New(0);
  PyObject *index = PyDict_New();  // value -> PyLong position
  PyObject *inv = PyByteArray_FromStringAndSize(nullptr, n * 8);
  if (!uniques || !index || !inv) {
    Py_XDECREF(uniques);
    Py_XDECREF(index);
    Py_XDECREF(inv);
    return nullptr;
  }
  int64_t *out = (int64_t *)PyByteArray_AS_STRING(inv);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *v = PyList_GET_ITEM(arg, i);
    PyObject *pos = PyDict_GetItemWithError(index, v);  // borrowed
    if (!pos) {
      if (PyErr_Occurred()) goto fail;  // unhashable etc.
      pos = PyLong_FromSsize_t(PyList_GET_SIZE(uniques));
      if (!pos || PyDict_SetItem(index, v, pos) != 0 ||
          PyList_Append(uniques, v) != 0) {
        Py_XDECREF(pos);
        goto fail;
      }
      out[i] = PyList_GET_SIZE(uniques) - 1;
      Py_DECREF(pos);
      continue;
    }
    out[i] = PyLong_AsSsize_t(pos);
  }
  Py_DECREF(index);
  return Py_BuildValue("(NN)", uniques, inv);
fail:
  Py_DECREF(uniques);
  Py_DECREF(index);
  Py_DECREF(inv);
  return nullptr;
}

// delta_diffs(deltas) -> int64 bytearray of the diff field, or None when a
// diff exceeds int64 (callers fall back to the Python listcomp)
static PyObject *py_delta_diffs(PyObject *, PyObject *arg) {
  if (!PyList_Check(arg)) {
    PyErr_SetString(PyExc_TypeError, "delta_diffs expects a list");
    return nullptr;
  }
  Py_ssize_t n = PyList_GET_SIZE(arg);
  PyObject *buf = PyByteArray_FromStringAndSize(nullptr, n * 8);
  if (!buf) return nullptr;
  int64_t *out = (int64_t *)PyByteArray_AS_STRING(buf);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *t = PyList_GET_ITEM(arg, i);
    if (!PyTuple_Check(t) || PyTuple_GET_SIZE(t) != 3) {
      Py_DECREF(buf);
      PyErr_SetString(PyExc_ValueError, "delta_diffs: triples expected");
      return nullptr;
    }
    PyObject *d = PyTuple_GET_ITEM(t, 2);
    int overflow = 0;
    long long x = PyLong_AsLongLongAndOverflow(d, &overflow);
    if (overflow != 0 || (x == -1 && PyErr_Occurred())) {
      PyErr_Clear();
      Py_DECREF(buf);
      Py_RETURN_NONE;
    }
    out[i] = (int64_t)x;
  }
  return buf;
}

// stage_static(rows: list[(key, row, time, diff)], list_cls) ->
//   list[(time, deltas_list, clean_bool)] — one pass partitioning build-time
// rows by timestamp, proving per-bucket cleanliness (all diffs == 1, keys
// unique) so the emit path can skip its consolidate scan entirely.
// Clean buckets are built as instances of ``list_cls`` (the engine's
// CleanDeltas list subclass) directly — no tag-copy afterwards.
static PyObject *py_stage_static(PyObject *, PyObject *args) {
  PyObject *arg, *list_cls;
  if (!PyArg_ParseTuple(args, "OO", &arg, &list_cls)) return nullptr;
  if (!PyList_Check(arg)) {
    PyErr_SetString(PyExc_TypeError, "stage_static expects a list");
    return nullptr;
  }
  Py_ssize_t n = PyList_GET_SIZE(arg);
  PyObject *buckets = PyDict_New();   // time -> [deltas, key_set, clean]
  PyObject *order = PyList_New(0);    // first-seen time order
  if (!buckets || !order) {
    Py_XDECREF(buckets);
    Py_XDECREF(order);
    return nullptr;
  }
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *quad = PyList_GET_ITEM(arg, i);
    if (!PyTuple_Check(quad) || PyTuple_GET_SIZE(quad) != 4) {
      PyErr_SetString(PyExc_ValueError, "stage_static: rows must be quads");
      goto fail;
    }
    {
      PyObject *key = PyTuple_GET_ITEM(quad, 0);
      PyObject *row = PyTuple_GET_ITEM(quad, 1);
      PyObject *time = PyTuple_GET_ITEM(quad, 2);
      PyObject *diff = PyTuple_GET_ITEM(quad, 3);
      PyObject *bucket = PyDict_GetItem(buckets, time);  // borrowed
      if (!bucket) {
        // deltas list built as list_cls (CleanDeltas) up front; dirty
        // buckets are downgraded to plain lists at assembly time
        PyObject *deltas_new = PyObject_CallNoArgs(list_cls);
        if (!deltas_new || !PyList_Check(deltas_new)) {
          Py_XDECREF(deltas_new);
          if (!PyErr_Occurred())
            PyErr_SetString(PyExc_TypeError,
                            "stage_static: list_cls must make lists");
          goto fail;
        }
        bucket = Py_BuildValue("[N,N,O]", deltas_new, PySet_New(nullptr),
                               Py_True);
        if (!bucket || PyDict_SetItem(buckets, time, bucket) != 0) {
          Py_XDECREF(bucket);
          goto fail;
        }
        Py_DECREF(bucket);  // dict holds it
        if (PyList_Append(order, time) != 0) goto fail;
        bucket = PyDict_GetItem(buckets, time);
      }
      PyObject *deltas = PyList_GET_ITEM(bucket, 0);
      PyObject *keyset = PyList_GET_ITEM(bucket, 1);
      PyObject *clean = PyList_GET_ITEM(bucket, 2);
      if (clean == Py_True) {
        int is_one = 0;
        if (PyLong_Check(diff)) {
          long d = PyLong_AsLong(diff);
          if (d == -1 && PyErr_Occurred())
            PyErr_Clear();  // out-of-range diff: simply not clean
          else
            is_one = (d == 1);
        }
        int dup = is_one ? PySet_Contains(keyset, key) : 0;
        if (dup < 0) goto fail;
        if (!is_one || dup) {
          PyList_SET_ITEM(bucket, 2, Py_False);
          Py_INCREF(Py_False);
          Py_DECREF(clean);
        } else if (PySet_Add(keyset, key) != 0) {
          goto fail;
        }
      }
      PyObject *triple = PyTuple_Pack(3, key, row, diff);
      if (!triple) goto fail;
      if (PyList_Append(deltas, triple) != 0) {
        Py_DECREF(triple);
        goto fail;
      }
      Py_DECREF(triple);
    }
  }
  {
    Py_ssize_t n_times = PyList_GET_SIZE(order);
    PyObject *out = PyList_New(n_times);
    if (!out) goto fail;
    for (Py_ssize_t i = 0; i < n_times; i++) {
      PyObject *time = PyList_GET_ITEM(order, i);
      PyObject *bucket = PyDict_GetItem(buckets, time);
      PyObject *deltas = PyList_GET_ITEM(bucket, 0);
      PyObject *clean = PyList_GET_ITEM(bucket, 2);
      PyObject *entry;
      if (clean == Py_True) {
        entry = PyTuple_Pack(3, time, deltas, clean);
      } else {
        // downgrade: a CleanDeltas instance must not carry dirty rows
        PyObject *plain = PyList_GetSlice(deltas, 0, PyList_GET_SIZE(deltas));
        if (!plain) {
          Py_DECREF(out);
          goto fail;
        }
        entry = PyTuple_Pack(3, time, plain, clean);
        Py_DECREF(plain);
      }
      if (!entry) {
        Py_DECREF(out);
        goto fail;
      }
      PyList_SET_ITEM(out, i, entry);
    }
    Py_DECREF(buckets);
    Py_DECREF(order);
    return out;
  }
fail:
  Py_DECREF(buckets);
  Py_DECREF(order);
  return nullptr;
}

// filter_deltas(deltas, mask buffer (uint8), n_cols) -> kept deltas with
// rows truncated to n_cols (the filter drops helper columns)
static PyObject *py_filter_deltas(PyObject *, PyObject *args) {
  PyObject *deltas, *mask_obj;
  Py_ssize_t n_cols;
  if (!PyArg_ParseTuple(args, "O!On", &PyList_Type, &deltas, &mask_obj,
                        &n_cols))
    return nullptr;
  Py_ssize_t n = PyList_GET_SIZE(deltas);
  Py_buffer mask;
  if (PyObject_GetBuffer(mask_obj, &mask, PyBUF_CONTIG_RO) != 0)
    return nullptr;
  if (mask.len != n) {
    PyBuffer_Release(&mask);
    PyErr_SetString(PyExc_ValueError, "filter: mask length mismatch");
    return nullptr;
  }
  const char *m = (const char *)mask.buf;
  PyObject *out = PyList_New(0);
  if (!out) {
    PyBuffer_Release(&mask);
    return nullptr;
  }
  for (Py_ssize_t i = 0; i < n; i++) {
    if (!m[i]) continue;
    PyObject *src = PyList_GET_ITEM(deltas, i);
    if (!PyTuple_Check(src) || PyTuple_GET_SIZE(src) != 3) {
      PyErr_SetString(PyExc_ValueError, "filter: deltas must be triples");
      goto fail;
    }
    {
      PyObject *row = PyTuple_GET_ITEM(src, 1);
      if (!PyTuple_Check(row) || PyTuple_GET_SIZE(row) < n_cols) {
        PyErr_SetString(PyExc_ValueError, "filter: short row");
        goto fail;
      }
      PyObject *item = src;
      if (PyTuple_GET_SIZE(row) != n_cols) {
        PyObject *cut = PyTuple_GetSlice(row, 0, n_cols);
        if (!cut) goto fail;
        item = PyTuple_Pack(3, PyTuple_GET_ITEM(src, 0), cut,
                            PyTuple_GET_ITEM(src, 2));
        Py_DECREF(cut);
        if (!item) goto fail;
        if (PyList_Append(out, item) != 0) {
          Py_DECREF(item);
          goto fail;
        }
        Py_DECREF(item);
        continue;
      }
      if (PyList_Append(out, item) != 0) goto fail;
    }
  }
  PyBuffer_Release(&mask);
  return out;
fail:
  Py_DECREF(out);
  PyBuffer_Release(&mask);
  return nullptr;
}

// gather_key_rows(deltas, idxs) -> [tuple(row[i] for i in idxs), ...]
// The multi-column groupby's per-row key-tuple build as one C pass; the
// tuples then hash-group through group_indices (same PyDict semantics as
// the row path's arrangement dict).
static PyObject *py_gather_key_rows(PyObject *, PyObject *args) {
  PyObject *deltas, *idxs;
  if (!PyArg_ParseTuple(args, "O!O!", &PyList_Type, &deltas, &PyTuple_Type,
                        &idxs))
    return nullptr;
  Py_ssize_t n = PyList_GET_SIZE(deltas);
  Py_ssize_t n_keys = PyTuple_GET_SIZE(idxs);
  std::vector<Py_ssize_t> kidx(n_keys);
  for (Py_ssize_t c = 0; c < n_keys; c++) {
    kidx[c] = PyLong_AsSsize_t(PyTuple_GET_ITEM(idxs, c));
    if (kidx[c] < 0) {
      if (!PyErr_Occurred())
        PyErr_SetString(PyExc_ValueError, "gather: bad key index");
      return nullptr;
    }
  }
  PyObject *out = PyList_New(n);
  if (!out) return nullptr;
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *item = PyList_GET_ITEM(deltas, i);
    if (!PyTuple_Check(item) || PyTuple_GET_SIZE(item) != 3) {
      PyErr_SetString(PyExc_ValueError, "gather: deltas must be triples");
      Py_DECREF(out);
      return nullptr;
    }
    PyObject *row = PyTuple_GET_ITEM(item, 1);
    PyObject *key = PyTuple_New(n_keys);
    if (!key) {
      Py_DECREF(out);
      return nullptr;
    }
    for (Py_ssize_t c = 0; c < n_keys; c++) {
      if (!PyTuple_Check(row) || kidx[c] >= PyTuple_GET_SIZE(row)) {
        PyErr_SetString(PyExc_ValueError, "gather: key index out of range");
        Py_DECREF(key);
        Py_DECREF(out);
        return nullptr;
      }
      PyObject *v = PyTuple_GET_ITEM(row, kidx[c]);
      Py_INCREF(v);
      PyTuple_SET_ITEM(key, c, v);
    }
    PyList_SET_ITEM(out, i, key);
  }
  return out;
}

// split_deltas(deltas, mask) -> (kept, dropped): partition a delta list by
// a uint8 mask without touching rows — the temporal buffers' release scan
// (BufferNode) and freeze/forget admit paths run it once per epoch batch.
static PyObject *py_split_deltas(PyObject *, PyObject *args) {
  PyObject *deltas, *mask_obj;
  if (!PyArg_ParseTuple(args, "O!O", &PyList_Type, &deltas, &mask_obj))
    return nullptr;
  Py_buffer mask;
  if (PyObject_GetBuffer(mask_obj, &mask, PyBUF_CONTIG_RO) != 0)
    return nullptr;
  Py_ssize_t n = PyList_GET_SIZE(deltas);
  if (mask.len != n) {
    PyBuffer_Release(&mask);
    PyErr_SetString(PyExc_ValueError, "split: mask length mismatch");
    return nullptr;
  }
  const char *m = (const char *)mask.buf;
  PyObject *kept = PyList_New(0);
  PyObject *dropped = PyList_New(0);
  if (!kept || !dropped) {
    Py_XDECREF(kept);
    Py_XDECREF(dropped);
    PyBuffer_Release(&mask);
    return nullptr;
  }
  for (Py_ssize_t i = 0; i < n; i++) {
    if (PyList_Append(m[i] ? kept : dropped, PyList_GET_ITEM(deltas, i)) !=
        0) {
      Py_DECREF(kept);
      Py_DECREF(dropped);
      PyBuffer_Release(&mask);
      return nullptr;
    }
  }
  PyBuffer_Release(&mask);
  return Py_BuildValue("(NN)", kept, dropped);
}

// freeze_scan(kind "q"|"d", t buffer, thr buffer, watermark|None)
//   -> (keep-mask bytearray, new watermark|None)
// FreezeNode's sequential admit/advance scan as one GIL-released pass:
// row i is kept unless thr[i] <= wm; kept rows advance wm to max(wm, t[i])
// *in scan order* (later rows see earlier rows' watermark — the data
// dependence that keeps this out of numpy).
static PyObject *py_freeze_scan(PyObject *, PyObject *args) {
  const char *kind;
  PyObject *t_obj, *thr_obj, *wm_obj;
  if (!PyArg_ParseTuple(args, "sOOO", &kind, &t_obj, &thr_obj, &wm_obj))
    return nullptr;
  Py_buffer t, thr;
  if (PyObject_GetBuffer(t_obj, &t, PyBUF_CONTIG_RO) != 0) return nullptr;
  if (PyObject_GetBuffer(thr_obj, &thr, PyBUF_CONTIG_RO) != 0) {
    PyBuffer_Release(&t);
    return nullptr;
  }
  PyObject *result = nullptr;
  Py_ssize_t n = t.len / 8;
  bool is_int = kind[0] == 'q';
  bool has_wm = wm_obj != Py_None;
  int64_t wm_i = 0;
  double wm_d = 0.0;
  bool ok = true;
  if (t.len != thr.len || t.len % 8 != 0) {
    PyErr_SetString(PyExc_ValueError, "freeze_scan: buffer length mismatch");
    ok = false;
  } else if (kind[0] != 'q' && kind[0] != 'd') {
    PyErr_SetString(PyExc_ValueError, "freeze_scan: unknown kind");
    ok = false;
  } else if (has_wm) {
    if (is_int) {
      wm_i = PyLong_AsLongLong(wm_obj);
      if (wm_i == -1 && PyErr_Occurred()) ok = false;
    } else {
      wm_d = PyFloat_AsDouble(wm_obj);
      if (wm_d == -1.0 && PyErr_Occurred()) ok = false;
    }
  }
  PyObject *mask = ok ? PyByteArray_FromStringAndSize(nullptr, n) : nullptr;
  if (ok && mask) {
    char *m = PyByteArray_AS_STRING(mask);
    const int64_t *ti = (const int64_t *)t.buf;
    const int64_t *thi = (const int64_t *)thr.buf;
    const double *td = (const double *)t.buf;
    const double *thd = (const double *)thr.buf;
    Py_BEGIN_ALLOW_THREADS
    if (is_int) {
      for (Py_ssize_t i = 0; i < n; i++) {
        if (has_wm && thi[i] <= wm_i) {
          m[i] = 0;
          continue;
        }
        if (!has_wm || ti[i] > wm_i) {
          wm_i = ti[i];
          has_wm = true;
        }
        m[i] = 1;
      }
    } else {
      for (Py_ssize_t i = 0; i < n; i++) {
        if (has_wm && thd[i] <= wm_d) {
          m[i] = 0;
          continue;
        }
        if (!has_wm || td[i] > wm_d) {
          wm_d = td[i];
          has_wm = true;
        }
        m[i] = 1;
      }
    }
    Py_END_ALLOW_THREADS
    PyObject *wm_out;
    if (!has_wm) {
      wm_out = Py_None;
      Py_INCREF(wm_out);
    } else if (is_int) {
      wm_out = PyLong_FromLongLong(wm_i);
    } else {
      wm_out = PyFloat_FromDouble(wm_d);
    }
    if (wm_out) result = Py_BuildValue("(NN)", mask, wm_out);
    if (!result) Py_DECREF(mask);
  } else {
    Py_XDECREF(mask);
  }
  PyBuffer_Release(&t);
  PyBuffer_Release(&thr);
  return result;
}

// route_deltas(deltas, key_idxs, n_dest, hash_none) -> [dest lists]
// The exchange hot loop (engine/comm.py exchange_deltas) batched: per row,
// serialize the routing-key columns exactly as hash_values does, blake2b,
// dest = (low 16 bits) % n_dest — the shard_to_worker rule.  hash_none=0
// (equi-join none_guard semantics): a None/Error key value routes the row
// by its own key; hash_none=1 (groupby keys): Nones hash like any value.
// Any per-row serialization failure routes by row key, mirroring the
// Python loop's per-row exception fallback.
static PyObject *py_route_deltas(PyObject *, PyObject *args) {
  PyObject *deltas, *idxs;
  int n_dest, hash_none;
  if (!PyArg_ParseTuple(args, "O!O!ip", &PyList_Type, &deltas, &PyTuple_Type,
                        &idxs, &n_dest, &hash_none))
    return nullptr;
  if (n_dest <= 0) {
    PyErr_SetString(PyExc_ValueError, "route: n_dest must be positive");
    return nullptr;
  }
  Py_ssize_t n_keys = PyTuple_GET_SIZE(idxs);
  std::vector<Py_ssize_t> kidx(n_keys);
  for (Py_ssize_t c = 0; c < n_keys; c++) {
    kidx[c] = PyLong_AsSsize_t(PyTuple_GET_ITEM(idxs, c));
    if (kidx[c] < 0) {
      if (!PyErr_Occurred())
        PyErr_SetString(PyExc_ValueError, "route: bad key index");
      return nullptr;
    }
  }
  PyObject *out = PyList_New(n_dest);
  if (!out) return nullptr;
  for (int d = 0; d < n_dest; d++) {
    PyObject *lst = PyList_New(0);
    if (!lst) {
      Py_DECREF(out);
      return nullptr;
    }
    PyList_SET_ITEM(out, d, lst);
  }
  Py_ssize_t n = PyList_GET_SIZE(deltas);
  Buf buf;
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *item = PyList_GET_ITEM(deltas, i);
    long dest = -1;
    if (PyTuple_Check(item) && PyTuple_GET_SIZE(item) == 3) {
      PyObject *row = PyTuple_GET_ITEM(item, 1);
      bool by_key = false;
      if (!PyTuple_Check(row)) {
        by_key = true;
      } else {
        for (Py_ssize_t c = 0; c < n_keys && !by_key; c++) {
          if (kidx[c] >= PyTuple_GET_SIZE(row)) {
            by_key = true;
            break;
          }
          PyObject *v = PyTuple_GET_ITEM(row, kidx[c]);
          if (!hash_none && (v == Py_None || v == g_error_obj)) by_key = true;
        }
      }
      if (!by_key) {
        buf.d.clear();
        for (Py_ssize_t c = 0; c < n_keys && !by_key; c++) {
          if (!ser_value(PyTuple_GET_ITEM(row, kidx[c]), buf)) {
            PyErr_Clear();  // per-row fallback, like the Python loop
            by_key = true;
          }
        }
      }
      if (by_key) {
        PyObject *key = PyTuple_GET_ITEM(item, 0);
        uint64_t lo = PyLong_AsUnsignedLongLongMask(key);
        if (lo == (uint64_t)-1 && PyErr_Occurred()) {
          Py_DECREF(out);
          return nullptr;  // a non-int row key crashes the Python loop too
        }
        dest = (long)((lo & 0xFFFFu) % (uint64_t)n_dest);
      } else {
        uint8_t digest[16];
        blake2b_hash(digest, 16, buf.d.data(), buf.d.size());
        uint64_t lo;
        std::memcpy(&lo, digest, 8);
        dest = (long)((lo & 0xFFFFu) % (uint64_t)n_dest);
      }
    } else {
      PyErr_SetString(PyExc_ValueError, "route: deltas must be triples");
      Py_DECREF(out);
      return nullptr;
    }
    if (PyList_Append(PyList_GET_ITEM(out, dest), item) != 0) {
      Py_DECREF(out);
      return nullptr;
    }
  }
  return out;
}

// rebuild_delta_rows(deltas, cols) with cols entries:
//   ("q"|"d"|"?", buffer) | ("U", list) | ("P", source column index) —
//   "P" copies the value straight from the input row (passthrough)
//   -> new list of (key, tuple(values...), diff) with keys/diffs reused
static PyObject *py_rebuild_delta_rows(PyObject *, PyObject *args) {
  PyObject *deltas, *cols;
  if (!PyArg_ParseTuple(args, "O!O!", &PyList_Type, &deltas, &PyList_Type,
                        &cols))
    return nullptr;
  Py_ssize_t n = PyList_GET_SIZE(deltas);
  Py_ssize_t n_cols = PyList_GET_SIZE(cols);

  struct Col {
    char kind;
    const char *data = nullptr;
    PyObject *lst = nullptr;
    Py_ssize_t src_idx = -1;
    Py_buffer view{};
    bool has_view = false;
  };
  std::vector<Col> parsed(n_cols);
  bool fail = false;
  for (Py_ssize_t c = 0; c < n_cols && !fail; c++) {
    PyObject *entry = PyList_GET_ITEM(cols, c);
    const char *kind_s;
    PyObject *payload;
    if (!PyArg_ParseTuple(entry, "sO", &kind_s, &payload)) { fail = true; break; }
    parsed[c].kind = kind_s[0];
    if (parsed[c].kind == 'P') {
      parsed[c].src_idx = PyLong_AsSsize_t(payload);
      if (parsed[c].src_idx < 0) {
        if (!PyErr_Occurred())
          PyErr_SetString(PyExc_ValueError, "rebuild: bad passthrough index");
        fail = true;
      }
    } else if (parsed[c].kind == 'U') {
      if (!PyList_Check(payload) || PyList_GET_SIZE(payload) != n) {
        PyErr_SetString(PyExc_ValueError, "rebuild: U column length mismatch");
        fail = true; break;
      }
      parsed[c].lst = payload;
    } else {
      if (PyObject_GetBuffer(payload, &parsed[c].view, PyBUF_CONTIG_RO) != 0) {
        fail = true; break;
      }
      parsed[c].has_view = true;
      Py_ssize_t itemsize = parsed[c].kind == '?' ? 1 : 8;
      if (parsed[c].view.len != n * itemsize) {
        PyErr_SetString(PyExc_ValueError, "rebuild: column length mismatch");
        fail = true; break;
      }
      parsed[c].data = (const char *)parsed[c].view.buf;
    }
  }
  PyObject *out = nullptr;
  if (!fail) {
    out = PyList_New(n);
    for (Py_ssize_t i = 0; i < n && out; i++) {
      PyObject *src = PyList_GET_ITEM(deltas, i);
      if (!PyTuple_Check(src) || PyTuple_GET_SIZE(src) != 3) {
        PyErr_SetString(PyExc_ValueError, "rebuild: deltas must be triples");
        Py_CLEAR(out);
        break;
      }
      PyObject *row = PyTuple_New(n_cols);
      if (!row) { Py_CLEAR(out); break; }
      for (Py_ssize_t c = 0; c < n_cols; c++) {
        PyObject *v = nullptr;
        switch (parsed[c].kind) {
          case 'q':
            v = PyLong_FromLongLong(((const int64_t *)parsed[c].data)[i]);
            break;
          case 'd':
            v = PyFloat_FromDouble(((const double *)parsed[c].data)[i]);
            break;
          case '?':
            v = PyBool_FromLong(parsed[c].data[i]);
            break;
          case 'U':
            v = PyList_GET_ITEM(parsed[c].lst, i);
            Py_INCREF(v);
            break;
          case 'P': {
            PyObject *srow = PyTuple_GET_ITEM(src, 1);
            if (!PyTuple_Check(srow) ||
                parsed[c].src_idx >= PyTuple_GET_SIZE(srow)) {
              PyErr_SetString(PyExc_ValueError,
                              "rebuild: passthrough index out of range");
              break;
            }
            v = PyTuple_GET_ITEM(srow, parsed[c].src_idx);
            Py_INCREF(v);
            break;
          }
          default:
            PyErr_SetString(PyExc_ValueError, "rebuild: unknown column kind");
        }
        if (!v) { Py_DECREF(row); Py_CLEAR(out); break; }
        PyTuple_SET_ITEM(row, c, v);
      }
      if (!out) break;
      PyObject *key = PyTuple_GET_ITEM(src, 0);
      PyObject *diff = PyTuple_GET_ITEM(src, 2);
      PyObject *triple = PyTuple_Pack(3, key, row, diff);
      Py_DECREF(row);
      if (!triple) { Py_CLEAR(out); break; }
      PyList_SET_ITEM(out, i, triple);
    }
  }
  for (auto &col : parsed)
    if (col.has_view) PyBuffer_Release(&col.view);
  return out;
}

// ---------------------------------------------------------------------------
// HNSW approximate-nearest-neighbor core (Malkov & Yashunin 2016).
//
// Parity role: the reference links the USearch C library for its HNSW
// external index (src/external_integration/usearch_integration.rs:163); this
// is the equivalent native core.  The Python layer
// (stdlib/indexing/hnsw.py) keeps key mapping, metadata filters and
// tombstone-compaction policy; this core owns the graph, the vector store
// and the hot search/insert loops over dense u32 node ids.
// ---------------------------------------------------------------------------

#include <cmath>
#include <queue>
#include <random>
#include <algorithm>

namespace hnsw {

struct Index {
  int dim;
  int metric;  // 0 = dot-based (cos/ip; cos pre-normalized on add), 1 = l2sq
  int m, m0, ef_construction;
  bool normalize;
  double ml;
  std::mt19937_64 rng;
  std::vector<float> vecs;                               // node * dim
  std::vector<int> levels;                               // per node
  std::vector<char> dead;                                // tombstones
  std::vector<std::vector<std::vector<uint32_t>>> links; // [layer][node]
  int64_t entry = -1;
  size_t n_dead = 0;
  // visited-set epoch marking: O(1) reset per search
  std::vector<uint32_t> visit_mark;
  uint32_t visit_epoch = 0;

  size_t size() const { return levels.size(); }

  const float *vec(uint32_t id) const { return vecs.data() + (size_t)id * dim; }

  float dist(const float *a, const float *b) const {
    float acc = 0.f;
    if (metric == 1) {
      for (int i = 0; i < dim; i++) {
        float d = a[i] - b[i];
        acc += d * d;
      }
      return acc;
    }
    for (int i = 0; i < dim; i++) acc += a[i] * b[i];
    return -acc;  // similarity -> distance
  }

  int draw_level() {
    double u = std::uniform_real_distribution<double>(1e-12, 1.0)(rng);
    return (int)(-std::log(u) * ml);
  }

  uint32_t greedy(const float *q, uint32_t start, int layer) const {
    uint32_t cur = start;
    float cur_d = dist(q, vec(cur));
    bool improved = true;
    while (improved) {
      improved = false;
      for (uint32_t nb : links[layer][cur]) {
        float d = dist(q, vec(nb));
        if (d < cur_d) {
          cur_d = d;
          cur = nb;
          improved = true;
        }
      }
    }
    return cur;
  }

  // beam search on a layer; results (dist, id) sorted ascending, may
  // include tombstoned nodes (callers filter)
  void search_layer(const float *q, uint32_t ep, int layer, int ef,
                    std::vector<std::pair<float, uint32_t>> &out) {
    if (++visit_epoch == 0) {  // u32 wrap: clear marks once per 4G searches
      std::fill(visit_mark.begin(), visit_mark.end(), 0);
      visit_epoch = 1;
    }
    visit_mark.resize(size(), 0);
    using Entry = std::pair<float, uint32_t>;
    std::priority_queue<Entry, std::vector<Entry>, std::greater<Entry>> cand;
    std::priority_queue<Entry> best;  // max-heap
    float d0 = dist(q, vec(ep));
    cand.push({d0, ep});
    best.push({d0, ep});
    visit_mark[ep] = visit_epoch;
    while (!cand.empty()) {
      auto [d, id] = cand.top();
      if ((int)best.size() >= ef && d > best.top().first) break;
      cand.pop();
      for (uint32_t nb : links[layer][id]) {
        if (visit_mark[nb] == visit_epoch) continue;
        visit_mark[nb] = visit_epoch;
        float nd = dist(q, vec(nb));
        if ((int)best.size() < ef || nd < best.top().first) {
          cand.push({nd, nb});
          best.push({nd, nb});
          if ((int)best.size() > ef) best.pop();
        }
      }
    }
    out.resize(best.size());
    for (size_t i = best.size(); i-- > 0;) {
      out[i] = best.top();
      best.pop();
    }
  }

  int64_t add(const float *raw) {
    uint32_t id = (uint32_t)size();
    vecs.insert(vecs.end(), raw, raw + dim);
    if (normalize) {
      float *v = vecs.data() + (size_t)id * dim;
      float n = 0.f;
      for (int i = 0; i < dim; i++) n += v[i] * v[i];
      if (n > 0.f) {
        n = 1.0f / std::sqrt(n);
        for (int i = 0; i < dim; i++) v[i] *= n;
      }
    }
    int level = draw_level();
    levels.push_back(level);
    dead.push_back(0);
    while ((int)links.size() <= level) links.emplace_back();
    for (auto &layer : links) layer.resize(size());

    if (entry < 0 || dead[entry]) {
      entry = id;
      return id;
    }
    const float *q = vec(id);
    uint32_t ep = (uint32_t)entry;
    int top = levels[entry];
    for (int layer = top; layer > level; layer--) ep = greedy(q, ep, layer);
    std::vector<std::pair<float, uint32_t>> cands;
    for (int layer = std::min(level, top); layer >= 0; layer--) {
      search_layer(q, ep, layer, ef_construction, cands);
      int m_max = layer == 0 ? m0 : m;
      auto &mine = links[layer][id];
      mine.clear();
      for (auto &[d, k] : cands) {
        if (k == id) continue;
        mine.push_back(k);
        if ((int)mine.size() >= m) break;
      }
      for (uint32_t nb : mine) {
        auto &lst = links[layer][nb];
        lst.push_back(id);
        if ((int)lst.size() > m_max) {
          // prune: keep the m_max closest to nb
          const float *nv = vec(nb);
          std::vector<std::pair<float, uint32_t>> scored;
          scored.reserve(lst.size());
          for (uint32_t t : lst) scored.push_back({dist(nv, vec(t)), t});
          std::nth_element(scored.begin(), scored.begin() + m_max,
                           scored.end());
          lst.clear();
          for (int i = 0; i < m_max; i++) lst.push_back(scored[i].second);
        }
      }
      if (!cands.empty()) ep = cands[0].second;
    }
    if (level > levels[entry]) entry = id;
    return id;
  }

  void remove(uint32_t id) {
    if (id >= size() || dead[id]) return;
    dead[id] = 1;
    n_dead++;
    if ((int64_t)id == entry) {
      entry = -1;
      int best_level = -1;
      for (size_t i = 0; i < size(); i++)
        if (!dead[i] && levels[i] > best_level) {
          best_level = levels[i];
          entry = (int64_t)i;
        }
    }
  }

  void search(const float *raw_q, int k, int ef,
              std::vector<std::pair<float, uint32_t>> &out) {
    out.clear();
    if (entry < 0) return;
    std::vector<float> qbuf(raw_q, raw_q + dim);
    if (normalize) {
      float n = 0.f;
      for (int i = 0; i < dim; i++) n += qbuf[i] * qbuf[i];
      if (n > 0.f) {
        n = 1.0f / std::sqrt(n);
        for (int i = 0; i < dim; i++) qbuf[i] *= n;
      }
    }
    const float *q = qbuf.data();
    if (ef < k) ef = k;
    uint32_t ep = (uint32_t)entry;
    for (int layer = levels[entry]; layer > 0; layer--) ep = greedy(q, ep, layer);
    std::vector<std::pair<float, uint32_t>> found;
    search_layer(q, ep, 0, ef, found);
    for (auto &e : found)
      if (!dead[e.second]) out.push_back(e);
  }
};

}  // namespace hnsw

static void hnsw_capsule_free(PyObject *cap) {
  delete (hnsw::Index *)PyCapsule_GetPointer(cap, "pathway_tpu.hnsw");
}

static hnsw::Index *hnsw_from(PyObject *cap) {
  return (hnsw::Index *)PyCapsule_GetPointer(cap, "pathway_tpu.hnsw");
}

static PyObject *py_hnsw_new(PyObject *, PyObject *args) {
  int dim, m, efc;
  unsigned long long seed;
  const char *metric;
  if (!PyArg_ParseTuple(args, "isiiK", &dim, &metric, &m, &efc, &seed))
    return nullptr;
  auto *ix = new hnsw::Index();
  ix->dim = dim;
  std::string ms(metric);
  ix->metric = ms == "l2sq" ? 1 : 0;
  ix->normalize = ms == "cos";
  ix->m = m < 2 ? 2 : m;
  ix->m0 = 2 * ix->m;
  ix->ef_construction = efc < ix->m ? ix->m : efc;
  ix->ml = 1.0 / std::log((double)ix->m);
  ix->rng.seed(seed);
  return PyCapsule_New(ix, "pathway_tpu.hnsw", hnsw_capsule_free);
}

static int hnsw_get_floats(PyObject *obj, int dim, Py_buffer *view) {
  if (PyObject_GetBuffer(obj, view, PyBUF_CONTIG_RO) != 0) return -1;
  if (view->len != (Py_ssize_t)(dim * sizeof(float))) {
    PyBuffer_Release(view);
    PyErr_Format(PyExc_ValueError, "expected %d float32 values", dim);
    return -1;
  }
  return 0;
}

static PyObject *py_hnsw_add(PyObject *, PyObject *args) {
  PyObject *cap, *buf;
  if (!PyArg_ParseTuple(args, "OO", &cap, &buf)) return nullptr;
  auto *ix = hnsw_from(cap);
  if (!ix) return nullptr;
  Py_buffer view;
  if (hnsw_get_floats(buf, ix->dim, &view) != 0) return nullptr;
  int64_t id = ix->add((const float *)view.buf);
  PyBuffer_Release(&view);
  return PyLong_FromLongLong(id);
}

static PyObject *py_hnsw_remove(PyObject *, PyObject *args) {
  PyObject *cap;
  unsigned long id;
  if (!PyArg_ParseTuple(args, "Ok", &cap, &id)) return nullptr;
  auto *ix = hnsw_from(cap);
  if (!ix) return nullptr;
  ix->remove((uint32_t)id);
  Py_RETURN_NONE;
}

static PyObject *py_hnsw_search(PyObject *, PyObject *args) {
  PyObject *cap, *buf;
  int k, ef;
  if (!PyArg_ParseTuple(args, "OOii", &cap, &buf, &k, &ef)) return nullptr;
  auto *ix = hnsw_from(cap);
  if (!ix) return nullptr;
  Py_buffer view;
  if (hnsw_get_floats(buf, ix->dim, &view) != 0) return nullptr;
  std::vector<std::pair<float, uint32_t>> out;
  ix->search((const float *)view.buf, k, ef, out);
  PyBuffer_Release(&view);
  PyObject *res = PyList_New((Py_ssize_t)out.size());
  if (!res) return nullptr;
  for (size_t i = 0; i < out.size(); i++) {
    PyObject *pair =
        Py_BuildValue("(kf)", (unsigned long)out[i].second, out[i].first);
    if (!pair) {
      Py_DECREF(res);
      return nullptr;
    }
    PyList_SET_ITEM(res, (Py_ssize_t)i, pair);
  }
  return res;
}

static PyObject *py_hnsw_get_vector(PyObject *, PyObject *args) {
  PyObject *cap;
  unsigned long id;
  if (!PyArg_ParseTuple(args, "Ok", &cap, &id)) return nullptr;
  auto *ix = hnsw_from(cap);
  if (!ix) return nullptr;
  if (id >= ix->size()) {
    PyErr_SetString(PyExc_KeyError, "unknown hnsw node id");
    return nullptr;
  }
  // prepped form (cos: normalized) — re-adding it is idempotent
  return PyBytes_FromStringAndSize((const char *)ix->vec((uint32_t)id),
                                   (Py_ssize_t)ix->dim * sizeof(float));
}

static PyObject *py_hnsw_stats(PyObject *, PyObject *arg) {
  auto *ix = hnsw_from(arg);
  if (!ix) return nullptr;
  return Py_BuildValue("(kk)", (unsigned long)ix->size(),
                       (unsigned long)ix->n_dead);
}

// ---------------------------------------------------------------------------
// Native inner equi-join (reference hot path: src/engine/dataflow.rs:2740).
//
// The Python JoinNode.step costs ~µs/row in closure calls, tuple builds and
// per-pair hash_values round trips.  This C++ index holds both sides keyed
// by the blake2b-128 of the join-key column values (the same 128-bit key
// discipline the whole engine uses) and runs the full delta-join rule
// (dL⋈R then dR⋈L′) in one call per epoch.  Semantics mirror the row path
// exactly: None/Error join keys match nothing and are not stored (SQL null
// semantics); inserts replace, removals drop; emission diff = delta diff.
// ---------------------------------------------------------------------------

namespace joinx {

struct U128 {
  uint64_t lo = 0, hi = 0;
  bool operator==(const U128 &o) const { return lo == o.lo && hi == o.hi; }
};
struct U128H {
  size_t operator()(const U128 &k) const {
    return (size_t)(k.lo ^ (k.hi * 0x9E3779B97F4A7C15ull));
  }
};
struct Entry {
  U128 kh;        // row-key hash (bucket membership)
  PyObject *key;  // owned
  PyObject *row;  // owned
  long long matches = 0;  // outer modes: live matches on the other side
};
// buckets are small vectors, not maps: the common join has a handful of
// rows per key, where a linear scan beats a per-key unordered_map heap
// allocation by ~2x; a heavily skewed bucket degrades removals to O(rows)
using Bucket = std::vector<Entry>;
using Side = std::unordered_map<U128, Bucket, U128H>;  // jk-hash -> rows

struct Index {
  Side sides[2];  // 0 = left, 1 = right
  ~Index() {
    for (auto &side : sides)
      for (auto &b : side)
        for (auto &e : b.second) {
          Py_DECREF(e.key);
          Py_DECREF(e.row);
        }
  }
};

}  // namespace joinx

static void join_capsule_free(PyObject *cap) {
  delete (joinx::Index *)PyCapsule_GetPointer(cap, "pathway_tpu.join");
}

static joinx::Index *join_from(PyObject *cap) {
  return (joinx::Index *)PyCapsule_GetPointer(cap, "pathway_tpu.join");
}

static PyObject *py_join_new(PyObject *, PyObject *) {
  return PyCapsule_New(new joinx::Index(), "pathway_tpu.join",
                       join_capsule_free);
}

// 128-bit <-> PyLong converters.  On CPython <= 3.12 the private-but-
// exported byte-array functions skip all object churn; 3.13 changed
// _PyLong_AsByteArray's signature (added with_exceptions), so any newer
// interpreter takes the portable PyNumber path — slower, never ABI-wrong.
#if PY_VERSION_HEX < 0x030D0000
#define PW_HAVE_LONG_BYTEARRAY 1
extern "C" {
PyObject *_PyLong_FromByteArray(const unsigned char *bytes, size_t n,
                                int little_endian, int is_signed);
int _PyLong_AsByteArray(PyLongObject *v, unsigned char *bytes, size_t n,
                        int little_endian, int is_signed);
}
#else
#define PW_HAVE_LONG_BYTEARRAY 0
#endif

static PyObject *pylong_from_u128(uint64_t lo, uint64_t hi) {
#if PW_HAVE_LONG_BYTEARRAY
  uint8_t bytes[16];
  std::memcpy(bytes, &lo, 8);
  std::memcpy(bytes + 8, &hi, 8);
  return _PyLong_FromByteArray(bytes, 16, 1, 0);
#else
  PyObject *plo = PyLong_FromUnsignedLongLong(lo);
  PyObject *phi = PyLong_FromUnsignedLongLong(hi);
  PyObject *sixtyfour = PyLong_FromLong(64);
  PyObject *shifted = phi ? PyNumber_Lshift(phi, sixtyfour) : nullptr;
  PyObject *res = shifted ? PyNumber_Or(shifted, plo) : nullptr;
  Py_XDECREF(plo);
  Py_XDECREF(phi);
  Py_XDECREF(sixtyfour);
  Py_XDECREF(shifted);
  return res;
#endif
}

// portable 128-bit extraction (mask low 64, shift for high)
static bool u128_of_pylong_slow(PyObject *v, joinx::U128 *out) {
  out->lo = PyLong_AsUnsignedLongLongMask(v);
  if (PyErr_Occurred()) return false;
  PyObject *sixtyfour = PyLong_FromLong(64);
  PyObject *shifted = PyNumber_Rshift(v, sixtyfour);
  Py_DECREF(sixtyfour);
  if (!shifted) return false;
  out->hi = PyLong_AsUnsignedLongLongMask(shifted);
  Py_DECREF(shifted);
  return !PyErr_Occurred();
}

// 128-bit row key from its PyLong
static bool u128_of_pylong(PyObject *v, joinx::U128 *out) {
#if PW_HAVE_LONG_BYTEARRAY
  uint8_t bytes[16];
  if (_PyLong_AsByteArray((PyLongObject *)v, bytes, 16, 1, 0) < 0) {
    // negative or >128-bit keys never occur (KEY_MASK); be exact anyway
    PyErr_Clear();
    return u128_of_pylong_slow(v, out);
  }
  std::memcpy(&out->lo, bytes, 8);
  std::memcpy(&out->hi, bytes + 8, 8);
  return true;
#else
  return u128_of_pylong_slow(v, out);
#endif
}

// join key of a row: blake2b-128 of ser_value over the key columns.
// Returns 1 ok, 0 null-key (None/Error present — matches nothing), -1 error.
// ``buf`` is caller-provided so row loops reuse one allocation.
static int join_key_of(PyObject *row, PyObject *idxs, Buf &buf,
                       joinx::U128 *out) {
  buf.d.clear();
  Py_ssize_t n = PyTuple_GET_SIZE(idxs);
  bool row_is_tuple = PyTuple_Check(row);
  for (Py_ssize_t i = 0; i < n; i++) {
    Py_ssize_t idx = PyLong_AsSsize_t(PyTuple_GET_ITEM(idxs, i));
    if (idx < 0 && PyErr_Occurred()) return -1;
    PyObject *v;
    if (row_is_tuple) {
      if (idx >= PyTuple_GET_SIZE(row)) {
        PyErr_SetString(PyExc_IndexError, "join key index out of range");
        return -1;
      }
      v = PyTuple_GET_ITEM(row, idx);
    } else {
      v = PySequence_GetItem(row, idx);
      if (!v) return -1;
      Py_DECREF(v);  // row holds a ref; borrow like the tuple path
    }
    if (v == Py_None || Py_TYPE(v) == Py_TYPE(g_error_obj)) return 0;
    if (!ser_value(v, buf)) return -1;
  }
  uint8_t digest[16];
  blake2b_hash(digest, 16, buf.d.data(), buf.d.size());
  std::memcpy(&out->lo, digest, 8);
  std::memcpy(&out->hi, digest + 8, 8);
  return 1;
}

// output row key: mode 0 = hash_values([Pointer(lkey), Pointer(rkey)]),
// mode 1 = lkey (join id'd to the left side), mode 2 = rkey
static PyObject *join_okey(int mode, PyObject *lkey, PyObject *rkey,
                           const joinx::U128 &lk, const joinx::U128 &rk) {
  if (mode == 1) {
    Py_INCREF(lkey);
    return lkey;
  }
  if (mode == 2) {
    Py_INCREF(rkey);
    return rkey;
  }
  // ser(Pointer) is tag 0x06 + 16-byte LE value — build both inline
  uint8_t data[34];
  data[0] = 0x06;
  std::memcpy(data + 1, &lk.lo, 8);
  std::memcpy(data + 9, &lk.hi, 8);
  data[17] = 0x06;
  std::memcpy(data + 18, &rk.lo, 8);
  std::memcpy(data + 26, &rk.hi, 8);
  uint8_t digest[16];
  blake2b_hash(digest, 16, data, 34);
  uint64_t lo, hi;
  std::memcpy(&lo, digest, 8);
  std::memcpy(&hi, digest + 8, 8);
  return pylong_from_u128(lo, hi);
}

// hash_values([Pointer(k), None]) / ([None, Pointer(k)]) for null-padded
// outer rows (ser None = single 0x00 byte)
static PyObject *join_okey_null(bool left_null, const joinx::U128 &k) {
  uint8_t buf[18];
  if (left_null) {
    buf[0] = 0x00;
    buf[1] = 0x06;
    std::memcpy(buf + 2, &k.lo, 8);
    std::memcpy(buf + 10, &k.hi, 8);
  } else {
    buf[0] = 0x06;
    std::memcpy(buf + 1, &k.lo, 8);
    std::memcpy(buf + 9, &k.hi, 8);
    buf[17] = 0x00;
  }
  uint8_t digest[16];
  blake2b_hash(digest, 16, buf, 18);
  uint64_t lo, hi;
  std::memcpy(&lo, digest, 8);
  std::memcpy(&hi, digest + 8, 8);
  return pylong_from_u128(lo, hi);
}

// one null-padded outer row: (okey, (key, None, row, None), diff) when
// null_side == 1 (right null), or (okey, (None, key, None, row), diff)
static int join_emit_null(PyObject *out, int null_side, PyObject *key,
                          PyObject *row, const joinx::U128 &kh,
                          long long diff) {
  PyObject *okey = join_okey_null(null_side == 0, kh);
  if (!okey) return -1;
  PyObject *payload =
      null_side == 1 ? PyTuple_Pack(4, key, Py_None, row, Py_None)
                     : PyTuple_Pack(4, Py_None, key, Py_None, row);
  PyObject *pdiff = payload ? PyLong_FromLongLong(diff) : nullptr;
  PyObject *item = pdiff ? PyTuple_New(3) : nullptr;
  if (!item) {
    Py_DECREF(okey);
    Py_XDECREF(payload);
    Py_XDECREF(pdiff);
    return -1;
  }
  PyTuple_SET_ITEM(item, 0, okey);
  PyTuple_SET_ITEM(item, 1, payload);
  PyTuple_SET_ITEM(item, 2, pdiff);
  int rc = PyList_Append(out, item);
  Py_DECREF(item);
  return rc;
}

static int join_emit(PyObject *out, int mode, PyObject *lkey, PyObject *rkey,
                     PyObject *lrow, PyObject *rrow, const joinx::U128 &lk,
                     const joinx::U128 &rk, PyObject *diff) {
  PyObject *okey = join_okey(mode, lkey, rkey, lk, rk);
  if (!okey) return -1;
  PyObject *payload = PyTuple_Pack(4, lkey, rkey, lrow, rrow);
  if (!payload) {
    Py_DECREF(okey);
    return -1;
  }
  PyObject *item = PyTuple_New(3);
  if (!item) {
    Py_DECREF(okey);
    Py_DECREF(payload);
    return -1;
  }
  Py_INCREF(diff);
  PyTuple_SET_ITEM(item, 0, okey);
  PyTuple_SET_ITEM(item, 1, payload);
  PyTuple_SET_ITEM(item, 2, diff);
  int rc = PyList_Append(out, item);
  Py_DECREF(item);
  return rc;
}

// apply one side's deltas: probe the other side, then update own index.
// side 0 = deltas are left rows, 1 = right rows.  *replaced is set when an
// insert overwrote an existing row key (cleanliness analysis cares).
// mine_outer = THIS side is outer (its unmatched rows get null pads);
// other_outer = the probed side is outer (its rows' match counts
// transition as this side's deltas arrive) — the exact bookkeeping of
// JoinNode.step's row path.
static int join_apply_side(joinx::Index *ix, int side, PyObject *deltas,
                           PyObject *idxs, int mode, PyObject *out,
                           bool *replaced, bool mine_outer,
                           bool other_outer) {
  auto &mine = ix->sides[side];
  auto &other = ix->sides[1 - side];
  PyObject *seq = PySequence_Fast(deltas, "join deltas must be a sequence");
  if (!seq) return -1;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  mine.reserve(mine.size() + (size_t)n);
  Buf buf;
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *d = PySequence_Fast_GET_ITEM(seq, i);
    PyObject *key = PyTuple_GET_ITEM(d, 0);
    PyObject *row = PyTuple_GET_ITEM(d, 1);
    PyObject *diff = PyTuple_GET_ITEM(d, 2);
    long long dval = PyLong_AsLongLong(diff);
    if (dval == -1 && PyErr_Occurred()) {
      Py_DECREF(seq);
      return -1;
    }
    joinx::U128 jk;
    int st = join_key_of(row, idxs, buf, &jk);
    if (st < 0) {
      Py_DECREF(seq);
      return -1;
    }
    joinx::U128 kh;
    if (!u128_of_pylong(key, &kh)) {
      Py_DECREF(seq);
      return -1;
    }
    if (st == 0) {
      // null join key matches nothing (SQL), but an outer side still
      // carries the row with a null-padded partner
      if (mine_outer &&
          join_emit_null(out, side == 0 ? 1 : 0, key, row, kh, dval) < 0) {
        Py_DECREF(seq);
        return -1;
      }
      continue;
    }
    auto oit = other.find(jk);
    long long n_matches = oit == other.end() ? 0 : (long long)oit->second.size();
    if (oit != other.end()) {
      for (auto &e : oit->second) {
        int rc = side == 0
                     ? join_emit(out, mode, key, e.key, row, e.row, kh, e.kh,
                                 diff)
                     : join_emit(out, mode, e.key, key, e.row, row, e.kh, kh,
                                 diff);
        if (rc == 0 && other_outer) {
          // the probed row's match count transitions: its null pad
          // retracts on the first match, reappears on the last unmatch
          long long old = e.matches;
          e.matches = old + dval;
          if (old == 0 && dval > 0) {
            rc = join_emit_null(out, side == 0 ? 0 : 1, e.key, e.row, e.kh,
                                -1);
          } else if (old + dval == 0) {
            rc = join_emit_null(out, side == 0 ? 0 : 1, e.key, e.row, e.kh,
                                1);
          }
        }
        if (rc < 0) {
          Py_DECREF(seq);
          return -1;
        }
      }
    }
    if (mine_outer && n_matches == 0 &&
        join_emit_null(out, side == 0 ? 1 : 0, key, row, kh, dval) < 0) {
      Py_DECREF(seq);
      return -1;
    }
    if (dval > 0) {
      auto &bucket = mine[jk];
      joinx::Entry *found = nullptr;
      for (auto &e : bucket)
        if (e.kh == kh) {
          found = &e;
          break;
        }
      if (found) {  // replace (row path: dict put, match count kept)
        *replaced = true;
        Py_DECREF(found->key);
        Py_DECREF(found->row);
        Py_INCREF(key);
        Py_INCREF(row);
        found->key = key;
        found->row = row;
      } else {
        Py_INCREF(key);
        Py_INCREF(row);
        bucket.push_back({kh, key, row, n_matches});
      }
    } else {
      auto mit = mine.find(jk);
      if (mit != mine.end()) {
        auto &bucket = mit->second;
        for (size_t bi = 0; bi < bucket.size(); bi++)
          if (bucket[bi].kh == kh) {
            Py_DECREF(bucket[bi].key);
            Py_DECREF(bucket[bi].row);
            bucket.erase(bucket.begin() + bi);
            break;
          }
        if (bucket.empty()) mine.erase(mit);
      }
    }
  }
  Py_DECREF(seq);
  return 0;
}

// (capsule, left_deltas, right_deltas, l_idxs, r_idxs, okey_mode,
//  left_outer, right_outer) -> (out list, replaced: bool)
static PyObject *py_join_step(PyObject *, PyObject *args) {
  PyObject *cap, *dl, *dr, *l_idxs, *r_idxs;
  int mode, left_outer = 0, right_outer = 0;
  if (!PyArg_ParseTuple(args, "OOOO!O!i|ii", &cap, &dl, &dr, &PyTuple_Type,
                        &l_idxs, &PyTuple_Type, &r_idxs, &mode, &left_outer,
                        &right_outer))
    return nullptr;
  auto *ix = join_from(cap);
  if (!ix) return nullptr;
  PyObject *out = PyList_New(0);
  if (!out) return nullptr;
  bool replaced = false;
  // delta-join rule: dL against R, then dR against L' (already incl. dL)
  if (join_apply_side(ix, 0, dl, l_idxs, mode, out, &replaced,
                      left_outer != 0, right_outer != 0) < 0 ||
      join_apply_side(ix, 1, dr, r_idxs, mode, out, &replaced,
                      right_outer != 0, left_outer != 0) < 0) {
    Py_DECREF(out);
    return nullptr;
  }
  PyObject *res = Py_BuildValue("(Oi)", out, replaced ? 1 : 0);
  Py_DECREF(out);
  return res;
}

// (capsule) -> ([(key, row), ...] left, [(key, row), ...] right) — for
// operator snapshots; join keys are recomputed from the rows on load
static PyObject *py_join_dump(PyObject *, PyObject *arg) {
  auto *ix = join_from(arg);
  if (!ix) return nullptr;
  PyObject *sides[2] = {nullptr, nullptr};
  for (int s = 0; s < 2; s++) {
    PyObject *lst = PyList_New(0);
    if (!lst) {
      Py_XDECREF(sides[0]);
      return nullptr;
    }
    for (auto &b : ix->sides[s])
      for (auto &e : b.second) {
        PyObject *pair = PyTuple_Pack(2, e.key, e.row);
        if (!pair || PyList_Append(lst, pair) < 0) {
          Py_XDECREF(pair);
          Py_DECREF(lst);
          Py_XDECREF(sides[0]);
          return nullptr;
        }
        Py_DECREF(pair);
      }
    sides[s] = lst;
  }
  PyObject *res = PyTuple_Pack(2, sides[0], sides[1]);
  Py_DECREF(sides[0]);
  Py_DECREF(sides[1]);
  return res;
}

// (capsule, side, items, idxs) -> None; re-inserts snapshot rows
static PyObject *py_join_load(PyObject *, PyObject *args) {
  PyObject *cap, *items, *idxs;
  int side;
  if (!PyArg_ParseTuple(args, "OiOO!", &cap, &side, &items, &PyTuple_Type,
                        &idxs))
    return nullptr;
  auto *ix = join_from(cap);
  if (!ix || side < 0 || side > 1) {
    if (ix) PyErr_SetString(PyExc_ValueError, "side must be 0 or 1");
    return nullptr;
  }
  PyObject *seq = PySequence_Fast(items, "join_load expects a sequence");
  if (!seq) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  Buf buf;
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *pair = PySequence_Fast_GET_ITEM(seq, i);
    PyObject *key = PyTuple_GET_ITEM(pair, 0);
    PyObject *row = PyTuple_GET_ITEM(pair, 1);
    joinx::U128 jk, kh;
    int st = join_key_of(row, idxs, buf, &jk);
    if (st < 0 || (st == 1 && !u128_of_pylong(key, &kh))) {
      Py_DECREF(seq);
      return nullptr;
    }
    if (st == 0) continue;
    auto &bucket = ix->sides[side][jk];
    joinx::Entry *found = nullptr;
    for (auto &e : bucket)
      if (e.kh == kh) { found = &e; break; }
    Py_INCREF(key);
    Py_INCREF(row);
    if (found) {
      Py_DECREF(found->key);
      Py_DECREF(found->row);
      found->key = key;
      found->row = row;
    } else {
      bucket.push_back({kh, key, row});
    }
  }
  Py_DECREF(seq);
  // recount outer match counters from the live invariant (count = size of
  // the other side's bucket); cheap, and correct whichever side loads last
  for (int s = 0; s < 2; s++) {
    auto &other = ix->sides[1 - s];
    for (auto &b : ix->sides[s]) {
      auto oit = other.find(b.first);
      long long m = oit == other.end() ? 0 : (long long)oit->second.size();
      for (auto &e : b.second) e.matches = m;
    }
  }
  Py_RETURN_NONE;
}

// Pointer(key) without the Python-level call: tp_alloc + slot store.
// Engine keys are already & KEY_MASK (KEY_MASK = 2^128-1, an identity for
// the non-negative 128-bit hashes every key derives from), so skipping
// __init__'s mask is exact.
static PyObject *make_pointer_fast(PyObject *key) {
  static PyObject *value_name = nullptr;
  if (!value_name) {
    value_name = PyUnicode_InternFromString("value");
    if (!value_name) return nullptr;
  }
  PyTypeObject *tp = (PyTypeObject *)g_pointer_cls;
  PyObject *obj = tp->tp_alloc(tp, 0);
  if (!obj) return nullptr;
  if (PyObject_SetAttr(obj, value_name, key) < 0) {
    Py_DECREF(obj);
    return nullptr;
  }
  return obj;
}

// (deltas, spec) -> ([(key, projected_row, diff)], err_keys | None) — the
// join-select projection over (lkey, rkey, lrow, rrow) payload rows in
// one C pass.  spec entries: (src, idx) with src 0 = lrow[idx] (None when
// lrow is None), 1 = rrow[idx], 2 = Pointer(lkey) or None, 3 =
// Pointer(rkey) or None, 4 = Pointer(out key).  Mirrors table.py
// JoinBinder accessors.  err_keys lists keys of inserted rows whose
// projection carries an Error value (the row path logs those; parity).
// Returns None (not an exception) on any malformed payload shape — the
// caller then falls back to the row interpreter, like the other native
// fast paths in this file.
static PyObject *py_project_join_rows(PyObject *, PyObject *args) {
  PyObject *deltas, *spec;
  if (!PyArg_ParseTuple(args, "OO!", &deltas, &PyTuple_Type, &spec))
    return nullptr;
  Py_ssize_t n_out = PyTuple_GET_SIZE(spec);
  // decode the spec once
  std::vector<std::pair<long, long>> cols(n_out);
  for (Py_ssize_t i = 0; i < n_out; i++) {
    PyObject *entry = PyTuple_GET_ITEM(spec, i);
    cols[i] = {PyLong_AsLong(PyTuple_GET_ITEM(entry, 0)),
               PyLong_AsLong(PyTuple_GET_ITEM(entry, 1))};
    if (PyErr_Occurred()) return nullptr;
  }
  PyObject *seq = PySequence_Fast(deltas, "project expects a sequence");
  if (!seq) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  PyObject *out = PyList_New(n);
  PyObject *err_keys = nullptr;
  PyTypeObject *err_type = Py_TYPE(g_error_obj);
  if (!out) {
    Py_DECREF(seq);
    return nullptr;
  }
  // 0 = ok, 1 = bail (fall back to the row path), 2 = error set
  auto one = [&](Py_ssize_t i) -> int {
    PyObject *d = PySequence_Fast_GET_ITEM(seq, i);
    if (!PyTuple_Check(d) || PyTuple_GET_SIZE(d) != 3) return 1;
    PyObject *key = PyTuple_GET_ITEM(d, 0);
    PyObject *payload = PyTuple_GET_ITEM(d, 1);
    PyObject *diff = PyTuple_GET_ITEM(d, 2);
    if (!PyTuple_Check(payload) || PyTuple_GET_SIZE(payload) != 4) return 1;
    PyObject *lkey = PyTuple_GET_ITEM(payload, 0);
    PyObject *rkey = PyTuple_GET_ITEM(payload, 1);
    PyObject *lrow = PyTuple_GET_ITEM(payload, 2);
    PyObject *rrow = PyTuple_GET_ITEM(payload, 3);
    PyObject *row = PyTuple_New(n_out);
    bool has_err = false;
    if (!row) return 2;
    for (Py_ssize_t c = 0; c < n_out; c++) {
      long src_ = cols[c].first, idx = cols[c].second;
      PyObject *v = nullptr;
      switch (src_) {
        case 0:
        case 1: {
          PyObject *r = src_ == 0 ? lrow : rrow;
          if (r == Py_None) {
            Py_INCREF(Py_None);
            v = Py_None;
          } else {
            if (!PyTuple_Check(r) || idx >= PyTuple_GET_SIZE(r)) {
              Py_DECREF(row);
              return 1;
            }
            v = PyTuple_GET_ITEM(r, idx);
            if (Py_TYPE(v) == err_type) has_err = true;
            Py_INCREF(v);
          }
          break;
        }
        case 2:
        case 3: {
          PyObject *k = src_ == 2 ? lkey : rkey;
          if (k == Py_None) {
            Py_INCREF(Py_None);
            v = Py_None;
          } else {
            v = make_pointer_fast(k);
          }
          break;
        }
        case 4:
          v = make_pointer_fast(key);
          break;
        default:
          PyErr_SetString(PyExc_ValueError, "bad projection src");
      }
      if (!v) {
        Py_DECREF(row);
        return 2;
      }
      PyTuple_SET_ITEM(row, c, v);
    }
    if (has_err) {
      // row-path parity: an inserted row whose projection carries an
      // Error cell is logged (the payload itself never holds a top-level
      // Error, so the row path's "new Error" condition reduces to this)
      long long dv = PyLong_AsLongLong(diff);
      if (dv == -1 && PyErr_Occurred()) {
        Py_DECREF(row);
        return 2;
      }
      if (dv > 0) {
        if (!err_keys) {
          err_keys = PyList_New(0);
          if (!err_keys) {
            Py_DECREF(row);
            return 2;
          }
        }
        if (PyList_Append(err_keys, key) < 0) {
          Py_DECREF(row);
          return 2;
        }
      }
    }
    PyObject *item = PyTuple_New(3);
    if (!item) {
      Py_DECREF(row);
      return 2;
    }
    Py_INCREF(key);
    Py_INCREF(diff);
    PyTuple_SET_ITEM(item, 0, key);
    PyTuple_SET_ITEM(item, 1, row);
    PyTuple_SET_ITEM(item, 2, diff);
    PyList_SET_ITEM(out, i, item);
    return 0;
  };
  for (Py_ssize_t i = 0; i < n; i++) {
    int rc = one(i);
    if (rc == 0) continue;
    Py_DECREF(seq);
    Py_DECREF(out);
    Py_XDECREF(err_keys);
    if (rc == 1) Py_RETURN_NONE;  // malformed: caller uses the row path
    return nullptr;
  }
  Py_DECREF(seq);
  PyObject *res =
      Py_BuildValue("(OO)", out, err_keys ? err_keys : Py_None);
  Py_DECREF(out);
  Py_XDECREF(err_keys);
  return res;
}

// (deltas, col_idx, with_origin) -> [(new_key, new_row, diff)] or None.
// The Table.flatten hot loop in one C pass: one output row per element of
// the iterable column, new_key = hash_values([Pointer(key), pos]) built
// without Python objects, tuple splice in C.  None = bail to the row path
// (malformed rows); non-iterable cell values flatten as a single item and
// None cells emit nothing, exactly like the Python fn.
static PyObject *py_flatten_deltas(PyObject *, PyObject *args) {
  PyObject *deltas;
  Py_ssize_t col_idx;
  int with_origin;
  if (!PyArg_ParseTuple(args, "Oni", &deltas, &col_idx, &with_origin))
    return nullptr;
  PyObject *seq = PySequence_Fast(deltas, "flatten expects a sequence");
  if (!seq) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  // shape prevalidation BEFORE any cell is touched: the bail-to-row-path
  // contract must be side-effect-free (a one-shot iterator cell consumed
  // by a partial native pass would be empty when the row path re-runs)
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *d = PySequence_Fast_GET_ITEM(seq, i);
    if (!PyTuple_Check(d) || PyTuple_GET_SIZE(d) != 3 ||
        !PyLong_Check(PyTuple_GET_ITEM(d, 0)) ||
        !PyTuple_Check(PyTuple_GET_ITEM(d, 1)) ||
        col_idx >= PyTuple_GET_SIZE(PyTuple_GET_ITEM(d, 1))) {
      Py_DECREF(seq);
      Py_RETURN_NONE;  // malformed: the row path handles it
    }
  }
  PyObject *out = PyList_New(0);
  if (!out) {
    Py_DECREF(seq);
    return nullptr;
  }
  // 0 ok; 2 error (shapes already validated — no bail from here on)
  auto one = [&](Py_ssize_t i) -> int {
    PyObject *d = PySequence_Fast_GET_ITEM(seq, i);
    PyObject *key = PyTuple_GET_ITEM(d, 0);
    PyObject *row = PyTuple_GET_ITEM(d, 1);
    PyObject *diff = PyTuple_GET_ITEM(d, 2);
    Py_ssize_t width = PyTuple_GET_SIZE(row);
    PyObject *cell = PyTuple_GET_ITEM(row, col_idx);
    if (cell == Py_None) return 0;  // None flattens to nothing
    PyObject *items = PySequence_Fast(cell, "");
    bool single = false;
    if (!items) {
      // the row path's contract: only TypeError means "not iterable —
      // flatten as a single item"; anything else propagates
      if (!PyErr_ExceptionMatches(PyExc_TypeError)) return 2;
      PyErr_Clear();
      single = true;
    }
    Py_ssize_t m = single ? 1 : PySequence_Fast_GET_SIZE(items);
    joinx::U128 kh;
    if (!u128_of_pylong(key, &kh)) {
      Py_XDECREF(items);
      return 2;
    }
    // ser prefix: Pointer tag + 16-byte key (shared by every position)
    uint8_t buf[1 + 16 + 1 + 16];
    buf[0] = 0x06;
    std::memcpy(buf + 1, &kh.lo, 8);
    std::memcpy(buf + 9, &kh.hi, 8);
    buf[17] = 0x02;  // int tag; positions are small non-negative ints
    PyObject *origin = nullptr;
    if (with_origin) {
      origin = make_pointer_fast(key);
      if (!origin) {
        Py_XDECREF(items);
        return 2;
      }
    }
    int rc = 0;
    for (Py_ssize_t pos = 0; pos < m && rc == 0; pos++) {
      PyObject *item =
          single ? cell : PySequence_Fast_GET_ITEM(items, pos);
      int64_t p = (int64_t)pos;
      std::memcpy(buf + 18, &p, 8);
      std::memset(buf + 26, 0, 8);  // i128 little-endian, non-negative
      uint8_t digest[16];
      blake2b_hash(digest, 16, buf, sizeof(buf));
      uint64_t lo, hi;
      std::memcpy(&lo, digest, 8);
      std::memcpy(&hi, digest + 8, 8);
      PyObject *new_key = pylong_from_u128(lo, hi);
      PyObject *new_row = PyTuple_New(width + (with_origin ? 1 : 0));
      if (!new_key || !new_row) {
        Py_XDECREF(new_key);
        Py_XDECREF(new_row);
        rc = 2;
        break;
      }
      for (Py_ssize_t c = 0; c < width; c++) {
        PyObject *v = c == col_idx ? item : PyTuple_GET_ITEM(row, c);
        Py_INCREF(v);
        PyTuple_SET_ITEM(new_row, c, v);
      }
      if (with_origin) {
        Py_INCREF(origin);
        PyTuple_SET_ITEM(new_row, width, origin);
      }
      PyObject *entry = PyTuple_New(3);
      if (!entry) {
        Py_DECREF(new_key);
        Py_DECREF(new_row);
        rc = 2;
        break;
      }
      Py_INCREF(diff);
      PyTuple_SET_ITEM(entry, 0, new_key);
      PyTuple_SET_ITEM(entry, 1, new_row);
      PyTuple_SET_ITEM(entry, 2, diff);
      if (PyList_Append(out, entry) < 0) rc = 2;
      Py_DECREF(entry);
    }
    Py_XDECREF(origin);
    Py_XDECREF(items);
    return rc;
  };
  for (Py_ssize_t i = 0; i < n; i++) {
    if (one(i) != 0) {
      Py_DECREF(seq);
      Py_DECREF(out);
      return nullptr;  // exception set; errors propagate, never re-run
    }
  }
  Py_DECREF(seq);
  return out;
}

// (deltas, salt) -> [(hash_values([Pointer(key), salt]), row, diff)] or
// None when a key is not a plain int (row path handles it).  Injective
// for distinct keys at a fixed salt — the salted-branch rekey of the
// vectorized sliding-window assignment.
static PyObject *py_rekey_deltas(PyObject *, PyObject *args) {
  PyObject *deltas;
  long long salt;
  if (!PyArg_ParseTuple(args, "OL", &deltas, &salt)) return nullptr;
  PyObject *seq = PySequence_Fast(deltas, "rekey expects a sequence");
  if (!seq) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *d = PySequence_Fast_GET_ITEM(seq, i);
    if (!PyTuple_Check(d) || PyTuple_GET_SIZE(d) != 3 ||
        !PyLong_Check(PyTuple_GET_ITEM(d, 0))) {
      Py_DECREF(seq);
      Py_RETURN_NONE;
    }
  }
  PyObject *out = PyList_New(n);
  if (!out) {
    Py_DECREF(seq);
    return nullptr;
  }
  uint8_t buf[1 + 16 + 1 + 16];
  buf[0] = 0x06;
  buf[17] = 0x02;
  int64_t s = (int64_t)salt;
  std::memcpy(buf + 18, &s, 8);
  std::memset(buf + 26, s < 0 ? 0xFF : 0x00, 8);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *d = PySequence_Fast_GET_ITEM(seq, i);
    PyObject *key = PyTuple_GET_ITEM(d, 0);
    joinx::U128 kh;
    if (!u128_of_pylong(key, &kh)) {
      Py_DECREF(seq);
      Py_DECREF(out);
      return nullptr;
    }
    std::memcpy(buf + 1, &kh.lo, 8);
    std::memcpy(buf + 9, &kh.hi, 8);
    uint8_t digest[16];
    blake2b_hash(digest, 16, buf, sizeof(buf));
    uint64_t lo, hi;
    std::memcpy(&lo, digest, 8);
    std::memcpy(&hi, digest + 8, 8);
    PyObject *new_key = pylong_from_u128(lo, hi);
    PyObject *entry = new_key ? PyTuple_New(3) : nullptr;
    if (!entry) {
      Py_XDECREF(new_key);
      Py_DECREF(seq);
      Py_DECREF(out);
      return nullptr;
    }
    PyObject *row = PyTuple_GET_ITEM(d, 1);
    PyObject *diff = PyTuple_GET_ITEM(d, 2);
    Py_INCREF(row);
    Py_INCREF(diff);
    PyTuple_SET_ITEM(entry, 0, new_key);
    PyTuple_SET_ITEM(entry, 1, row);
    PyTuple_SET_ITEM(entry, 2, diff);
    PyList_SET_ITEM(out, i, entry);
  }
  Py_DECREF(seq);
  return out;
}

static PyObject *py_join_stats(PyObject *, PyObject *arg) {
  auto *ix = join_from(arg);
  if (!ix) return nullptr;
  size_t counts[2] = {0, 0};
  for (int s = 0; s < 2; s++)
    for (auto &b : ix->sides[s]) counts[s] += b.second.size();
  return Py_BuildValue("(kk)", (unsigned long)counts[0],
                       (unsigned long)counts[1]);
}

static PyMethodDef methods[] = {
    {"join_new", py_join_new, METH_NOARGS, "native equi-join index capsule"},
    {"join_step", py_join_step, METH_VARARGS,
     "(capsule, dl, dr, l_idxs, r_idxs, okey_mode) -> output deltas"},
    {"join_dump", py_join_dump, METH_O,
     "(capsule) -> (left [(key, row)], right [(key, row)])"},
    {"join_load", py_join_load, METH_VARARGS,
     "(capsule, side, items, idxs) re-inserts snapshot rows"},
    {"join_stats", py_join_stats, METH_O, "(capsule) -> (n_left, n_right)"},
    {"project_join_rows", py_project_join_rows, METH_VARARGS,
     "(join deltas, ((src, idx), ...)) -> projected deltas"},
    {"flatten_deltas", py_flatten_deltas, METH_VARARGS,
     "(deltas, col_idx, with_origin) -> flattened deltas or None"},
    {"rekey_deltas", py_rekey_deltas, METH_VARARGS,
     "(deltas, salt) -> salted-hash rekeyed deltas or None"},
    {"materialize_columns", py_materialize_columns, METH_VARARGS,
     "(rows|deltas, needed tuple, from_deltas) -> {idx: (kind, buf|list)} "
     "or None on bail"},
    {"rebuild_delta_rows", py_rebuild_delta_rows, METH_VARARGS,
     "(deltas, [(kind, buf|list|src_idx), ...]) -> [(key, row, diff), ...]"},
    {"filter_deltas", py_filter_deltas, METH_VARARGS,
     "(deltas, uint8 mask buffer, n_cols) -> kept deltas, rows truncated"},
    {"split_deltas", py_split_deltas, METH_VARARGS,
     "(deltas, uint8 mask buffer) -> (kept, dropped), rows untouched"},
    {"gather_key_rows", py_gather_key_rows, METH_VARARGS,
     "(deltas, idxs) -> per-row key tuples (multi-column group keys)"},
    {"freeze_scan", py_freeze_scan, METH_VARARGS,
     "(kind, t buffer, thr buffer, watermark|None) -> (keep mask, new "
     "watermark) — FreezeNode's sequential admit/advance scan"},
    {"route_deltas", py_route_deltas, METH_VARARGS,
     "(deltas, key_idxs, n_dest, hash_none) -> per-destination delta "
     "lists (exchange shard routing, hash_values-compatible)"},
    {"stage_static", py_stage_static, METH_VARARGS,
     "(quads, clean_list_cls) -> [(time, deltas, clean)] partition + "
     "cleanliness proof; clean buckets built as clean_list_cls"},
    {"group_indices", py_group_indices, METH_O,
     "(values) -> (uniques, int64 inverse bytearray) hash grouping"},
    {"delta_diffs", py_delta_diffs, METH_O,
     "(deltas) -> int64 bytearray of diffs (None when beyond int64)"},
    {"hnsw_new", py_hnsw_new, METH_VARARGS,
     "HNSW index: (dim, metric, m, ef_construction, seed) -> capsule"},
    {"hnsw_add", py_hnsw_add, METH_VARARGS,
     "(capsule, float32 buffer) -> dense node id"},
    {"hnsw_remove", py_hnsw_remove, METH_VARARGS, "(capsule, id) tombstone"},
    {"hnsw_search", py_hnsw_search, METH_VARARGS,
     "(capsule, query buffer, k, ef) -> [(id, dist)] live nodes, ascending"},
    {"hnsw_get_vector", py_hnsw_get_vector, METH_VARARGS,
     "(capsule, id) -> float32 bytes of the stored (prepped) vector"},
    {"hnsw_stats", py_hnsw_stats, METH_O, "(capsule) -> (n_total, n_dead)"},
    {"setup", py_setup, METH_VARARGS, "register engine classes and helpers"},
    {"hash_values", py_hash_values, METH_O, "stable 128-bit value hash"},
    {"blake2b_128", py_blake2b_128, METH_O, "blake2b-128 digest"},
    {"encode_row", py_encode_row, METH_O, "PWT1-encode a row"},
    {"encode_events", py_encode_events, METH_O,
     "PWT1-encode a batch of snapshot events"},
    {"crc32c", py_crc32c, METH_VARARGS,
     "CRC-32C (Castagnoli), hardware-accelerated, GIL-released"},
    {"decode_row", py_decode_row, METH_VARARGS, "PWT1-decode a row"},
    {"upsert_chain", py_upsert_chain, METH_VARARGS,
     "(deltas, state) -> chained retract+insert delta list"},
    {"consolidate_dirty", py_consolidate_dirty, METH_O,
     "accumulate a known-dirty delta list (retractions first)"},
    {"sequential_keys", py_sequential_keys, METH_VARARGS,
     "bulk sequential row keys: blake2b16(salt + le16(start+i))"},
    {nullptr, nullptr, 0, nullptr}};

static struct PyModuleDef moduledef = {PyModuleDef_HEAD_INIT, "_native",
                                       "pathway_tpu native runtime core", -1,
                                       methods};

PyMODINIT_FUNC PyInit__native(void) { return PyModule_Create(&moduledef); }
