"""Native runtime core loader.

Compiles ``src/_native.cpp`` with g++ on first use (cached as a .so keyed by
the source hash), registers the engine's value classes and slow-path codec
helpers, and exposes the module.  Pure-Python fallbacks stay in place when
compilation is unavailable (``PATHWAY_NATIVE=0`` forces them).

Parity role: the reference's value/key/snapshot hot paths are Rust
(src/engine/value.rs, src/persistence/input_snapshot.rs); here they are C++
behind the same Python interfaces.
"""

from __future__ import annotations

import hashlib
import importlib.util
import os
import subprocess
import sys
import sysconfig
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "src", "_native.cpp")
_BUILD_DIR = os.path.join(_HERE, "build")
# compile flags participate in the build-cache key (a flag change must
# rebuild even with identical source)
_FLAGS_DIGEST = b"O3-march-native-v1"

_lock = threading.Lock()
_loaded = False
_module = None


def _cpu_tag() -> bytes:
    """Host-CPU identity for the build-cache key: -march=native binaries
    must not be dlopened on a CPU without the ISA extensions they were
    compiled for (SIGILL via a shared/rsync'd build dir)."""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    return hashlib.blake2b(
                        line.encode(), digest_size=4
                    ).hexdigest().encode()
    except OSError:
        pass
    import platform

    return platform.machine().encode()


def _compile() -> str | None:
    with open(_SRC, "rb") as f:
        src_hash = hashlib.blake2b(
            f.read() + _FLAGS_DIGEST + _cpu_tag(), digest_size=8
        ).hexdigest()
    # key the cache by interpreter ABI too: a .so built for another CPython
    # version/ABI (including free-threaded or debug builds, which share a
    # hexversion) must not be dlopened into this one
    abi = sysconfig.get_config_var("SOABI") or f"{sys.hexversion:08x}"
    so_path = os.path.join(_BUILD_DIR, f"_native_{src_hash}_{abi}.so")
    if os.path.exists(so_path):
        return so_path
    os.makedirs(_BUILD_DIR, exist_ok=True)
    include = sysconfig.get_paths()["include"]
    cmd = [
        "g++",
        "-O3",
        # the .so is built on (and cached per) the machine that runs it,
        # so native tuning is safe — it vectorizes the HNSW distance loops
        "-march=native",
        "-std=c++17",
        "-shared",
        "-fPIC",
        f"-I{include}",
        _SRC,
        "-o",
        so_path + ".tmp",
    ]
    try:
        subprocess.run(
            cmd, check=True, capture_output=True, text=True, timeout=120
        )
    except (subprocess.SubprocessError, OSError) as exc:
        import logging

        detail = getattr(exc, "stderr", "") or str(exc)
        logging.getLogger("pathway_tpu.native").warning(
            "native core build failed, using Python fallbacks: %s", detail[-2000:]
        )
        return None
    os.replace(so_path + ".tmp", so_path)
    return so_path


def _load():
    so_path = _compile()
    if so_path is None:
        return None
    # module name must match PyInit__native
    spec = importlib.util.spec_from_file_location("_native", so_path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    # register classes + slow-path helpers
    import numpy as np

    from pathway_tpu.engine import codec
    from pathway_tpu.engine import types as tz

    def encode_slow(v):
        import io as _io

        out = _io.BytesIO()
        codec.encode_value(v, out)
        return out.getvalue()

    def decode_slow(tag, view, pos):
        # pos points just past the tag byte; codec.decode_value re-reads it.
        # Same corrupt-buffer contract as decode_row_py: everything decode
        # raises surfaces as the one documented, catchable ValueError.
        try:
            return codec.decode_value(view, pos - 1)
        except ValueError:
            raise
        except MemoryError:
            raise
        except Exception as exc:
            raise ValueError(f"codec: corrupt buffer ({exc})") from exc

    def ser_slow(v):
        out: list[bytes] = []
        tz._ser_value(v, out)
        return b"".join(out)

    mod.setup(
        tz.Pointer,
        tz.Json,
        tz.PyObjectWrapper,
        np.ndarray,
        tz.ERROR,
        encode_slow,
        decode_slow,
        ser_slow,
    )
    return mod


def get():
    """The native module, or None when disabled/unavailable."""
    global _loaded, _module
    if _loaded:
        return _module
    with _lock:
        if _loaded:
            return _module
        from pathway_tpu.internals.config import env_bool

        if not env_bool("PATHWAY_NATIVE"):
            _module = None
        else:
            try:
                _module = _load()
            except Exception:
                import logging

                logging.getLogger("pathway_tpu.native").warning(
                    "native core unavailable, using Python fallbacks",
                    exc_info=True,
                )
                _module = None
        _loaded = True
    return _module
