"""Device mesh construction.

The reference sizes its worker grid from ``PATHWAY_THREADS`` ×
``PATHWAY_PROCESSES`` (``src/engine/dataflow/config.rs:88-120``).  Here the
grid is a ``jax.sharding.Mesh``; one chip plays the role of one worker
(BASELINE north star).  ``make_mesh`` factors the device count into
``(data, model)`` with a modest tensor-parallel degree — encoder weights
are small enough that dp should dominate.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("data", "model")


def mesh_shape_for(n_devices: int, max_model: int = 2) -> tuple[int, int]:
    """Factor ``n_devices`` into (data, model).

    Tensor parallelism is capped at ``max_model`` — MiniLM/BGE-class
    encoders saturate a chip long before weight memory is a constraint, so
    extra chips are worth more as data parallelism.
    """
    model = 1
    for cand in range(min(max_model, n_devices), 0, -1):
        if n_devices % cand == 0:
            model = cand
            break
    return n_devices // model, model


def make_mesh(
    n_devices: int | None = None,
    *,
    devices: list | None = None,
    max_model: int = 2,
) -> Mesh:
    """An ``("data", "model")`` mesh over the first ``n_devices`` devices."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    data, model = mesh_shape_for(len(devices), max_model)
    grid = np.asarray(devices).reshape(data, model)
    return Mesh(grid, AXES)


def flat_axes(mesh: Mesh) -> tuple[str, ...]:
    """All mesh axes — for state sharded over every chip (the index)."""
    return tuple(mesh.axis_names)


# Process-wide default mesh for device-resident indexes.  When set, every
# BruteForceKnn/USearchKnn index (and the DocumentStore/VectorStore built on
# them) shards its corpus matrix over this mesh and answers queries through
# the shard_map top-k — the analog of the reference attaching its external
# index to every SPMD worker (src/engine/dataflow.rs:2694).
_DEFAULT_INDEX_MESH: Mesh | None = None


def set_default_index_mesh(mesh: Mesh | None) -> None:
    """Route all subsequently-built device indexes over ``mesh``."""
    global _DEFAULT_INDEX_MESH
    _DEFAULT_INDEX_MESH = mesh


def get_default_index_mesh() -> Mesh | None:
    return _DEFAULT_INDEX_MESH
