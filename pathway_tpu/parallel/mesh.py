"""Device mesh construction.

The reference sizes its worker grid from ``PATHWAY_THREADS`` ×
``PATHWAY_PROCESSES`` (``src/engine/dataflow/config.rs:88-120``).  Here the
grid is a ``jax.sharding.Mesh``; one chip plays the role of one worker
(BASELINE north star).  ``make_mesh`` factors the device count into
``(data, model)`` with a modest tensor-parallel degree — encoder weights
are small enough that dp should dominate.
"""

from __future__ import annotations

import os

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("data", "model")

_DISTRIBUTED = False


def initialize_distributed(
    *,
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Join the multi-host device runtime so ``jax.devices()`` spans hosts.

    The reference sizes its worker grid from the ``PATHWAY_*`` env
    (``src/engine/dataflow/config.rs:88-120``) and its ``spawn`` CLI forks
    processes with those variables set (``python/pathway/cli.py:53-110``);
    here the same env powers ``jax.distributed.initialize`` so ``make_mesh``
    returns a GLOBAL mesh and XLA collectives ride DCN between hosts (ICI
    within one).  Resolution order per field: explicit argument →
    ``PATHWAY_DEVICE_COORDINATOR`` env → derived from the worker-cluster
    config (first peer host, ``first_port + 1000`` — off the TCP-mesh port
    range).  Returns False (no-op) for single-process runs; idempotent.
    """
    global _DISTRIBUTED
    if _DISTRIBUTED:
        return True
    from pathway_tpu.internals.config import get_config

    cfg = get_config()
    nproc = cfg.processes if num_processes is None else num_processes
    pid = cfg.process_id if process_id is None else process_id
    if nproc <= 1:
        return False
    if coordinator_address is None:
        from pathway_tpu.internals.config import env_str

        coordinator_address = env_str("PATHWAY_DEVICE_COORDINATOR")
    if coordinator_address is None:
        host = (cfg.peer_hosts[0] if cfg.peer_hosts else "127.0.0.1")
        # supervised restarts (engine/supervisor.py) offset the derived
        # coordinator port by the restart attempt: the previous attempt's
        # coordinator may linger in FIN_WAIT/teardown for seconds after
        # SIGKILL, and jax.distributed.initialize fails hard on a port that
        # is merely slow to free — a fresh port per attempt sidesteps it
        from pathway_tpu.engine.faults import restart_attempt

        coordinator_address = (
            f"{host}:{cfg.first_port + 1000 + restart_attempt()}"
        )
    # multi-process CPU meshes need a cross-process collectives backend:
    # XLA:CPU's default ("none") hard-fails any computation spanning
    # processes ("Multiprocess computations aren't implemented on the CPU
    # backend").  jaxlib ships gloo TCP collectives; select them before
    # the backend initializes.  Only when CPU is the explicitly requested
    # platform — on TPU the collectives ride ICI/DCN and this flag is
    # irrelevant (and older jaxlibs may not know it, hence best-effort).
    platforms = (
        getattr(jax.config, "jax_platforms", None)
        or os.environ.get("JAX_PLATFORMS", "")
        or ""
    )
    if "cpu" in platforms.lower():
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # noqa: BLE001 - unavailable on this jaxlib
            pass
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=nproc,
        process_id=pid,
    )
    _DISTRIBUTED = True
    return True


def put_global(arr: np.ndarray, sharding) -> jax.Array:
    """``device_put`` that also works when the mesh spans hosts.

    Multi-host: every process holds the full host-side array (the SPMD
    "every worker builds the same data" invariant) and each device reads
    its own slice via ``make_array_from_callback`` — ``jax.device_put``
    alone cannot target non-addressable devices.
    """
    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    arr = np.asarray(arr)
    return jax.make_array_from_callback(arr.shape, sharding, lambda idx: arr[idx])


def mesh_shape_for(n_devices: int, max_model: int = 2) -> tuple[int, int]:
    """Factor ``n_devices`` into (data, model).

    Tensor parallelism is capped at ``max_model`` — MiniLM/BGE-class
    encoders saturate a chip long before weight memory is a constraint, so
    extra chips are worth more as data parallelism.
    """
    model = 1
    for cand in range(min(max_model, n_devices), 0, -1):
        if n_devices % cand == 0:
            model = cand
            break
    return n_devices // model, model


def make_mesh(
    n_devices: int | None = None,
    *,
    devices: list | None = None,
    max_model: int = 2,
) -> Mesh:
    """An ``("data", "model")`` mesh over the first ``n_devices`` devices."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    data, model = mesh_shape_for(len(devices), max_model)
    grid = np.asarray(devices).reshape(data, model)
    return Mesh(grid, AXES)


def flat_axes(mesh: Mesh) -> tuple[str, ...]:
    """All mesh axes — for state sharded over every chip (the index)."""
    return tuple(mesh.axis_names)


# Process-wide default mesh for device-resident indexes.  When set, every
# BruteForceKnn/USearchKnn index (and the DocumentStore/VectorStore built on
# them) shards its corpus matrix over this mesh and answers queries through
# the shard_map top-k — the analog of the reference attaching its external
# index to every SPMD worker (src/engine/dataflow.rs:2694).
_DEFAULT_INDEX_MESH: Mesh | None = None


def set_default_index_mesh(mesh: Mesh | None) -> None:
    """Route all subsequently-built device indexes over ``mesh``."""
    global _DEFAULT_INDEX_MESH
    _DEFAULT_INDEX_MESH = mesh


def get_default_index_mesh() -> Mesh | None:
    return _DEFAULT_INDEX_MESH
