"""Training-state checkpoint/resume for the model families (orbax).

The reference persists *pipeline* state — connector offsets and operator
snapshots (``src/persistence/tracker.rs:49``) — but has no trainable
models, so it has nothing like this.  This framework trains (contrastive
encoder fine-tuning, causal-LM, MoE), which makes model/optimizer
checkpointing part of its persistence story: long fine-tunes must survive
preemption the same way pipelines survive crashes.

Design: a thin ``TrainCheckpointer`` over ``orbax.checkpoint``'s
``CheckpointManager`` —

* **Sharding-agnostic saves.**  Orbax gathers each array from however it
  is sharded (dp×tp, stage-stacked pp, expert-sharded MoE trees all work);
  what lands on disk is placement-free.
* **Sharding-aware restores.**  ``restore`` takes a ``like`` TrainState
  (typically a fresh ``init``) and re-places every leaf onto that state's
  exact ``NamedSharding`` — so a checkpoint written from one mesh layout
  can resume on another (chips added, tp degree changed) without a
  reshard step.
* **Retention.**  ``max_to_keep`` prunes old steps; ``latest_step`` +
  ``restore(like)`` resumes from the newest checkpoint, mirroring how the
  engine's persistence rewinds to the last committed frontier.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import numpy as np

from pathway_tpu.parallel.train import TrainState


def _abstract_like(tree):
    """ShapeDtypeStructs carrying each concrete leaf's sharding, so orbax
    restores arrays directly onto their target devices.

    Leaves still on a single default device (a fresh ``optimizer.init``
    leaves scalar state like Adam's ``count`` unplaced until the first
    jitted step) are restored REPLICATED over the like-tree's mesh —
    restoring them single-device would clash with the mesh-wide params
    inside the next jitted step.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = None
    for x in jax.tree_util.tree_leaves(tree):
        if isinstance(x, jax.Array) and isinstance(x.sharding, NamedSharding):
            mesh = x.sharding.mesh
            break

    def leaf(x):
        if isinstance(x, jax.Array):
            sh = x.sharding
            if mesh is not None and not isinstance(sh, NamedSharding):
                sh = NamedSharding(mesh, P())
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sh)
        if isinstance(x, np.ndarray):
            return jax.ShapeDtypeStruct(x.shape, x.dtype)
        return x

    return jax.tree_util.tree_map(leaf, tree)


class TrainCheckpointer:
    """Save/restore ``TrainState`` snapshots under ``directory/<step>/``."""

    def __init__(self, directory: str, *, max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep),
        )

    def save(self, state: TrainState, *, wait: bool = True) -> int:
        """Write ``state`` at its step number; returns the step."""
        tree = {"params": state.params, "opt_state": state.opt_state}
        self.manager.save(
            int(state.step), args=self._ocp.args.StandardSave(tree)
        )
        if wait:
            self.manager.wait_until_finished()
        return int(state.step)

    def latest_step(self) -> int | None:
        return self.manager.latest_step()

    def all_steps(self) -> list[int]:
        return sorted(self.manager.all_steps())

    def restore(self, like: TrainState, step: int | None = None) -> TrainState:
        """Restore the checkpoint at ``step`` (default: newest), placing
        every leaf with the sharding of the corresponding ``like`` leaf."""
        if step is None:
            step = self.manager.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no checkpoints under {self.directory!r}"
            )
        abstract = _abstract_like(
            {"params": like.params, "opt_state": like.opt_state}
        )
        tree = self.manager.restore(
            int(step), args=self._ocp.args.StandardRestore(abstract)
        )
        return TrainState(
            params=tree["params"], opt_state=tree["opt_state"], step=int(step)
        )

    def close(self) -> None:
        self.manager.close()

    def __enter__(self) -> "TrainCheckpointer":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()
