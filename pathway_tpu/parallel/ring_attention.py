"""Ring attention: sequence/context parallelism for long sequences.

Shards the sequence axis of bidirectional (encoder) attention over the
device mesh: every chip holds one sequence block of Q/K/V in HBM, and the
K/V blocks rotate around the ring via ``jax.lax.ppermute`` while each chip
accumulates its queries' attention with the online-softmax (flash)
recurrence — running row-max ``m``, denominator ``l``, and weighted sum
``o`` are updated per incoming block, so the full ``[S, S]`` score matrix
never materializes and sequences scale with the number of chips.

The collectives ride ICI: per ring step each chip sends/receives one K
block + one V block + one bias block (its neighbors'), which XLA overlaps
with the local block's compute.  This is the long-context answer the
framework pairs with row-sharded SPMD dataflow: the host engine scales by
key shards, the device path scales batch via data parallelism
(``parallel/train.py``), corpora via the sharded index
(``parallel/index.py``), and sequence length via this module.

The reference has no sequence/context parallelism anywhere (its only axis
is key-shard data parallelism — SURVEY.md §2b/§5); this module is
TPU-native capability beyond the reference, required for long-context
workloads.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pathway_tpu.parallel._compat import pcast, shard_map

NEG_INF = -1e30


def _ring_attention_local(q, k, v, bias, *, heads: int, axis_name: str):
    """Per-device body: q/k/v [B, S_blk, H] packed-lanes, bias [B, S_blk]."""
    B, S_blk, H = q.shape
    hd = H // heads
    scale = 1.0 / (hd**0.5)
    n = jax.lax.psum(1, axis_name)

    # [B, heads, S_blk, hd] — local reshape only; S never gathers
    def split(x):
        return jnp.swapaxes(x.reshape(B, S_blk, heads, hd), 1, 2)

    q4 = split(q.astype(jnp.float32)) * scale
    perm = [(j, (j + 1) % n) for j in range(n)]

    def accumulate(k_blk, v_blk, b_blk, m, l, o):
        k4 = split(k_blk.astype(jnp.float32))
        v4 = split(v_blk.astype(jnp.float32))
        s = jnp.einsum("bhqd,bhkd->bhqk", q4, k4) + b_blk[:, None, None, :]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1)
        o = o * alpha[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, v4)
        return m_new, l, o

    def step(carry, _):
        k_blk, v_blk, b_blk, m, l, o = carry
        m, l, o = accumulate(k_blk, v_blk, b_blk, m, l, o)
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        b_blk = jax.lax.ppermute(b_blk, axis_name, perm)
        return (k_blk, v_blk, b_blk, m, l, o), None

    # mark the accumulator carries device-varying along the ring axis up
    # front (they become varying after one ppermute'd step; scan requires
    # carry types to be loop-invariant)
    def varying(x):
        return pcast(x, (axis_name,), to="varying")

    m0 = varying(jnp.full((B, heads, S_blk), NEG_INF, jnp.float32))
    l0 = varying(jnp.zeros((B, heads, S_blk), jnp.float32))
    o0 = varying(jnp.zeros((B, heads, S_blk, hd), jnp.float32))
    # n-1 rotate-and-accumulate rounds; the final block accumulates without
    # the trailing ppermute round whose result would be discarded
    (k_blk, v_blk, b_blk, m, l, o), _ = jax.lax.scan(
        step, (k, v, bias.astype(jnp.float32), m0, l0, o0), None, length=n - 1
    )
    _, l, o = accumulate(k_blk, v_blk, b_blk, m, l, o)
    out = o / jnp.maximum(l[..., None], 1e-30)
    return jnp.swapaxes(out, 1, 2).reshape(B, S_blk, H).astype(q.dtype)


def ring_attention_traced(
    mesh: Mesh, q, k, v, mask_bias, heads: int, axis: str | None = None
):
    """Jit-traceable form: same computation as
    :func:`ring_encoder_attention` but without the eager ``device_put``
    calls, so it composes inside a larger jitted forward (shard_map
    splits the operands per ``in_specs`` itself).  Used by the
    long-context encoder (``models/long_context.py``)."""
    axis = axis or mesh.axis_names[0]
    B, S, H = q.shape
    n = mesh.shape[axis]
    if S % n:
        raise ValueError(f"sequence length {S} not divisible by mesh axis {n}")
    spec3 = P(None, axis, None)
    spec2 = P(None, axis)
    fn = shard_map(
        functools.partial(_ring_attention_local, heads=heads, axis_name=axis),
        mesh=mesh,
        in_specs=(spec3, spec3, spec3, spec2),
        out_specs=spec3,
    )
    return fn(q, k, v, mask_bias)


def ring_encoder_attention(
    mesh: Mesh, q, k, v, mask_bias, heads: int, axis: str | None = None
):
    """Bidirectional multi-head attention with the sequence axis sharded.

    Args:
      mesh: device mesh; ``axis`` names the sequence axis (defaults to the
        mesh's first axis).
      q, k, v: ``[B, S, H]`` with heads packed in the lane dim; ``S`` must
        divide evenly by the axis size.
      mask_bias: ``[B, S]`` additive key bias (0 valid, ``-1e9`` padded).
    Returns:
      ctx ``[B, S, H]``, sharded like the inputs along ``S``.
    """
    axis = axis or mesh.axis_names[0]
    # eager entry point: pre-place the operands on the mesh, then run the
    # same traced computation.  Check divisibility BEFORE device_put so
    # the caller sees the actionable error, not a sharding failure.
    n = mesh.shape[axis]
    if q.shape[1] % n:
        raise ValueError(
            f"sequence length {q.shape[1]} not divisible by mesh axis {n}"
        )
    sh3 = NamedSharding(mesh, P(None, axis, None))
    sh2 = NamedSharding(mesh, P(None, axis))
    return ring_attention_traced(
        mesh,
        jax.device_put(q, sh3),
        jax.device_put(k, sh3),
        jax.device_put(v, sh3),
        jax.device_put(mask_bias, sh2),
        heads,
        axis,
    )
