"""JAX API compatibility shims for the parallel layer.

``shard_map`` graduated from ``jax.experimental.shard_map`` to the
top-level ``jax.shard_map`` export, and its replication-check kwarg was
renamed ``check_rep`` → ``check_vma`` along the way.  Every module here
imports it from this shim so both vintages work — the container pins an
older jax than the one the newest call-site syntax targets.
"""

from __future__ import annotations

import inspect
from typing import Any

try:  # newer jax: top-level export (kwarg: check_vma)
    from jax import shard_map as _shard_map  # type: ignore[attr-defined]
except ImportError:  # jax 0.4/0.5: experimental home (kwarg: check_rep)
    from jax.experimental.shard_map import shard_map as _shard_map

try:
    _PARAMS = frozenset(inspect.signature(_shard_map).parameters)
except (TypeError, ValueError):  # pragma: no cover - exotic builds
    _PARAMS = frozenset()


def pcast(x: Any, axes: Any, *, to: str = "varying") -> Any:
    """``jax.lax.pcast`` when this build tracks varying-manifest axes;
    identity otherwise (older jax does not type-check carry variance, so
    there is nothing to cast)."""
    import jax

    fn = getattr(jax.lax, "pcast", None)
    if fn is None:
        return x
    return fn(x, axes, to=to)


def shard_map(f: Any, **kwargs: Any) -> Any:
    """``jax.shard_map`` with the replication-check kwarg translated to
    whatever this jax build understands (dropped if it knows neither)."""
    if "check_vma" in kwargs and "check_vma" not in _PARAMS:
        value = kwargs.pop("check_vma")
        if "check_rep" in _PARAMS:
            kwargs["check_rep"] = value
    elif "check_rep" in kwargs and "check_rep" not in _PARAMS:
        value = kwargs.pop("check_rep")
        if "check_vma" in _PARAMS:
            kwargs["check_vma"] = value
    return _shard_map(f, **kwargs)
