"""Mixture-of-Experts layer with expert parallelism (the ``expert`` mesh axis).

The reference serves dense Mistral-class chat models through a host torch
pipeline (``xpacks/llm/llms.py:314``); the MoE siblings of that family
(Mixtral-class) are out of its reach on one GPU.  On TPU they are the
natural scale-out: expert FFN weights shard over an ``expert`` mesh axis,
tokens route to experts through the GShard einsum formulation — dispatch
and combine are dense one-hot contractions, so XLA lowers the token
exchange to ``all_to_all`` over ICI from the sharding annotations alone
(no hand-written collectives, per the scaling-book recipe).

Design points, all MXU/XLA-motivated:

* **Static capacity.**  Each expert processes a fixed ``capacity`` of
  token slots per batch; overflow tokens are dropped from that expert
  (their residual stream passes through unchanged).  Static shapes keep
  the whole layer one compiled program — no data-dependent reshapes.
* **Top-k routing with renormalised gates** (k=2 default, the
  Mixtral/GShard setting): the combine weights of the selected experts
  are renormalised to sum to 1, so with identical experts the layer
  degenerates exactly to the dense FFN (pinned by tests).
* **Load-balance auxiliary loss** (Switch-Transformer form):
  ``E * Σ_e f_e · P_e`` where ``f_e`` is the fraction of tokens whose
  top-1 choice is ``e`` and ``P_e`` the mean router probability — keeps
  routing from collapsing onto one chip's experts.
* **Router in f32.**  Routing decisions are taken in f32 regardless of
  the activation dtype (bf16 softmax ties break non-deterministically
  across backends).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    hidden: int
    experts: int
    intermediate: int
    top_k: int = 2
    capacity_factor: float = 1.25
    # GShard group axis: tokens are chunked into groups of at most this
    # many and dispatched group-locally, so the [G, Tg, E, C] dispatch
    # tensor stays LINEAR in the total token count (C scales with Tg, not
    # T).  0 disables grouping (one global group).
    group_size: int = 4096
    # the lossless serving path (full_capacity=True) sets C = Tg, making
    # the dispatch/combine tensors [G, Tg, E, Tg] — quadratic in the
    # group size.  Serving therefore uses this smaller group (and maps
    # over groups one at a time) so large-batch MoE prefill cannot
    # pressure HBM; 0 falls back to group_size.
    serving_group_size: int = 1024
    dtype: Any = jnp.float32

    def capacity(self, n_tokens: int) -> int:
        """Static per-expert token slots for an ``n_tokens`` group."""
        return max(
            self.top_k,
            int(math.ceil(self.capacity_factor * self.top_k * n_tokens / self.experts)),
        )


def init_moe_params(cfg: MoEConfig, seed: int = 0):
    """Scaled-normal init; expert weights stacked on a leading [E, ...] axis."""
    E, H, F = cfg.experts, cfg.hidden, cfg.intermediate
    keys = jax.random.split(jax.random.PRNGKey(seed), 4)

    def norm_init(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) / np.sqrt(fan_in)).astype(
            cfg.dtype
        )

    return {
        # routing is f32 end-to-end: init directly in f32, never rounded
        # through cfg.dtype
        "router": jax.random.normal(keys[0], (H, E), jnp.float32) / np.sqrt(H),
        "wg": norm_init(keys[1], (E, H, F), H),
        "wu": norm_init(keys[2], (E, H, F), H),
        "wd": norm_init(keys[3], (E, F, H), F),
    }


def ep_param_specs(axis: str = "expert"):
    """Expert-parallel PartitionSpecs: each chip owns ``E / |axis|`` experts'
    FFN weights; the router (tiny) is replicated."""
    return {
        "router": P(None, None),
        "wg": P(axis, None, None),
        "wu": P(axis, None, None),
        "wd": P(axis, None, None),
    }


def _routing(
    router_logits: jnp.ndarray,
    cfg: MoEConfig,
    capacity: int,
    valid: jnp.ndarray | None = None,
):
    """Top-k dispatch/combine tensors from router logits ``[T, E]`` (f32).

    Returns ``(dispatch [T,E,C] bool-ish, combine [T,E,C] f32, aux f32)``.
    Buffer positions are assigned rank-major (every token's first choice
    beats any token's second choice), token-major within a rank — the
    GShard priority order, so capacity overflow drops second opinions
    first.  ``valid`` masks padding tokens out of dispatch, capacity
    accounting, and the aux statistics.
    """
    T, E = router_logits.shape
    K = cfg.top_k
    probs = jax.nn.softmax(router_logits, axis=-1)  # [T, E] f32
    gate_k, idx_k = jax.lax.top_k(probs, K)  # [T, K]
    gate_k = gate_k / jnp.maximum(gate_k.sum(-1, keepdims=True), 1e-9)

    sel = jax.nn.one_hot(idx_k.T, E, dtype=jnp.float32)  # [K, T, E]
    if valid is not None:
        sel = sel * valid.astype(jnp.float32)[None, :, None]
    flat = sel.reshape(K * T, E)
    pos = jnp.cumsum(flat, axis=0) - flat  # buffer slot per (rank, token)
    keep = (pos < capacity).astype(jnp.float32) * flat  # dropped past capacity
    cap_oh = jax.nn.one_hot(pos.astype(jnp.int32), capacity, dtype=jnp.float32)
    disp_flat = keep[..., None] * cap_oh  # [K*T, E, C]
    gates_flat = gate_k.T.reshape(K * T)
    dispatch = disp_flat.reshape(K, T, E, capacity).sum(0)
    combine = (disp_flat * gates_flat[:, None, None]).reshape(
        K, T, E, capacity
    ).sum(0)

    # Switch load-balance loss over top-1 assignment (valid tokens only)
    top1 = jax.nn.one_hot(idx_k[:, 0], E, dtype=jnp.float32)
    if valid is not None:
        v = valid.astype(jnp.float32)[:, None]
        n = jnp.maximum(v.sum(), 1.0)
        frac_tokens = (top1 * v).sum(0) / n
        frac_probs = (probs * v).sum(0) / n
    else:
        frac_tokens = top1.mean(0)
        frac_probs = probs.mean(0)
    aux = E * jnp.sum(frac_tokens * frac_probs)
    return dispatch, combine, aux


def _qeinsum(spec: str, x, w):
    """``einsum`` over a float weight or an int8 weight-only quant pair
    (``{"q", "s"}`` with per-output-channel scales over the contraction
    axis): the dot consumes int8→activation-dtype converts and the scale
    multiplies the OUTPUT (exact for per-output-channel scales)."""
    if isinstance(w, dict) and "q" in w:
        out = jnp.einsum(spec, x, w["q"].astype(x.dtype))
        # s keeps a singleton on the contraction axis, which lines up
        # against the batch-ish axis of the output under broadcasting
        return out * w["s"].astype(x.dtype)[None]
    return jnp.einsum(spec, x, w)


def moe_ffn(
    params,
    x: jnp.ndarray,
    cfg: MoEConfig,
    mesh: Mesh | None = None,
    *,
    full_capacity: bool = False,
):
    """MoE feed-forward over tokens ``x [..., H]`` → ``(y [..., H], aux)``.

    Pure function of sharded inputs: under ``jit`` with ``ep_param_specs``
    placements, the ``gtec,gth->gech`` dispatch einsum (token-sharded ×
    expert-sharded) lowers to an ``all_to_all`` over the ``expert`` axis,
    and the combine einsum to its inverse.  ``mesh`` adds explicit
    sharding constraints on the expert-major intermediates so the
    placement is pinned rather than inferred.

    Tokens beyond the group size are chunked into GShard groups and
    dispatched group-locally (one ragged tail group padded and masked),
    keeping dispatch memory linear in the token count.
    ``full_capacity=True`` gives every token guaranteed slots — capacity
    ``C = Tg`` per group, which no expert can exceed, still linear in the
    token count (``T·E·Tg`` dispatch elements).  The serving paths
    (prefill and single-token decode) use it: capacity drops there would
    silently degrade generations.  Because ``C = Tg`` makes the per-group
    tensors quadratic in the group size, serving uses the smaller
    ``cfg.serving_group_size`` and processes groups one at a time
    (``lax.map``), bounding transient HBM to a single group.  Training
    keeps the capacity-factor drop policy (and the fully vmapped groups),
    which is what makes routing learnable under a static budget.
    """
    orig_shape = x.shape
    H = orig_shape[-1]
    xt = x.reshape(-1, H)
    T = xt.shape[0]
    group_size = cfg.group_size
    if full_capacity and cfg.serving_group_size:
        group_size = (
            min(group_size, cfg.serving_group_size)
            if group_size
            else cfg.serving_group_size
        )
    if not group_size or T <= group_size:
        G, Tg = 1, T
    else:
        G = -(-T // group_size)
        Tg = group_size
    pad = G * Tg - T
    if pad:
        xt = jnp.concatenate([xt, jnp.zeros((pad, H), xt.dtype)], axis=0)
    C = Tg if full_capacity else cfg.capacity(Tg)
    xg = xt.reshape(G, Tg, H)
    router_logits = xg.astype(jnp.float32) @ params["router"]  # [G, Tg, E]
    valid = (jnp.arange(G * Tg) < T).reshape(G, Tg)

    def groups_ffn(router_logits, valid, xg):
        """Dispatch → expert FFN → combine, vectorized over the leading
        group axis; returns (y [G, Tg, H], aux [G])."""
        dispatch, combine, aux_g = jax.vmap(
            lambda lg, vg: _routing(lg, cfg, C, vg)
        )(router_logits, valid)
        dispatch = dispatch.astype(cfg.dtype)
        expert_in = jnp.einsum("gtec,gth->gech", dispatch, xg.astype(cfg.dtype))
        if mesh is not None and "expert" in mesh.axis_names:
            expert_in = jax.lax.with_sharding_constraint(
                expert_in, NamedSharding(mesh, P(None, "expert", None, None))
            )
        h = jax.nn.silu(_qeinsum("gech,ehf->gecf", expert_in, params["wg"]))
        h = h * _qeinsum("gech,ehf->gecf", expert_in, params["wu"])
        expert_out = _qeinsum("gecf,efh->gech", h, params["wd"])
        if mesh is not None and "expert" in mesh.axis_names:
            expert_out = jax.lax.with_sharding_constraint(
                expert_out, NamedSharding(mesh, P(None, "expert", None, None))
            )
        y = jnp.einsum("gtec,gech->gth", combine.astype(cfg.dtype), expert_out)
        return y, aux_g

    if full_capacity and G > 1:
        # one group live at a time: the [Tg, E, Tg] serving dispatch
        # tensors never materialize for all groups together
        y_g, aux_g = jax.lax.map(
            lambda a: jax.tree_util.tree_map(
                lambda t: t[0], groups_ffn(a[0][None], a[1][None], a[2][None])
            ),
            (router_logits, valid, xg),
        )
    else:
        y_g, aux_g = groups_ffn(router_logits, valid, xg)

    # aux: weighted mean over groups by their real-token counts
    w = valid.astype(jnp.float32).sum(axis=1)
    aux = (aux_g * w).sum() / jnp.maximum(w.sum(), 1.0)
    y = y_g.reshape(G * Tg, H)[:T]
    return y.reshape(orig_shape).astype(x.dtype), aux


def make_ep_mesh(n_devices: int, expert_parallel: int | None = None) -> Mesh:
    """A ``("data", "expert")`` mesh: expert axis as large as divides both
    the device count and nothing else — callers pass ``expert_parallel``
    to pin it (defaults to all devices on the expert axis)."""
    devices = jax.devices()[:n_devices]
    ep = expert_parallel or len(devices)
    assert len(devices) % ep == 0, (len(devices), ep)
    grid = np.asarray(devices).reshape(len(devices) // ep, ep)
    return Mesh(grid, ("data", "expert"))


def make_moe_train_step(
    cfg: MoEConfig,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    *,
    aux_weight: float = 0.01,
) -> tuple[Callable, Callable]:
    """Expert-parallel training: tokens sharded over ``data``, expert
    weights over ``expert``; the objective is denoising regression (fit
    the layer to a fixed random target map), enough to drive gradients
    through routing, dispatch and both collectives.

    Returns ``(init_fn, step_fn)`` where ``step_fn(params, opt_state, x,
    target) -> (params, opt_state, loss)`` is jitted SPMD.
    """
    from pathway_tpu.parallel.mesh import put_global

    specs = ep_param_specs()

    def init_fn(seed: int = 0):
        params = init_moe_params(cfg, seed)
        params = jax.tree_util.tree_map(
            lambda t, s: jax.device_put(t, NamedSharding(mesh, s)), params, specs
        )
        return params, optimizer.init(params)

    def loss_fn(params, x, target):
        y, aux = moe_ffn(params, x, cfg, mesh)
        mse = jnp.mean(jnp.square(y.astype(jnp.float32) - target.astype(jnp.float32)))
        return mse + aux_weight * aux

    @jax.jit
    def _step(params, opt_state, x, target):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, target)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    data_sharding = NamedSharding(mesh, P("data"))

    def step_fn(params, opt_state, x, target):
        x = put_global(np.asarray(x), data_sharding)
        target = put_global(np.asarray(target), data_sharding)
        return _step(params, opt_state, x, target)

    return init_fn, step_fn
