"""Sharding rules for encoder parameters and batches.

Tensor parallelism: 2-D kernels split on their output (last) dimension over
the ``model`` axis when divisible; embeddings split on the vocab dimension;
everything else (biases, LayerNorm scales) is replicated.  XLA derives the
matching collectives (all-reduce of activations at layer boundaries) from
these annotations — the pjit analog of hand-placed NCCL calls.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pathway_tpu.parallel.mesh import put_global


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def _spec_for(path: tuple, leaf, model_size: int) -> P:
    if leaf.ndim >= 2:
        # embedding tables: shard the (large) vocab/row dimension
        name = "/".join(str(p) for p in path).lower()
        if "embed" in name and leaf.shape[0] % model_size == 0:
            return P(*(("model",) + (None,) * (leaf.ndim - 1)))
        # dense kernels: shard the output features
        if leaf.shape[-1] % model_size == 0 and leaf.shape[-1] >= model_size:
            return P(*((None,) * (leaf.ndim - 1) + ("model",)))
    return P()


def shard_params(params, mesh: Mesh):
    """Place a parameter pytree on the mesh with tensor-parallel sharding."""
    model_size = mesh.shape.get("model", 1)

    def place(path, leaf):
        spec = _spec_for(tuple(k.key if hasattr(k, "key") else str(k) for k in path), leaf, model_size)
        return put_global(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(place, params)


def shard_batch(batch, mesh: Mesh):
    """Shard leading (batch) dimension over the ``data`` axis."""
    sharding = NamedSharding(mesh, P("data"))

    def place(leaf):
        return put_global(leaf, sharding)

    return jax.tree_util.tree_map(place, batch)
