"""Corpus-sharded device index: distributed exact top-k over the mesh.

TPU-native replacement for the reference's external-index-per-worker model
(``src/external_integration/``): the document embedding matrix is sharded
row-wise over *all* chips (each chip's slice is the analog of one worker's
key-shard), queries are replicated, and retrieval is

    local MXU einsum → local top-k → all_gather of k candidates/chip →
    final top-k

so the payload crossing ICI is ``n_chips × k`` (id, score) pairs per query —
vectors never leave HBM, matching SURVEY.md §5's "exchange channels carry
only row ids" mapping.  Written with ``jax.shard_map`` so the collective
schedule is explicit; everything inside is jit-compiled.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pathway_tpu.parallel._compat import shard_map as _shard_map


def _flat_axis_index(axes: tuple[str, ...], mesh: Mesh):
    idx = lax.axis_index(axes[0])
    for ax in axes[1:]:
        idx = idx * mesh.shape[ax] + lax.axis_index(ax)
    return idx


@functools.partial(jax.jit, static_argnames=("k", "mesh", "axes", "metric"))
def _sharded_topk_impl(
    docs, mask, queries, *, k: int, mesh: Mesh, axes: tuple[str, ...], metric: str = "ip"
):
    n_chips = 1
    for ax in axes:
        n_chips *= mesh.shape[ax]
    # per-shard candidate count: k capped at the shard's row count; the
    # merge then sees n_chips * k_local >= k candidates (callers cap k at n)
    k_local = min(k, docs.shape[0] // n_chips)

    def local(docs_blk, mask_blk, q):
        # shared metric definition — scores match the single-chip path
        # (ops/topk.py score_block) bit-for-bit
        from pathway_tpu.ops.topk import exact_topk, score_block

        scores = score_block(docs_blk, q, metric)
        # keep the GEMM out of the top_k fusion (see ops/topk.py — 18x on
        # the CPU backend, harmless on TPU)
        scores = lax.optimization_barrier(scores) + mask_blk[None, :]
        # two-stage exact top-k: a full sort over the shard's megarow
        # (not the GEMM) is what dominates large-corpus latency
        vals, idx = exact_topk(scores, k_local)
        shard = _flat_axis_index(axes, mesh)
        idx = idx + shard * docs_blk.shape[0]
        vals_g = lax.all_gather(vals, axes, axis=1, tiled=True)
        idx_g = lax.all_gather(idx, axes, axis=1, tiled=True)
        best_vals, pos = lax.top_k(vals_g, k)
        return jnp.take_along_axis(idx_g, pos, axis=1), best_vals

    return _shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axes, None), P(axes), P(None, None)),
        out_specs=(P(None, None), P(None, None)),
        check_vma=False,
    )(docs, mask, queries)


def sharded_topk(
    mesh: Mesh,
    docs: jax.Array,
    mask: jax.Array,
    queries: jax.Array,
    k: int,
    metric: str = "ip",
) -> tuple[jax.Array, jax.Array]:
    """(indices, scores) of the k best doc rows per query, across all chips."""
    axes = tuple(mesh.axis_names)
    return _sharded_topk_impl(
        docs, mask, queries, k=k, mesh=mesh, axes=axes, metric=metric
    )


class ShardedDeviceIndex:
    """A padded, corpus-sharded embedding index resident across chip HBM.

    Capacity grows in multiples of ``n_chips × block`` so every chip holds
    an equal slice and streaming growth hits a warm compile cache.  Padded
    rows carry a ``-inf`` score mask.  Cosine similarity assumes rows are
    L2-normalized (the encoders in ``models/encoder.py`` guarantee this).
    """

    def __init__(self, mesh: Mesh, dim: int, block: int = 1024, dtype=None):
        self.mesh = mesh
        self.dim = dim
        self.n_chips = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
        self.block = block
        # north-star layout stores the corpus in bf16 (HBM: 2 bytes/dim —
        # 10M x 384 over 16 chips = 480 MB/chip); score_block casts to the
        # matmul dtype per backend, so storage dtype only sets memory
        self.dtype = np.float32 if dtype is None else dtype
        self._n = 0
        self._docs = None
        self._mask = None
        self._host_rows: list[np.ndarray] = []
        self._dirty = False

    def __len__(self) -> int:
        return self._n

    def add(self, vectors: np.ndarray) -> None:
        vectors = np.atleast_2d(np.asarray(vectors, np.float32))
        self._host_rows.append(vectors)
        self._n += vectors.shape[0]
        self._dirty = True

    def _capacity(self, n: int) -> int:
        unit = self.n_chips * self.block
        return max(unit, ((n + unit - 1) // unit) * unit)

    def _sync(self) -> None:
        if not self._dirty:
            return
        full = (
            np.concatenate(self._host_rows, axis=0)
            if self._host_rows
            else np.zeros((0, self.dim), np.float32)
        )
        cap = self._capacity(self._n)
        padded = np.zeros((cap, self.dim), self.dtype)
        padded[: self._n] = full
        mask = np.full((cap,), -np.inf, np.float32)
        mask[: self._n] = 0.0
        axes = tuple(self.mesh.axis_names)
        from pathway_tpu.parallel.mesh import put_global

        self._docs = put_global(padded, NamedSharding(self.mesh, P(axes, None)))
        self._mask = put_global(mask, NamedSharding(self.mesh, P(axes)))
        self._dirty = False

    def search(self, queries: np.ndarray, k: int) -> tuple[np.ndarray, np.ndarray]:
        if self._n == 0:
            q = np.atleast_2d(queries)
            return (
                np.zeros((q.shape[0], 0), np.int64),
                np.zeros((q.shape[0], 0), np.float32),
            )
        self._sync()
        from pathway_tpu.parallel.mesh import put_global

        # queries are replicated; route through put_global so a mesh that
        # spans hosts still accepts them (device_put cannot target
        # non-addressable devices)
        q = put_global(
            np.atleast_2d(np.asarray(queries, np.float32)),
            NamedSharding(self.mesh, P(None, None)),
        )
        k_eff = min(k, self._n)
        idx, vals = sharded_topk(self.mesh, self._docs, self._mask, q, k_eff)
        return np.asarray(idx), np.asarray(vals)
