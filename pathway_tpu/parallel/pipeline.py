"""Pipeline parallelism for the decoder family (GPipe schedule over a
``stage`` mesh axis).

The reference's only parallel axis is data sharding over SPMD workers
(SURVEY.md §2b); models that do not fit one device's memory are out of its
reach.  Here the decoder trunk splits into ``n_stages`` contiguous layer
groups, one per chip along a ``stage`` mesh axis, and microbatches stream
through the classic GPipe schedule: ``n_micro + n_stages - 1`` ticks, each
stage processing one microbatch per tick while activations rotate to the
next stage via ``ppermute`` (one hop over ICI per tick — the collective
pattern from the scaling-book pipelining chapter).

TPU-first design notes:

* **One compiled program.**  The whole schedule is a ``lax.scan`` over
  ticks inside a single ``shard_map`` — every stage runs the same SPMD
  code, XLA overlaps the ``ppermute`` with the next tick's matmuls.
* **Static schedule.**  Bubble ticks compute on zero activations with an
  all-False attention mask (finite by construction — uniform softmax over
  a constant row) and their results are discarded; no data-dependent
  control flow, no recompiles.
* **Backward = autodiff.**  The pipelined forward is a pure jittable
  function; ``jax.grad`` differentiates through ``ppermute`` (its
  transpose is the reverse rotation), giving pipeline-parallel training
  without a hand-written backward schedule.
* Embedding and the LM head are computed outside the pipeline on the
  full batch (replicated params — they are a few percent of weights);
  the stage axis carries only the transformer trunk, which is where the
  per-layer weight memory lives.

Microbatch inputs are replicated to every stage (the GPipe "all inputs
visible" simplification): memory cost ``n_micro × mb × S × H`` per chip,
negligible next to stage weights at serving shapes.  A production
refinement would stream microbatches into stage 0 only.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pathway_tpu.parallel._compat import shard_map

from pathway_tpu.models.decoder import DecoderConfig, decoder_layer, _rms, _sw_mask


def make_pp_mesh(n_stages: int) -> Mesh:
    """A 1-D ``("stage",)`` mesh over the first ``n_stages`` devices."""
    devices = jax.devices()[:n_stages]
    return Mesh(np.asarray(devices).reshape(n_stages), ("stage",))


def stack_stages(tree, n_stages: int):
    """Reshape the decoder's stacked layer tree ``[L, ...]`` into
    ``[n_stages, L/n_stages, ...]`` so stage ``s`` owns rows ``[s]``."""
    L = jax.tree_util.tree_leaves(tree["layers"])[0].shape[0]
    if L % n_stages:
        raise ValueError(f"{L} layers do not split into {n_stages} stages")
    lps = L // n_stages
    return {
        **tree,
        "layers": jax.tree_util.tree_map(
            lambda p: p.reshape(n_stages, lps, *p.shape[1:]), tree["layers"]
        ),
    }


def pp_param_specs(tree, axis: str = "stage"):
    """PartitionSpecs for the stage-stacked ``tree``: every layer leaf
    (whatever its name — dense or MoE) shards its leading stage axis;
    embed/norm/head replicated (computed off-pipeline)."""
    return {
        "embed": P(None, None),
        "final_norm": P(None),
        "lm_head": P(None, None),
        "layers": jax.tree_util.tree_map(lambda _: P(axis), tree["layers"]),
    }


def place_pp_params(tree, mesh: Mesh):
    """Stack ``tree`` by the mesh's stage count and shard it."""
    n_stages = mesh.shape["stage"]
    stacked = stack_stages(tree, n_stages)
    specs = pp_param_specs(stacked)
    return jax.tree_util.tree_map(
        lambda t, s: jax.device_put(t, NamedSharding(mesh, s)), stacked, specs
    )


def _stage_forward(stage_layers, x, valid, cfg: DecoderConfig):
    """Run one stage's layer rows over activations ``x [mb, S, H]``."""
    S = x.shape[1]
    positions = jnp.arange(S)[None, :].repeat(x.shape[0], axis=0)
    causal = jnp.tril(jnp.ones((S, S), bool))
    if cfg.sliding_window is not None:
        causal = causal & _sw_mask(
            jnp.arange(S)[:, None], jnp.arange(S)[None, :], cfg.sliding_window
        )
    mask = causal[None, :, :] & (valid > 0)[:, None, :]

    def body(x, lp):
        # the pipelined trunk is a serving path (MoE training under pp is
        # rejected), so MoE dispatch runs lossless
        x, _, _ = decoder_layer(lp, x, positions, mask, cfg, full_capacity=True)
        return x, None

    if cfg.remat:
        # honor the memory knob under pp training too: each stage's
        # backward recomputes its layers instead of storing activations
        body = jax.checkpoint(body, prevent_cse=False)
    x, _ = lax.scan(body, x, stage_layers)
    return x


def make_pipelined_causal_lm(
    cfg: DecoderConfig, mesh: Mesh, n_micro: int
) -> Callable:
    """Pipelined all-position logits: ``fn(tree, ids, lengths) -> [B, S, V]``.

    ``tree`` is a stage-stacked param tree (``place_pp_params``); the
    batch ``B = n_micro × mb`` splits into microbatches along its leading
    axis.  Matches ``causal_lm_logits`` within tight f32 tolerance (pinned
    by tests at 2e-4) — the schedule changes the execution order, not the
    math.

    MoE configs pipeline too, with LOSSLESS expert dispatch (the pipelined
    trunk is a serving path — MoE training under pp is rejected), so it
    matches ``causal_lm_logits`` — whose training-policy dispatch can drop
    tokens — only when the trunk drops nothing (ample capacity factor; the
    MoE pinning test uses 16.0).  The aux loss is not collected — see
    ``make_pp_train_step``.
    """
    n_stages = mesh.shape["stage"]
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    n_ticks = n_micro + n_stages - 1

    def trunk(stage_layers, xs, valids):
        # stage_layers: this stage's rows [1, Lps, ...]; xs [n_micro, mb, S, H]
        stage_layers = jax.tree_util.tree_map(lambda p: p[0], stage_layers)
        stage = lax.axis_index("stage")
        state_x = jnp.zeros_like(xs[0])
        state_valid = jnp.zeros_like(valids[0])
        outputs = jnp.zeros_like(xs)

        def tick(carry, t):
            state_x, state_valid, outputs = carry
            inj = jnp.clip(t, 0, n_micro - 1)
            in_x = lax.dynamic_index_in_dim(xs, inj, 0, keepdims=False)
            in_v = lax.dynamic_index_in_dim(valids, inj, 0, keepdims=False)
            first = stage == 0
            x = jnp.where(first, in_x, state_x)
            valid = jnp.where(first, in_v, state_valid)
            y = _stage_forward(stage_layers, x, valid, cfg)
            out_idx = t - (n_stages - 1)
            outputs = jnp.where(
                (stage == n_stages - 1) & (out_idx >= 0),
                lax.dynamic_update_index_in_dim(
                    outputs, y, jnp.clip(out_idx, 0, n_micro - 1), 0
                ),
                outputs,
            )
            state_x = lax.ppermute(y, "stage", perm)
            state_valid = lax.ppermute(valid, "stage", perm)
            return (state_x, state_valid, outputs), None

        (_, _, outputs), _ = lax.scan(
            tick, (state_x, state_valid, outputs), jnp.arange(n_ticks)
        )
        # only the last stage holds real outputs; psum broadcasts them
        outputs = jnp.where(stage == n_stages - 1, outputs, jnp.zeros_like(outputs))
        return lax.psum(outputs, "stage")

    trunk_sm = shard_map(
        trunk,
        mesh=mesh,
        # P("stage") is a tree prefix: every layer leaf (dense or MoE)
        # shards its leading stage axis
        in_specs=(P("stage"), P(None), P(None)),
        out_specs=P(None),
        check_vma=False,
    )

    def fn(tree, ids, lengths):
        B, S = ids.shape
        if B % n_micro:
            raise ValueError(f"batch {B} not divisible by n_micro={n_micro}")
        mb = B // n_micro
        x = tree["embed"][ids]  # [B, S, H]
        positions = jnp.arange(S)[None, :]
        valid = (positions < lengths[:, None]).astype(jnp.int32)
        xs = x.reshape(n_micro, mb, S, cfg.hidden)
        valids = valid.reshape(n_micro, mb, S)
        out = trunk_sm(tree["layers"], xs, valids)
        x = out.reshape(B, S, cfg.hidden)
        x = _rms(x, tree["final_norm"], cfg.norm_eps)
        return (x @ tree["lm_head"]).astype(jnp.float32)

    return fn


def make_pp_train_step(
    cfg: DecoderConfig,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    n_micro: int,
) -> tuple[Callable, Callable]:
    """Pipeline-parallel next-token training.

    Returns ``(init_state, run)``; same loss as
    ``make_causal_lm_train_step`` but the decoder trunk executes under the
    GPipe schedule with stage-sharded weights — backward runs through the
    transposed ``ppermute`` rotation automatically.
    """
    from pathway_tpu.models.decoder import init_decoder_params
    from pathway_tpu.parallel.train import TrainState, masked_next_token_loss

    if cfg.experts:
        raise NotImplementedError(
            "pipeline-parallel MoE training is not supported: the MoE "
            "load-balance aux loss is not threaded through the GPipe "
            "schedule (it would be silently dropped) — train MoE decoders "
            "with make_causal_lm_train_step (dp×tp×ep) instead; the "
            "pipelined FORWARD supports MoE configs"
        )

    fwd = make_pipelined_causal_lm(cfg, mesh, n_micro)

    def init_state(seed: int = 0) -> TrainState:
        tree = place_pp_params(init_decoder_params(cfg, seed), mesh)
        return TrainState(params=tree, opt_state=optimizer.init(tree))

    def loss_fn(tree, ids, lengths):
        return masked_next_token_loss(fwd(tree, ids, lengths), ids, lengths)

    @jax.jit
    def step(params, opt_state, ids, lengths):
        loss, grads = jax.value_and_grad(loss_fn)(params, ids, lengths)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    def run(state: TrainState, ids, lengths):
        ids = jnp.asarray(np.asarray(ids, np.int32))
        lengths = jnp.asarray(np.asarray(lengths, np.int32))
        params, opt_state, loss = step(state.params, state.opt_state, ids, lengths)
        return (
            TrainState(params=params, opt_state=opt_state, step=state.step + 1),
            loss,
        )

    return init_state, run
