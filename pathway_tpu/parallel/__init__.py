"""SPMD distribution over TPU device meshes.

Parity target: SURVEY.md §2b. The reference's only parallelism is data
parallelism by key-shard over identical dataflow replicas — timely workers
exchanging ``(Key, Value, Timestamp, diff)`` tuples over shared memory or
zero-copy TCP (``external/timely-dataflow/communication/``,
``src/engine/dataflow/shard.rs``).  The TPU-native mapping replaces the
row-tuple exchange with XLA collectives over ICI:

* host rows are sharded by the 16-bit shard field of the 128-bit key,
  exactly like the reference (``src/engine/value.rs:38``);
* dense state (embedding matrices, index shards) stays resident in HBM,
  sharded over the mesh; queries move, vectors do not;
* the compute path (encoder fwd/bwd, top-k retrieval) is pjit-compiled
  SPMD — XLA inserts ``all_gather``/``psum``/``reduce_scatter`` from the
  sharding annotations instead of hand-written NCCL/MPI calls.

Mesh convention: 2-D ``("data", "model")``. Batch/data parallelism rides
the ``data`` axis; tensor parallelism of encoder weights rides ``model``;
the document index is sharded over the *flattened* mesh (every chip holds
one slice of the corpus — the analog of the reference's key-shard space).
Further axes for the decoder family: ``("stage",)`` pipeline meshes
(``pipeline.py``, GPipe over ``ppermute``), ``("data", "expert")`` MoE
meshes (``moe.py``, GShard dispatch lowering to ``all_to_all``), and the
sequence-parallel ring (``ring_attention.py``).
"""

from __future__ import annotations

from pathway_tpu.parallel.mesh import (
    flat_axes,
    get_default_index_mesh,
    initialize_distributed,
    make_mesh,
    mesh_shape_for,
    put_global,
    set_default_index_mesh,
)
from pathway_tpu.parallel.sharding import (
    replicated,
    shard_batch,
    shard_params,
)
from pathway_tpu.parallel.train import (
    TrainState,
    make_causal_lm_train_step,
    make_contrastive_train_step,
    init_train_state,
)
from pathway_tpu.parallel.index import ShardedDeviceIndex, sharded_topk
from pathway_tpu.parallel.ring_attention import ring_encoder_attention
from pathway_tpu.parallel.moe import (
    MoEConfig,
    ep_param_specs,
    init_moe_params,
    make_ep_mesh,
    make_moe_train_step,
    moe_ffn,
)
from pathway_tpu.parallel.pipeline import (
    make_pipelined_causal_lm,
    make_pp_mesh,
    make_pp_train_step,
    place_pp_params,
    pp_param_specs,
)
from pathway_tpu.parallel.checkpoint import TrainCheckpointer

__all__ = [
    "initialize_distributed",
    "put_global",
    "make_mesh",
    "mesh_shape_for",
    "flat_axes",
    "set_default_index_mesh",
    "get_default_index_mesh",
    "shard_params",
    "shard_batch",
    "replicated",
    "TrainState",
    "init_train_state",
    "make_causal_lm_train_step",
    "make_contrastive_train_step",
    "ShardedDeviceIndex",
    "sharded_topk",
    "ring_encoder_attention",
    "MoEConfig",
    "init_moe_params",
    "ep_param_specs",
    "make_ep_mesh",
    "make_moe_train_step",
    "moe_ffn",
    "make_pp_mesh",
    "pp_param_specs",
    "place_pp_params",
    "make_pipelined_causal_lm",
    "make_pp_train_step",
    "TrainCheckpointer",
]
