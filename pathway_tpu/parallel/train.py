"""Distributed contrastive training step for the embedding encoders.

The reference consumes frozen sentence-transformer checkpoints; a TPU-native
framework should also be able to *adapt* its embedders in place (the same
InfoNCE objective sentence-transformers models are trained with).  This is
the framework's full distributed train step: data-parallel batch over the
``data`` axis, tensor-parallel encoder weights over ``model``, gradients
psum-reduced by XLA from the sharding annotations alone.

(The reference has no model training at all — SURVEY.md §2b.  Beyond the
dp×tp step here, ``parallel/pipeline.py`` adds the GPipe stage axis and
``parallel/moe.py`` the expert axis; together with the sequence-parallel
ring (``ring_attention.py``) and the corpus-sharded index
(``index.py``) the framework computes over all five dp/tp/pp/sp/ep axes.)
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pathway_tpu.parallel.mesh import put_global
from pathway_tpu.parallel.sharding import shard_params


@dataclasses.dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: int = 0


def init_train_state(
    module,
    mesh: Mesh,
    optimizer: optax.GradientTransformation,
    *,
    seq_len: int = 16,
    seed: int = 0,
) -> tuple[TrainState, optax.GradientTransformation]:
    rng = jax.random.PRNGKey(seed)
    dummy = jnp.zeros((1, seq_len), jnp.int32)
    params = module.init(rng, dummy, jnp.ones((1, seq_len), jnp.int32))
    params = shard_params(params, mesh)
    opt_state = optimizer.init(params)
    return TrainState(params=params, opt_state=opt_state), optimizer


def make_contrastive_train_step(
    module,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    *,
    temperature: float = 0.05,
) -> Callable:
    """jit-compiled SPMD step: (state, ids_a, mask_a, ids_b, mask_b) -> (state, loss).

    Symmetric InfoNCE over in-batch negatives.  Batch arrives sharded over
    ``data``; the logits matrix ``za @ zb.T`` is a cross-shard einsum, so XLA
    all-gathers the (small) embedding vectors over ICI while the (large)
    activations never leave their chip.
    """

    def loss_fn(params, ids_a, mask_a, ids_b, mask_b):
        za = module.apply(params, ids_a, mask_a)
        zb = module.apply(params, ids_b, mask_b)
        logits = (za @ zb.T) / temperature
        labels = jnp.arange(logits.shape[0])
        l_ab = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
        l_ba = optax.softmax_cross_entropy_with_integer_labels(logits.T, labels)
        return 0.5 * (jnp.mean(l_ab) + jnp.mean(l_ba))

    @jax.jit
    def step(params, opt_state, ids_a, mask_a, ids_b, mask_b):
        loss, grads = jax.value_and_grad(loss_fn)(params, ids_a, mask_a, ids_b, mask_b)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    batch_sharding = NamedSharding(mesh, P("data"))

    def run(state: TrainState, ids_a, mask_a, ids_b, mask_b) -> tuple[TrainState, float]:
        import numpy as _np

        args = [
            put_global(_np.asarray(x, _np.int32), batch_sharding)
            for x in (ids_a, mask_a, ids_b, mask_b)
        ]
        params, opt_state, loss = step(state.params, state.opt_state, *args)
        return TrainState(params=params, opt_state=opt_state, step=state.step + 1), loss

    return run


def masked_next_token_loss(logits, ids, lengths):
    """Length-masked next-token NLL shared by the dp×tp and pipeline-parallel
    causal-LM train steps (``parallel/pipeline.py``) — one definition so the
    two paths cannot drift."""
    targets = ids[:, 1:]
    logp = jax.nn.log_softmax(logits[:, :-1, :], axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    pos = jnp.arange(ids.shape[1] - 1)[None, :]
    m = (pos < (lengths - 1)[:, None]).astype(jnp.float32)
    return -(ll * m).sum() / jnp.maximum(m.sum(), 1.0)


def make_lm_step_runner(
    cfg,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    *,
    moe_aux_weight: float = 0.01,
) -> Callable:
    """The shared causal-LM training core: jitted value_and_grad step over
    ``masked_next_token_loss`` (+ MoE aux) with the batch sharded over
    ``data``.  One definition serves full fine-tuning below and LoRA
    (``models/lora.py``) so the loss/step semantics cannot drift."""
    from pathway_tpu.models.decoder import causal_lm_logits_and_aux

    def loss_fn(tree, ids, lengths):
        logits, aux = causal_lm_logits_and_aux(tree, ids, lengths, cfg)
        # aux is exactly 0 for dense configs, so one code path serves both
        return masked_next_token_loss(logits, ids, lengths) + moe_aux_weight * aux

    @jax.jit
    def step(params, opt_state, ids, lengths):
        loss, grads = jax.value_and_grad(loss_fn)(params, ids, lengths)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    batch_sharding = NamedSharding(mesh, P("data"))

    def run(state: TrainState, ids, lengths) -> tuple[TrainState, float]:
        import numpy as _np

        ids = put_global(_np.asarray(ids, _np.int32), batch_sharding)
        lengths = put_global(_np.asarray(lengths, _np.int32), batch_sharding)
        params, opt_state, loss = step(state.params, state.opt_state, ids, lengths)
        return (
            TrainState(params=params, opt_state=opt_state, step=state.step + 1),
            loss,
        )

    return run


def make_causal_lm_train_step(
    cfg,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    *,
    moe_aux_weight: float = 0.01,
) -> tuple[Callable, Callable]:
    """Distributed next-token training for the decoder LLM family.

    Returns ``(init_state, run)``: data-parallel batch over ``data``,
    tensor-parallel decoder weights over ``model`` (the same
    ``tp_param_specs`` layout serving uses — train and serve share one
    placement, so fine-tuned weights drop straight into ``DecoderLM``).
    Loss is masked next-token cross-entropy; gradients are psum-reduced by
    XLA from the sharding annotations alone.
    """
    from pathway_tpu.models.decoder import init_decoder_params, tp_param_specs

    def init_state(seed: int = 0) -> TrainState:
        tree = init_decoder_params(cfg, seed)
        specs = tp_param_specs(cfg)
        tree = jax.tree_util.tree_map(
            lambda t, s: jax.device_put(t, NamedSharding(mesh, s)), tree, specs
        )
        return TrainState(params=tree, opt_state=optimizer.init(tree))

    run = make_lm_step_runner(cfg, optimizer, mesh, moe_aux_weight=moe_aux_weight)
    return init_state, run
