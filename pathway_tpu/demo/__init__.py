"""``pw.demo`` — synthetic streams (parity: python/pathway/demo/__init__.py:28-310)."""

from __future__ import annotations

import csv as _csv
import time as _time
from typing import Any, Callable, Mapping

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.table import Table
from pathway_tpu.io import _utils
from pathway_tpu.io._utils import COMMIT, Reader
from pathway_tpu.io.python import ConnectorSubject


class _GeneratorReader(Reader):
    def __init__(self, nb_rows, row_fn, input_rate):
        self.nb_rows = nb_rows
        self.row_fn = row_fn
        self.input_rate = input_rate

    def run(self, emit) -> None:
        i = 0
        while self.nb_rows is None or i < self.nb_rows:
            emit(self.row_fn(i))
            emit(COMMIT)
            i += 1
            if self.input_rate:
                _time.sleep(1.0 / self.input_rate)


def generate_custom_stream(
    value_generators: Mapping[str, Callable[[int], Any]],
    *,
    schema: type[schema_mod.Schema],
    nb_rows: int | None = None,
    autocommit_duration_ms: int = 1000,
    input_rate: float = 1.0,
    persistent_id: str | None = None,
) -> Table:
    """Generate a stream from per-column generator functions.

    Example:

    >>> import pathway_tpu as pw
    >>> class S(pw.Schema):
    ...     n: int
    >>> t = pw.demo.generate_custom_stream(
    ...     value_generators={'n': lambda i: i * i},
    ...     schema=S,
    ...     nb_rows=3,
    ...     autocommit_duration_ms=10,
    ...     input_rate=1000.0,
    ... )
    >>> pw.debug.compute_and_print(t, include_id=False)
    n
    0
    1
    4
    """

    def row_fn(i: int) -> dict:
        return {name: gen(i) for name, gen in value_generators.items()}

    return _utils.make_input_table(
        schema,
        lambda: _GeneratorReader(nb_rows, row_fn, input_rate),
        autocommit_duration_ms=autocommit_duration_ms,
    )


def noisy_linear_stream(nb_rows: int = 10, input_rate: float = 1.0) -> Table:
    """y ≈ x with noise (docs tutorial stream)."""
    import random

    schema = schema_mod.schema_from_types(x=float, y=float)
    rng = random.Random(0)

    def row_fn(i: int) -> dict:
        return {"x": float(i), "y": float(i) + (2.0 * rng.random() - 1.0)}

    return _utils.make_input_table(
        schema, lambda: _GeneratorReader(nb_rows, row_fn, input_rate)
    )


def range_stream(
    nb_rows: int = 30, offset: int = 0, input_rate: float = 1.0, autocommit_duration_ms: int = 1000
) -> Table:
    schema = schema_mod.schema_from_types(value=float)

    def row_fn(i: int) -> dict:
        return {"value": float(i + offset)}

    return _utils.make_input_table(
        schema,
        lambda: _GeneratorReader(nb_rows, row_fn, input_rate),
        autocommit_duration_ms=autocommit_duration_ms,
    )


def replay_csv(
    path: str,
    *,
    schema: type[schema_mod.Schema],
    input_rate: float = 1.0,
) -> Table:
    """Replay a CSV file as a stream at input_rate rows/sec."""
    names = list(schema.__columns__.keys())
    dtypes = {n: schema.__columns__[n].dtype for n in names}

    class _ReplayReader(Reader):
        def run(self, emit) -> None:
            from pathway_tpu.io.csv import _convert

            with open(path, newline="") as f:
                for row in _csv.DictReader(f):
                    emit({n: _convert(row.get(n), dtypes[n]) for n in names})
                    emit(COMMIT)
                    if input_rate:
                        _time.sleep(1.0 / input_rate)

    return _utils.make_input_table(schema, _ReplayReader)


def replay_csv_with_time(
    path: str,
    *,
    schema: type[schema_mod.Schema],
    time_column: str,
    unit: str = "s",
    autocommit_ms: int = 100,
    speedup: float = 1,
) -> Table:
    """Replay a CSV using its own time column to pace the stream."""
    names = list(schema.__columns__.keys())
    dtypes = {n: schema.__columns__[n].dtype for n in names}
    div = {"s": 1.0, "ms": 1e3, "us": 1e6, "ns": 1e9}[unit] * speedup

    class _ReplayReader(Reader):
        def run(self, emit) -> None:
            from pathway_tpu.io.csv import _convert

            prev_t = None
            with open(path, newline="") as f:
                for row in _csv.DictReader(f):
                    parsed = {n: _convert(row.get(n), dtypes[n]) for n in names}
                    t = parsed.get(time_column)
                    if prev_t is not None and t is not None:
                        delay = (t - prev_t) / div
                        if delay > 0:
                            _time.sleep(min(delay, 10.0))
                    prev_t = t if t is not None else prev_t
                    emit(parsed)
                    emit(COMMIT)

    return _utils.make_input_table(schema, _ReplayReader)
