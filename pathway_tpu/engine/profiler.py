"""Performance observability: per-operator epoch profiler + JAX accounting.

Two measurement surfaces the perf arc (DeviceExecutor batching, columnar
hot path, serving loop — see ROADMAP.md) is pinned on:

* **Per-operator epoch profiler** (:class:`EpochProfiler`).  The epoch
  loop already stamps every operator step with a monotonic timer and row
  counters (``engine/dataflow.py:Scope.run_epoch`` accumulates
  ``node.step_seconds`` / ``rows_in`` / ``rows_out``); the profiler turns
  those always-on counters into top-N attribution snapshots at a sampled
  cadence — *where the epoch time went*, by operator.  Snapshots export
  through the unified metrics registry (``profiler.operator.*``), ride
  crash flight-recorder dumps (``engine/flight_recorder.py``), land in a
  ``PATHWAY_PROFILE_OUTPUT`` JSON at run end, and render as a tree via
  ``pathway_tpu profile``.  Sampling is gated by the ``PATHWAY_PROFILE_*``
  knob family so steady-state overhead is one modulo test per epoch when
  off-cadence and a plain attribute scan (no locks, no allocation per
  node beyond the snapshot list) every ``PATHWAY_PROFILE_SAMPLE_EVERY``
  epochs — priced by ``benchmarks/profiler_overhead.py``.

* **JAX device accounting** (:func:`install_jax_accounting`).  The
  dynamic half of the "recompile-count == 0 in steady state" pin whose
  static half is ``pathway_tpu lint``'s jit rules (``analysis/jit.py``):
  ``jax.monitoring`` listeners count every fresh jaxpr trace
  (``jax.cache.miss`` — a jit cache hit traces nothing), every XLA
  backend compilation (``jax.compile.count``) and its wall seconds
  (``jax.compile.seconds``).  A steady-state epoch loop feeding warm
  bucketed shapes must hold ``jax.cache.miss`` flat — pinned by
  ``tests/test_jax_accounting.py``.  Explicit host<->device transfer
  bytes (``jax.transfer.*``) are counted by opt-in wrappers around
  ``jax.device_put``/``jax.device_get`` (``PATHWAY_PROFILE_TRANSFERS``);
  transfers implicit in jit dispatch are invisible to the host layer and
  stay out of scope.

Listeners and counters register into the process-wide registry
(``engine/metrics.py``), so the profile rides every surface the rest of
the observability stack already has: ``/metrics`` scrapes, OTLP export,
and the console dashboard footer (p95 epoch latency + compile count).
"""

from __future__ import annotations

import json
import os
from typing import Any

from pathway_tpu.engine import metrics as _metrics

__all__ = [
    "EpochProfiler",
    "install_jax_accounting",
    "install_transfer_accounting",
    "uninstall_transfer_accounting",
    "render_snapshot",
]

# jax.monitoring event names this build observes (jax 0.4.x; a renamed
# event in a future jax simply stops matching — counters hold at zero
# rather than breaking the run)
_TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"
_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


def _node_path(node: Any) -> str | None:
    """Columnar-vs-row attribution of one operator: which evaluator its
    batches actually ran through (``None`` = the operator has no columnar
    fast path, or saw no batches)."""
    vec = getattr(node, "vec_batches", 0)
    row = getattr(node, "row_batches", 0)
    if vec and row:
        return "mixed"
    if vec:
        return "columnar"
    if row:
        return "row"
    return None


def _bail_snapshot() -> list[dict[str, Any]]:
    """Top columnar-bail reasons (never raises — snapshots must always
    build, including from the crash path)."""
    try:
        from pathway_tpu.internals import vector_compiler as vc

        return vc.bail_snapshot()
    except Exception:  # noqa: BLE001 - forensics must not fail the sample
        return []


class EpochProfiler:
    """Sampled top-N per-operator attribution over a running dataflow.

    One instance per run (the runner keeps it on ``RunResult.profiler``;
    the registry collector holds it weakly, so it dies with the result).
    ``on_epoch`` is the epoch-loop hook: a cheap cadence gate, then an
    attribute scan over the node arena — never a lock, never I/O.
    """

    def __init__(
        self,
        *,
        enabled: bool | None = None,
        sample_every: int | None = None,
        top_n: int | None = None,
        output_path: str | None = None,
    ):
        from pathway_tpu.internals.config import env_bool, env_int, env_str

        self.enabled = (
            env_bool("PATHWAY_PROFILE") if enabled is None else bool(enabled)
        )
        every = (
            env_int("PATHWAY_PROFILE_SAMPLE_EVERY")
            if sample_every is None
            else sample_every
        )
        self.sample_every = max(1, int(every or 1))
        top = env_int("PATHWAY_PROFILE_TOP") if top_n is None else top_n
        self.top_n = max(1, int(top or 1))
        self.output_path = (
            env_str("PATHWAY_PROFILE_OUTPUT")
            if output_path is None
            else output_path
        )
        self.epochs_sampled = 0
        self._snapshot: dict[str, Any] | None = None

    # -- epoch-loop hook ---------------------------------------------------
    def on_epoch(self, scope: Any, epochs: int) -> None:
        """Called after every processed epoch; samples on cadence only."""
        if not self.enabled or epochs % self.sample_every:
            return
        self.sample(scope, epochs)

    def sample(self, scope: Any, epochs: int) -> dict[str, Any]:
        """Aggregate the node arena's cumulative counters into a top-N
        snapshot.  Reads plain attributes only — safe from the epoch
        thread and (for crash snapshots) from a signal handler."""
        ranked: list[Any] = sorted(
            scope.nodes, key=lambda n: n.step_seconds, reverse=True
        )
        total = sum(n.step_seconds for n in ranked)
        operators = [
            {
                "id": node.id,
                "name": getattr(node, "name", None) or "node",
                "seconds": node.step_seconds,
                "share": (node.step_seconds / total) if total else 0.0,
                "rows_in": node.rows_in,
                "rows_out": node.rows_out,
                "inputs": [inp.id for inp in node.inputs],
                # which execution path actually ran (engine/dataflow.py
                # vec_batches/row_batches): "columnar" / "row" / "mixed",
                # None for operators without a columnar fast path
                "path": _node_path(node),
            }
            for node in ranked[: self.top_n]
        ]
        self.epochs_sampled += 1
        self._snapshot = {
            "epochs": epochs,
            "operators_total": len(ranked),
            "total_step_seconds": total,
            "operators": operators,
            "bails": _bail_snapshot(),
        }
        return self._snapshot

    def crash_snapshot(self, scope: Any) -> dict[str, Any] | None:
        """A fresh snapshot for post-mortems, regardless of the sampling
        gate — the underlying timers are always on, so a crash dump can
        always say where the time went.  Never raises (forensics)."""
        try:
            return self.sample(scope, getattr(scope, "epochs_run", 0))
        except Exception:  # noqa: BLE001 - a dying process must still dump
            return self._snapshot

    @property
    def snapshot(self) -> dict[str, Any] | None:
        return self._snapshot

    # -- exports -----------------------------------------------------------
    def metrics_snapshot(self) -> dict[str, float]:
        """Registry collector: the latest snapshot's top-N as labeled
        gauges (bounded cardinality — only sampled leaders export)."""
        snap = self._snapshot
        if snap is None:
            return {}
        out: dict[str, float] = {
            "profiler.epochs.sampled": float(self.epochs_sampled),
        }
        for op in snap["operators"]:
            labels = f"id={op['id']},operator={op['name']}"
            out[f"profiler.operator.seconds{{{labels}}}"] = op["seconds"]
            out[f"profiler.operator.rows{{{labels}}}"] = float(op["rows_in"])
        return out

    def write_output(self) -> str | None:
        """Persist the final snapshot to ``PATHWAY_PROFILE_OUTPUT``;
        best-effort (a failed profile write must never fail the run)."""
        if not self.output_path or self._snapshot is None:
            return None
        try:
            tmp = f"{self.output_path}.tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(self._snapshot, f, indent=2, sort_keys=True)
            os.replace(tmp, self.output_path)
            return self.output_path
        except OSError:
            return None


# ---------------------------------------------------------------------------
# JAX accounting: compile / jit-cache / transfer counters
# ---------------------------------------------------------------------------

_jax_installed = False
_orig_device_put = None
_orig_device_get = None


def install_jax_accounting(force: bool = False) -> bool:
    """Register ``jax.monitoring`` listeners feeding the unified registry.

    Idempotent and process-global (jax offers no per-run listener scope);
    the listeners bind registry children once, so the per-event cost is a
    string compare + a guarded float add.  Gated by ``PATHWAY_PROFILE_JAX``
    unless ``force`` (tests).  Returns whether accounting is active.
    """
    global _jax_installed
    if _jax_installed:
        return True
    if not force:
        from pathway_tpu.internals.config import env_bool

        if not env_bool("PATHWAY_PROFILE_JAX"):
            return False
    try:
        from jax import monitoring as _jm
    except Exception:  # noqa: BLE001 - no jax, no device accounting
        return False
    reg = _metrics.get_registry()
    cache_miss = reg.counter(
        "jax.cache.miss", "jit cache misses (fresh jaxpr traces) observed"
    )
    compile_count = reg.counter(
        "jax.compile.count", "XLA backend compilations observed"
    )
    compile_seconds = reg.counter(
        "jax.compile.seconds", "cumulative XLA backend compile wall seconds"
    )

    def _on_duration(event: str, duration: float, **_kw: Any) -> None:
        # one compare per event kind; monitoring fires only on cache
        # misses and compiles, so steady state pays nothing at all
        if event == _TRACE_EVENT:
            cache_miss.inc()
        elif event == _BACKEND_COMPILE_EVENT:
            compile_count.inc()
            compile_seconds.inc(duration)

    _jm.register_event_duration_secs_listener(_on_duration)
    _jax_installed = True
    return True


def _tree_nbytes(value: Any) -> int:
    try:
        import jax

        return sum(
            int(getattr(leaf, "nbytes", 0) or 0)
            for leaf in jax.tree_util.tree_leaves(value)
        )
    except Exception:  # noqa: BLE001 - accounting must never break a put
        return 0


def install_transfer_accounting(force: bool = False) -> bool:
    """Wrap ``jax.device_put`` / ``jax.device_get`` with byte counters.

    Counts *explicit* transfers only — arguments implicitly committed by
    jit dispatch never pass through these entry points.  Opt-in
    (``PATHWAY_PROFILE_TRANSFERS``) because it monkeypatches public jax
    attributes; reversible via :func:`uninstall_transfer_accounting`.
    """
    global _orig_device_put, _orig_device_get
    if _orig_device_put is not None:
        return True
    if not force:
        from pathway_tpu.internals.config import env_bool

        if not env_bool("PATHWAY_PROFILE_TRANSFERS"):
            return False
    try:
        import jax
    except Exception:  # noqa: BLE001
        return False
    reg = _metrics.get_registry()
    h2d = reg.counter(
        "jax.transfer.h2d.bytes", "explicit host-to-device transfer bytes"
    )
    d2h = reg.counter(
        "jax.transfer.d2h.bytes", "explicit device-to-host transfer bytes"
    )
    _orig_device_put = jax.device_put
    _orig_device_get = jax.device_get

    def device_put(x, *args, **kwargs):
        h2d.inc(_tree_nbytes(x))
        return _orig_device_put(x, *args, **kwargs)

    def device_get(x):
        d2h.inc(_tree_nbytes(x))
        return _orig_device_get(x)

    jax.device_put = device_put
    jax.device_get = device_get
    return True


def uninstall_transfer_accounting() -> None:
    global _orig_device_put, _orig_device_get
    if _orig_device_put is None:
        return
    import jax

    jax.device_put = _orig_device_put
    jax.device_get = _orig_device_get
    _orig_device_put = None
    _orig_device_get = None


# ---------------------------------------------------------------------------
# Snapshot rendering (CLI / post-mortem)
# ---------------------------------------------------------------------------


def render_snapshot(snapshot: dict[str, Any], *, top: int | None = None) -> str:
    """Human-readable top-N attribution tree of one profiler snapshot.

    Operators print by cumulative step time with a share bar; each line
    names its input operators (``<- name#id``), so the hot chain reads as
    a tree even though the graph is a DAG.
    """
    # .get() everywhere: this renders foreign artifacts (hand-edited or
    # cross-version flight-recorder dumps) — a partial snapshot must
    # render best-effort, never traceback mid-blackbox-listing
    ops = snapshot.get("operators") or []
    if top is not None:
        ops = ops[:top]
    total = snapshot.get("total_step_seconds") or 0.0
    names = {op.get("id"): op.get("name", "op") for op in ops}
    lines = [
        f"profile: {snapshot.get('epochs', '?')} epochs · "
        f"{snapshot.get('operators_total', len(ops))} operators · "
        f"{total:.3f} s total operator time"
    ]
    if not ops:
        lines.append("  (no operator samples)")
        return "\n".join(lines)

    def tag(op) -> str:
        return f"{op.get('name', 'op')}#{op.get('id', '?')}"

    width = max(len(tag(op)) for op in ops)
    for op in ops:
        share = op.get("share") or 0.0
        bar = "#" * max(1, round(share * 20)) if total else ""
        inputs = ", ".join(
            f"{names.get(i, 'op')}#{i}" for i in op.get("inputs") or []
        )
        path = op.get("path")
        lines.append(
            f"  {tag(op):<{width}}  "
            f"{op.get('seconds') or 0.0:>9.3f} s  {share:>6.1%}  {bar:<20}  "
            f"rows {op.get('rows_in', '?')}->{op.get('rows_out', '?')}"
            + (f"  [{path}]" if path else "")
            + (f"  <- {inputs}" if inputs else "")
        )
    bails = snapshot.get("bails") or []
    if bails:
        lines.append("  columnar bails (fast path fell back to row-wise):")
        for b in bails:
            lines.append(
                f"    {b.get('op', '?')}/{b.get('reason', '?')}: "
                f"{b.get('count', '?')}"
            )
    return "\n".join(lines)
