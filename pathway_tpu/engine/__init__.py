"""TPU-native incremental engine (the analog of Pathway's Rust engine crate)."""

from pathway_tpu.engine import dataflow, types
from pathway_tpu.engine.types import (
    ERROR,
    Error,
    Json,
    Pointer,
    PyObjectWrapper,
    wrap_py_object,
)

__all__ = [
    "dataflow",
    "types",
    "ERROR",
    "Error",
    "Json",
    "Pointer",
    "PyObjectWrapper",
    "wrap_py_object",
]
