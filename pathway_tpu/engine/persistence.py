"""Engine-side persistence: snapshots, offset frontiers, crash-resume.

Parity target: ``/root/reference/src/persistence/`` —
``WorkerPersistentStorage`` (tracker.rs:49-260), input snapshot event logs
(input_snapshot.rs: Event{Insert, Delete, AdvanceTime, Finished}), offset
antichains (frontier.rs), and the file/S3/memory/mock backends
(backends/*.rs).  Redesigned for this engine's epoch model:

* Each persisted source owns an append-only **event log** of encoded events
  (``engine/codec.py``), written one chunk per committed epoch.
* A worker-level **metadata file** records, per source, how many chunks are
  part of the last consistent snapshot plus the reader's **offset frontier**
  (an opaque JSON-able object the reader knows how to ``seek`` to).  The
  metadata write is atomic (tmp + rename), so a crash between chunk writes
  and metadata commit simply ignores the trailing chunks — the same
  "last consistent snapshot" rule the reference enforces with its antichains.
* On resume, committed events replay into the input session at artificial
  time 0 (``ARTIFICIAL_TIME_ON_REWIND_START``, connectors/mod.rs:222-258)
  and the reader seeks to the stored frontier before producing new rows.

Backend selection mirrors ``python/pathway/persistence/__init__.py``:
filesystem / mock (in-memory) / s3 (gated on client library presence).
"""

from __future__ import annotations

import json as _json
import os
import pickle
import threading
from contextvars import ContextVar
from typing import Any

from pathway_tpu.engine import codec

METADATA_FILE = "metadata.json"

# Filesystem root of the persistence backend of the currently-running
# pipeline (UDF DiskCache reads it; PersistenceMode::UdfCaching,
# src/connectors/mod.rs:114).  Context-local so concurrent runs in one
# process each see their own root (UDFs execute in the runner's context);
# the process-global fallback — for code that reads the root from a thread
# outside any run context — is first-wins and released only by its owner.
_root_var: ContextVar[str | None] = ContextVar("pathway_tpu_active_root", default=None)
_active_root: str | None = None
_root_owner: object | None = None
_root_lock = threading.Lock()


def acquire_active_root(root: str) -> tuple[object | None, object]:
    """Claim the UDF-cache root for the current run; returns a release token."""
    global _active_root, _root_owner
    var_token = _root_var.set(root)
    with _root_lock:
        if _active_root is None:
            _root_owner = object()
            _active_root = root
            return (_root_owner, var_token)
        return (None, var_token)


def release_active_root(token: tuple[object | None, object] | None) -> None:
    global _active_root, _root_owner
    if token is None:
        return
    owner, var_token = token
    _root_var.reset(var_token)
    if owner is None:
        return
    with _root_lock:
        if owner is _root_owner:
            _active_root = None
            _root_owner = None


def active_root() -> str | None:
    ctx = _root_var.get()
    return ctx if ctx is not None else _active_root


# ---------------------------------------------------------------------------
# Blob backends (backends/{file,memory,mock,s3}.rs)
# ---------------------------------------------------------------------------


class BlobBackend:
    """Key → bytes store; keys are slash-separated paths."""

    def put(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def get(self, key: str) -> bytes | None:
        raise NotImplementedError

    def list_keys(self, prefix: str) -> list[str]:
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError

    def put_atomic(self, key: str, data: bytes) -> None:
        self.put(key, data)


def _fsync_dir(path: str) -> None:
    """Flush a directory's entries (new files, renames) to stable storage.

    Best-effort on filesystems that refuse O_DIRECTORY fsync (some network
    mounts): the entry write is then only as durable as the mount allows.
    """
    try:
        fd = os.open(path, os.O_RDONLY | getattr(os, "O_DIRECTORY", 0))
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


class FileBackend(BlobBackend):
    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.root, *key.split("/"))

    def put(self, key: str, data: bytes) -> None:
        # Durability contract: after put() returns, the chunk survives a
        # power-cut — the metadata commit (put_atomic) durably references
        # chunks, so chunks themselves must be durable first.  fsyncing the
        # FILE makes its bytes durable, but a newly-created directory ENTRY
        # lives in the parent directory: without fsyncing the dirfd a crash
        # can persist the metadata yet lose the chunk it points at.
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        _fsync_dir(os.path.dirname(path))

    def put_atomic(self, key: str, data: bytes) -> None:
        # Durability contract: the rename is the commit point — after
        # put_atomic() returns, a crash yields either the OLD or the NEW
        # content, never a torn file and never a lost rename.  The rename
        # itself is a parent-directory mutation, so the dirfd fsync below
        # is what makes the commit durable (fsyncing the file alone leaves
        # the rename in the page cache).
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _fsync_dir(os.path.dirname(path))

    def get(self, key: str) -> bytes | None:
        path = self._path(key)
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            return f.read()

    def list_keys(self, prefix: str) -> list[str]:
        base = self._path(prefix)
        if not os.path.isdir(base):
            return []
        out = []
        for root, _dirs, files in os.walk(base):
            for f in files:
                if f.endswith(".tmp"):
                    continue
                full = os.path.join(root, f)
                rel = os.path.relpath(full, self.root)
                out.append(rel.replace(os.sep, "/"))
        return sorted(out)

    def delete(self, key: str) -> None:
        try:
            os.remove(self._path(key))
        except OSError:
            pass


class MemoryBackend(BlobBackend):
    """In-memory store; pass a shared dict to survive across runs in-process
    (Backend.mock semantics, persistence/__init__.py:71)."""

    def __init__(self, store: dict[str, bytes] | None = None):
        self.store: dict[str, bytes] = store if store is not None else {}
        self._lock = threading.Lock()

    def put(self, key: str, data: bytes) -> None:
        with self._lock:
            self.store[key] = data

    def get(self, key: str) -> bytes | None:
        with self._lock:
            return self.store.get(key)

    def list_keys(self, prefix: str) -> list[str]:
        with self._lock:
            return sorted(k for k in self.store if k.startswith(prefix))

    def delete(self, key: str) -> None:
        with self._lock:
            self.store.pop(key, None)


class _PrefixedObjectStore(BlobBackend):
    """Shared behavior for object-storage backends (S3, Azure, ...): prefix
    handling, 404 → None/no-op on get/delete, and the rule that a transient
    5xx/403 must NOT read as "no snapshot" (that would silently restart the
    pipeline from scratch).  Object PUTs are atomic per object on these
    stores, so ``put_atomic`` is plain ``put``.

    Subclasses set ``_error_cls`` and implement ``_put/_get/_list/_delete``.
    """

    _error_cls: type[Exception] = Exception

    def __init__(self, client: Any, prefix: str = ""):
        self.client = client
        self.prefix = prefix.strip("/")

    def _key(self, key: str) -> str:
        return f"{self.prefix}/{key}" if self.prefix else key

    def put(self, key: str, data: bytes) -> None:
        self._put(self._key(key), data)

    @staticmethod
    def _is_not_found(exc: Exception) -> bool:
        # clients that distinguish auth-404s set is_not_found themselves
        return bool(
            getattr(exc, "is_not_found", getattr(exc, "status", 0) == 404)
        )

    def get(self, key: str) -> bytes | None:
        try:
            return self._get(self._key(key))
        except Exception as exc:
            if isinstance(exc, self._error_cls) and self._is_not_found(exc):
                return None
            raise

    def list_keys(self, prefix: str) -> list[str]:
        full = self._key(prefix)
        strip = len(self.prefix) + 1 if self.prefix else 0
        return sorted(k[strip:] for k in self._list(full))

    def delete(self, key: str) -> None:
        try:
            self._delete(self._key(key))
        except Exception as exc:
            if isinstance(exc, self._error_cls) and self._is_not_found(exc):
                return
            raise

    def _put(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def _get(self, key: str) -> bytes:
        raise NotImplementedError

    def _list(self, prefix: str) -> list[str]:
        raise NotImplementedError

    def _delete(self, key: str) -> None:
        raise NotImplementedError


class S3Backend(_PrefixedObjectStore):
    """Object-storage persistence (backends/s3.rs analog) over the signed
    REST client in ``io/_s3http.py`` — works against AWS S3 and any
    S3-compatible endpoint (MinIO)."""

    @property
    def _error_cls(self):
        from pathway_tpu.io._s3http import S3Error

        return S3Error

    def _put(self, key: str, data: bytes) -> None:
        self.client.put_object(key, data)

    def _get(self, key: str) -> bytes:
        return self.client.get_object(key)

    def _list(self, prefix: str) -> list[str]:
        return [o["key"] for o in self.client.list_objects(prefix)]

    def _delete(self, key: str) -> None:
        self.client.delete_object(key)


class GcsBackend(_PrefixedObjectStore):
    """Google Cloud Storage persistence over the JSON-API client in
    ``io/_gcshttp.py`` — the natural store for TPU-pod deployments (ambient
    metadata-server identity, no key distribution)."""

    @property
    def _error_cls(self):
        from pathway_tpu.io._gcshttp import GcsError

        return GcsError

    def _put(self, key: str, data: bytes) -> None:
        self.client.put_object(key, data)

    def _get(self, key: str) -> bytes:
        return self.client.get_object(key)

    def _list(self, prefix: str) -> list[str]:
        return self.client.list_objects(prefix)

    def _delete(self, key: str) -> None:
        self.client.delete_object(key)


class AzureBackend(_PrefixedObjectStore):
    """Azure Blob persistence over the SharedKey REST client in
    ``io/_azureblob.py``."""

    @property
    def _error_cls(self):
        from pathway_tpu.io._azureblob import AzureBlobError

        return AzureBlobError

    def _put(self, key: str, data: bytes) -> None:
        self.client.put_blob(key, data)

    def _get(self, key: str) -> bytes:
        return self.client.get_blob(key)

    def _list(self, prefix: str) -> list[str]:
        return list(self.client.list_blobs(prefix))

    def _delete(self, key: str) -> None:
        self.client.delete_blob(key)


def _object_store_cfg(backend_cfg: Any) -> tuple[str, str, Any]:
    """``(bucket_or_container, prefix, client_or_None)`` from a Backend cfg.

    The root_path's ``scheme://bucket/prefix`` applies in BOTH construction
    modes — a pre-built client with a diverging root_path prefix would
    silently resume from a different object location.
    """
    path = getattr(backend_cfg, "path", "") or ""
    rest = path.split("://", 1)[-1]
    bucket, _, prefix = rest.partition("/")
    prefix = getattr(backend_cfg, "prefix", "") or prefix
    return bucket, prefix, getattr(backend_cfg, "client", None)


def backend_from_config(backend_cfg: Any) -> BlobBackend:
    """Build an engine backend from the user-facing ``pw.persistence.Backend``."""
    kind = getattr(backend_cfg, "kind", None)
    if kind == "filesystem":
        return FileBackend(backend_cfg.path)
    if kind == "mock":
        store = getattr(backend_cfg, "store", None)
        return MemoryBackend(store if isinstance(store, dict) else {})
    if kind == "s3":
        from pathway_tpu.io._s3http import AwsS3Settings

        settings = getattr(backend_cfg, "bucket_settings", None) or AwsS3Settings()
        path = getattr(backend_cfg, "path", "") or ""
        if path.startswith("s3://"):
            rest = path[5:]
            bucket, _, prefix = rest.partition("/")
        else:
            bucket, prefix = settings.bucket_name, path
        return S3Backend(settings.client(bucket), prefix)
    if kind == "gcs":
        from pathway_tpu.io._gcshttp import GcsClient

        bucket, prefix, client = _object_store_cfg(backend_cfg)
        if client is None:
            client = GcsClient(
                bucket,
                token_provider=getattr(backend_cfg, "token_provider", None),
                endpoint=getattr(backend_cfg, "endpoint", None),
            )
        return GcsBackend(client, prefix)
    if kind == "azure":
        from pathway_tpu.io._azureblob import AzureBlobClient

        container, prefix, client = _object_store_cfg(backend_cfg)
        if client is None:
            acct = getattr(backend_cfg, "account", None) or {}
            client = AzureBlobClient(
                acct.get("account_name", ""),
                container,
                account_key=acct.get("account_key", ""),
                endpoint=acct.get("endpoint"),
            )
        return AzureBackend(client, prefix)
    raise ValueError(f"unknown persistence backend {backend_cfg!r}")


# ---------------------------------------------------------------------------
# Per-source snapshot log
# ---------------------------------------------------------------------------


class SnapshotLog:
    """Append-only event log for one persisted source (input_snapshot.rs)."""

    def __init__(self, backend: BlobBackend, worker: int, source_id: str):
        self.backend = backend
        self.prefix = f"snapshots/{worker}/{source_id}"
        self.chunks_written = 0
        self._buffer: list[bytes] = []

    def record(self, key: int, row: tuple, diff: int) -> None:
        kind = codec.EV_INSERT if diff > 0 else codec.EV_DELETE
        for _ in range(abs(diff)):
            self._buffer.append(codec.encode_event(kind, key, row))

    def record_advance(self, time: int) -> None:
        self._buffer.append(codec.encode_event(codec.EV_ADVANCE_TIME, time=time))

    def flush_chunk(self) -> None:
        if not self._buffer:
            return
        data = b"".join(self._buffer)
        self._buffer.clear()
        self.backend.put(f"{self.prefix}/{self.chunks_written:08d}", data)
        self.chunks_written += 1

    def read_committed(self, committed_chunks: int):
        """Yield (kind, key, row, time) from the first `committed_chunks`."""
        for i in range(committed_chunks):
            data = self.backend.get(f"{self.prefix}/{i:08d}")
            if data is None:
                raise RuntimeError(
                    f"persistence: missing committed chunk {i} for {self.prefix}"
                )
            yield from codec.decode_events(data)


# ---------------------------------------------------------------------------
# Worker storage tracker (tracker.rs WorkerPersistentStorage)
# ---------------------------------------------------------------------------


class SourceState:
    def __init__(self, log: SnapshotLog, committed_chunks: int, offset: Any):
        self.log = log
        self.committed_chunks = committed_chunks
        self.offset = offset  # opaque reader frontier
        self.pending_offset: Any = offset
        self.schema_digest: str | None = None
        # operator-persisting mode: no input event log; offsets commit only
        # once the epoch their rows were staged into has been PROCESSED —
        # operator snapshots cover processed epochs, so an offset past an
        # unprocessed row would lose it on crash
        self.operator_mode = False
        self.pending_offsets: list[tuple[Any, int]] = []  # (offset, epoch)
        # high-water mark of auto-generated row keys: resumed runs continue
        # the sequence so fresh rows never collide with keys that already
        # live inside restored operator state / replayed snapshots
        self.key_seq = 0


class PersistentStorage:
    """Coordinates snapshot logs + the consistent-metadata commit for a worker."""

    def __init__(
        self,
        backend: BlobBackend,
        *,
        worker: int = 0,
        snapshot_interval_ms: int = 0,
        mode: Any = None,
    ):
        self.backend = backend
        self.worker = worker
        self.snapshot_interval_ms = snapshot_interval_ms
        self.mode = mode
        self.sources: dict[str, SourceState] = {}
        self._metadata = self._load_metadata()
        self.replayed_rows = 0
        # PersistenceMode::OperatorPersisting (mod.rs:108-116): persist
        # operator arrangements instead of input event logs, so resume is
        # O(state) not O(history)
        self.operator_persistence = (
            getattr(mode, "name", None) == "OPERATOR_PERSISTING"
        )
        self._op_gen = int(self._metadata.get("operators", {}).get("gen", 0))
        # set by the runner: returns {node_id: bytes} of dirty operator
        # states + the graph digest, collected at commit time; confirm is
        # invoked only after the referencing metadata write succeeds
        self.collect_operator_states: Any = None
        self.confirm_operator_commit: Any = None
        # record/replay mode (PATHWAY_SNAPSHOT_ACCESS): None = both
        # directions (ordinary persistence), "record" = write-only,
        # "replay" = read snapshots; continue_after_replay then decides
        # whether live connector data follows the replayed prefix
        self.snapshot_access: str | None = None
        self.continue_after_replay = True

    # -- metadata --
    def _meta_key(self) -> str:
        return f"{METADATA_FILE}.{self.worker}"

    def _load_metadata(self) -> dict:
        raw = self.backend.get(self._meta_key())
        if raw is None:
            return {"sources": {}}
        return _json.loads(raw.decode())

    def commit(
        self, processed_up_to: int | None = None, full_operator_dump: bool = False
    ) -> None:
        """Atomically record the current consistent snapshot frontier.

        Only chunks flushed at offset markers are committed — the mid-batch
        event buffer stays out, so the committed (chunks, offset) pair always
        refers to the same row prefix.  No-op when nothing advanced.

        Operator-persisting mode additionally dumps dirty operator states
        (via ``collect_operator_states``) and gates source offsets on
        ``processed_up_to`` (the last epoch the engine ran; None = all).
        """
        for sid, st in self.sources.items():
            if st.operator_mode:
                while st.pending_offsets and (
                    processed_up_to is None
                    or st.pending_offsets[0][1] <= processed_up_to
                ):
                    st.offset = st.pending_offsets.pop(0)[0]
                st.pending_offset = st.offset
            else:
                st.committed_chunks = st.log.chunks_written
                st.offset = st.pending_offset
        metadata = {
            "sources": {
                sid: {
                    "chunks": st.committed_chunks,
                    "offset": _offset_to_json(st.offset),
                    "schema": st.schema_digest,
                    "key_seq": st.key_seq,
                }
                for sid, st in self.sources.items()
            }
        }
        if self.operator_persistence and self.collect_operator_states is not None:
            dirty, digest = self.collect_operator_states(full_operator_dump)
            op_meta = dict(self._metadata.get("operators", {}).get("nodes", {}))
            if dirty:
                self._op_gen += 1
                for node_id, blob in dirty.items():
                    key = f"operators/{self.worker}/{self._op_gen}/{node_id}"
                    self.backend.put(key, blob)
                    op_meta[str(node_id)] = key
            metadata["operators"] = {
                "gen": self._op_gen,
                "digest": digest,
                "nodes": op_meta,
            }
        if metadata == self._metadata:
            if self.confirm_operator_commit is not None:
                self.confirm_operator_commit()  # nothing new: dumps are moot
            return
        self._metadata = metadata
        self.backend.put_atomic(
            self._meta_key(), _json.dumps(self._metadata).encode()
        )
        if self.confirm_operator_commit is not None:
            self.confirm_operator_commit()
        self._gc_operator_chunks()

    def _gc_operator_chunks(self) -> None:
        """Drop operator chunks superseded by the just-committed metadata."""
        meta = self._metadata.get("operators")
        if not meta:
            return
        live = set(meta.get("nodes", {}).values())
        for key in self.backend.list_keys(f"operators/{self.worker}/"):
            if key not in live:
                self.backend.delete(key)

    def load_operator_states(self, digest: str) -> dict[int, bytes]:
        """Committed operator snapshots keyed by node id; {} on first run."""
        meta = self._metadata.get("operators")
        if not meta or not meta.get("nodes"):
            return {}
        if meta.get("digest") != digest:
            raise ValueError(
                "persistence: operator snapshots were written by a different "
                "program shape — the dataflow graph changed between runs "
                "(clear the persistence directory to start fresh)"
            )
        out = {}
        for node_id, key in meta["nodes"].items():
            blob = self.backend.get(key)
            if blob is None:
                raise RuntimeError(f"persistence: missing operator chunk {key}")
            out[int(node_id)] = blob
        return out

    @property
    def input_snapshots_enabled(self) -> bool:
        """False for UDF-caching-only mode (PersistenceMode::UdfCaching,
        src/connectors/mod.rs:114): the persistence root backs UDF caches but
        sources are neither snapshotted nor replayed."""
        name = getattr(self.mode, "name", None)
        return name != "UDF_CACHING"

    # -- sources --
    def register_source(
        self, source_id: str, schema_digest: str | None = None
    ) -> SourceState:
        if source_id in self.sources:
            raise ValueError(
                f"persistence: duplicate source name {source_id!r}; give each "
                "persisted connector a unique name="
            )
        log = SnapshotLog(self.backend, self.worker, source_id)
        meta = self._metadata["sources"].get(source_id, {})
        stored_digest = meta.get("schema")
        if (
            schema_digest is not None
            and stored_digest is not None
            and stored_digest != schema_digest
        ):
            # positional ids shift when unnamed sources are added/reordered;
            # refuse to replay another source's snapshot into this input
            raise ValueError(
                f"persistence: source {source_id!r} has a snapshot with a "
                "different schema — the program changed between runs. Give "
                "persisted connectors stable name= arguments (or clear the "
                "persistence directory)."
            )
        committed = int(meta.get("chunks", 0))
        offset = _offset_from_json(meta.get("offset"))
        log.chunks_written = committed  # append after the committed prefix
        state = SourceState(log, committed, offset)
        state.schema_digest = schema_digest
        state.operator_mode = self.operator_persistence
        state.key_seq = int(meta.get("key_seq", 0))
        self.sources[source_id] = state
        return state

    def replay_into(self, state: SourceState, insert) -> int:
        """Feed committed events into an input session at rewind time 0.

        Returns the number of replayed row events (mod.rs:222-258 rewind).
        Operator-persisting mode replays nothing — restored operator states
        already contain the effect of every committed row.
        """
        if state.operator_mode:
            return 0
        n = 0
        for kind, key, row, _t in state.log.read_committed(state.committed_chunks):
            if kind == codec.EV_INSERT:
                insert(key, row, 1)
                n += 1
            elif kind == codec.EV_DELETE:
                insert(key, row, -1)
                n += 1
        self.replayed_rows += n
        return n


def _offset_to_json(offset: Any) -> Any:
    if offset is None:
        return None
    try:
        _json.dumps(offset)
        return {"j": offset}
    except (TypeError, ValueError):
        return {"p": pickle.dumps(offset).hex()}


def _offset_from_json(obj: Any) -> Any:
    if obj is None:
        return None
    if "j" in obj:
        return obj["j"]
    return pickle.loads(bytes.fromhex(obj["p"]))
