"""Engine-side persistence: snapshots, offset frontiers, crash-resume.

Parity target: ``/root/reference/src/persistence/`` —
``WorkerPersistentStorage`` (tracker.rs:49-260), input snapshot event logs
(input_snapshot.rs: Event{Insert, Delete, AdvanceTime, Finished}), offset
antichains (frontier.rs), and the file/S3/memory/mock backends
(backends/*.rs).  Redesigned for this engine's epoch model:

* Each persisted source owns an append-only **event log** of encoded events
  (``engine/codec.py``), written one chunk per committed epoch.
* Every persisted artifact (snapshot chunk, manifest, operator dump) is
  wrapped in a self-checking **integrity frame** (magic + version + length
  + CRC32C, ``engine/codec.py``), so torn writes, truncations and bit rot
  are *detected* at read time instead of silently corrupting recovery.
* Each commit writes a per-generation **manifest** (chunk list + SHA-256
  digests + operator/graph digest) atomically (tmp + rename / object PUT).
  The manifest is the commit point; the last ``PATHWAY_CHECKPOINT_GENERATIONS``
  manifests are retained with deferred GC, so recovery can fall back
  generation-by-generation to the newest FULLY VERIFIED checkpoint when
  the newest one is damaged.  A legacy ``metadata.json.<worker>`` pointer
  is still written for humans and for the supervisor's post-mortems.
* On resume, committed events replay into the input session at artificial
  time 0 (``ARTIFICIAL_TIME_ON_REWIND_START``, connectors/mod.rs:222-258)
  and the reader seeks to the stored frontier before producing new rows.
* Commits are **pipelined**: a bounded background writer pool
  (``PATHWAY_CHECKPOINT_WRITERS``, byte-capped backpressure via
  ``PATHWAY_CHECKPOINT_INFLIGHT_MB``) owns chunk/dump framing + SHA-256 +
  upload, and a committer thread publishes each generation manifest only
  after a **commit barrier** confirms every referenced artifact landed —
  so the epoch loop overlaps durability I/O with compute while the
  manifest-IS-the-commit-point invariant is unchanged (a crash mid-flight
  leaves an unreferenced partial generation that GC/scrub tolerate).

``scrub_root`` audits a persistence root offline (the ``pathway_tpu scrub``
CLI drives it) and reports per-generation health without mutating anything.

Backend selection mirrors ``python/pathway/persistence/__init__.py``:
filesystem / mock (in-memory) / s3 (gated on client library presence).
"""

from __future__ import annotations

import hashlib
import json as _json
import logging
import os
import pickle
import threading
import time as _time
from collections import deque
from contextvars import ContextVar
from typing import Any, Callable

from pathway_tpu.engine import codec
from pathway_tpu.engine import flight_recorder as _blackbox
from pathway_tpu.engine import metrics as _registry

METADATA_FILE = "metadata.json"
MANIFEST_FORMAT = 1

# -- incarnation fencing (split-brain protection) ---------------------------
# The supervisor owns a LEASE on the persistence root: a monotonically
# increasing *incarnation* number bumped before every (re)launch of the
# worker group, exported to workers via PATHWAY_INCARNATION.  Every
# commit-point write re-reads the lease and REFUSES to publish when it
# shows a newer incarnation — a zombie worker from a superseded restart
# attempt (alive but partitioned, SIGKILL not yet delivered) can therefore
# never splice a stale generation into a root the respawned cluster owns.
LEASE_KEY = "lease/LEASE"
LEASE_FORMAT = 1
ENV_INCARNATION = "PATHWAY_INCARNATION"

# -- elastic rescale (topology marker) --------------------------------------
# The root-level record of the CURRENT topology epoch: {"seq", "workers",
# "from_workers", "at"}.  ``seq`` increments on every rescale, and every
# manifest is stamped with the epoch it was published under — that is what
# makes a STALE shard detectable even when its stamped worker count
# coincidentally matches the current one (a 2 -> 1 -> 2 round trip).  The
# marker is written by the repartitioning workers themselves (idempotent:
# every worker of one rescale computes the same successor epoch), so it
# exists on supervised and solo roots alike.
TOPOLOGY_KEY = "topology/CURRENT"
TOPOLOGY_FORMAT = 1

_log = logging.getLogger("pathway_tpu.persistence")


class CheckpointError(RuntimeError):
    """A committed checkpoint artifact is missing or failed verification."""


class FencedError(CheckpointError):
    """A newer cluster incarnation owns this persistence root.

    Raised instead of performing a commit-point write (generation-manifest
    publish, advisory-pointer refresh) when the on-root lease shows an
    incarnation newer than this writer's ``PATHWAY_INCARNATION``.  The only
    correct reaction is to STOP: this process is a zombie from a superseded
    restart attempt, and anything it publishes would corrupt the live
    cluster's recovery provenance.  The runner lets it propagate, so the
    worker exits nonzero and its peers drop it from the mesh.
    """


def writer_incarnation() -> int:
    """This process's cluster incarnation (``PATHWAY_INCARNATION``); 0 when
    unleased — solo runs without a supervisor skip fencing entirely."""
    from pathway_tpu.internals.config import env_int

    return env_int(ENV_INCARNATION)


def _worker_fence() -> int:
    """This process's per-worker fence token (``PATHWAY_WORKER_FENCE``);
    0 for anything but a promoted standby — see bump_worker_fence()."""
    from pathway_tpu.internals.config import env_int

    return env_int("PATHWAY_WORKER_FENCE")


def _decode_lease(raw: bytes | None) -> dict | None:
    """Decode a raw lease blob; None when absent, torn, or malformed."""
    if raw is None:
        return None
    try:
        obj = _json.loads(codec.unframe_blob(raw, what=LEASE_KEY).decode())
    except (codec.IntegrityError, ValueError):
        return None
    if not isinstance(obj, dict) or not isinstance(obj.get("incarnation"), int):
        return None
    return obj


def read_lease(backend: "BlobBackend") -> dict | None:
    """The root's lease object, or None when absent/unreadable.

    Unreadable is treated as absent on the WRITE path (a torn lease must
    not brick every writer); ``scrub_root`` reports it as damage so an
    operator notices."""
    return _decode_lease(backend.get(LEASE_KEY))


def acquire_lease(
    backend: "BlobBackend",
    *,
    owner: str | None = None,
    run_id: str | None = None,
    workers: int | None = None,
) -> int:
    """Bump the root's lease to the next incarnation and return it.

    Monotonic across runs of the same root: a fresh supervisor on a reused
    root starts ABOVE every incarnation that ever wrote there, so any
    lingering zombie from a previous run is fenced on its next publish.
    Single-supervisor protocol — the lease serializes worker incarnations
    under one supervisor, it is not a distributed lock between supervisors.

    ``workers`` records the TARGET TOPOLOGY of this incarnation — the
    worker count the supervisor is about to launch.  The lease is the
    authoritative record an elastic rescale leaves behind: workers verify
    their own ``PATHWAY_PROCESSES`` against it at boot (the topology
    handshake in ``internals/runner.py``), and ``pathway_tpu scrub``
    renders the rescale history kept in ``topology_history`` (bounded to
    the last 16 changes).  ``None`` carries the previous recorded topology
    forward unchanged.
    """
    current = read_lease(backend)
    incarnation = (current["incarnation"] if current else 0) + 1
    history = list((current or {}).get("topology_history") or [])
    recorded = workers if workers is not None else (current or {}).get("workers")
    if workers is not None and (
        not history or history[-1].get("workers") != workers
    ):
        history.append(
            {
                "incarnation": incarnation,
                "workers": workers,
                "at": _time.time(),
            }
        )
    lease = {
        "format": LEASE_FORMAT,
        "incarnation": incarnation,
        "acquired_at": _time.time(),
        "owner": owner or f"pid-{os.getpid()}",
        "run_id": run_id,
        "workers": recorded,
        "topology_history": history[-16:],
    }
    backend.put_atomic(LEASE_KEY, codec.frame_blob(_json.dumps(lease).encode()))
    return incarnation


def read_topology_marker(backend: "BlobBackend") -> dict | None:
    """The root's current topology-epoch marker, or None when absent or
    unreadable (a pre-rescale root has none; an unreadable marker degrades
    to stamp-based detection and scrub reports it)."""
    raw = backend.get(TOPOLOGY_KEY)
    if raw is None:
        return None
    try:
        obj = _json.loads(
            codec.unframe_blob(raw, what=TOPOLOGY_KEY).decode()
        )
    except (codec.IntegrityError, ValueError):
        return None
    if (
        not isinstance(obj, dict)
        or not isinstance(obj.get("seq"), int)
        or not isinstance(obj.get("workers"), int)
    ):
        return None
    return obj


def read_lease_file(root: str) -> dict | None:
    """Read a filesystem root's lease WITHOUT constructing a FileBackend
    (which would mkdir the root as a side effect) — the boot-time topology
    handshake must stay read-only.  None when absent or unreadable."""
    path = os.path.join(root, *LEASE_KEY.split("/"))
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError:
        return None
    return _decode_lease(raw)


# -- live shard handoff coordination files --
#
# A planned rescale is coordinated through tiny advisory JSON files under
# the root's ``lease/`` directory — the same location as the lease and the
# progress beacons, and like them PLAIN (unframed) JSON written via
# atomic tmp+rename: they are supervisor↔worker signaling, not recovery
# state, so a torn or stale file degrades to "no handoff" and the
# supervisor falls back to the restart-based rescale.  Protocol:
#
#   1. the supervisor posts ``lease/HANDOFF`` ({incarnation, from_workers,
#      to_workers, reason}); workers ignore requests whose incarnation is
#      not THEIR incarnation (a zombie must not join a handoff).
#   2. worker 0 notices the request at an epoch boundary and broadcasts
#      the handoff decision on the epoch channel; EVERY worker then drains
#      a synchronous commit of its exact frontier (stamped ``handoff_to``),
#      fences its own storage (``fence_for_handoff``), barriers, and
#      writes ``lease/handoff.ack.<worker>`` before exiting cleanly.
#   3. the supervisor sees all workers exit 0 WITH a complete ack set and
#      relaunches at the new topology — the PR-10 repartition machinery
#      replays the moving shard ranges from the acked frontiers.  Any
#      other outcome (death, missing ack, deadline) → restart fallback.
HANDOFF_KEY = "lease/HANDOFF"
HANDOFF_ACK_PREFIX = "lease/handoff.ack."
HANDOFF_FORMAT = 1


def _lease_dir_write_json(root: str, key: str, obj: dict) -> None:
    """Atomically (tmp+rename) write an advisory JSON file under the
    root's lease/ directory without constructing a FileBackend."""
    path = os.path.join(root, *key.split("/"))
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        _json.dump(obj, f)
    os.replace(tmp, path)


def _lease_dir_read_json(root: str, key: str) -> dict | None:
    """Best-effort read of an advisory lease/ JSON file; None when absent,
    torn, or malformed (advisory contract: damage degrades to absence)."""
    path = os.path.join(root, *key.split("/"))
    try:
        with open(path, "r", encoding="utf-8") as f:
            obj = _json.load(f)
    except (OSError, ValueError):
        return None
    return obj if isinstance(obj, dict) else None


def post_handoff_request(
    root: str,
    *,
    incarnation: int,
    from_workers: int,
    to_workers: int,
    reason: str = "",
) -> None:
    """Supervisor side: ask the CURRENT incarnation's workers to hand the
    root off to ``to_workers`` at their next epoch boundary."""
    _lease_dir_write_json(
        root,
        HANDOFF_KEY,
        {
            "format": HANDOFF_FORMAT,
            "incarnation": incarnation,
            "from_workers": from_workers,
            "to_workers": to_workers,
            "reason": reason,
            "at": _time.time(),
        },
    )


def read_handoff_request(root: str) -> dict | None:
    """The pending handoff request, or None when absent/unreadable/not a
    well-formed request (advisory: malformed never raises)."""
    obj = _lease_dir_read_json(root, HANDOFF_KEY)
    if (
        obj is None
        or not isinstance(obj.get("incarnation"), int)
        or not isinstance(obj.get("to_workers"), int)
        or obj["to_workers"] < 1
    ):
        return None
    return obj


def write_handoff_ack(
    root: str,
    worker: int,
    *,
    incarnation: int,
    to_workers: int,
    frontier: Any = None,
) -> None:
    """Worker side: record that this worker fenced + committed its exact
    frontier for the handoff to ``to_workers`` and is about to exit."""
    _lease_dir_write_json(
        root,
        f"{HANDOFF_ACK_PREFIX}{worker}",
        {
            "format": HANDOFF_FORMAT,
            "worker": worker,
            "incarnation": incarnation,
            "to_workers": to_workers,
            "frontier": frontier,
            "at": _time.time(),
        },
    )


def read_handoff_acks(root: str, workers: int) -> dict[int, dict]:
    """{worker: ack} for every well-formed ack of workers 0..workers-1.
    The supervisor declares a handoff successful only when the set is
    COMPLETE and every ack matches the request's incarnation/target."""
    out: dict[int, dict] = {}
    for w in range(workers):
        obj = _lease_dir_read_json(root, f"{HANDOFF_ACK_PREFIX}{w}")
        if obj is not None and obj.get("worker") == w:
            out[w] = obj
    return out


def clear_handoff(root: str, workers: int) -> None:
    """Remove the request and every ack file — called by the supervisor
    after a handoff concludes (either way), so a stale request can never
    leak into the next incarnation."""
    keys = [HANDOFF_KEY] + [
        f"{HANDOFF_ACK_PREFIX}{w}" for w in range(workers)
    ]
    for key in keys:
        try:
            os.remove(os.path.join(root, *key.split("/")))
        except OSError:
            pass


# -- warm-standby promotion coordination files --
#
# Unplanned worker loss is coordinated through the same advisory lease/
# JSON mechanism as the planned handoff above — but the protocol is the
# mirror image: the group does NOT exit.  On a worker death the
# supervisor bumps the dead worker's PER-WORKER fence in the lease (so a
# half-dead writer can never publish again without fencing the whole
# incarnation — the survivors keep their incarnation and must keep
# publishing), posts ``lease/PROMOTE``, and waits:
#
#   1. the chosen standby acks ``lease/promote.ack.standby``, adopts the
#      dead worker's id + fence token, and falls into the normal worker
#      boot path (resume from the dead shard's committed generations);
#   2. every SURVIVOR's promote sentinel poisons its mesh, drain-commits
#      its consistent frontier in-process, acks
#      ``lease/promote.ack.<worker>``, and re-enters the event loop with
#      a fresh mesh — the OS process never exits;
#   3. the supervisor sees the complete ack set, records the promotion in
#      ``lease/promotions.json`` (scrub/top provenance), and clears the
#      request.  Any other outcome (standby death, missing ack, deadline)
#      → the two-tier fallback: whole-group restart, exactly as before
#      standbys existed.
#
# Standbys additionally maintain ``lease/standby.<sid>`` apply-cursor
# beacons (per-worker verified-generation cursors + lag) so operators —
# and ``pathway_tpu scrub`` — can see how warm the pool is.  All of it is
# advisory: torn or stale files degrade to "no promotion" and the
# restart fallback absorbs the loss.
PROMOTE_KEY = "lease/PROMOTE"
PROMOTE_ACK_PREFIX = "lease/promote.ack."
PROMOTIONS_KEY = "lease/promotions.json"
STANDBY_BEACON_PREFIX = "lease/standby."
PROMOTE_FORMAT = 1


def bump_worker_fence(backend: "BlobBackend", worker: int) -> int:
    """Fence ``worker`` (and only it) out of the root: bump its entry in
    the lease's per-worker fence map and return the new token.

    The promoted standby inherits the new token (``PATHWAY_WORKER_FENCE``)
    and passes ``_check_fence``; anything still writing as this worker
    with an older token — the dead worker's lingering writer threads, a
    zombie that was SIGKILLed but whose async publish is still in flight
    on a remote store — is rejected at its next commit point.  Distinct
    from the INCARNATION bump a whole-group restart performs: survivors
    keep publishing under the same incarnation, so promotion must never
    touch it.  ``acquire_lease`` rebuilds the lease without carrying
    ``fences`` forward, so a later restart-all clears every per-worker
    fence along with the incarnation bump that supersedes them."""
    lease = read_lease(backend)
    if lease is None:
        raise CheckpointError(
            "cannot fence a worker on an unleased root — promotion is a "
            "supervised-run protocol and the supervisor owns the lease"
        )
    fences = dict(lease.get("fences") or {})
    token = int(fences.get(str(worker), 0)) + 1
    fences[str(worker)] = token
    lease["fences"] = fences
    backend.put_atomic(LEASE_KEY, codec.frame_blob(_json.dumps(lease).encode()))
    return token


def post_promote_request(
    root: str,
    *,
    incarnation: int,
    worker: int,
    standby: int,
    fence: int,
    seq: int,
    workers: int,
    reason: str = "",
) -> None:
    """Supervisor side: ask standby ``standby`` to adopt dead ``worker``
    and every survivor to rejoin the mesh in-process."""
    _lease_dir_write_json(
        root,
        PROMOTE_KEY,
        {
            "format": PROMOTE_FORMAT,
            "incarnation": incarnation,
            "worker": worker,
            "standby": standby,
            "fence": fence,
            "seq": seq,
            "workers": workers,
            "reason": reason,
            "at": _time.time(),
        },
    )


def read_promote_request(root: str) -> dict | None:
    """The pending promotion request, or None when absent/unreadable/not
    well-formed (advisory: malformed never raises)."""
    obj = _lease_dir_read_json(root, PROMOTE_KEY)
    if obj is None or not all(
        isinstance(obj.get(k), int)
        for k in ("incarnation", "worker", "standby", "fence", "seq", "workers")
    ):
        return None
    return obj


def write_promote_ack(
    root: str, who: int | str, *, seq: int, worker: int, incarnation: int
) -> None:
    """Record participation in promotion ``seq``: ``who`` is a surviving
    worker id, or the string ``"standby"`` for the adopting standby
    (written BEFORE it takes the dead worker's id, so the ack never
    collides with the survivors' numeric files)."""
    _lease_dir_write_json(
        root,
        f"{PROMOTE_ACK_PREFIX}{who}",
        {
            "format": PROMOTE_FORMAT,
            "who": str(who),
            "seq": seq,
            "worker": worker,
            "incarnation": incarnation,
            "at": _time.time(),
        },
    )


def read_promote_acks(root: str, workers: int) -> dict[str, dict]:
    """{who: ack} for every promotion ack present.  Keys are stringified
    worker ids (survivors), ``"standby"`` (the chosen standby is alive
    and participating), and ``"adopted"`` (the standby finished waiting
    for the survivors and took the dead worker's identity — the
    supervisor's completion trigger, written LAST so clearing the files
    can never race the standby's own wait)."""
    out: dict[str, dict] = {}
    for who in ["standby", "adopted"] + [str(w) for w in range(workers)]:
        obj = _lease_dir_read_json(root, f"{PROMOTE_ACK_PREFIX}{who}")
        if obj is not None and obj.get("who") == who:
            out[who] = obj
    return out


def clear_promote(root: str, workers: int) -> None:
    """Remove the promotion request and every ack — supervisor side,
    after a promotion concludes either way."""
    keys = [
        PROMOTE_KEY,
        f"{PROMOTE_ACK_PREFIX}standby",
        f"{PROMOTE_ACK_PREFIX}adopted",
    ] + [f"{PROMOTE_ACK_PREFIX}{w}" for w in range(workers)]
    for key in keys:
        try:
            os.remove(os.path.join(root, *key.split("/")))
        except OSError:
            pass


_PROMOTIONS_CAP = 64


def append_promotion(root: str, record: dict) -> None:
    """Append one promotion record to the root's bounded promotion
    history (``lease/promotions.json``) — the provenance ``pathway_tpu
    scrub``/``top`` render and the workers re-export as the
    ``supervisor.promotions`` counter."""
    history = read_promotions(root)
    history.append(record)
    _lease_dir_write_json(
        root, PROMOTIONS_KEY, {"promotions": history[-_PROMOTIONS_CAP:]}
    )


def read_promotions(root: str) -> list[dict]:
    """The root's promotion history, oldest first; [] when absent/torn."""
    obj = _lease_dir_read_json(root, PROMOTIONS_KEY)
    if obj is None or not isinstance(obj.get("promotions"), list):
        return []
    return [p for p in obj["promotions"] if isinstance(p, dict)]


def write_standby_beacon(
    root: str,
    standby: int,
    *,
    cursors: dict[int, int],
    lag_s: float,
    verified_chunks: int,
    pid: int | None = None,
) -> None:
    """Standby side: publish this standby's apply cursor — the newest
    verified generation per worker shard — plus its apply lag."""
    _lease_dir_write_json(
        root,
        f"{STANDBY_BEACON_PREFIX}{standby}",
        {
            "format": PROMOTE_FORMAT,
            "standby": standby,
            "cursors": {str(w): g for w, g in cursors.items()},
            "lag_s": lag_s,
            "verified_chunks": verified_chunks,
            "pid": pid if pid is not None else os.getpid(),
            "at": _time.time(),
        },
    )


def read_standby_beacons(root: str) -> dict[int, dict]:
    """{standby id: beacon} for every well-formed standby apply-cursor
    beacon under the root's lease/ directory."""
    lease_dir = os.path.join(root, "lease")
    prefix = STANDBY_BEACON_PREFIX.rsplit("/", 1)[-1]
    out: dict[int, dict] = {}
    try:
        names = os.listdir(lease_dir)
    except OSError:
        return out
    for name in names:
        tail = name[len(prefix):]
        if not name.startswith(prefix) or not tail.isdigit():
            continue
        obj = _lease_dir_read_json(root, f"lease/{name}")
        if obj is not None and obj.get("standby") == int(tail):
            out[int(tail)] = obj
    return out


_BASE_SID_RE = None


def base_source_id(source_id: str) -> str:
    """Strip the per-worker ``-w<N>`` suffix of a snapshot source id.

    Multi-worker runs shard source logs as ``<name>-w<worker>``; a
    topology rescale matches old and new logs by this BASE name, so
    ``src-w3`` of a 4-worker root and ``src-w1`` of its 2-worker successor
    are the same logical source."""
    global _BASE_SID_RE
    if _BASE_SID_RE is None:
        import re

        _BASE_SID_RE = re.compile(r"-w\d+$")
    return _BASE_SID_RE.sub("", source_id)


def merge_offsets(offsets: list[Any], *, source: str = "?") -> Any:
    """Merge the reader offset frontiers of several old-topology workers
    into one frontier the re-striped reader can ``seek`` to.

    Per-file progress maps (the FileReader/S3 shape: ``{path: [mtime,
    units]}``) union — stripes are disjoint, and on the rare overlap (a
    file reassigned mid-rescale) the entry with the larger trailing
    progress value wins.  Row-count frontiers (``{"rows": n}``) cannot be
    re-striped: they are only mergeable when a single old worker held one
    (the non-partitioned-source case, which reads on worker 0 under every
    topology).  Opaque non-dict offsets merge only when identical.
    Raises :class:`CheckpointError` on an unmergeable combination — the
    source then cannot rescale and the operator must intervene.
    """
    present = [o for o in offsets if o is not None]
    if not present:
        return None
    if len(present) == 1:
        return present[0]
    if all(isinstance(o, dict) for o in present):
        if any("rows" in o for o in present):
            rows = [o for o in present if "rows" in o]
            if len(rows) > 1:
                raise CheckpointError(
                    f"persistence: source {source!r} committed row-count "
                    f"offset frontiers on {len(rows)} old workers — "
                    "row-count frontiers cannot be re-striped across a "
                    "topology rescale (give the source an offset-aware "
                    "reader, or clear the persistence root)"
                )
        merged: dict = {}
        for off in present:
            for k, v in off.items():
                if k not in merged:
                    merged[k] = v
                    continue
                prev = merged[k]
                try:
                    # per-file progress entries are [mtime, units]: keep
                    # the one that consumed more
                    if (
                        isinstance(v, (list, tuple))
                        and isinstance(prev, (list, tuple))
                        and len(v) == len(prev) >= 1
                        and v[-1] > prev[-1]
                    ):
                        merged[k] = v
                except TypeError:
                    pass  # incomparable: first wins, deterministically
        return merged
    first = present[0]
    if all(o == first for o in present[1:]):
        return first
    raise CheckpointError(
        f"persistence: source {source!r} committed opaque offset frontiers "
        "that differ across old workers — this source cannot rescale "
        "(clear the persistence root to deliberately re-ingest)"
    )


def _retain_generations() -> int:
    """How many committed generations to keep (deferred GC window)."""
    from pathway_tpu.internals.config import env_int

    return max(1, env_int("PATHWAY_CHECKPOINT_GENERATIONS"))


def _checkpoint_writers() -> int:
    """Background checkpoint writer threads; 0 = fully synchronous commits
    (the pre-pipelining inline path)."""
    from pathway_tpu.internals.config import env_int

    return max(0, env_int("PATHWAY_CHECKPOINT_WRITERS"))


def _inflight_cap_bytes() -> int:
    """Backpressure bound: bytes of raw snapshot data the epoch thread may
    hand to the writer pool before it must stall and let uploads drain."""
    from pathway_tpu.internals.config import env_int

    return max(1, env_int("PATHWAY_CHECKPOINT_INFLIGHT_MB")) << 20


def _publish_interval_s() -> float:
    """Minimum spacing between pipelined manifest publishes
    (``PATHWAY_CHECKPOINT_PUBLISH_INTERVAL_MS``, default 20): staged
    frontiers CONFLATE while the committer waits, so a tighter interval
    buys lower durability lag at the price of more manifest/fsync
    overhead per second.  0 publishes as fast as the store allows.
    Blocking commits (drains, finals) ignore it."""
    from pathway_tpu.internals.config import env_float

    return max(0.0, env_float("PATHWAY_CHECKPOINT_PUBLISH_INTERVAL_MS")) / 1000.0


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _approx_row_size(row: tuple) -> int:
    """Cheap wire-size estimate of one event for admission-cap accounting
    (the exact size is only known after the pool encodes the batch).
    Bulk carriers must be counted at their real size — a 6 KB embedding
    charged as 16 bytes would let the writer pool admit ~100x the
    configured in-flight cap before backpressure ever engaged."""
    n = 48
    if row:
        for v in row:
            if isinstance(v, (str, bytes)):
                n += len(v) + 16
            elif v is None or isinstance(v, (int, float, bool)):
                n += 16
            elif isinstance(v, (tuple, list)):
                n += _approx_row_size(tuple(v))
            else:
                nbytes = getattr(v, "nbytes", None)  # ndarray-likes
                if nbytes is not None:
                    n += int(nbytes) + 16
                else:
                    # Json / wrapped / pickled objects encode to payloads
                    # proportional to their repr — charge that, not a flat
                    # 16 bytes, or bulk documents would sail past the cap
                    try:
                        n += len(str(v)) + 32
                    except Exception:  # noqa: BLE001 - estimate only
                        n += 256
    return n

# Filesystem root of the persistence backend of the currently-running
# pipeline (UDF DiskCache reads it; PersistenceMode::UdfCaching,
# src/connectors/mod.rs:114).  Context-local so concurrent runs in one
# process each see their own root (UDFs execute in the runner's context);
# the process-global fallback — for code that reads the root from a thread
# outside any run context — is first-wins and released only by its owner.
_root_var: ContextVar[str | None] = ContextVar("pathway_tpu_active_root", default=None)
_active_root: str | None = None
_root_owner: object | None = None
_root_lock = threading.Lock()


def acquire_active_root(root: str) -> tuple[object | None, object]:
    """Claim the UDF-cache root for the current run; returns a release token."""
    global _active_root, _root_owner
    var_token = _root_var.set(root)
    with _root_lock:
        if _active_root is None:
            _root_owner = object()
            _active_root = root
            return (_root_owner, var_token)
        return (None, var_token)


def release_active_root(token: tuple[object | None, object] | None) -> None:
    global _active_root, _root_owner
    if token is None:
        return
    owner, var_token = token
    _root_var.reset(var_token)
    if owner is None:
        return
    with _root_lock:
        if owner is _root_owner:
            _active_root = None
            _root_owner = None


def active_root() -> str | None:
    ctx = _root_var.get()
    return ctx if ctx is not None else _active_root


# ---------------------------------------------------------------------------
# Blob backends (backends/{file,memory,mock,s3}.rs)
# ---------------------------------------------------------------------------


class BlobBackend:
    """Key → bytes store; keys are slash-separated paths."""

    def describe(self) -> str:
        """Human-readable location of this store, for error messages."""
        return type(self).__name__

    def put(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def get(self, key: str) -> bytes | None:
        raise NotImplementedError

    def list_keys(self, prefix: str) -> list[str]:
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError

    def put_atomic(self, key: str, data: bytes) -> None:
        self.put(key, data)

    def put_staged(self, key: str, data: bytes) -> None:
        """A put whose full durability may be DEFERRED to ``sync_staged``:
        the async commit pipeline stages many artifact writes and group-
        syncs them once at the commit barrier, before the manifest that
        references them publishes.  Stores whose ``put`` is already
        durable on return (object stores, memory) inherit this alias."""
        self.put(key, data)

    def sync_staged(self, keys: list[str]) -> None:
        """Make every prior ``put_staged`` of ``keys`` power-cut durable.
        Must complete before a manifest referencing them is published."""


def _fsync_dir(path: str) -> None:
    """Flush a directory's entries (new files, renames) to stable storage.

    Best-effort on filesystems that refuse O_DIRECTORY fsync (some network
    mounts): the entry write is then only as durable as the mount allows.
    """
    try:
        fd = os.open(path, os.O_RDONLY | getattr(os, "O_DIRECTORY", 0))
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


class FileBackend(BlobBackend):
    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def describe(self) -> str:
        return f"file://{os.path.abspath(self.root)}"

    def _path(self, key: str) -> str:
        return os.path.join(self.root, *key.split("/"))

    def put(self, key: str, data: bytes) -> None:
        # Durability contract: after put() returns, the chunk survives a
        # power-cut — the metadata commit (put_atomic) durably references
        # chunks, so chunks themselves must be durable first.  fsyncing the
        # FILE makes its bytes durable, but a newly-created directory ENTRY
        # lives in the parent directory: without fsyncing the dirfd a crash
        # can persist the metadata yet lose the chunk it points at.
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        _fsync_dir(os.path.dirname(path))

    def put_atomic(self, key: str, data: bytes) -> None:
        # Durability contract: the rename is the commit point — after
        # put_atomic() returns, a crash yields either the OLD or the NEW
        # content, never a torn file and never a lost rename.  The rename
        # itself is a parent-directory mutation, so the dirfd fsync below
        # is what makes the commit durable (fsyncing the file alone leaves
        # the rename in the page cache).  The staging name is per-process:
        # cluster-shared keys (the topology marker) are written by several
        # workers concurrently, and a shared ``.tmp`` would let one
        # writer's rename consume another's staging file mid-flight.
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _fsync_dir(os.path.dirname(path))
        # unlike the old fixed ".tmp" name, a per-pid staging file is
        # never reclaimed by the next writer of its key, so a crash
        # mid-put_atomic would leak it forever; opportunistically sweep
        # stale siblings (a live put_atomic stages and renames within
        # seconds — minutes-old staging files have no owner).  Supervised
        # restarts also settle *.tmp residue; this covers solo runs.
        self._sweep_stale_staging(os.path.dirname(path))

    @staticmethod
    def _sweep_stale_staging(dirname: str, *, max_age_s: float = 300.0) -> None:
        cutoff = _time.time() - max_age_s
        try:
            with os.scandir(dirname) as entries:
                for entry in entries:
                    if not entry.name.endswith(".tmp"):
                        continue
                    try:
                        if entry.stat().st_mtime < cutoff:
                            os.remove(entry.path)
                    except OSError:
                        pass
        except OSError:
            pass

    def put_staged(self, key: str, data: bytes) -> None:
        # file BYTES are made durable here (the writer pool spreads these
        # fsyncs across its threads, overlapped with epoch compute); the
        # parent-directory ENTRY is deferred to sync_staged, which the
        # commit barrier runs once per publish instead of once per chunk —
        # measured ~2x fewer fsync stalls on the upload path.  A/B against
        # deferring the file fsyncs too (write-only puts, batch fsync at
        # the barrier) showed the barrier then serializes the whole fsync
        # burst on the committer thread and loses ~15%.
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())

    def sync_staged(self, keys: list[str]) -> None:
        for dirname in {os.path.dirname(self._path(k)) for k in keys}:
            _fsync_dir(dirname)

    def get(self, key: str) -> bytes | None:
        path = self._path(key)
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            return f.read()

    def list_keys(self, prefix: str) -> list[str]:
        base = self._path(prefix)
        if not os.path.isdir(base):
            return []
        out = []
        for root, _dirs, files in os.walk(base):
            for f in files:
                if f.endswith(".tmp"):
                    continue
                full = os.path.join(root, f)
                rel = os.path.relpath(full, self.root)
                out.append(rel.replace(os.sep, "/"))
        return sorted(out)

    def delete(self, key: str) -> None:
        try:
            os.remove(self._path(key))
        except OSError:
            pass


class MemoryBackend(BlobBackend):
    """In-memory store; pass a shared dict to survive across runs in-process
    (Backend.mock semantics, persistence/__init__.py:71)."""

    def __init__(self, store: dict[str, bytes] | None = None):
        self.store: dict[str, bytes] = store if store is not None else {}
        self._lock = threading.Lock()

    def describe(self) -> str:
        return "memory"

    def put(self, key: str, data: bytes) -> None:
        with self._lock:
            self.store[key] = data

    def get(self, key: str) -> bytes | None:
        with self._lock:
            return self.store.get(key)

    def list_keys(self, prefix: str) -> list[str]:
        with self._lock:
            return sorted(k for k in self.store if k.startswith(prefix))

    def delete(self, key: str) -> None:
        with self._lock:
            self.store.pop(key, None)


class _PrefixedObjectStore(BlobBackend):
    """Shared behavior for object-storage backends (S3, Azure, ...): prefix
    handling, 404 → None/no-op on get/delete, and the rule that a transient
    5xx/403 must NOT read as "no snapshot" (that would silently restart the
    pipeline from scratch).  Object PUTs are atomic per object on these
    stores, so ``put_atomic`` is plain ``put``.

    Transient errors (429 / 5xx / client-flagged ``is_transient``) are
    retried with the shared udfs exponential-backoff schedule, bounded by
    ``PATHWAY_BLOB_RETRIES`` (default 3; ``PATHWAY_BLOB_RETRY_INITIAL_MS``
    tunes the first delay).  Auth errors (403) and not-found are NEVER
    retried — a 403 is a configuration problem, and retrying it would only
    delay the operator-visible failure.

    Subclasses set ``_error_cls`` and implement ``_put/_get/_list/_delete``.
    """

    _error_cls: type[Exception] = Exception
    _TRANSIENT_STATUS = (429, 500, 502, 503, 504)

    def __init__(self, client: Any, prefix: str = ""):
        self.client = client
        self.prefix = prefix.strip("/")
        from pathway_tpu.internals.config import env_int

        self.max_retries = max(0, env_int("PATHWAY_BLOB_RETRIES"))
        self.retry_initial_ms = max(1, env_int("PATHWAY_BLOB_RETRY_INITIAL_MS"))

    def _key(self, key: str) -> str:
        return f"{self.prefix}/{key}" if self.prefix else key

    def describe(self) -> str:
        name = type(self).__name__.removesuffix("Backend").lower()
        return f"{name}:{self.prefix}" if self.prefix else name

    @staticmethod
    def _is_not_found(exc: Exception) -> bool:
        # clients that distinguish auth-404s set is_not_found themselves
        return bool(
            getattr(exc, "is_not_found", getattr(exc, "status", 0) == 404)
        )

    def _is_transient(self, exc: Exception) -> bool:
        if not isinstance(exc, self._error_cls):
            return False
        if self._is_not_found(exc):
            return False
        return bool(getattr(exc, "is_transient", False)) or (
            getattr(exc, "status", None) in self._TRANSIENT_STATUS
        )

    def _with_retry(self, op: str, fn: Any, *args: Any) -> Any:
        """Run one store call, retrying transient errors with udfs backoff."""
        from pathway_tpu.internals.udfs.retries import (
            ExponentialBackoffRetryStrategy,
        )

        delays = ExponentialBackoffRetryStrategy(
            max_retries=self.max_retries,
            initial_delay=self.retry_initial_ms,
            backoff_factor=2,
            jitter_ms=max(1, self.retry_initial_ms // 2),
        ).delays()
        attempt = 0
        while True:
            try:
                return fn(*args)
            except Exception as exc:
                if not self._is_transient(exc):
                    raise
                try:
                    delay = next(delays)
                except StopIteration:
                    raise exc  # retry budget exhausted: surface the error
                attempt += 1
                _log.warning(
                    "%s: transient %s error on %s (attempt %d/%d): %s — "
                    "retrying in %.2fs",
                    self.describe(), op, args[0] if args else "?",
                    attempt, self.max_retries, exc, delay,
                )
                _time.sleep(delay)

    def put(self, key: str, data: bytes) -> None:
        self._with_retry("put", self._put, self._key(key), data)

    def get(self, key: str) -> bytes | None:
        try:
            return self._with_retry("get", self._get, self._key(key))
        except Exception as exc:
            if isinstance(exc, self._error_cls) and self._is_not_found(exc):
                return None
            raise

    def list_keys(self, prefix: str) -> list[str]:
        full = self._key(prefix)
        strip = len(self.prefix) + 1 if self.prefix else 0
        return sorted(k[strip:] for k in self._with_retry("list", self._list, full))

    def delete(self, key: str) -> None:
        try:
            self._with_retry("delete", self._delete, self._key(key))
        except Exception as exc:
            if isinstance(exc, self._error_cls) and self._is_not_found(exc):
                return
            raise

    def _put(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def _get(self, key: str) -> bytes:
        raise NotImplementedError

    def _list(self, prefix: str) -> list[str]:
        raise NotImplementedError

    def _delete(self, key: str) -> None:
        raise NotImplementedError


class S3Backend(_PrefixedObjectStore):
    """Object-storage persistence (backends/s3.rs analog) over the signed
    REST client in ``io/_s3http.py`` — works against AWS S3 and any
    S3-compatible endpoint (MinIO)."""

    @property
    def _error_cls(self):
        from pathway_tpu.io._s3http import S3Error

        return S3Error

    def _put(self, key: str, data: bytes) -> None:
        self.client.put_object(key, data)

    def _get(self, key: str) -> bytes:
        return self.client.get_object(key)

    def _list(self, prefix: str) -> list[str]:
        return [o["key"] for o in self.client.list_objects(prefix)]

    def _delete(self, key: str) -> None:
        self.client.delete_object(key)


class GcsBackend(_PrefixedObjectStore):
    """Google Cloud Storage persistence over the JSON-API client in
    ``io/_gcshttp.py`` — the natural store for TPU-pod deployments (ambient
    metadata-server identity, no key distribution)."""

    @property
    def _error_cls(self):
        from pathway_tpu.io._gcshttp import GcsError

        return GcsError

    def _put(self, key: str, data: bytes) -> None:
        self.client.put_object(key, data)

    def _get(self, key: str) -> bytes:
        return self.client.get_object(key)

    def _list(self, prefix: str) -> list[str]:
        return self.client.list_objects(prefix)

    def _delete(self, key: str) -> None:
        self.client.delete_object(key)


class AzureBackend(_PrefixedObjectStore):
    """Azure Blob persistence over the SharedKey REST client in
    ``io/_azureblob.py``."""

    @property
    def _error_cls(self):
        from pathway_tpu.io._azureblob import AzureBlobError

        return AzureBlobError

    def _put(self, key: str, data: bytes) -> None:
        self.client.put_blob(key, data)

    def _get(self, key: str) -> bytes:
        return self.client.get_blob(key)

    def _list(self, prefix: str) -> list[str]:
        return list(self.client.list_blobs(prefix))

    def _delete(self, key: str) -> None:
        self.client.delete_blob(key)


def _object_store_cfg(backend_cfg: Any) -> tuple[str, str, Any]:
    """``(bucket_or_container, prefix, client_or_None)`` from a Backend cfg.

    The root_path's ``scheme://bucket/prefix`` applies in BOTH construction
    modes — a pre-built client with a diverging root_path prefix would
    silently resume from a different object location.
    """
    path = getattr(backend_cfg, "path", "") or ""
    rest = path.split("://", 1)[-1]
    bucket, _, prefix = rest.partition("/")
    prefix = getattr(backend_cfg, "prefix", "") or prefix
    return bucket, prefix, getattr(backend_cfg, "client", None)


def backend_from_config(backend_cfg: Any) -> BlobBackend:
    """Build an engine backend from the user-facing ``pw.persistence.Backend``."""
    kind = getattr(backend_cfg, "kind", None)
    if kind == "filesystem":
        return FileBackend(backend_cfg.path)
    if kind == "mock":
        store = getattr(backend_cfg, "store", None)
        return MemoryBackend(store if isinstance(store, dict) else {})
    if kind == "s3":
        from pathway_tpu.io._s3http import AwsS3Settings

        settings = getattr(backend_cfg, "bucket_settings", None) or AwsS3Settings()
        path = getattr(backend_cfg, "path", "") or ""
        if path.startswith("s3://"):
            rest = path[5:]
            bucket, _, prefix = rest.partition("/")
        else:
            bucket, prefix = settings.bucket_name, path
        return S3Backend(settings.client(bucket), prefix)
    if kind == "gcs":
        from pathway_tpu.io._gcshttp import GcsClient

        bucket, prefix, client = _object_store_cfg(backend_cfg)
        if client is None:
            client = GcsClient(
                bucket,
                token_provider=getattr(backend_cfg, "token_provider", None),
                endpoint=getattr(backend_cfg, "endpoint", None),
            )
        return GcsBackend(client, prefix)
    if kind == "azure":
        from pathway_tpu.io._azureblob import AzureBlobClient

        container, prefix, client = _object_store_cfg(backend_cfg)
        if client is None:
            acct = getattr(backend_cfg, "account", None) or {}
            client = AzureBlobClient(
                acct.get("account_name", ""),
                container,
                account_key=acct.get("account_key", ""),
                endpoint=acct.get("endpoint"),
            )
        return AzureBackend(client, prefix)
    raise ValueError(f"unknown persistence backend {backend_cfg!r}")


# ---------------------------------------------------------------------------
# Pipelined async commit: writer pool + commit barrier
# ---------------------------------------------------------------------------


class CommitMetrics:
    """Thread-safe commit-pipeline telemetry: per-stage timings
    (buffer/frame/hash/upload/barrier) and in-flight gauges.

    ``snapshot()`` feeds the telemetry sampler (``engine/telemetry.py``),
    so the async-commit win — and any backpressure stall — is measurable
    on a live pipeline, not only in benchmarks."""

    _STAGES = ("buffer", "frame", "hash", "upload", "barrier")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.stage_s: dict[str, float] = dict.fromkeys(self._STAGES, 0.0)
        self.artifacts = 0
        self.bytes_written = 0
        self.commits_published = 0
        self.commits_noop = 0
        self.backpressure_s = 0.0
        self.inflight_bytes = 0
        self.inflight_jobs = 0
        self.max_inflight_bytes = 0
        # deferred-GC health: sweeps run, artifacts actually deleted, and
        # sweeps deferred because the newest generation failed read-back
        self.gc_runs = 0
        self.gc_deleted = 0
        self.gc_deferred = 0

    def add_stage(self, stage: str, seconds: float) -> None:
        with self._lock:
            self.stage_s[stage] += seconds

    def add_backpressure(self, seconds: float) -> None:
        with self._lock:
            self.backpressure_s += seconds

    def job_started(self, size: int) -> None:
        with self._lock:
            self.inflight_bytes += size
            self.inflight_jobs += 1
            if self.inflight_bytes > self.max_inflight_bytes:
                self.max_inflight_bytes = self.inflight_bytes

    def job_finished(self, size: int, *, ok: bool) -> None:
        with self._lock:
            self.inflight_bytes -= size
            self.inflight_jobs -= 1
            if ok:
                self.artifacts += 1
                self.bytes_written += size

    def commit_published(self, *, noop: bool) -> None:
        with self._lock:
            if noop:
                self.commits_noop += 1
            else:
                self.commits_published += 1

    def snapshot(self) -> dict[str, float]:
        """Gauge dict in telemetry metric-name form."""
        with self._lock:
            out = {
                f"checkpoint.commit.{stage}": value
                for stage, value in self.stage_s.items()
            }
            out["checkpoint.commit.backpressure"] = self.backpressure_s
            out["checkpoint.inflight.bytes"] = float(self.inflight_bytes)
            out["checkpoint.inflight.jobs"] = float(self.inflight_jobs)
            # the same in-flight state under the unified backlog.*
            # backpressure namespace (engine/freshness.py), so one view
            # ranks the commit pipeline against every other wait point
            out["backlog.checkpoint.bytes"] = float(self.inflight_bytes)
            out["backlog.checkpoint.jobs"] = float(self.inflight_jobs)
            out["checkpoint.inflight.bytes.max"] = float(self.max_inflight_bytes)
            out["checkpoint.artifacts"] = float(self.artifacts)
            out["checkpoint.bytes"] = float(self.bytes_written)
            out["checkpoint.commits"] = float(self.commits_published)
            out["checkpoint.commits.noop"] = float(self.commits_noop)
            out["checkpoint.gc.runs"] = float(self.gc_runs)
            out["checkpoint.gc.deleted"] = float(self.gc_deleted)
            out["checkpoint.gc.deferred"] = float(self.gc_deferred)
            return out

    def gc_run(self, *, deferred: bool, deleted: int = 0) -> None:
        with self._lock:
            self.gc_runs += 1
            self.gc_deleted += deleted
            if deferred:
                self.gc_deferred += 1


class _ArtifactJob:
    """Handle for one artifact write owned by the writer pool."""

    __slots__ = ("key", "size", "digest", "error", "_done")

    def __init__(self, key: str, size: int):
        self.key = key
        self.size = size
        self.digest: str | None = None
        self.error: BaseException | None = None
        self._done = threading.Event()

    def wait(self, timeout: float | None = None) -> bool:
        return self._done.wait(timeout)

    @property
    def done(self) -> bool:
        return self._done.is_set()


class _WriterPool:
    """Bounded background writers for checkpoint artifacts.

    ``submit`` takes ownership of the raw byte parts (zero-copy handoff —
    no ``b"".join`` on the caller's thread) and returns a job handle; a
    pool thread joins, frames, hashes and uploads.  Admission is bounded
    by ``cap_bytes`` of in-flight payload: once exceeded, ``submit``
    blocks — that stall IS the backpressure that keeps a slow store from
    buffering unbounded snapshot data in memory.

    Threads start lazily and exit after ``_IDLE_EXIT_S`` without work, so
    storages that never commit through the pool cost nothing.
    """

    _IDLE_EXIT_S = 10.0

    def __init__(
        self,
        backend: BlobBackend,
        metrics: CommitMetrics,
        *,
        worker: int = 0,
        writers: int = 2,
        cap_bytes: int = 256 << 20,
    ):
        self.backend = backend
        self.metrics = metrics
        self.worker = worker
        self.writers = max(1, writers)
        self.cap_bytes = cap_bytes
        self._cv = threading.Condition()
        self._queue: deque[tuple[_ArtifactJob, list[bytes], Any]] = deque()
        self._threads: list[threading.Thread] = []
        self._idle = 0
        self._inflight = 0  # admitted bytes, released at job completion
        # keys written via put_staged whose group sync is still owed; the
        # commit barrier drains this BEFORE any manifest publishes
        self._staged_keys: list[str] = []

    def submit(
        self,
        key: str,
        parts: list,
        *,
        encode: Callable[[list], bytes] | None = None,
        size_hint: int | None = None,
        sink: Callable[[str], None] | None = None,
    ) -> _ArtifactJob:
        """Queue one artifact write; ``sink(digest)`` runs on the pool
        thread after the upload succeeds, before the job reads done.

        ``parts`` is either byte chunks (joined on the pool) or, with
        ``encode``, raw items the pool encodes first — then ``size_hint``
        feeds the admission cap (accounting is symmetric on the hint, so
        an off estimate never leaks admitted bytes)."""
        size = sum(len(p) for p in parts) if size_hint is None else size_hint
        job = _ArtifactJob(key, size)
        t0 = _time.perf_counter()
        with self._cv:
            # backpressure: a single artifact may exceed the cap on an
            # empty pool (it must be writable at all), anything else waits
            while self._inflight > 0 and self._inflight + size > self.cap_bytes:
                self._cv.wait(0.05)
            waited = _time.perf_counter() - t0
            self._inflight += size
            # gauge BEFORE the job becomes poppable: a fast writer thread
            # could otherwise record job_finished first and drive the
            # exported in-flight gauges negative
            self.metrics.job_started(size)
            self._queue.append((job, parts, encode, sink))
            self._spawn_if_needed()
            self._cv.notify()
        if waited > 0.0005:
            self.metrics.add_backpressure(waited)
        return job

    def sync_staged_now(self) -> None:
        """Group-sync every staged artifact write (the deferred half of
        ``put_staged``).  Called at the commit barrier, strictly before a
        manifest publishes; a key staged concurrently with this call is
        synced by the next barrier, which necessarily precedes the first
        manifest that could reference it."""
        with self._cv:
            if not self._staged_keys:
                return
            keys = self._staged_keys
            self._staged_keys = []
        self.backend.sync_staged(keys)

    def _spawn_if_needed(self) -> None:  # call with self._cv held
        self._threads = [t for t in self._threads if t.is_alive()]
        if len(self._threads) < self.writers and len(self._queue) > self._idle:
            t = threading.Thread(
                target=self._run, daemon=True,
                name=f"pathway:ckpt-writer-{self.worker}",
            )
            t.start()
            self._threads.append(t)

    # pathway-lint: context=writer
    def _run(self) -> None:
        while True:
            with self._cv:
                deadline = _time.monotonic() + self._IDLE_EXIT_S
                while not self._queue:
                    self._idle += 1
                    try:
                        self._cv.wait(max(0.05, deadline - _time.monotonic()))
                    finally:
                        self._idle -= 1
                    if not self._queue and _time.monotonic() >= deadline:
                        # idle exit: DEREGISTER while still holding the cv,
                        # so a submit() racing this decision either enqueued
                        # before it (queue non-empty, no exit) or sees the
                        # pruned thread list and respawns — a job can never
                        # be orphaned behind a thread that decided to die
                        try:
                            self._threads.remove(threading.current_thread())
                        except ValueError:
                            pass
                        return
                job, parts, encode, sink = self._queue.popleft()
            self._execute(job, parts, encode, sink)
            with self._cv:
                self._inflight -= job.size
                self._cv.notify_all()
            self.metrics.job_finished(job.size, ok=job.error is None)
            job._done.set()

    def _execute(
        self, job: _ArtifactJob, parts: list, encode: Any, sink: Any
    ) -> None:
        try:
            t0 = _time.perf_counter()
            if encode is not None:
                data = encode(parts)
            else:
                data = parts[0] if len(parts) == 1 else b"".join(parts)
            t1 = _time.perf_counter()
            framed = codec.frame_blob(data)
            t2 = _time.perf_counter()
            digest = _sha256(framed)
            t3 = _time.perf_counter()
            # chaos hook: a writer_crash fault SIGKILLs here, mid-flight —
            # after hashing, before the upload — so the commit barrier
            # leaves the whole generation unreferenced (lazy import keeps
            # persistence ↔ faults acyclic at module load)
            from pathway_tpu.engine import faults as _faults

            _faults.maybe_crash_writer(worker=self.worker, key=job.key)
            self.backend.put_staged(job.key, framed)
            t4 = _time.perf_counter()
            with self._cv:
                self._staged_keys.append(job.key)
            m = self.metrics
            m.add_stage("buffer", t1 - t0)
            m.add_stage("frame", t2 - t1)
            m.add_stage("hash", t3 - t2)
            m.add_stage("upload", t4 - t3)
            job.digest = digest
            if sink is not None:
                sink(digest)
        except BaseException as exc:  # noqa: BLE001 - surfaced at the barrier
            job.error = exc
            _log.warning(
                "persistence: async write of %s to %s failed: %s",
                job.key, self.backend.describe(), exc,
            )


class _PendingCommit:
    """One staged-but-unpublished generation awaiting its commit barrier.

    ``sources`` maps source id → (manifest meta without digests, log):
    chunk digests resolve on the writer pool, so the committer fills them
    in AFTER the barrier, when every referenced chunk has landed."""

    __slots__ = ("seq", "sources")

    def __init__(self, seq: int, sources: dict[str, tuple[dict, Any]]):
        self.seq = seq
        self.sources = sources


# ---------------------------------------------------------------------------
# Per-source snapshot log
# ---------------------------------------------------------------------------


class SnapshotLog:
    """Append-only event log for one persisted source (input_snapshot.rs).

    Chunks are written inside an integrity frame (``codec.frame_blob``) and
    their SHA-256 digests accumulate in ``chunk_digests`` so the commit can
    pin the exact chunk contents into the generation manifest.  A ``None``
    digest marks a chunk written before framing existed (legacy store):
    it is read permissively but cannot be deep-verified.
    """

    def __init__(
        self,
        backend: BlobBackend,
        worker: int,
        source_id: str,
        *,
        pool: _WriterPool | None = None,
    ):
        self.backend = backend
        self.pool = pool
        self.prefix = f"snapshots/{worker}/{source_id}"
        self.chunks_written = 0
        self.chunk_digests: list[str | None] = []
        # sync mode: encoded event bytes; async mode: raw event tuples
        # (kind, key, row, time) encoded on the pool at flush
        self._buffer: list = []
        self._buffer_bytes = 0  # admission-cap estimate of the raw buffer
        # chunk index → in-flight pool job; reaped by barrier()
        self._inflight: dict[int, _ArtifactJob] = {}

    def record(self, key: int, row: tuple, diff: int) -> None:
        kind = codec.EV_INSERT if diff > 0 else codec.EV_DELETE
        if self.pool is not None:
            # raw-event handoff: the ~12 µs/row event encode runs on the
            # writer pool at flush, not here on the epoch thread; only a
            # cheap size estimate (admission-cap accounting) is paid inline
            ev = (kind, key, row, 0)
            size = _approx_row_size(row)
            for _ in range(abs(diff)):
                self._buffer.append(ev)
                self._buffer_bytes += size
        else:
            for _ in range(abs(diff)):
                self._buffer.append(codec.encode_event(kind, key, row))

    def record_advance(self, time: int) -> None:
        if self.pool is not None:
            self._buffer.append((codec.EV_ADVANCE_TIME, 0, (), time))
            self._buffer_bytes += 16
        else:
            self._buffer.append(
                codec.encode_event(codec.EV_ADVANCE_TIME, time=time)
            )

    @staticmethod
    def _encode_events(events: list[tuple]) -> bytes:
        """Encode a raw-event batch into chunk payload bytes (pool-side)."""
        return codec.encode_events(events)

    def flush_chunk(self) -> None:
        if not self._buffer:
            return
        index = self.chunks_written
        # keep digests index-aligned: a fallback resume overwrites orphaned
        # chunks above the committed prefix, so truncate before appending
        del self.chunk_digests[index:]
        key = f"{self.prefix}/{index:08d}"
        if self.pool is not None:
            # zero-copy handoff: the pool takes ownership of the raw event
            # batch — encode/join/frame/hash/upload all run off this
            # thread; the digest placeholder resolves via the sink before
            # the job reads done, so any barrier observing the job sees it
            parts = self._buffer
            self._buffer = []
            size_hint = self._buffer_bytes
            self._buffer_bytes = 0
            self.chunk_digests.append(None)
            self._inflight[index] = self.pool.submit(
                key, parts,
                encode=self._encode_events,
                size_hint=size_hint,
                sink=lambda digest, i=index: self._resolve_digest(i, digest),
            )
        else:
            framed = codec.frame_blob(b"".join(self._buffer))
            self._buffer.clear()
            self.backend.put(key, framed)
            self.chunk_digests.append(_sha256(framed))
        self.chunks_written = index + 1

    def _resolve_digest(self, index: int, digest: str) -> None:
        self.chunk_digests[index] = digest

    def barrier(self, committed: int) -> None:
        """Block until every in-flight chunk below ``committed`` is durably
        on the store (the per-log half of the commit barrier).  Raises
        :class:`CheckpointError` on the first failed write — the failed job
        stays registered so every later commit referencing that chunk fails
        too, instead of publishing a manifest that pins a missing chunk."""
        # list(dict) is a single C-level snapshot: the epoch thread's
        # flush_chunk inserts into _inflight concurrently, and iterating
        # the live dict here would intermittently raise RuntimeError
        for index in sorted(i for i in list(self._inflight) if i < committed):
            job = self._inflight[index]
            job.wait()
            if job.error is not None:
                raise CheckpointError(
                    f"persistence: async write of chunk {index} of "
                    f"{self.prefix} to backend {self.backend.describe()} "
                    f"failed: {job.error}"
                ) from job.error
            del self._inflight[index]

    def _chunk_context(self, i: int, generation: int) -> str:
        return (
            f"chunk {i} of {self.prefix} (generation {generation}) "
            f"in backend {self.backend.describe()}"
        )

    def read_committed(
        self,
        committed_chunks: int,
        *,
        start: int = 0,
        generation: int = 0,
        digests: list[str | None] | None = None,
        verified: set[str] | None = None,
    ):
        """Yield (kind, key, row, time) from chunks [start, committed_chunks).

        Errors name the backend, the source log prefix and the generation,
        so an operator can locate the damaged artifact directly.

        ``start`` — the first chunk index belonging to this log's own
        range (``SourceState.chunk_start``): a log re-seeded by a topology
        rescale appends ABOVE the superseded topology's committed chunks,
        whose rows are covered by the manifest's ``refs`` instead.

        ``verified`` — the storage's process-lifetime artifact cache: a
        chunk whose ``key:digest`` token is present was already digest-
        verified this process (by ``_load_state``), so replay skips
        re-hashing it; resume then hashes each chunk once, not twice.
        """
        yield from _read_chunks(
            self.backend,
            self.prefix,
            start,
            committed_chunks,
            digests,
            digests_base=0,
            generation=generation,
            verified=verified,
        )


def _read_chunks(
    backend: BlobBackend,
    prefix: str,
    start: int,
    end: int,
    digests: list[str | None] | None,
    *,
    digests_base: int = 0,
    generation: int = 0,
    verified: set[str] | None = None,
):
    """Yield decoded events from chunks ``[start, end)`` of one log prefix.

    The single chunk-read path shared by own-log replay
    (:meth:`SnapshotLog.read_committed`) and cross-worker ``refs`` replay
    after a topology rescale, so integrity handling cannot drift between
    them.  ``digests[i - digests_base]`` pins chunk ``i`` (manifest ref
    entries store digests relative to their own ``start``)."""

    def context(i: int) -> str:
        return (
            f"chunk {i} of {prefix} (generation {generation}) "
            f"in backend {backend.describe()}"
        )

    for i in range(start, end):
        key = f"{prefix}/{i:08d}"
        data = backend.get(key)
        if data is None:
            raise CheckpointError(
                "persistence: missing committed " + context(i)
            )
        j = i - digests_base
        digest = (
            digests[j] if digests is not None and 0 <= j < len(digests) else None
        )
        if (
            digest is not None
            and (verified is None or f"{key}:{digest}" not in verified)
            and _sha256(data) != digest
        ):
            raise CheckpointError(
                "persistence: digest mismatch on committed " + context(i)
            )
        try:
            payload = codec.unframe_blob(
                data,
                what=key,
                allow_legacy=digest is None,
                # a matched SHA-256 digest subsumes the frame CRC
                verify_crc=digest is None,
            )
        except codec.IntegrityError as exc:
            raise CheckpointError(
                f"persistence: corrupt committed {context(i)}: {exc}"
            ) from exc
        try:
            yield from codec.decode_events(payload)
        except ValueError as exc:
            # legacy (digest-less) chunks can rot undetected by any
            # frame; surface decode failures with the same locator
            # context as frame/digest failures
            raise CheckpointError(
                f"persistence: undecodable events in committed "
                f"{context(i)}: {exc}"
            ) from exc


# ---------------------------------------------------------------------------
# Worker storage tracker (tracker.rs WorkerPersistentStorage)
# ---------------------------------------------------------------------------


class SourceState:
    def __init__(self, log: SnapshotLog, committed_chunks: int, offset: Any):
        self.log = log
        self.committed_chunks = committed_chunks
        self.offset = offset  # opaque reader frontier
        self.pending_offset: Any = offset
        self.schema_digest: str | None = None
        # operator-persisting mode: no input event log; offsets commit only
        # once the epoch their rows were staged into has been PROCESSED —
        # operator snapshots cover processed epochs, so an offset past an
        # unprocessed row would lose it on crash
        self.operator_mode = False
        self.pending_offsets: list[tuple[Any, int]] = []  # (offset, epoch)
        # high-water mark of auto-generated row keys: resumed runs continue
        # the sequence so fresh rows never collide with keys that already
        # live inside restored operator state / replayed snapshots
        self.key_seq = 0
        # elastic-rescale state (engine-wide design: docs/fault_tolerance.md
        # "Elastic rescale"): chunk_start is the first chunk index of this
        # log's OWN range — a rescale re-seeds the log above the superseded
        # topology's committed chunks so they are never clobbered; refs are
        # pinned references {worker, source, start, chunks, chunk_digests}
        # to committed chunk ranges of OTHER (old-topology) logs, replayed
        # filtered by shard_to_worker(key, current_topology) and carried
        # forward in every manifest so the scheme composes across chained
        # rescales
        self.chunk_start = 0
        self.refs: list[dict] = []
        # the BASE (worker-suffix-free) source name, recorded in every
        # manifest so rescale matching never has to guess whether a
        # trailing ``-w<N>`` was appended by the engine or is part of the
        # user's own name
        self.base: str | None = None


class PersistentStorage:
    """Coordinates snapshot logs + the consistent-metadata commit for a worker."""

    def __init__(
        self,
        backend: BlobBackend,
        *,
        worker: int = 0,
        snapshot_interval_ms: int = 0,
        mode: Any = None,
    ):
        self.backend = backend
        self.worker = worker
        self.snapshot_interval_ms = snapshot_interval_ms
        self.mode = mode
        self.sources: dict[str, SourceState] = {}
        self.retain_generations = _retain_generations()
        # the cluster incarnation this writer belongs to (0 = unleased solo
        # run, fencing disabled).  Every commit-point write re-checks the
        # on-root lease against it — see FencedError.
        self.incarnation = writer_incarnation()
        # this writer's per-worker fence token (warm-standby promotion):
        # a promoted standby carries the token bump_worker_fence() minted
        # when its predecessor died; the predecessor's zombie writes carry
        # the older token and are rejected at their next commit point
        self.worker_fence = _worker_fence()
        # generational recovery state, filled by _load_state(): the adopted
        # (verified) generation, the generations rejected on the way down,
        # and whether we resumed from a pre-manifest legacy metadata file
        self.generation = 0
        self.recovered_generation = 0
        self.rejected_generations: list[tuple[int, str]] = []
        self.legacy_resume = False
        # artifacts (chunks, operator dumps) that already passed digest +
        # frame verification this process-lifetime; they are immutable once
        # written, so GC's pre-delete re-verification only pays for the
        # delta since the last check
        self._verified_artifacts: set[str] = set()
        # pipelined commit state: the bounded writer pool (None = fully
        # synchronous commits), the queue of staged-but-unpublished
        # generations, the committer thread publishing them in order, and
        # the sticky first async failure (surfaced on the next
        # commit/commit_async/drain call)
        self.metrics = CommitMetrics()
        # the commit-pipeline gauges ride the unified registry too, so the
        # /metrics scrape and OTLP export see them without runner plumbing;
        # WeakMethod registration means a dead storage drops out on its own
        _registry.get_registry().register_collector(
            f"persistence.worker{worker}", self.metrics.snapshot
        )
        # per-publish wall time on ms-scale bounds: the quantile estimates
        # (commit.duration.ms.p95 etc., engine/metrics.py) need buckets
        # that resolve the 0.1-100 ms publishes the pipelined committer
        # actually produces
        self._commit_hist = _registry.get_registry().histogram(
            "commit.duration.ms",
            "wall time of one generation-manifest publish (ms)",
            buckets=_registry.MS_BUCKETS,
            worker=worker,
        )
        writers = _checkpoint_writers()
        self._pool: _WriterPool | None = (
            _WriterPool(
                backend, self.metrics, worker=worker, writers=writers,
                cap_bytes=_inflight_cap_bytes(),
            )
            if writers > 0
            else None
        )
        self._pending: deque[_PendingCommit] = deque()
        self._pending_active = False
        self._pending_cv = threading.Condition()
        self._committer: threading.Thread | None = None
        self._async_error: BaseException | None = None
        self._last_submit_sig: Any = None
        # monotonically increasing durability counter: bumped when a staged
        # frontier becomes durable (manifest published, or confirmed no-op).
        # The runner acks broker offsets on THIS advancing, never on
        # commit_async returning — an async snapshot is not yet durable.
        self.published_seq = 0
        self._seq = 0
        # rate limiters for the BEST-EFFORT halves of a publish — the
        # advisory pointer refresh and the GC sweep.  Pipelined publishes
        # run at epoch cadence; paying 4+ fsyncs of advisory work per
        # generation would put the durability tax right back.  Sync
        # commits (drains, finals, direct callers) always do both.
        self._last_pointer_refresh = 0.0
        self._last_gc = 0.0
        self._publish_interval = _publish_interval_s()
        self._last_publish = 0.0
        # PersistenceMode::OperatorPersisting (mod.rs:108-116): persist
        # operator arrangements instead of input event logs, so resume is
        # O(state) not O(history)
        self.operator_persistence = (
            getattr(mode, "name", None) == "OPERATOR_PERSISTING"
        )
        # incremental GC indexes: this worker shard has exactly one writer
        # (this storage), so the manifest/operator key sets can be
        # maintained in memory after ONE full listing instead of walking
        # the whole persistence root on every published generation.
        # _known_generations seeds from _load_state()'s existing listing;
        # _op_index stays None until the first operator GC pays its single
        # full walk (catching residue from prior runs), then is O(delta).
        self._known_generations: set[int] = set()
        self._op_index: set[str] | None = None
        # elastic rescale: the topology (worker count) THIS process runs
        # under; _load_state compares it against the topology stamped on
        # the root's newest manifests and, on mismatch, enters repartition
        # resume — gathering every old worker's newest verified generation
        # into per-base-source refs replayed by shard range.
        self.topology = max(1, _cluster_processes())
        # the topology EPOCH this storage runs in (see TOPOLOGY_KEY):
        # incremented by every rescale, stamped into every manifest, and
        # the staleness test for shards whose stamped worker count
        # coincidentally matches the current one
        self.topology_seq = 0
        self.repartitioned_from: int | None = None
        # live shard handoff: once this storage has drained its handoff
        # commit (stamped handoff_to), it is FENCED — later commits no-op
        # (returning the already-durable seq) so the shutdown path's final
        # commit cannot advance the frontier past what the acks recorded
        self.handoff_fenced = False
        self.handoff_to: int | None = None
        # base source name -> {"offset", "key_seq", "schema", "refs",
        # "own_chunks"} gathered from the superseded topology's manifests;
        # None outside repartition resume
        self._repartition: dict[str, dict] | None = None
        self._metadata = self._load_state()
        self.replayed_rows = 0
        if (
            self.operator_persistence
            and self.rejected_generations
            and _cluster_processes() > 1
        ):
            # input-log mode tolerates one worker falling back further than
            # its peers (all state recomputes from replayed + re-read
            # input), but restored OPERATOR state on the peers already
            # contains the deltas this worker would re-send — the group
            # would double-apply them.  There is no cross-worker generation
            # consensus yet, so refuse rather than corrupt.
            raise CheckpointError(
                f"persistence: worker {self.worker} fell back past damaged "
                f"generation(s) {[g for g, _ in self.rejected_generations]} "
                "in operator-persisting mode, but the other workers of the "
                "group may hold newer operator state — divergent rollback "
                "would double-apply exchanged deltas. Repair the damaged "
                "generation (see `pathway_tpu scrub`) or clear every "
                "worker's shard to restart the group consistently."
            )
        # fast-fail for zombies: a stale-incarnation worker must not even
        # resume (its replay would feed a run whose every publish will be
        # rejected anyway) — cheap, because the lease is one tiny read
        self._check_fence("resume from")
        self._op_gen = int(self._metadata.get("operators", {}).get("gen", 0))
        # set by the runner: returns {node_id: bytes} of dirty operator
        # states + the graph digest, collected at commit time; confirm is
        # invoked only after the referencing metadata write succeeds
        self.collect_operator_states: Any = None
        self.confirm_operator_commit: Any = None
        # record/replay mode (PATHWAY_SNAPSHOT_ACCESS): None = both
        # directions (ordinary persistence), "record" = write-only,
        # "replay" = read snapshots; continue_after_replay then decides
        # whether live connector data follows the replayed prefix
        self.snapshot_access: str | None = None
        self.continue_after_replay = True

    # -- incarnation fencing --
    def _check_fence(self, what: str) -> None:
        """Refuse ``what`` when the root's lease shows a newer incarnation.

        Called immediately before every commit-point write.  One tiny
        lease read per publish (publishes are already rate-limited); a
        missing or unreadable lease never fences — fencing is only as
        strong as the supervisor that maintains the lease, and a solo run
        (incarnation 0) skips the check entirely."""
        if self.incarnation <= 0:
            return
        lease = read_lease(self.backend)
        if lease is None:
            return
        if lease["incarnation"] > self.incarnation:
            _registry.get_registry().counter(
                "persistence.fenced",
                "commit-point writes rejected because a newer incarnation "
                "owns the root",
                worker=self.worker,
            ).inc()
            _blackbox.record(
                "persistence.fenced", worker=self.worker, what=what,
                incarnation=self.incarnation, lease=lease["incarnation"],
            )
            raise FencedError(
                f"persistence: worker {self.worker} of incarnation "
                f"{self.incarnation} is fenced off {self.backend.describe()}: "
                f"the lease shows incarnation {lease['incarnation']} — a "
                f"newer cluster incarnation owns this root; refusing to "
                f"{what} (this process is a zombie from a superseded restart "
                "attempt and must terminate)"
            )
        # per-worker fence (same incarnation): a warm-standby promotion
        # fenced this worker id specifically — bump_worker_fence() minted
        # a newer token for the promoted standby, and any writer still
        # carrying the older token is the dead worker's zombie
        fences = lease.get("fences") or {}
        fence = fences.get(str(self.worker), 0)
        if isinstance(fence, int) and fence > self.worker_fence:
            _registry.get_registry().counter(
                "persistence.fenced",
                "commit-point writes rejected because a newer incarnation "
                "owns the root",
                worker=self.worker,
            ).inc()
            _blackbox.record(
                "persistence.fenced", worker=self.worker, what=what,
                incarnation=self.incarnation, worker_fence=self.worker_fence,
                lease_fence=fence,
            )
            raise FencedError(
                f"persistence: worker {self.worker} (fence token "
                f"{self.worker_fence}) is fenced off "
                f"{self.backend.describe()}: the lease carries per-worker "
                f"fence {fence} — a standby was promoted into this worker "
                f"id; refusing to {what} (this process is the dead "
                "worker's zombie and must terminate)"
            )

    def fence_for_handoff(self, to_workers: int) -> None:
        """Enter the handoff fence: the NEXT commit is the handoff commit
        (stamped ``handoff_to``), every commit after it silently no-ops.

        Called by the runner immediately before its handoff drain commit;
        the fence guarantees the frontier recorded in the ack files is
        exactly the frontier the successor topology replays — nothing the
        shutdown path does afterwards can move it."""
        self.handoff_to = to_workers

    def _seal_handoff_fence(self) -> None:
        if self.handoff_to is not None:
            self.handoff_fenced = True

    def _zombie_stall(self, spec: Any) -> None:
        """The ``zombie`` fault: wedge this publish until the lease shows a
        NEWER incarnation — the deterministic re-creation of a stale writer
        whose in-flight publish lands after the respawned cluster took
        over.  The fence check that follows must then reject it.  Bounded
        (``delay_ms``, default 30 s) so a mis-set plan cannot hang a run
        forever; gating is on on-disk lease state, never on timing."""
        deadline = _time.monotonic() + (
            float(spec.delay_ms) / 1000.0 if spec.delay_ms else 30.0
        )
        while _time.monotonic() < deadline:
            lease = read_lease(self.backend)
            if lease is not None and lease["incarnation"] > self.incarnation:
                return
            _time.sleep(0.02)

    # -- metadata / manifests --
    def _meta_key(self) -> str:
        return f"{METADATA_FILE}.{self.worker}"

    def _manifest_prefix(self) -> str:
        return f"manifests/{self.worker}/"

    def _manifest_key(self, generation: int) -> str:
        return f"{self._manifest_prefix()}{generation:08d}"

    def _list_generations(self) -> dict[int, str]:
        """{generation: manifest key} for every manifest blob on the store."""
        out: dict[int, str] = {}
        for key in self.backend.list_keys(self._manifest_prefix()):
            tail = key.rsplit("/", 1)[-1]
            if tail.isdigit():
                out[int(tail)] = key
        return out

    def _scan_root_manifests(self) -> dict[int, list[tuple[int, str]]]:
        """{worker: [(generation, key) newest-first]} for EVERY manifest on
        the root — the cross-worker view a topology-rescale resume (and
        orphan-topology GC) reads.  One listing; the common same-topology
        resume never calls this."""
        out: dict[int, list[tuple[int, str]]] = {}
        for key in self.backend.list_keys("manifests/"):
            parts = key.split("/")
            if len(parts) == 3 and parts[1].isdigit() and parts[2].isdigit():
                out.setdefault(int(parts[1]), []).append((int(parts[2]), key))
        for entries in out.values():
            entries.sort(reverse=True)
        return out

    def _write_topology_marker(self, marker: dict | None) -> None:
        """Publish (or refresh) the root's topology-epoch marker for the
        epoch this repartition opened.  Idempotent across the workers of
        one rescale: they all compute the same (seq, workers) and the
        write is a whole-blob atomic put."""
        if (
            marker is not None
            and marker.get("workers") == self.topology
            and marker.get("seq") == self.topology_seq
        ):
            return
        payload = {
            "format": TOPOLOGY_FORMAT,
            "seq": self.topology_seq,
            "workers": self.topology,
            "from_workers": self.repartitioned_from,
            "at": _time.time(),
        }
        self.backend.put_atomic(
            TOPOLOGY_KEY, codec.frame_blob(_json.dumps(payload).encode())
        )

    def _gather_repartition(
        self,
        root: dict[int, list[tuple[int, str]]],
        own_adopted: dict | None,
        *,
        seq: int,
    ) -> dict[str, dict]:
        """Gather the superseded topology's committed state into per-base
        repartition metadata: for every worker prefix on the root, adopt
        its newest fully verified generation, flatten its sources' own
        chunk ranges and carried ``refs`` into one deduplicated ref set per
        BASE source name, and merge the reader offset frontiers.

        A converged worker (manifest already stamped with the CURRENT
        topology — the mixed state a crash mid-rescale leaves) contributes
        only its carried refs: its own post-rescale chunks are replayed by
        that worker itself, unfiltered, and re-routed by the exchange.  An
        unconverged or orphaned worker's own range becomes a ref, replayed
        by every new worker filtered to its shard.  Refs dedup by
        ``(worker, source, start)`` and a containment filter — the same
        old range reached through several manifests must replay once,
        while DISJOINT sub-ranges of one log (a carried ref covering the
        original epoch plus the own range a later epoch appended above
        it) must each survive."""
        if self.operator_persistence:
            raise CheckpointError(
                f"persistence: worker {self.worker} found checkpoints "
                f"written under a different worker topology in backend "
                f"{self.backend.describe()}, but operator-persisting "
                "snapshots are opaque per-node state and cannot be "
                "re-partitioned by shard range. Resume at the original "
                "worker count, or clear the persistence root to start "
                "fresh."
            )
        bases: dict[str, dict] = {}
        refs_seen: dict[tuple[str, int, str, int], dict] = {}
        for w in sorted(root):
            if w == self.worker:
                adopted = own_adopted  # already verified (or absent)
            else:
                adopted = None
                for gen, key in root[w]:
                    manifest, reason = _read_manifest(self.backend, key)
                    if manifest is None:
                        self.rejected_generations.append(
                            (gen, f"worker {w}: {reason or 'unreadable'}")
                        )
                        continue
                    problems = verify_manifest(
                        self.backend, w, manifest,
                        cache=self._verified_artifacts,
                    )
                    if problems:
                        self.rejected_generations.append(
                            (gen, f"worker {w}: " + "; ".join(problems[:3]))
                        )
                        continue
                    adopted = manifest
                    break
            if adopted is None and root[w]:
                # symmetric for every shard, our own included: a worker
                # whose generations all failed verification cannot be
                # silently dropped from the repartition
                raise CheckpointError(
                    f"persistence: topology rescale needs worker {w}'s "
                    f"committed state, but none of its {len(root[w])} "
                    f"generation(s) in backend {self.backend.describe()} "
                    "verified — refusing to repartition with data loss "
                    "(run `pathway_tpu scrub` to inspect the damage)"
                )
            if adopted is None:
                continue
            m_top = adopted.get("topology")
            # converged = already republished in the epoch being joined:
            # that worker replays its own post-rescale chunks itself.  A
            # NEW rescale (seq above every stamp) converges nothing.
            converged = (
                isinstance(m_top, int)
                and m_top == self.topology
                and int(adopted.get("topology_seq", 0)) == seq
            )
            for sid, meta in (adopted.get("sources") or {}).items():
                # the recorded base name is authoritative (a user-chosen
                # name may itself end in -w<N>); the strip heuristic only
                # covers manifests written before base recording
                base = meta.get("base") or base_source_id(sid)
                entry = bases.setdefault(
                    base,
                    {"offsets": [], "key_seq": 0, "schema": None, "own": {}},
                )
                entry["offsets"].append(_offset_from_json(meta.get("offset")))
                if entry["schema"] is None and meta.get("schema") is not None:
                    entry["schema"] = meta["schema"]
                start = int(meta.get("chunk_start", 0))
                chunks = int(meta.get("chunks", 0))
                if w == self.worker:
                    entry["key_seq"] = max(
                        entry["key_seq"], int(meta.get("key_seq", 0))
                    )
                    entry["own"][sid] = chunks
                candidates = list(meta.get("refs") or [])
                if not converged and chunks > start:
                    candidates.append(
                        {
                            "worker": w,
                            "source": sid,
                            "start": start,
                            "chunks": chunks,
                            "chunk_digests": list(
                                meta.get("chunk_digests") or []
                            ),
                        }
                    )
                for ref in candidates:
                    rkey = (
                        base,
                        int(ref["worker"]),
                        str(ref["source"]),
                        int(ref.get("start", 0)),
                    )
                    prev = refs_seen.get(rkey)
                    if prev is None or int(ref["chunks"]) > int(prev["chunks"]):
                        refs_seen[rkey] = dict(ref)
        for base, entry in bases.items():
            # per-log range filter: ranges of one (worker, source) log are
            # generated start-monotone across epochs, so any two are
            # disjoint or nested — keep every range not fully covered by
            # an already-kept one (dedup), never conflate disjoint ones
            groups: dict[tuple[int, str], list[dict]] = {}
            for (b, w, s, _start), ref in refs_seen.items():
                if b == base:
                    groups.setdefault((w, s), []).append(ref)
            refs: list[dict] = []
            for w, s in sorted(groups):
                ranges = sorted(
                    groups[(w, s)],
                    key=lambda r: (int(r.get("start", 0)), -int(r["chunks"])),
                )
                kept: list[dict] = []
                for ref in ranges:
                    rs, rc = int(ref.get("start", 0)), int(ref["chunks"])
                    if any(
                        int(k.get("start", 0)) <= rs and int(k["chunks"]) >= rc
                        for k in kept
                    ):
                        continue
                    kept.append(ref)
                refs.extend(kept)
            entry["refs"] = refs
            entry["offset"] = merge_offsets(entry.pop("offsets"), source=base)
        return bases

    def _load_state(self) -> dict:
        """Adopt the newest FULLY VERIFIED generation, falling back
        generation-by-generation past damaged ones (torn manifest, missing
        or corrupt chunk, digest mismatch).  Raises :class:`CheckpointError`
        when generations exist but none verifies — silently starting fresh
        would break exactly-once for sources with externally committed
        offsets.  The one full manifest listing here also seeds the
        in-memory generation index incremental GC runs against.

        When the adopted manifest (or the rest of the root) was written
        under a DIFFERENT worker topology than this process runs in, the
        resume becomes a **repartition resume**: the committed state of
        every old worker is gathered into shard-filtered ``refs``
        (:meth:`_gather_repartition`) and this worker starts a fresh
        metadata lineage that republishes under the new topology.

        Verification reads every chunk of the candidate generation BEFORE
        adoption, and replay later re-fetches them (the verified-artifact
        cache skips the re-hash, not the re-read): falling back is only
        possible while nothing has been replayed into live input sessions
        yet, so the doubled read is the price of never adopting a
        generation that cannot be fully restored."""
        gens = self._list_generations()
        self._known_generations = set(gens)
        adopted: dict | None = None
        adopted_gen = 0
        for gen in sorted(gens, reverse=True):
            manifest, reason = _read_manifest(self.backend, gens[gen])
            if manifest is None:
                self.rejected_generations.append((gen, reason or "unreadable"))
                continue
            problems = verify_manifest(
                self.backend, self.worker, manifest,
                cache=self._verified_artifacts,
            )
            if problems:
                self.rejected_generations.append(
                    (gen, "; ".join(problems[:3]))
                )
                continue
            adopted, adopted_gen = manifest, gen
            break
        # -- elastic rescale detection ---------------------------------
        own_topo = adopted.get("topology") if adopted is not None else None
        own_seq = (
            int(adopted.get("topology_seq", 0)) if adopted is not None else 0
        )
        marker = read_topology_marker(self.backend)
        repartition_from: int | None = None
        new_seq = 0
        root_manifests: dict[int, list[tuple[int, str]]] = {}
        if marker is not None:
            self.topology_seq = int(marker["seq"])
            if marker["workers"] != self.topology:
                # a NEW rescale of a root that has rescaled before: open
                # the next topology epoch
                repartition_from = int(marker["workers"])
                new_seq = int(marker["seq"]) + 1
            elif (
                adopted is None
                or own_topo != self.topology
                or own_seq != int(marker["seq"])
            ):
                # this shard is STALE for the current epoch (it crashed
                # mid-rescale, or its stamped worker count only
                # coincidentally matches after a round trip) — or absent
                # entirely: re-join the current epoch by gathering refs
                root_manifests = self._scan_root_manifests()
                repartition_from = (
                    own_topo
                    if isinstance(own_topo, int)
                    and own_topo != self.topology
                    else int(marker.get("from_workers") or marker["workers"])
                )
                new_seq = int(marker["seq"])
        elif adopted is None or not isinstance(own_topo, int) or (
            own_topo != self.topology
        ):
            root_manifests = self._scan_root_manifests()
            if isinstance(own_topo, int) and own_topo != self.topology:
                repartition_from = own_topo
            else:
                orphans = [w for w in root_manifests if w >= self.topology]
                stamp = None
                if adopted is None:
                    # no committed state of our own: a rescale is still
                    # recognizable from the peers' topology stamps
                    for w in sorted(root_manifests):
                        if w == self.worker:
                            continue
                        for _gen, key in root_manifests[w]:
                            m, _r = _read_manifest(self.backend, key)
                            if m is None:
                                continue
                            t = m.get("topology")
                            if isinstance(t, int):
                                stamp = t
                            break
                        if stamp is not None:
                            break
                if stamp is not None and stamp != self.topology:
                    repartition_from = stamp
                elif orphans:
                    # worker prefixes outside the current topology: the
                    # root was written by a larger (possibly pre-stamp)
                    # cluster — their shards must be re-partitioned, not
                    # silently dropped
                    repartition_from = max(orphans) + 1
            if repartition_from is not None:
                new_seq = 1  # the root's first rescale opens epoch 1
            elif (
                adopted is not None
                and not isinstance(own_topo, int)
                and self.topology > 1
            ):
                # a pre-topology-stamp root resumed multi-worker: a GROW
                # of such a root is undetectable (stamps are what make the
                # old stripe layout provable), so it would resume
                # mis-striped silently.  We cannot distinguish it from a
                # legitimate same-count resume — warn loudly instead of
                # breaking legacy roots.
                _log.warning(
                    "persistence: worker %d resumes a legacy checkpoint "
                    "root (no topology stamps) under %d workers — if this "
                    "root was written by a DIFFERENT worker count, the "
                    "resume is mis-striped; legacy roots can only be "
                    "resumed at their original count (this run's commits "
                    "add the stamps that make future rescales safe)",
                    self.worker, self.topology,
                )
        if repartition_from is not None:
            # a zombie from a superseded incarnation must not even begin
            # re-partitioning (let alone write the topology marker below)
            self._check_fence("repartition the root")
            if not root_manifests:
                root_manifests = self._scan_root_manifests()
            self.repartitioned_from = repartition_from
            self.topology_seq = new_seq
            self.generation = self.recovered_generation = adopted_gen
            self._repartition = self._gather_repartition(
                root_manifests, own_adopted=adopted, seq=new_seq
            )
            self._write_topology_marker(marker)
            _registry.get_registry().counter(
                "persistence.repartition.sources",
                "base sources re-partitioned by a topology-rescale resume",
                worker=self.worker,
            ).inc(max(1, len(self._repartition)))
            _blackbox.record(
                "checkpoint.repartition", worker=self.worker,
                from_topology=repartition_from, to_topology=self.topology,
                bases=sorted(self._repartition),
            )
            _log.warning(
                "persistence: worker %d resumes under topology %d from a "
                "root written by %d worker(s) in %s — re-partitioning %d "
                "source(s) by shard range",
                self.worker, self.topology, repartition_from,
                self.backend.describe(), len(self._repartition),
            )
            return {"sources": {}}
        if adopted is not None:
            gen = adopted_gen
            self.generation = self.recovered_generation = gen
            _blackbox.record(
                "checkpoint.recovery", worker=self.worker, generation=gen,
                rejected=[g for g, _ in self.rejected_generations],
            )
            if self.rejected_generations:
                _log.warning(
                    "persistence: worker %d fell back to generation %d in "
                    "%s — rejected newer generation(s): %s",
                    self.worker, gen, self.backend.describe(),
                    "; ".join(f"{g}: {r}" for g, r in self.rejected_generations),
                )
            return adopted
        # no manifest verified — try the pre-generational metadata file
        raw = self.backend.get(self._meta_key())
        if raw is not None:
            try:
                obj = _json.loads(raw.decode())
            except ValueError as exc:
                raise CheckpointError(
                    f"persistence: metadata file {self._meta_key()} in "
                    f"backend {self.backend.describe()} is undecodable "
                    f"({exc}) and no verified generation manifest exists"
                ) from exc
            if "generation" not in obj and "sources" in obj:
                self.legacy_resume = True
                return obj
            if "generation" in obj and not gens:
                # a new-format pointer survived but the manifests it points
                # at are GONE (partial restore, deleted prefix): this root
                # HAD committed state — starting fresh would silently
                # duplicate processing for externally-committed offsets
                raise CheckpointError(
                    f"persistence: {self._meta_key()} in backend "
                    f"{self.backend.describe()} records committed generation "
                    f"{obj.get('generation')} but no generation manifests "
                    "exist under "
                    f"{self._manifest_prefix()!r} — the root was partially "
                    "restored or its manifests were deleted (clear the "
                    "persistence directory to deliberately start fresh)"
                )
        if self.rejected_generations:
            raise CheckpointError(
                f"persistence: worker {self.worker} has "
                f"{len(self.rejected_generations)} checkpoint generation(s) "
                f"in backend {self.backend.describe()} but NONE verified — "
                "refusing to silently restart from scratch (run "
                "`pathway_tpu scrub` on the root to inspect the damage; "
                "clear the persistence directory to deliberately start "
                "fresh). Rejected: "
                + "; ".join(f"{g}: {r}" for g, r in self.rejected_generations)
            )
        return {"sources": {}}

    def _advance_sources(self, processed_up_to: int | None) -> None:
        """Advance each source's committed frontier to its flushed state."""
        for sid, st in self.sources.items():
            if st.operator_mode:
                while st.pending_offsets and (
                    processed_up_to is None
                    or st.pending_offsets[0][1] <= processed_up_to
                ):
                    st.offset = st.pending_offsets.pop(0)[0]
                st.pending_offset = st.offset
            else:
                st.committed_chunks = st.log.chunks_written
                st.offset = st.pending_offset

    def _state_sig(self) -> list:
        """Cheap equality token for the advanced commit frontier: lets
        ``commit_async`` skip staging a generation when nothing moved
        (a false inequality only costs one no-op pending commit)."""
        return [
            (sid, st.committed_chunks, st.offset, st.key_seq)
            for sid, st in sorted(self.sources.items())
        ]

    @staticmethod
    def _source_meta(st: SourceState) -> dict:
        """Manifest source entry WITHOUT chunk digests (the async path
        fills those in post-barrier).  ``chunk_start``/``refs`` ride every
        manifest so a rescaled root stays self-describing across resumes."""
        meta: dict[str, Any] = {
            "chunks": st.committed_chunks,
            "offset": _offset_to_json(st.offset),
            "schema": st.schema_digest,
            "key_seq": st.key_seq,
        }
        if st.base is not None:
            meta["base"] = st.base
        if st.chunk_start:
            meta["chunk_start"] = st.chunk_start
        if st.refs:
            meta["refs"] = st.refs
        return meta

    def commit(
        self, processed_up_to: int | None = None, full_operator_dump: bool = False
    ) -> int:
        """Atomically commit the current consistent frontier as a new
        checkpoint generation, BLOCKING until it is durable.  Returns the
        durability sequence of this commit (already published on return).

        Only chunks flushed at offset markers are committed — the mid-batch
        event buffer stays out, so the committed (chunks, offset) pair always
        refers to the same row prefix.  No-op when nothing advanced.

        Any generations previously staged via :meth:`commit_async` are
        drained (published in order) first, and the commit barrier waits
        for every in-flight chunk of the committed prefix — so direct
        callers keep exact pre-pipelining semantics: when this returns,
        everything flushed so far is durably committed.

        The atomically-written generation manifest (chunk list + digests +
        operator/graph digest) IS the commit point; the legacy
        ``metadata.json.<worker>`` pointer is refreshed afterwards for
        humans and post-mortem tooling.  Superseded generations are GC'd
        only once they fall out of the retention window
        (``PATHWAY_CHECKPOINT_GENERATIONS``), so recovery always has
        verified fallbacks.

        Operator-persisting mode additionally dumps dirty operator states
        (via ``collect_operator_states``) — hashed and uploaded in parallel
        on the writer pool — and gates source offsets on ``processed_up_to``
        (the last epoch the engine ran; None = all).
        """
        if self.handoff_fenced:
            # the handoff commit already landed and its frontier is what
            # the ack files (and the successor topology) recorded — any
            # later commit (the shutdown path's final full dump) must not
            # move it.  Silent no-op by contract, not an error: the
            # shutdown path is shared with ordinary clean finishes.
            return self.published_seq
        self._drain_pending()
        self._advance_sources(processed_up_to)
        # commit barrier: every in-flight chunk of the committed prefix
        # must be durable before a manifest may reference it
        t0 = _time.perf_counter()
        for st in self.sources.values():
            if not st.operator_mode:
                st.log.barrier(st.committed_chunks)
        self.metrics.add_stage("barrier", _time.perf_counter() - t0)
        metadata: dict[str, Any] = {
            "sources": {
                sid: {
                    **self._source_meta(st),
                    "chunk_digests": st.log.chunk_digests[
                        st.chunk_start : st.committed_chunks
                    ],
                }
                for sid, st in self.sources.items()
            }
        }
        if self.operator_persistence and self.collect_operator_states is not None:
            dirty, digest = self.collect_operator_states(full_operator_dump)
            op_meta = {
                node_id: _op_ref(ref)
                for node_id, ref in (
                    self._metadata.get("operators", {}).get("nodes", {}).items()
                )
            }
            if dirty:
                self._op_gen += 1
                if self._pool is not None:
                    # the dirty dumps of one commit frame/hash/upload in
                    # PARALLEL on the writer pool instead of serially
                    jobs: list[tuple[str, _ArtifactJob]] = []
                    for node_id, blob in dirty.items():
                        key = f"operators/{self.worker}/{self._op_gen}/{node_id}"
                        if self._op_index is not None:
                            self._op_index.add(key)
                        ref = {"key": key, "digest": None}
                        op_meta[str(node_id)] = ref
                        jobs.append(
                            (
                                key,
                                self._pool.submit(
                                    key,
                                    [blob],
                                    sink=lambda d, r=ref: r.__setitem__(
                                        "digest", d
                                    ),
                                ),
                            )
                        )
                    t0 = _time.perf_counter()
                    for key, job in jobs:
                        job.wait()
                        if job.error is not None:
                            raise CheckpointError(
                                f"persistence: async write of operator dump "
                                f"{key} to backend "
                                f"{self.backend.describe()} failed: "
                                f"{job.error}"
                            ) from job.error
                    self.metrics.add_stage(
                        "barrier", _time.perf_counter() - t0
                    )
                else:
                    for node_id, blob in dirty.items():
                        key = f"operators/{self.worker}/{self._op_gen}/{node_id}"
                        if self._op_index is not None:
                            self._op_index.add(key)
                        framed = codec.frame_blob(blob)
                        self.backend.put(key, framed)
                        op_meta[str(node_id)] = {
                            "key": key,
                            "digest": _sha256(framed),
                        }
            metadata["operators"] = {
                "gen": self._op_gen,
                "digest": digest,
                "nodes": op_meta,
            }
        if self._pool is not None:
            # deferred group sync: directory entries of every staged
            # artifact write become durable here, before the manifest that
            # references them can publish
            t0 = _time.perf_counter()
            self._pool.sync_staged_now()
            self.metrics.add_stage("barrier", _time.perf_counter() - t0)
        if self.handoff_to is None and (
            _manifest_core(metadata) == _manifest_core(self._metadata)
        ):
            # (a handoff commit always publishes, even when nothing
            # advanced: the handoff_to stamp must land on a manifest)
            if self.confirm_operator_commit is not None:
                self.confirm_operator_commit()  # nothing new: dumps are moot
            self.metrics.commit_published(noop=True)
        else:
            self._publish_manifest(
                metadata, confirm=self.confirm_operator_commit
            )
            self.metrics.commit_published(noop=False)
        self._last_submit_sig = self._state_sig()
        self._seal_handoff_fence()
        with self._pending_cv:
            self._seq += 1
            self.published_seq = self._seq
            return self._seq

    def commit_async(self, processed_up_to: int | None = None) -> int:
        """Stage the current consistent frontier as a pipelined commit and
        return WITHOUT waiting for durability: the writer pool uploads the
        chunks while the epoch loop keeps computing, and the committer
        thread publishes the generation manifest once the commit barrier
        confirms every referenced artifact landed.

        Returns the staged durability sequence: the snapshot is durable
        once :attr:`published_seq` reaches it — a caller acking external
        offsets must wait for that (and only ack what was drained at
        STAGING time), never treat this method returning as durability.
        Falls back to the blocking :meth:`commit` when the pool is
        disabled (``PATHWAY_CHECKPOINT_WRITERS=0``) and in
        operator-persisting mode, where ``confirm_operator_commit`` may
        only mark nodes clean once the manifest referencing their dumps is
        durably published (the drain-on-confirm rule).
        """
        if self.handoff_fenced:
            return self.published_seq  # see commit(): frontier is sealed
        if self._pool is None or (
            self.operator_persistence
            and self.collect_operator_states is not None
        ):
            return self.commit(processed_up_to=processed_up_to)
        self._raise_async_error()
        self._advance_sources(processed_up_to)
        sig = self._state_sig()
        sources = {
            sid: (
                self._source_meta(st),
                None if st.operator_mode else st.log,
            )
            for sid, st in self.sources.items()
        }
        with self._pending_cv:
            if sig == self._last_submit_sig:
                # nothing advanced since the last staged frontier; if that
                # frontier already published, refresh the durability seq so
                # idle streams keep acking their drained commit markers
                if not self._pending and not self._pending_active:
                    self._seq += 1
                    self.published_seq = self._seq
                return self._seq
            self._seq += 1
            if self._pending:
                # commit CONFLATION: a newer frontier subsumes any queued,
                # not-yet-active staging (chunks are append-only prefixes,
                # offsets monotone), so replace the tail instead of
                # queueing per-epoch generations.  Under a commit cadence
                # faster than the store can publish, durability lag stays
                # one publish cycle, the queue length stays <= 1, and the
                # epoch loop never stalls behind superseded generations —
                # the writer pool's byte cap is the one backpressure.
                tail = self._pending[-1]
                tail.seq = self._seq
                tail.sources = sources
            else:
                self._pending.append(_PendingCommit(self._seq, sources))
            self._last_submit_sig = sig
            self._ensure_committer()
            self._pending_cv.notify_all()
            return self._seq

    def drain(self) -> None:
        """Block until every staged async commit has published (or failed)
        and surface the first failure — the explicit shutdown/final-commit
        drain.  ``commit()`` drains implicitly, so direct synchronous
        callers never observe a half-published pipeline."""
        self._drain_pending()

    def _drain_pending(self) -> None:
        with self._pending_cv:
            while self._pending or self._pending_active:
                self._pending_cv.wait(0.1)
        self._raise_async_error()

    def _raise_async_error(self) -> None:
        exc = self._async_error
        if exc is not None:
            if isinstance(exc, CheckpointError):
                raise exc
            raise CheckpointError(
                f"persistence: a pipelined commit failed; generation "
                f"{self.generation} remains the newest published recovery "
                f"point: {exc}"
            ) from exc

    def _ensure_committer(self) -> None:  # call with self._pending_cv held
        if self._committer is None or not self._committer.is_alive():
            self._committer = threading.Thread(
                target=self._committer_loop, daemon=True,
                name=f"pathway:ckpt-commit-{self.worker}",
            )
            self._committer.start()

    # pathway-lint: context=committer
    def _committer_loop(self) -> None:
        """Single consumer of the pending queue: generations publish in
        submission order, so the manifest sequence on the store is exactly
        the staging sequence (no reordering across a slow upload)."""
        while True:
            with self._pending_cv:
                deadline = _time.monotonic() + _WriterPool._IDLE_EXIT_S
                while not self._pending:
                    self._pending_cv.wait(
                        max(0.05, deadline - _time.monotonic())
                    )
                    if not self._pending and _time.monotonic() >= deadline:
                        # idle exit: null the handle while still holding the
                        # cv — commit_async stages and calls _ensure_committer
                        # under this same cv, so it either enqueued before
                        # this check (no exit) or sees None and respawns; a
                        # staged generation can never be orphaned behind a
                        # thread that decided to die (is_alive() lies for a
                        # moment after return)
                        self._committer = None
                        return
                # pace publishes: newer frontiers keep CONFLATING into the
                # queue tail while we hold off, so one manifest (and one
                # set of fsyncs) covers the whole burst — the interval is
                # the durability-lag / publish-overhead tradeoff knob
                until = self._last_publish + self._publish_interval
                while self._pending and _time.monotonic() < until:
                    self._pending_cv.wait(
                        max(0.001, until - _time.monotonic())
                    )
                pc = self._pending.popleft()
                self._pending_active = True
                self._pending_cv.notify_all()
            try:
                self._publish_pending(pc)
            except BaseException as exc:  # noqa: BLE001 - sticky, surfaced later
                with self._pending_cv:
                    if self._async_error is None:
                        self._async_error = exc
                _log.error(
                    "persistence: pipelined commit (worker %d, seq %d) "
                    "failed — generation %d remains the newest published "
                    "recovery point: %s",
                    self.worker, pc.seq, self.generation, exc,
                )
            finally:
                self._last_publish = _time.monotonic()
                with self._pending_cv:
                    self._pending_active = False
                    self._pending_cv.notify_all()

    def _publish_pending(self, pc: _PendingCommit) -> None:
        # the commit barrier: every chunk the manifest will reference must
        # be durable BEFORE put_atomic publishes the manifest — the
        # manifest-IS-the-commit-point invariant of the sync path, kept.
        # A crash anywhere before the put_atomic leaves an unreferenced
        # partial generation; resume ignores it and the next run's commits
        # overwrite the orphaned chunk slots.
        t0 = _time.perf_counter()
        for meta, log in pc.sources.values():
            if log is not None:
                log.barrier(meta["chunks"])
        if self._pool is not None:
            self._pool.sync_staged_now()  # deferred dir-entry durability
        self.metrics.add_stage("barrier", _time.perf_counter() - t0)
        metadata: dict[str, Any] = {
            "sources": {
                sid: {
                    **meta,
                    # digests resolved on the pool before each job reads
                    # done, so post-barrier they are all present; the
                    # manifest stores them for the log's OWN range only
                    "chunk_digests": (
                        list(
                            log.chunk_digests[
                                meta.get("chunk_start", 0) : meta["chunks"]
                            ]
                        )
                        if log is not None
                        else []
                    ),
                }
                for sid, (meta, log) in pc.sources.items()
            }
        }
        if _manifest_core(metadata) == _manifest_core(self._metadata):
            self.metrics.commit_published(noop=True)
        else:
            now = _time.monotonic()
            self._publish_manifest(
                metadata,
                refresh_pointer=now - self._last_pointer_refresh >= 1.0,
                run_gc=now - self._last_gc >= 2.0,
            )
            self.metrics.commit_published(noop=False)
        with self._pending_cv:
            self.published_seq = pc.seq

    def _publish_manifest(
        self,
        metadata: dict,
        confirm: Callable[[], None] | None = None,
        *,
        refresh_pointer: bool = True,
        run_gc: bool = True,
    ) -> None:
        """Bump the generation and atomically publish its manifest — THE
        commit point — then confirm, refresh the advisory pointer, GC.

        ``refresh_pointer``/``run_gc`` let the pipelined publish path
        rate-limit the two best-effort follow-ups (both are advisory /
        deferred by contract; a lagging pointer or a temporarily oversized
        retention window changes no recovery semantics)."""
        _publish_t0 = _time.perf_counter()
        # chaos hook: a `zombie` fault wedges this publish until the lease
        # is superseded, modelling a stale writer publishing late (lazy
        # import keeps persistence ↔ faults acyclic at module load)
        from pathway_tpu.engine import faults as _faults

        spec = _faults.check(
            "zombie", worker=self.worker,
            key=self._manifest_key(self.generation + 1),
        )
        if spec is not None:
            self._zombie_stall(spec)
        # incarnation fence: THE split-brain gate.  Checked here, after the
        # barrier and immediately before the commit point, so a zombie
        # worker can never splice a stale generation (or refresh the
        # advisory pointer, which follows below) into a root a newer
        # incarnation owns.
        self._check_fence("publish a generation manifest")
        self.generation += 1
        metadata["format"] = MANIFEST_FORMAT
        metadata["generation"] = self.generation
        # recovery provenance rides every manifest so the supervisor (and
        # scrub) can reconstruct which generation a restart resumed from —
        # the incarnation stamp lets scrub cross-check every generation
        # against the lease (a stamp above the lease means fencing was
        # bypassed and the root deserves operator attention)
        metadata["recovered_from"] = self.recovered_generation
        metadata["attempt"] = _restart_attempt()
        metadata["incarnation"] = self.incarnation
        # the topology stamp is what makes elastic rescale detectable: a
        # resume under a different worker count sees the mismatch and
        # re-partitions (see _load_state); repartitioned_from records the
        # rescale provenance the supervisor surfaces on
        # SupervisorResult.recovery
        metadata["topology"] = self.topology
        metadata["topology_seq"] = self.topology_seq
        if self.repartitioned_from is not None:
            metadata["repartitioned_from"] = self.repartitioned_from
        if self.handoff_to is not None:
            # live-handoff provenance: this manifest is the exact frontier
            # the worker fenced before the coordinated drain — the
            # successor topology's repartition replay starts here
            metadata["handoff_to"] = self.handoff_to
        metadata["rejected"] = [[g, r] for g, r in self.rejected_generations]
        self.backend.put_atomic(
            self._manifest_key(self.generation),
            codec.frame_blob(_json.dumps(metadata).encode()),
        )
        self._known_generations.add(self.generation)
        self._metadata = metadata
        _blackbox.record(
            "checkpoint.publish", worker=self.worker,
            generation=self.generation,
        )
        if confirm is not None:
            confirm()
        # advisory pointer: unframed JSON, deliberately human-readable.
        # Best-effort — the manifest above IS the durable commit, so a
        # pointer write failure must not fail the commit (same rule as GC)
        if refresh_pointer:
            self._last_pointer_refresh = _time.monotonic()
            try:
                self.backend.put_atomic(
                    self._meta_key(),
                    _json.dumps(
                        {
                            "format": MANIFEST_FORMAT,
                            "generation": self.generation,
                            "manifest": self._manifest_key(self.generation),
                            "recovered_from": self.recovered_generation,
                            "attempt": metadata["attempt"],
                            "incarnation": self.incarnation,
                            "topology": self.topology,
                            "rejected": metadata["rejected"],
                        }
                    ).encode(),
                )
            except Exception as exc:  # noqa: BLE001 - advisory artifact only
                _log.warning(
                    "persistence: failed to refresh the advisory metadata "
                    "pointer %s (generation %d is committed regardless): %s",
                    self._meta_key(), self.generation, exc,
                )
        if run_gc:
            self._last_gc = _time.monotonic()
            self._gc_generations()
        self._commit_hist.observe(
            (_time.perf_counter() - _publish_t0) * 1000.0
        )

    def _verify_current_generation(self) -> bool:
        """Read back the just-committed generation and deep-verify it (with
        the process-lifetime artifact cache, so steady state only pays for
        the new delta).  This is the gate that keeps deferred GC honest: a
        generation that was mangled on its way to stable storage (torn
        write, bit rot in the write path) must never cause the deletion of
        the older generations recovery would fall back to."""
        key = self._manifest_key(self.generation)
        raw = self.backend.get(key)
        if raw is None:
            return False
        try:
            codec.unframe_blob(raw, what=key)
        except codec.IntegrityError:
            return False
        return not verify_manifest(
            self.backend, self.worker, self._metadata,
            cache=self._verified_artifacts,
        )

    def _gc_generations(self) -> None:
        """Deferred GC: drop manifests past the retention window, then drop
        operator chunks no retained (parseable) manifest references.  Input
        log chunks are append-only prefixes shared by every retained
        generation, so they are never deleted here.

        Nothing is deleted unless the NEWEST generation passes read-back
        verification: if what actually landed on the store is damaged, the
        older generations are the only recovery points left and the window
        simply grows until a sound commit lands.  GC failure must never
        fail a commit — the commit is already durable.

        Steady-state cost is O(delta): the generation set is the in-memory
        index maintained by ``_load_state``/``_publish_manifest`` (this
        storage is the shard's only writer), and the operator-chunk set
        pays ONE full listing on the first sweep (prior-run residue), then
        is maintained per dump write — no per-publish walk of the
        persistence root (``pathway_tpu scrub`` still walks everything)."""
        try:
            gens = {
                g: self._manifest_key(g) for g in self._known_generations
            }
            horizon = self.generation - self.retain_generations
            doomed = [g for g in sorted(gens) if g <= horizon]
            rejected_stale = {
                g for g, _ in self.rejected_generations
                if g > self.generation and g in gens
            }
            # a rescaled root (topology epoch > 0) owes one orphan-shard
            # sweep per process on worker 0; never-rescaled roots skip at
            # zero cost and the sticky done-flag ends it after one pass
            orphan_pending = (
                self.worker == 0
                and self.topology_seq > 0
                and not getattr(self, "_orphan_gc_done", False)
            )
            if (
                not doomed
                and not rejected_stale
                and not orphan_pending
                and not self.operator_persistence
            ):
                return
            if not self._verify_current_generation():
                self.metrics.gc_run(deferred=True)
                _log.warning(
                    "persistence: generation %d failed read-back "
                    "verification on %s — deferring GC, keeping %d older "
                    "generation(s) as recovery fallbacks",
                    self.generation, self.backend.describe(), len(doomed),
                )
                return
            deleted = 0
            retained: list[tuple[int, str]] = []
            for gen, key in sorted(gens.items()):
                if gen in doomed:
                    self.backend.delete(key)
                    self._known_generations.discard(gen)
                    deleted += 1
                else:
                    retained.append((gen, key))
            # stale damaged manifests ABOVE the current generation (the ones
            # this resume rejected, minus slots already overwritten): this
            # run's verified commit supersedes them, and leaving them would
            # make every later resume re-reject them — and permanently trip
            # the loud-failure guards (external-resume sources, operator
            # multi-worker) even though a verified generation exists
            for gen, key in retained:
                if gen in rejected_stale:
                    self.backend.delete(key)
                    self._known_generations.discard(gen)
                    deleted += 1
            retained = [
                (g, k) for g, k in retained if g not in rejected_stale
            ]
            deleted += self._gc_orphan_topology()
            if not self.operator_persistence:
                self.metrics.gc_run(deferred=False, deleted=deleted)
                return
            live: set[str] = set()
            for gen, key in retained:
                if gen == self.generation:
                    manifest: Any = self._metadata
                else:
                    manifest, _reason = _read_manifest(self.backend, key)
                    if manifest is None:
                        continue  # corrupt manifest pins nothing
                for ref in (
                    (manifest.get("operators") or {}).get("nodes") or {}
                ).values():
                    live.add(_op_ref(ref)["key"])
            if self._op_index is None:
                # first sweep: the single full walk that folds in operator
                # chunks left behind by previous runs of this root
                self._op_index = set(
                    self.backend.list_keys(f"operators/{self.worker}/")
                )
            for key in sorted(self._op_index - live):
                self.backend.delete(key)
                self._op_index.discard(key)
                deleted += 1
            self.metrics.gc_run(deferred=False, deleted=deleted)
        except Exception as exc:  # noqa: BLE001 - GC is best-effort
            _log.warning(
                "persistence: generation GC failed (will retry next "
                "commit): %s", exc,
            )

    def _gc_orphan_topology(self) -> int:
        """Sweep the shard debris a SHRINK leaves behind: manifests,
        advisory pointers and progress beacons of worker ids outside the
        current topology.  Their snapshot chunks are deliberately KEPT —
        every live worker's manifests pin them through ``refs``.

        Worker 0 only, and only once EVERY live worker's newest readable
        manifest is stamped with the current topology: until then a crash
        could still force a live worker back into repartition resume,
        which reads the orphaned manifests.  A sticky done-flag keeps the
        post-sweep steady state at zero extra listings (the O(delta) GC
        contract)."""
        if (
            self.worker != 0
            or self.topology_seq <= 0  # never-rescaled roots: zero cost
            or self.operator_persistence
            or getattr(self, "_orphan_gc_done", False)
        ):
            return 0
        root = self._scan_root_manifests()
        orphans = sorted(w for w in root if w >= self.topology)
        if not orphans:
            self._orphan_gc_done = True
            return 0
        for w in range(self.topology):
            entries = root.get(w) or []
            if not entries:
                continue  # a worker that never committed pins nothing
            if w == 0:
                converged = True  # our own publish carries the stamp
            else:
                newest, _reason = _read_manifest(self.backend, entries[0][1])
                converged = (
                    newest is not None
                    and newest.get("topology") == self.topology
                    and int(newest.get("topology_seq", 0))
                    == self.topology_seq
                )
            if not converged:
                return 0  # defer: the root has not converged yet
        deleted = 0
        for w in orphans:
            for _gen, key in root[w]:
                self.backend.delete(key)
                deleted += 1
            self.backend.delete(f"{METADATA_FILE}.{w}")
            self.backend.delete(f"lease/progress.{w}")
        self._orphan_gc_done = True
        _log.info(
            "persistence: GC'd %d orphaned manifest(s) of superseded "
            "worker(s) %s (snapshot chunks stay pinned by refs)",
            deleted, orphans,
        )
        return deleted

    def load_operator_states(self, digest: str) -> dict[int, bytes]:
        """Committed operator snapshots keyed by node id; {} on first run."""
        meta = self._metadata.get("operators")
        if not meta or not meta.get("nodes"):
            return {}
        if meta.get("digest") != digest:
            raise ValueError(
                "persistence: operator snapshots were written by a different "
                "program shape — the dataflow graph changed between runs "
                "(clear the persistence directory to start fresh)"
            )
        out = {}
        for node_id, ref in meta["nodes"].items():
            ref = _op_ref(ref)
            key = ref["key"]
            blob = self.backend.get(key)
            if blob is None:
                raise CheckpointError(
                    f"persistence: missing operator chunk {key} "
                    f"(generation {self.generation}) in backend "
                    f"{self.backend.describe()}"
                )
            if ref.get("digest") is not None and _sha256(blob) != ref["digest"]:
                raise CheckpointError(
                    f"persistence: digest mismatch on operator chunk {key} "
                    f"(generation {self.generation}) in backend "
                    f"{self.backend.describe()}"
                )
            try:
                out[int(node_id)] = codec.unframe_blob(
                    blob,
                    what=key,
                    allow_legacy=ref.get("digest") is None,
                    verify_crc=ref.get("digest") is None,
                )
            except codec.IntegrityError as exc:
                raise CheckpointError(
                    f"persistence: corrupt operator chunk {key} "
                    f"(generation {self.generation}) in backend "
                    f"{self.backend.describe()}: {exc}"
                ) from exc
        return out

    @property
    def input_snapshots_enabled(self) -> bool:
        """False for UDF-caching-only mode (PersistenceMode::UdfCaching,
        src/connectors/mod.rs:114): the persistence root backs UDF caches but
        sources are neither snapshotted nor replayed."""
        name = getattr(self.mode, "name", None)
        return name != "UDF_CACHING"

    # -- sources --
    def has_repartition_state(
        self, source_id: str, base: str | None = None
    ) -> bool:
        """True when ``source_id`` must register on THIS worker even if its
        reader partitions to nothing here: either a repartition resume
        holds gathered state for its base name, or the adopted manifest
        carries ``refs`` for it (a root that rescaled in its past keeps
        every worker replaying its shard of the referenced old logs)."""
        if self._repartition is not None:
            return (base or base_source_id(source_id)) in self._repartition
        meta = self._metadata.get("sources", {}).get(source_id)
        return bool(meta and meta.get("refs"))

    def register_source(
        self,
        source_id: str,
        schema_digest: str | None = None,
        *,
        base: str | None = None,
    ) -> SourceState:
        if source_id in self.sources:
            raise ValueError(
                f"persistence: duplicate source name {source_id!r}; give each "
                "persisted connector a unique name="
            )
        base = base or base_source_id(source_id)
        log = SnapshotLog(self.backend, self.worker, source_id, pool=self._pool)
        if self._repartition is not None:
            return self._register_repartitioned(
                source_id, log, schema_digest, base
            )
        meta = self._metadata["sources"].get(source_id, {})
        stored_digest = meta.get("schema")
        if (
            schema_digest is not None
            and stored_digest is not None
            and stored_digest != schema_digest
        ):
            # positional ids shift when unnamed sources are added/reordered;
            # refuse to replay another source's snapshot into this input
            raise ValueError(
                f"persistence: source {source_id!r} has a snapshot with a "
                "different schema — the program changed between runs. Give "
                "persisted connectors stable name= arguments (or clear the "
                "persistence directory)."
            )
        committed = int(meta.get("chunks", 0))
        start = min(int(meta.get("chunk_start", 0)), committed)
        offset = _offset_from_json(meta.get("offset"))
        log.chunks_written = committed  # append after the committed prefix
        digests = meta.get("chunk_digests")
        # the manifest stores digests for the log's OWN range
        # [chunk_start, chunks); below chunk_start live superseded-topology
        # chunks covered by refs — pad so the list stays absolute-indexed
        log.chunk_digests = [None] * start + (
            list(digests[: committed - start])
            if isinstance(digests, list)
            else [None] * (committed - start)
        )
        state = SourceState(log, committed, offset)
        state.schema_digest = schema_digest
        state.operator_mode = self.operator_persistence
        state.key_seq = int(meta.get("key_seq", 0))
        state.chunk_start = start
        state.refs = [dict(r) for r in (meta.get("refs") or [])]
        state.base = base
        self.sources[source_id] = state
        return state

    def _register_repartitioned(
        self,
        source_id: str,
        log: SnapshotLog,
        schema_digest: str | None,
        base: str,
    ) -> SourceState:
        """Repartition-resume registration: seed the state from the gathered
        cross-worker base metadata instead of this worker's own manifest.
        The fresh log appends ABOVE this worker's own superseded committed
        range (when the old and new source ids coincide), so old chunks —
        still referenced by every new worker's refs — are never clobbered."""
        entry = (self._repartition or {}).get(base)
        if entry is None:
            # a source the old topology never committed: genuinely fresh
            state = SourceState(log, 0, None)
            state.schema_digest = schema_digest
            state.operator_mode = self.operator_persistence
            state.base = base
            self.sources[source_id] = state
            return state
        stored_digest = entry.get("schema")
        if (
            schema_digest is not None
            and stored_digest is not None
            and stored_digest != schema_digest
        ):
            raise ValueError(
                f"persistence: source {source_id!r} has a snapshot with a "
                "different schema — the program changed between runs. Give "
                "persisted connectors stable name= arguments (or clear the "
                "persistence directory)."
            )
        start = int(entry["own"].get(source_id, 0))
        # refs under MY OWN prefix also pin chunk ranges the fresh log
        # must not clobber — e.g. a round-tripped sid (src-w0 at N=2,
        # again at N=2 after a 2 -> 1 -> 2 trip) whose old range is only
        # reachable through carried refs, not my newest manifest
        for ref in entry.get("refs") or []:
            if (
                int(ref["worker"]) == self.worker
                and str(ref["source"]) == source_id
            ):
                start = max(start, int(ref["chunks"]))
        log.chunks_written = start
        log.chunk_digests = [None] * start
        state = SourceState(log, start, entry.get("offset"))
        state.schema_digest = schema_digest
        state.operator_mode = self.operator_persistence
        state.key_seq = int(entry.get("key_seq", 0))
        state.chunk_start = start
        state.refs = [dict(r) for r in entry.get("refs") or []]
        state.base = base
        self.sources[source_id] = state
        return state

    def replay_into(self, state: SourceState, insert) -> int:
        """Feed committed events into an input session at rewind time 0.

        Returns the number of replayed row events (mod.rs:222-258 rewind).
        Operator-persisting mode replays nothing — restored operator states
        already contain the effect of every committed row.

        Two row populations replay, in order:

        * **refs** — committed chunk ranges of superseded-topology logs
          (this worker's own old range included), read FILTERED to this
          worker's shard (``shard_to_worker(key, topology) == worker``).
          Every worker of the new topology replays every ref the same way,
          so the shard union covers the old row set exactly once and each
          row lands directly on its owner — the read amplification the
          rescale benchmark (``benchmarks/rescale_recovery.py``) prices;
        * **own chunks** ``[chunk_start, committed)`` — this worker's own
          (current-topology) ingest log, replayed UNfiltered; the
          coordinated epoch loop's post-ingest exchange re-routes them by
          key shard exactly like live rows.
        """
        if state.operator_mode:
            return 0
        from pathway_tpu.engine.types import shard_to_worker

        n = 0
        if state.refs:
            reg = _registry.get_registry()
            rows_kept = reg.counter(
                "persistence.repartition.rows",
                "rows replayed from superseded-topology logs (post shard "
                "filter)",
                worker=self.worker,
            )
            chunks_read = reg.counter(
                "persistence.repartition.chunks",
                "superseded-topology chunks read during refs replay",
                worker=self.worker,
            )
            for ref in state.refs:
                start = int(ref.get("start", 0))
                end = int(ref["chunks"])
                chunks_read.inc(end - start)
                for kind, key, row, _t in _read_chunks(
                    self.backend,
                    f"snapshots/{int(ref['worker'])}/{ref['source']}",
                    start,
                    end,
                    ref.get("chunk_digests"),
                    digests_base=start,
                    generation=self.generation,
                    verified=self._verified_artifacts,
                ):
                    if shard_to_worker(key, self.topology) != self.worker:
                        continue
                    if kind == codec.EV_INSERT:
                        insert(key, row, 1)
                        n += 1
                    elif kind == codec.EV_DELETE:
                        insert(key, row, -1)
                        n += 1
            rows_kept.inc(n)
        for kind, key, row, _t in state.log.read_committed(
            state.committed_chunks,
            start=state.chunk_start,
            generation=self.generation,
            digests=state.log.chunk_digests,
            verified=self._verified_artifacts,
        ):
            if kind == codec.EV_INSERT:
                insert(key, row, 1)
                n += 1
            elif kind == codec.EV_DELETE:
                insert(key, row, -1)
                n += 1
        self.replayed_rows += n
        return n


def _read_manifest(
    backend: BlobBackend, key: str
) -> tuple[dict | None, str | None]:
    """Fetch + unframe + parse one generation manifest.

    Returns ``(manifest, None)`` on success, ``(None, reason)`` when the
    blob is gone or fails integrity/parsing — the single implementation
    behind resume, GC and scrub so the three paths cannot drift.
    """
    raw = backend.get(key)
    if raw is None:
        return None, "manifest vanished"
    try:
        return _json.loads(codec.unframe_blob(raw, what=key).decode()), None
    except (codec.IntegrityError, ValueError) as exc:
        return None, f"manifest undecodable: {exc}"


def _manifest_core(meta: dict) -> dict:
    """The state-bearing part of a manifest: provenance fields (generation,
    attempt, recovery trail) are excluded so "nothing advanced" commits stay
    no-ops."""
    return {k: meta[k] for k in ("sources", "operators") if k in meta}


def _op_ref(ref: Any) -> dict:
    """Normalize an operator-chunk reference (legacy plain key vs dict)."""
    if isinstance(ref, dict):
        return ref
    return {"key": ref, "digest": None}


def _restart_attempt() -> int:
    """Supervisor restart attempt (dup of faults.restart_attempt; reading
    the env registry directly avoids a persistence ↔ faults import cycle)."""
    from pathway_tpu.internals.config import env_int

    return env_int("PATHWAY_RESTART_ATTEMPT")


def _cluster_processes() -> int:
    """Live ``PATHWAY_PROCESSES`` read (not the cached PathwayConfig:
    resume may run before the config snapshot of a freshly-spawned worker
    exists, and tests repoint the env between runs)."""
    from pathway_tpu.internals.config import env_int

    return env_int("PATHWAY_PROCESSES")


def verify_manifest(
    backend: BlobBackend,
    worker: int,
    manifest: dict,
    *,
    cache: set[str] | None = None,
) -> list[str]:
    """Deep-verify every artifact a generation manifest references.

    Returns a list of problem descriptions (empty = generation is sound):
    missing chunks, frame integrity failures (torn write / truncation /
    bit rot), and digest mismatches, each naming the damaged key so an
    operator can locate it in the store.

    ``cache`` (optional) is a set of ``"key:digest"`` entries that already
    verified; sound artifacts are added to it and skipped next time —
    artifacts are immutable once written, so repeated in-process scans
    (resume probing, per-commit GC gating) only pay for the new delta.
    Offline audits (``scrub_root``) pass no cache and re-read everything.
    """
    problems: list[str] = []

    def check(key: str, digest: str | None, label: str) -> None:
        token = f"{key}:{digest}"
        if cache is not None and digest is not None and token in cache:
            return
        data = backend.get(key)
        if data is None:
            problems.append(f"missing {label} {key}")
            return
        if digest is not None and _sha256(data) != digest:
            problems.append(f"{label} {key}: digest mismatch")
            return
        try:
            # a matched SHA-256 digest subsumes the frame CRC; still parse
            # the header so torn frames are reported precisely
            codec.unframe_blob(
                data, what=key, allow_legacy=digest is None,
                verify_crc=digest is None,
            )
        except codec.IntegrityError as exc:
            problems.append(str(exc))
            return
        if cache is not None and digest is not None:
            cache.add(token)

    for sid, meta in (manifest.get("sources") or {}).items():
        n = int(meta.get("chunks", 0))
        start = min(int(meta.get("chunk_start", 0)), n)
        own = n - start
        digests = meta.get("chunk_digests")
        if not isinstance(digests, list):
            digests = [None] * own
        elif len(digests) < own:
            problems.append(
                f"source {sid!r}: manifest lists {len(digests)} digest(s) "
                f"for {own} committed chunk(s)"
            )
        for i in range(start, n):
            j = i - start
            check(
                f"snapshots/{worker}/{sid}/{i:08d}",
                digests[j] if j < len(digests) else None,
                "chunk",
            )
        # refs pin superseded-topology chunk ranges this generation's
        # replay still reads — damage there is damage HERE
        for ref in meta.get("refs") or []:
            try:
                rworker = int(ref["worker"])
                rsource = str(ref["source"])
                rstart = int(ref.get("start", 0))
                rn = int(ref["chunks"])
            except (KeyError, TypeError, ValueError):
                problems.append(
                    f"source {sid!r}: malformed repartition ref {ref!r}"
                )
                continue
            rdigests = ref.get("chunk_digests")
            if not isinstance(rdigests, list):
                rdigests = []
            for i in range(rstart, rn):
                j = i - rstart
                check(
                    f"snapshots/{rworker}/{rsource}/{i:08d}",
                    rdigests[j] if j < len(rdigests) else None,
                    f"ref chunk (source {sid!r})",
                )
    ops = manifest.get("operators") or {}
    for node_id, ref in (ops.get("nodes") or {}).items():
        ref = _op_ref(ref)
        check(ref["key"], ref.get("digest"), f"operator chunk (node {node_id})")
    return problems


def scrub_root(
    backend: BlobBackend, *, worker: int | None = None
) -> dict[str, Any]:
    """Offline audit of a persistence root: per-worker, per-generation
    health, without mutating anything.  Drives ``pathway_tpu scrub``.

    Report shape::

        {"backend": "...", "ok": bool,
         "workers": {w: {"generations": [{"generation": g, "ok": bool,
                                          "problems": [...]}, ...],  # newest first
                         "newest": g | None,
                         "newest_verified": g | None,
                         "legacy_metadata": bool,
                         "pointer": {...} | None}}}

    ``ok`` is True iff every audited worker's NEWEST generation verifies
    (a root whose newest checkpoint is damaged recovers — via fallback —
    but deserves operator attention: that is the non-zero-exit condition).
    A worker with no generations at all is only healthy if it also has no
    broken legacy metadata.

    The ``lease/`` directory (incarnation fencing) and ``blackbox/`` dumps
    (crash flight recorder) are first-class residents of a persistence
    root, not foreign keys: the lease is unframed + validated (an
    unreadable lease, or any generation manifest stamped with an
    incarnation ABOVE the lease's, fails the audit — the latter means a
    fencing bypass), and flight-recorder dumps are parse-checked
    best-effort (they are torn-tolerant by design, so damage is reported
    but never fails the root).
    """
    all_keys = backend.list_keys("")
    workers: set[int] = set()
    for key in all_keys:
        parts = key.split("/")
        if parts[0] in ("manifests", "snapshots", "operators") and len(parts) > 1:
            if parts[1].isdigit():
                workers.add(int(parts[1]))
        elif parts[0].startswith(METADATA_FILE + "."):
            tail = parts[0].rsplit(".", 1)[-1]
            if tail.isdigit():
                workers.add(int(tail))
    report: dict[str, Any] = {
        "backend": backend.describe(),
        "ok": True,
        "workers": {},
    }
    # -- lease (incarnation fencing) audit --
    lease_report: dict[str, Any] | None = None
    lease_incarnation: int | None = None
    lease_raw = backend.get(LEASE_KEY)  # one read: presence AND decode
    if lease_raw is not None:
        lease = _decode_lease(lease_raw)
        if lease is None:
            # an unreadable lease is the fencing authority gone dark:
            # writers treat it as absent (and stop fencing), so the audit
            # must fail loudly instead of reading as clean
            lease_report = {
                "ok": False,
                "error": "lease undecodable (torn or corrupt frame)",
            }
            report["ok"] = False
        else:
            lease_incarnation = lease["incarnation"]
            lease_report = {
                "ok": True,
                "incarnation": lease_incarnation,
                "owner": lease.get("owner"),
                "run_id": lease.get("run_id"),
                # elastic-rescale provenance: the target topology of the
                # current incarnation and the recorded rescale history
                "workers": lease.get("workers"),
                "topology_history": lease.get("topology_history") or [],
            }
    if lease_report is not None:
        # progress beacons live beside the lease; count them so the audit
        # acknowledges them as first-class rather than unexplained keys
        lease_report["progress_workers"] = sorted(
            int(k.rsplit(".", 1)[-1])
            for k in all_keys
            if k.startswith("lease/progress.")
            and k.rsplit(".", 1)[-1].isdigit()
        )
        # autoscaler residue (load beacons, a handoff request/acks left by
        # a crash mid-handoff, the controller state file) is advisory by
        # contract — reported so the audit explains the keys, never a
        # failure: the supervisor clears it and falls back on relaunch
        lease_report["load_workers"] = sorted(
            int(k.rsplit(".", 1)[-1])
            for k in all_keys
            if k.startswith("lease/load.")
            and k.rsplit(".", 1)[-1].isdigit()
        )
        handoff_acks = sorted(
            int(k.rsplit(".", 1)[-1])
            for k in all_keys
            if k.startswith(HANDOFF_ACK_PREFIX)
            and k.rsplit(".", 1)[-1].isdigit()
        )
        if HANDOFF_KEY in all_keys or handoff_acks:
            lease_report["handoff"] = {
                "pending_request": HANDOFF_KEY in all_keys,
                "acks": handoff_acks,
            }
        # warm-standby residue: apply-cursor beacons, the promotion
        # history, per-worker fences, and any PROMOTE request/acks a
        # crash left behind.  All advisory (never a failure): a standby
        # that stops beaconing just means the pool is cold, and the
        # supervisor clears a stale PROMOTE on relaunch.
        standbys: dict[int, dict[str, Any]] = {}
        for key in all_keys:
            tail = key.rsplit(".", 1)[-1]
            if not key.startswith(STANDBY_BEACON_PREFIX) or not tail.isdigit():
                continue
            try:
                beacon = _json.loads((backend.get(key) or b"").decode())
            except (ValueError, AttributeError):
                continue  # torn beacon: the next tick rewrites it
            if isinstance(beacon, dict):
                standbys[int(tail)] = {
                    "lag_s": beacon.get("lag_s"),
                    "cursors": beacon.get("cursors"),
                    "verified_chunks": beacon.get("verified_chunks"),
                    "at": beacon.get("at"),
                }
        if standbys:
            lease_report["standbys"] = standbys
        if PROMOTIONS_KEY in all_keys:
            try:
                hist = _json.loads(
                    (backend.get(PROMOTIONS_KEY) or b"").decode()
                )
            except (ValueError, AttributeError):
                hist = None
            if isinstance(hist, dict) and isinstance(
                hist.get("promotions"), list
            ):
                lease_report["promotions"] = hist["promotions"]
        if lease_report.get("ok") and lease_raw is not None:
            lease_obj = _decode_lease(lease_raw)
            if lease_obj is not None and lease_obj.get("fences"):
                lease_report["fences"] = lease_obj["fences"]
        promote_acks = sorted(
            k[len(PROMOTE_ACK_PREFIX):]
            for k in all_keys
            if k.startswith(PROMOTE_ACK_PREFIX)
        )
        if PROMOTE_KEY in all_keys or promote_acks:
            lease_report["promote"] = {
                "pending_request": PROMOTE_KEY in all_keys,
                "acks": promote_acks,
            }
        report["lease"] = lease_report
    # -- flight-recorder dump audit (best-effort, never fails the root) --
    dump_keys = [
        k for k in all_keys
        if k.startswith("blackbox/") and k.endswith(".json")
    ]
    if dump_keys:
        unreadable: list[str] = []
        dump_workers: set[int] = set()
        for key in dump_keys:
            raw = backend.get(key)
            try:
                payload = _json.loads((raw or b"").decode())
                if not isinstance(payload.get("dumped_at"), (int, float)):
                    raise ValueError("missing dumped_at stamp")
                dump_workers.add(int(payload.get("worker", -1)))
            except (ValueError, TypeError, AttributeError):
                unreadable.append(key)
        report["blackbox"] = {
            "dumps": len(dump_keys),
            "workers": sorted(dump_workers),
            "unreadable": unreadable,
        }
    if worker is not None:
        if worker not in workers:
            # a filter that matches nothing must not read as "clean" —
            # the operator asked about a shard that does not exist
            report["ok"] = False
            report["error"] = (
                f"worker {worker} has no checkpoint state on this root "
                f"(workers present: {sorted(workers) or 'none'})"
            )
            return report
        workers &= {worker}
    # per-invocation verification cache: retained generations share their
    # append-only chunk prefix, so without it a K-generation audit would
    # fetch and hash most chunks K times (artifacts are immutable and
    # tokens are key:digest, so the cache cannot mask real damage)
    audit_cache: set[str] = set()
    newest_stamps: list[tuple[int, int]] = []  # (incarnation, topology)
    for w in sorted(workers):
        prefix = f"manifests/{w}/"
        gens = sorted(
            (
                int(k.rsplit("/", 1)[-1])
                for k in all_keys
                if k.startswith(prefix) and k.rsplit("/", 1)[-1].isdigit()
            ),
            reverse=True,
        )
        entries = []
        newest_verified = None
        for gen in gens:
            manifest, reason = _read_manifest(backend, f"{prefix}{gen:08d}")
            stamp = None
            topo = None
            if manifest is None:
                problems = [reason or "unreadable"]
            else:
                problems = verify_manifest(
                    backend, w, manifest, cache=audit_cache
                )
                stamp = manifest.get("incarnation")
                topo = manifest.get("topology")
                if gen == gens[0] and isinstance(stamp, int) and isinstance(
                    topo, int
                ):
                    newest_stamps.append((stamp, topo))
                if (
                    lease_incarnation is not None
                    and isinstance(stamp, int)
                    and stamp > lease_incarnation
                ):
                    # a generation stamped ABOVE the lease means a writer
                    # published without holding a current incarnation —
                    # the fencing protocol was bypassed or the lease was
                    # rolled back; either way the root needs an operator
                    problems = problems + [
                        f"manifest stamped with incarnation {stamp} above "
                        f"the lease's {lease_incarnation} (fencing bypass)"
                    ]
            if not problems and newest_verified is None:
                newest_verified = gen
            entries.append(
                {
                    "generation": gen,
                    "ok": not problems,
                    "problems": problems,
                    "incarnation": stamp,
                    "topology": topo,
                    "topology_seq": (
                        manifest.get("topology_seq", 0)
                        if manifest is not None
                        else None
                    ),
                    "repartitioned_from": (
                        manifest.get("repartitioned_from")
                        if manifest is not None
                        else None
                    ),
                }
            )
        pointer = None
        raw = backend.get(f"{METADATA_FILE}.{w}")
        legacy = False
        if raw is not None:
            try:
                pointer = _json.loads(raw.decode())
                legacy = "generation" not in pointer and "sources" in pointer
            except ValueError:
                pointer = {"error": "metadata file undecodable"}
            if pointer is not None and "generation" in pointer and not gens:
                # resume refuses this root (partial restore: committed
                # state recorded, manifests gone) — scrub must agree
                pointer = dict(pointer)
                pointer["error"] = (
                    f"pointer records committed generation "
                    f"{pointer.get('generation')} but no generation "
                    "manifests exist (partially restored root)"
                )
        worker_ok = (
            (entries[0]["ok"] if entries else True)
            and not (pointer or {}).get("error")
        )
        report["workers"][w] = {
            "generations": entries,
            "newest": gens[0] if gens else None,
            "newest_verified": newest_verified,
            "legacy_metadata": legacy,
            "pointer": pointer,
            "ok": worker_ok,
        }
        report["ok"] = report["ok"] and worker_ok
    # -- topology audit (elastic rescale) -----------------------------------
    # The cluster's CURRENT worker count: the data plane's topology-epoch
    # marker first (written by the repartitioning workers themselves),
    # then the lease's recorded target, then the topology stamped by the
    # most recent (highest-incarnation) writer.
    marker = read_topology_marker(backend)
    current_workers = None
    if marker is not None:
        current_workers = marker["workers"]
    elif lease_report is not None and isinstance(
        lease_report.get("workers"), int
    ):
        current_workers = lease_report["workers"]
    elif newest_stamps:
        current_workers = max(newest_stamps)[1]
    if current_workers is not None:
        report["topology"] = {
            "workers": current_workers,
            "seq": (marker or {}).get("seq", 0),
            "repartitioned_from": (marker or {}).get("from_workers"),
            "history": (lease_report or {}).get("topology_history") or [],
        }
        for w, wrep in report["workers"].items():
            entries = wrep["generations"]
            newest_topo = entries[0].get("topology") if entries else None
            if w >= current_workers:
                # a shard of a superseded (larger) topology: its manifests
                # are fenced debris awaiting orphan GC, never damage — the
                # live workers' refs pin its CHUNKS, and damage there is
                # reported on the live manifests that reference them
                wrep["orphaned"] = True
                wrep["status"] = "fenced, pending GC"
                if not wrep["ok"]:
                    wrep["ok"] = True
                    report["ok"] = all(
                        rep["ok"] for rep in report["workers"].values()
                    ) and (lease_report is None or lease_report["ok"])
            elif entries and (
                (
                    isinstance(newest_topo, int)
                    and newest_topo != current_workers
                )
                or (
                    marker is not None
                    and entries[0].get("topology_seq") is not None
                    and entries[0]["topology_seq"] != marker["seq"]
                )
            ):
                # a live worker that has not republished under the current
                # topology epoch yet: mid-rescale, not damage
                wrep["pending_repartition"] = True
    reg = _registry.get_registry()
    reg.counter("persistence.scrub.runs", "offline scrub audits run").inc()
    if not report["ok"]:
        reg.counter(
            "persistence.scrub.damaged", "scrub audits that found damage"
        ).inc()
    return report


def repair_root(
    backend: BlobBackend, *, worker: int | None = None
) -> list[str]:
    """Quarantine damaged generations that sit ABOVE a worker's newest
    fully verified one (``pathway_tpu scrub --repair``).

    Resume already falls back past damaged generations, but configurations
    where fallback would silently lose data (broker-offset sources,
    operator-persisting multi-worker groups) refuse to start while damaged
    newer generations exist.  This is the deliberate operator action those
    errors point at: each damaged manifest is MOVED to
    ``quarantine/<worker>/<generation>`` (kept for forensics, invisible to
    resume), leaving the newest verified generation as the newest on the
    root.  Returns a description of every action taken.
    """
    actions: list[str] = []
    audit = scrub_root(backend, worker=worker)
    for w, wrep in audit.get("workers", {}).items():
        newest_verified = wrep.get("newest_verified")
        if newest_verified is None and wrep["generations"]:
            # nothing verifies: quarantining everything would turn a
            # repairable-looking root into a refused partial restore —
            # that calls for a human, not a tool
            actions.append(
                f"worker {w}: NO generation verifies — not quarantining "
                "(clear the shard deliberately to start fresh)"
            )
            continue
        for entry in wrep["generations"]:
            gen = entry["generation"]
            if entry["ok"] or gen < (newest_verified or 0):
                continue  # sound, or a damaged gen fallback never reaches
            src = f"manifests/{w}/{gen:08d}"
            dst = f"quarantine/{w}/{gen:08d}"
            blob = backend.get(src)
            if blob is not None:
                backend.put(dst, blob)
            backend.delete(src)
            actions.append(
                f"worker {w}: quarantined damaged generation {gen} "
                f"({'; '.join(entry['problems'][:2]) or 'unreadable'}) "
                f"-> {dst}"
            )
    return actions


def _offset_to_json(offset: Any) -> Any:
    if offset is None:
        return None
    try:
        _json.dumps(offset)
        return {"j": offset}
    except (TypeError, ValueError):
        return {"p": pickle.dumps(offset).hex()}


def _offset_from_json(obj: Any) -> Any:
    if obj is None:
        return None
    if "j" in obj:
        return obj["j"]
    return pickle.loads(bytes.fromhex(obj["p"]))
