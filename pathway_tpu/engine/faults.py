"""Deterministic fault injection for the engine and runtime layers.

The reference framework earns its consistency story with a Rust engine
that is exercised by chaos-style integration tests (the wordcount
recovery harness SIGKILLs pipeline processes mid-run).  This module is
the equivalent lever for this engine: a seeded, declarative **fault
plan** that the comm mesh, the persistence backends, the connector read
loop, and the epoch loop all consult, so failure paths are exercised
deterministically from unit tests — and from soak runs via the
``PATHWAY_FAULT_PLAN`` environment variable.

Plan format (JSON, also accepted as a Python list of dicts)::

    {"seed": 7, "faults": [
        {"kind": "comm_drop",    "worker": 0, "peer": 1, "nth": 2},
        {"kind": "comm_reset",   "worker": 1, "nth": 5},
        {"kind": "comm_corrupt", "worker": 0, "peer": 1, "nth": 1},
        {"kind": "comm_delay",   "worker": 0, "delay_ms": 50, "prob": 0.2},
        {"kind": "crash",        "worker": 1, "at_epoch": 3, "attempt": 0},
        {"kind": "hang",         "worker": 1, "at_epoch": 3, "attempt": 0},
        {"kind": "zombie",       "worker": 0, "nth": 3, "attempt": 0},
        {"kind": "blob_put",     "nth": 2, "key": "manifests"},
        {"kind": "blob_get",     "prob": 0.1, "max_times": 3},
        {"kind": "blob_bitflip", "key": "manifests/0/", "from_nth": 3},
        {"kind": "blob_torn",    "key": "snapshots", "nth": 2, "frac": 0.5},
        {"kind": "blob_truncate", "key": "operators", "nth": 1},
        {"kind": "connector_read", "source": "CsvReader", "nth": 4},
        {"kind": "connector_stall", "source": "SubjectReader", "nth": 3,
         "delay_ms": 500},
        {"kind": "load_spike", "source": "SubjectReader", "nth": 5,
         "delay_ms": 2000},
        {"kind": "handoff_crash", "worker": 1, "attempt": 0},
        {"kind": "device_stall", "source": "encoder", "nth": 1,
         "delay_ms": 500},
        {"kind": "device_error", "source": "rowsum", "from_nth": 1,
         "max_times": 5},
        {"kind": "device_oom", "source": "rowsum", "nth": 2},
        {"kind": "device_compile_fail", "source": "rowsum", "nth": 1},
        {"kind": "device_hang", "source": "embed", "nth": 1,
         "delay_ms": 10000},
        {"kind": "request_churn", "source": "pw-tiny-decoder", "nth": 3,
         "count": 6},
        {"kind": "standby_lag",   "worker": 2, "delay_ms": 400},
        {"kind": "promote_crash", "worker": 2}
    ]}

Matching rules:

* ``worker``/``peer``/``attempt`` match exactly when present (``attempt``
  is the supervisor restart attempt, ``PATHWAY_RESTART_ATTEMPT``; a spec
  without it fires on any attempt).
* ``key``/``source`` are substring filters on the blob key / reader name.
* ``nth`` fires exactly once, on the Nth **matching** event (1-based).
* ``from_nth`` fires on EVERY matching event from the Nth onward (bounded
  by ``max_times``) — e.g. "corrupt every checkpoint generation after the
  second", the lever the corrupt-recovery chaos tests use.
* ``prob`` fires with the given probability per matching event, from a
  per-spec seeded RNG (same seed → same firing pattern), bounded by
  ``max_times`` (default unbounded).
* ``at_epoch`` (crash only) matches the 0-based processed-epoch index.

Fault kinds and their injection sites:

========== =============================================================
comm_drop    ``TcpMesh.send``: the frame is NOT written and the link is
             severed — simulates a frame lost to a TCP reset.  The
             retransmit buffer + reconnect resync must re-deliver it.
comm_reset   ``TcpMesh.send``: the frame IS written, then the link is
             severed — resync must not re-deliver it twice (seq dedup).
comm_corrupt ``TcpMesh.send``: a bit-flipped copy goes on the wire; the
             receiver's decode failure must drop the link, and resync
             must re-deliver the pristine frame from the send buffer.
comm_delay   ``TcpMesh.send``: sleep ``delay_ms`` before the write.
crash        ``Scope.run_epoch``: SIGKILL the current process at the
             chosen epoch boundary (a hard worker death, not an
             exception — nothing gets to flush).
hang         ``Scope.run_epoch``: WEDGE the epoch loop at the chosen epoch
             boundary — the process stays alive but makes no progress (a
             deadlock / stuck blob I/O stand-in).  Only a signal ends it:
             the supervisor's progress watchdog must detect the stall,
             SIGUSR1 a flight-recorder dump out of it, then escalate
             SIGTERM → SIGKILL and restart the group.
zombie       ``persistence._publish_manifest``: stall the Nth manifest
             publish until the root's lease shows a NEWER incarnation
             (bounded by ``delay_ms``, default 30 s) — a stale writer from
             a superseded restart attempt publishing late.  The
             incarnation fence must then reject the publish
             (``FencedError``) and the worker must self-terminate.
writer_crash ``persistence._WriterPool``: SIGKILL from a checkpoint
             writer thread mid-async-commit (artifact hashed, upload
             pending) — the staged generation must stay unreferenced
             because its manifest never published.  ``key`` filters on
             the artifact key (e.g. ``"snapshots"``).
blob_put /   ``FlakyBackend``: the wrapped ``BlobBackend`` call raises
blob_get /   ``InjectedFault`` instead of performing the I/O.
blob_delete
blob_torn    ``FlakyBackend.put/put_atomic``: the write SUCCEEDS but only
             a prefix of the data lands (``frac``, default 0.5) — a torn
             write a power cut leaves behind.  The integrity frame
             (``engine/codec.py``) must flag it on read.
blob_truncate  Like blob_torn but keeps only ``keep_bytes`` (default 0):
             the zero-length/short blob some filesystems leave after a
             crash between create and write-back.
blob_bitflip ``FlakyBackend.put/put_atomic``: one bit of the written data
             is flipped (``bit`` index, default seeded) — storage-medium
             bit rot.  CRC32C framing must flag it on read.
connector_read  The reader supervision loop (``io/_utils.py``): the Nth
             emitted item raises before it is enqueued, exercising the
             consecutive-error budget + restart/reseek path.
connector_stall  The reader supervision loop: the Nth emitted item is
             DELAYED by ``delay_ms`` (required for any effect; a spec
             without it stalls 0 ms) before enqueue — a
             stuck broker / slow upstream stand-in.  No error is raised
             and no epoch slows down; only the data-plane freshness
             layer (``engine/freshness.py``: ``output.staleness.s``)
             can see it — exactly what its chaos tests prove.
load_spike   The reader supervision loop: from the Nth emitted item, the
             reader BUFFERS its output for a ``delay_ms`` window and then
             flushes everything in one instantaneous burst.  No error, no
             data change — delivered rows are byte-identical to an
             unfaulted run — but downstream sees silence followed by a
             backlog wall, so ``output.staleness.s`` and ``backlog.*``
             climb deterministically.  The reproducible load wave the
             autoscaler chaos tests (``engine/autoscaler.py``) drive the
             scale controller with.
handoff_crash  The live-handoff participation point in the epoch loop
             (``internals/runner.py``): SIGKILL this worker AFTER its
             handoff drain-commit fenced the storage but BEFORE its ack —
             the mid-handoff death that must make the supervisor fall
             back to the restart-based rescale, with nothing spliced.
device_stall  The DeviceExecutor dispatch thread (``pathway_tpu/device/
             executor.py``): the Nth dispatched batch job is DELAYED by
             ``delay_ms`` before it runs — a slow device / saturated
             interconnect stand-in.  No error, and the epoch thread is
             never slowed (dispatch is async): only ``backlog.device.*``
             and the freshness layer can see it, which is exactly what
             the device-executor chaos test proves.  ``source`` filters
             on the submitted job name (e.g. the batcher name).
device_error The DeviceExecutor's fixed-shape dispatch
             (``_dispatch_fixed``): the Nth matching device call raises
             an INTERNAL-flavored transient failure *inside* the
             dispatch, so it takes the real classify → retry → breaker
             → host-fallback path (``device/resilience.py``).  Repeated
             with ``from_nth``/``max_times`` it trips the per-callable
             circuit breaker — the device-fault chaos tests' lever.
             ``source`` filters on the registered callable name.
device_oom   Same site: the call raises RESOURCE_EXHAUSTED — the
             executor must SPLIT the chunk onto a smaller bucket and
             ratchet the callable's max-bucket cap
             (``device.oom.splits`` / ``device.bucket.cap``) instead of
             failing the stream.
device_compile_fail  Same site: the call raises an XLA compilation
             failure — deterministic, never retried; counts toward the
             breaker and the batch serves from the host fallback.
device_hang  The dispatch thread: the Nth matching batch job WEDGES
             (bounded by ``delay_ms``, default 60 s) without raising —
             a stuck device call / driver deadlock.  Only the hard
             dispatch deadline (``PATHWAY_DEVICE_DISPATCH_DEADLINE_S``)
             ends it: the job's waiters get a typed hang error and the
             dispatch thread is torn down and respawned
             (``device.dispatch.restarts``).  ``source`` filters on the
             submitted job name.
request_flood  The REST admission path (``engine/serving.py``): a firing
             spec saturates the whole admission budget with synthetic
             in-flight requests for ``delay_ms`` (default 1000) — a
             request flood without real sockets.  Real arrivals behind
             it queue and overflow answers 429 + Retry-After, which is
             exactly the serving-overload contract the chaos tests pin.
             ``source`` filters on the route path.
slow_handler  The REST request handler (``io/http/_server.py``): the Nth
             matching request stalls ``delay_ms`` (async — the event
             loop keeps serving) while holding its admission slot — a
             slow pipeline / slow client stand-in that drives queue
             delay up so shedding, degraded mode and 429/504 paths fire
             deterministically.  ``source`` filters on the route path.
request_churn  The continuous-batching generation scheduler
             (``serving/generation.py``): a firing spec injects a burst
             of ``count`` (default 4) short synthetic requests into the
             admission queue mid-tick — new arrivals landing while a
             long generation holds slots.  The chaos test pins that the
             burst's TTFT stays bounded (chunked prefill + per-step
             admission: no head-of-line blocking) while the long
             generation keeps producing.  ``source`` filters on the
             model name.
trace_storm  The request-tracing layer (``engine/tracing.py``): a firing
             spec bursts ``count`` (default 64) synthetic traced
             requests, each carrying a deep chained span tree, through
             the bounded telemetry export queue — proving the queue
             drops oldest (``telemetry.export.dropped``) without ever
             blocking the serving path.  ``source`` filters on the
             route path.
standby_lag  The warm-standby tail loop (``engine/standby.py``): each
             matching apply tick is DELAYED by ``delay_ms`` before the
             standby verifies newly committed generations — a cold/
             starved standby stand-in.  No error and nothing observable
             to the primaries; only ``standby.lag.s`` (and a promotion's
             replay tail) grows.  ``worker`` matches the STANDBY id.
promote_crash  The promotion adoption point (``engine/standby.py``): a
             standby that just acked a PROMOTE request — the dead
             worker already fenced, the standby's ack already durable —
             is SIGKILLed BEFORE it publishes anything as its new
             worker id.  The narrowest window of the promotion
             protocol: the supervisor must see the missing boot,
             abort at the promote deadline, and fall back to the
             whole-group restart (tier two), with the root left clean.
             ``worker`` matches the STANDBY id.
========== =============================================================
"""

from __future__ import annotations

import json as _json
import os
import random
import signal
import threading
import time as _time
from typing import Any

from pathway_tpu.engine import flight_recorder as _blackbox
from pathway_tpu.engine.persistence import BlobBackend

ENV_PLAN = "PATHWAY_FAULT_PLAN"
ENV_ATTEMPT = "PATHWAY_RESTART_ATTEMPT"

_COMM_KINDS = ("comm_drop", "comm_reset", "comm_corrupt", "comm_delay")
_BLOB_KINDS = ("blob_put", "blob_get", "blob_delete")
# write-corruption kinds: the I/O succeeds but the stored bytes are damaged
# (torn write / truncation / bit rot) — the persistence integrity frames
# must catch them on the read side
_BLOB_CORRUPT_KINDS = ("blob_torn", "blob_truncate", "blob_bitflip")
KINDS = (
    _COMM_KINDS
    + _BLOB_KINDS
    + _BLOB_CORRUPT_KINDS
    + (
        "crash", "writer_crash", "hang", "zombie", "connector_read",
        "connector_stall", "load_spike", "handoff_crash", "device_stall",
        "device_error", "device_oom", "device_compile_fail", "device_hang",
        "request_flood", "slow_handler", "request_churn", "trace_storm",
        "standby_lag", "promote_crash",
    )
)


class InjectedFault(IOError):
    """An error raised by the fault plan, never by real infrastructure."""


def restart_attempt() -> int:
    """Supervisor restart attempt of this process (0 = first launch)."""
    from pathway_tpu.internals.config import env_int

    return env_int(ENV_ATTEMPT)


class FaultSpec:
    """One declarative fault; counts its own matches and firings."""

    __slots__ = (
        "kind", "worker", "peer", "nth", "from_nth", "prob", "delay_ms",
        "at_epoch", "key", "source", "attempt", "max_times", "frac",
        "keep_bytes", "bit", "count", "seen", "fired", "_rng",
    )

    def __init__(self, spec: dict[str, Any], *, seed: int, index: int):
        kind = spec.get("kind")
        if kind not in KINDS:
            raise ValueError(
                f"fault plan: unknown kind {kind!r} (valid: {', '.join(KINDS)})"
            )
        self.kind = kind
        self.worker = spec.get("worker")
        self.peer = spec.get("peer")
        self.nth = spec.get("nth")
        self.from_nth = spec.get("from_nth")
        self.prob = spec.get("prob")
        self.delay_ms = float(spec.get("delay_ms", 0.0))
        self.at_epoch = spec.get("at_epoch")
        self.key = spec.get("key")
        self.source = spec.get("source")
        self.attempt = spec.get("attempt")
        self.max_times = spec.get("max_times")
        # corruption-kind knobs (blob_torn / blob_truncate / blob_bitflip)
        self.frac = spec.get("frac")
        self.keep_bytes = spec.get("keep_bytes")
        self.bit = spec.get("bit")
        # request_churn burst size
        self.count = spec.get("count")
        if (
            self.nth is None
            and self.from_nth is None
            and self.prob is None
            and self.at_epoch is None
        ):
            self.nth = 1  # a bare spec fires once, on the first match
        self.seen = 0
        self.fired = 0
        # per-spec RNG: the firing pattern of a prob-spec depends only on
        # (plan seed, spec position), never on interleaving with other specs
        self._rng = random.Random(f"{seed}:{index}")

    def _matches(self, ctx: dict[str, Any]) -> bool:
        if self.worker is not None and ctx.get("worker") != self.worker:
            return False
        if self.peer is not None and ctx.get("peer") != self.peer:
            return False
        if self.attempt is not None and restart_attempt() != self.attempt:
            return False
        if self.key is not None and self.key not in str(ctx.get("key", "")):
            return False
        if self.source is not None and self.source not in str(
            ctx.get("source", "")
        ):
            return False
        if self.at_epoch is not None and ctx.get("epoch") != self.at_epoch:
            return False
        return True

    def consider(self, ctx: dict[str, Any]) -> bool:
        """Record one matching event; True if the fault fires on it."""
        if not self._matches(ctx):
            return False
        self.seen += 1
        if self.max_times is not None and self.fired >= self.max_times:
            return False
        if self.nth is not None:
            fire = self.seen == self.nth
        elif self.from_nth is not None:
            fire = self.seen >= self.from_nth
        elif self.prob is not None:
            fire = self._rng.random() < self.prob
        else:  # at_epoch-only spec (crash): the match IS the trigger
            fire = self.fired == 0
        if fire:
            self.fired += 1
        return fire

    def describe(self) -> str:
        parts = [self.kind]
        for name in (
            "worker", "peer", "nth", "from_nth", "prob", "at_epoch", "key",
            "source",
        ):
            v = getattr(self, name)
            if v is not None:
                parts.append(f"{name}={v}")
        return " ".join(parts)


class FaultPlan:
    """A seeded set of :class:`FaultSpec`; thread-safe, deterministic."""

    def __init__(self, faults: list[dict[str, Any]], *, seed: int = 0):
        self.seed = seed
        self.specs = [
            FaultSpec(s, seed=seed, index=i) for i, s in enumerate(faults)
        ]
        self._kinds = {s.kind for s in self.specs}
        self._lock = threading.Lock()
        self.log: list[str] = []  # fired faults, for test assertions

    @classmethod
    def from_json(cls, raw: str) -> "FaultPlan":
        obj = _json.loads(raw)
        if isinstance(obj, list):
            return cls(obj)
        return cls(obj.get("faults", []), seed=int(obj.get("seed", 0)))

    @classmethod
    def from_env(cls) -> "FaultPlan | None":
        from pathway_tpu.internals.config import env_raw

        raw = env_raw(ENV_PLAN)
        if not raw:
            return None
        return cls.from_json(raw)

    def has(self, *kinds: str) -> bool:
        return any(k in self._kinds for k in kinds)

    def check(self, kind: str, **ctx: Any) -> FaultSpec | None:
        """The firing spec for this event, or None.  Exactly one spec fires
        per event (the first declared match), so plans stay readable."""
        if kind not in self._kinds:
            return None
        with self._lock:
            for spec in self.specs:
                if spec.kind == kind and spec.consider(ctx):
                    self.log.append(
                        f"{spec.describe()} @ "
                        + ",".join(f"{k}={v}" for k, v in sorted(ctx.items()))
                    )
                    # every fired injection lands in the crash flight
                    # recorder, so a post-mortem dump shows WHICH fault
                    # preceded the failure it is being read to explain
                    _blackbox.record("fault.injected", fault=kind, **ctx)
                    return spec
        return None


# ---------------------------------------------------------------------------
# Process-wide active plan
# ---------------------------------------------------------------------------

_active: FaultPlan | None = None
_env_loaded = False
_load_lock = threading.Lock()


def install_plan(plan: FaultPlan | None) -> None:
    """Set (or clear, with None) the process-wide plan — test entry point."""
    global _active, _env_loaded
    with _load_lock:
        _active = plan
        _env_loaded = True  # an explicit install wins over the env


def clear_plan() -> None:
    """Forget any installed/env plan; the env is re-read on next access."""
    global _active, _env_loaded
    with _load_lock:
        _active = None
        _env_loaded = False


def active_plan() -> FaultPlan | None:
    """The installed plan, else one parsed from PATHWAY_FAULT_PLAN (cached).

    Counters live on the plan object, so every injection site shares one
    instance per process — "the 3rd put" means the 3rd put anywhere.
    """
    global _active, _env_loaded
    if _env_loaded:
        return _active
    with _load_lock:
        if not _env_loaded:
            _active = FaultPlan.from_env()
            _env_loaded = True
    return _active


def check(kind: str, **ctx: Any) -> FaultSpec | None:
    plan = active_plan()
    if plan is None:
        return None
    return plan.check(kind, **ctx)


def maybe_crash(*, worker: int, epoch: int) -> None:
    """Epoch-boundary crash injection: SIGKILL this process — a hard worker
    death (no flush, no atexit), exactly what the supervisor must survive."""
    plan = active_plan()
    if plan is None or not plan.has("crash"):
        return
    if plan.check("crash", worker=worker, epoch=epoch) is not None:
        # the black box is the only record that survives a SIGKILL: dump
        # it BEFORE the kill (a real external SIGKILL leaves no dump,
        # like a real flight recorder losing power)
        _blackbox.dump(f"injected crash (worker {worker}, epoch {epoch})")
        os.kill(os.getpid(), signal.SIGKILL)


def maybe_hang(*, worker: int, epoch: int) -> None:
    """Epoch-boundary hang injection: WEDGE the epoch loop forever — the
    process stays alive, heartbeats may even keep flowing on comm threads,
    but no epoch ever completes.  Exactly the silent-stall failure mode
    the supervisor's progress watchdog exists for: no exit code, no
    exception, just a progress file whose mtime stops moving.

    The wedge is a plain interruptible sleep loop so the watchdog's
    SIGUSR1 (flight-recorder dump) still runs in this main thread before
    SIGTERM/SIGKILL ends the process."""
    plan = active_plan()
    if plan is None or not plan.has("hang"):
        return
    if plan.check("hang", worker=worker, epoch=epoch) is not None:
        _blackbox.record("fault.hang", worker=worker, epoch=epoch)
        while True:  # only a signal ends this — that is the point
            # pathway-lint: disable=ctx-blocking-call — the hang injector exists to wedge the epoch loop (watchdog chaos tests); blocking IS the feature
            _time.sleep(0.05)


def maybe_crash_handoff(*, worker: int, to_workers: int) -> None:
    """Mid-handoff crash injection: SIGKILL this worker between its handoff
    drain-commit (storage already fenced, frontier already durable) and its
    ack — the narrowest window of the live-handoff protocol.  The
    supervisor must see the nonzero death inside the handoff window and
    fall back to the restart-based rescale at the same target topology;
    the fenced drain-commit stays the (valid) newest generation, so
    nothing is spliced and ``pathway_tpu scrub`` stays clean."""
    plan = active_plan()
    if plan is None or not plan.has("handoff_crash"):
        return
    if plan.check("handoff_crash", worker=worker) is not None:
        _blackbox.dump(
            f"injected handoff crash (worker {worker}, "
            f"handoff to {to_workers} worker(s))"
        )
        os.kill(os.getpid(), signal.SIGKILL)


def maybe_standby_lag(*, standby: int) -> None:
    """Warm-standby lag injection: delay this standby's apply tick by
    ``delay_ms`` — no error, nothing the primaries can observe.  Only the
    standby's apply-cursor beacon (``standby.lag.s``) grows, and a
    promotion that lands during the window has a correspondingly longer
    uncommitted tail to replay.  ``worker`` in the spec matches the
    standby id (standbys have no worker id until promoted)."""
    plan = active_plan()
    if plan is None or not plan.has("standby_lag"):
        return
    spec = plan.check("standby_lag", worker=standby)
    if spec is not None:
        _blackbox.record(
            "fault.standby_lag", standby=standby,
            delay_ms=spec.delay_ms or 0,
        )
        _time.sleep((spec.delay_ms or 0) / 1000.0)


def maybe_crash_promote(*, standby: int, worker: int) -> None:
    """Mid-promotion crash injection: SIGKILL the adopting standby in the
    narrowest window of the promotion protocol — AFTER its PROMOTE ack is
    durable and the dead worker's fence is bumped, BEFORE it publishes
    anything as its new worker id.  The supervisor must detect the
    standby's death (or the missing boot at the promote deadline), abort
    the promotion, and fall back to the whole-group restart, leaving the
    root clean for the tier-two recovery.  ``worker`` in the spec matches
    the standby id."""
    plan = active_plan()
    if plan is None or not plan.has("promote_crash"):
        return
    if plan.check("promote_crash", worker=standby) is not None:
        _blackbox.dump(
            f"injected promote crash (standby {standby}, adopting "
            f"worker {worker})"
        )
        os.kill(os.getpid(), signal.SIGKILL)


def maybe_crash_writer(*, worker: int, key: str) -> None:
    """Async-commit crash injection: SIGKILL from a checkpoint writer-pool
    thread MID-FLIGHT — after the artifact was framed and hashed, before
    its upload.  Some chunks of the staged generation may already be on the
    store, the generation manifest is not: the crash must leave only an
    unreferenced partial generation (invisible to resume and to
    ``pathway_tpu scrub``), which supervised recovery rolls past."""
    plan = active_plan()
    if plan is None or not plan.has("writer_crash"):
        return
    if plan.check("writer_crash", worker=worker, key=key) is not None:
        _blackbox.dump(
            f"injected writer crash (worker {worker}, key {key!r})"
        )
        os.kill(os.getpid(), signal.SIGKILL)


# ---------------------------------------------------------------------------
# Flaky blob backend
# ---------------------------------------------------------------------------


class FlakyBackend(BlobBackend):
    """A ``BlobBackend`` wrapper that fails OR corrupts calls per the plan.

    Raising kinds (``blob_put``/``blob_get``/``blob_delete``) abort the
    call with :class:`InjectedFault`.  Corruption kinds (``blob_torn``,
    ``blob_truncate``, ``blob_bitflip``) let the write SUCCEED but damage
    the stored bytes — exactly what real storage faults look like to the
    process that wrote them, and what the persistence layer's integrity
    frames + generation manifests must catch on the read side.

    With no explicit ``plan`` the process-wide active plan is consulted at
    call time, so env-driven soak runs inject persistence faults without
    any code change (``wrap_backend`` below is applied by the runner).
    """

    def __init__(self, inner: BlobBackend, plan: FaultPlan | None = None):
        self.inner = inner
        self.plan = plan

    def describe(self) -> str:
        return self.inner.describe()

    def _active(self) -> FaultPlan | None:
        return self.plan if self.plan is not None else active_plan()

    def _gate(self, kind: str, key: str) -> None:
        plan = self._active()
        if plan is None:
            return
        if plan.check(kind, key=key) is not None:
            raise InjectedFault(f"injected {kind} failure for key {key!r}")

    def _mangle(self, key: str, data: bytes) -> bytes:
        """Apply write-corruption specs (torn / truncate / bitflip)."""
        plan = self._active()
        if plan is None:
            return data
        spec = plan.check("blob_torn", key=key)
        if spec is not None:
            frac = spec.frac if spec.frac is not None else 0.5
            data = data[: max(0, int(len(data) * float(frac)))]
        spec = plan.check("blob_truncate", key=key)
        if spec is not None:
            data = data[: int(spec.keep_bytes or 0)]
        spec = plan.check("blob_bitflip", key=key)
        if spec is not None and data:
            nbits = len(data) * 8
            bit = (
                int(spec.bit) if spec.bit is not None
                else spec._rng.randrange(nbits)
            ) % nbits
            mangled = bytearray(data)
            mangled[bit // 8] ^= 1 << (bit % 8)
            data = bytes(mangled)
        return data

    def put(self, key: str, data: bytes) -> None:
        self._gate("blob_put", key)
        self.inner.put(key, self._mangle(key, data))

    def put_atomic(self, key: str, data: bytes) -> None:
        self._gate("blob_put", key)
        self.inner.put_atomic(key, self._mangle(key, data))

    def put_staged(self, key: str, data: bytes) -> None:
        self._gate("blob_put", key)
        self.inner.put_staged(key, self._mangle(key, data))

    def sync_staged(self, keys: list[str]) -> None:
        self.inner.sync_staged(keys)

    def get(self, key: str) -> bytes | None:
        self._gate("blob_get", key)
        return self.inner.get(key)

    def list_keys(self, prefix: str) -> list[str]:
        return self.inner.list_keys(prefix)

    def delete(self, key: str) -> None:
        self._gate("blob_delete", key)
        self.inner.delete(key)


def wrap_backend(backend: BlobBackend) -> BlobBackend:
    """Wrap with FlakyBackend iff the active plan injects blob faults."""
    plan = active_plan()
    if plan is not None and plan.has(*_BLOB_KINDS, *_BLOB_CORRUPT_KINDS):
        return FlakyBackend(backend, plan)
    return backend
