"""Load-adaptive scale controller: the sensor→actuator loop made autonomous.

PR 9 built the sensors (``output.staleness.s``, ``backlog.*`` attribution
at every wait point) and PR 10 built the actuator (rescale-via-recovery
at N′ ≠ N with shard-range repartitioning); this module closes the loop.
The supervisor (``engine/supervisor.py``) runs one :class:`ScaleController`
beside its liveness watch: every poll it reads the per-worker **load
beacons** the runners drop beside the lease (``lease/load.<worker>`` —
plain advisory JSON, same contract as the progress beacons), feeds the
worst staleness + total backlog into :meth:`ScaleController.observe`, and
applies whatever decision comes back by initiating a **live shard
handoff** (see ``engine/persistence.py``'s handoff files) with automatic
fallback to the PR-10 restart-based rescale.

The controller itself is *pure decision logic over an injected clock* —
``observe(now, ...)`` takes the timestamp explicitly, so the hysteresis
unit tests (``tests/test_autoscaler.py``) drive years of synthetic load
in microseconds.  The policy, deliberately boring:

* **grow** — worst staleness above ``PATHWAY_AUTOSCALE_STALENESS_S``
  *continuously* for ``PATHWAY_AUTOSCALE_DWELL_S`` (one dip resets the
  clock) grows the target by one worker, up to ``_MAX_WORKERS``.
* **shrink** — staleness comfortably low (< half the grow threshold) AND
  backlog ~empty continuously for ``PATHWAY_AUTOSCALE_IDLE_S`` shrinks by
  one, never below ``_MIN_WORKERS`` (and never below 1 — the same floor
  degraded-mode shrink honors).
* **cooldown** — after any rescale, no decision in either direction for
  ``PATHWAY_AUTOSCALE_COOLDOWN_S``.  Dwell + cooldown together are the
  anti-flap guarantee: load oscillating across the threshold faster than
  the dwell window never triggers, and a triggered rescale cannot be
  immediately reversed.
* **budget** — at most ``PATHWAY_AUTOSCALE_BUDGET`` rescales per
  supervisor run.  Exhaustion is LOUD (``log.error``, a ``suppressed``
  decision entry, ``autoscaler.budget.exhausted`` metric) and then
  silent: the topology pins where it is.

Every decision — applied or suppressed — lands in a bounded provenance
log (:attr:`ScaleController.decisions`) that rides
``SupervisorResult.rescales``, flight-recorder dumps
(``set_autoscaler_supplier``), ``pathway_tpu blackbox``, and the
``/status`` + ``pathway_tpu top`` autoscaler panels via the state file
this module maintains at ``lease/autoscaler.json``.
"""

from __future__ import annotations

import logging
import os
import time as _time
from collections import deque
from typing import Any

from pathway_tpu.engine import metrics as _registry
from pathway_tpu.engine.persistence import (
    _lease_dir_read_json,
    _lease_dir_write_json,
)
from pathway_tpu.internals.config import (
    env_bool,
    env_float,
    env_int,
)

_log = logging.getLogger("pathway_tpu.autoscaler")

ENV_AUTOSCALE = "PATHWAY_AUTOSCALE"

LOAD_PREFIX = "lease/load."
STATE_KEY = "lease/autoscaler.json"

# a beacon older than this is a dead sensor, not a fresh reading: its
# staleness number is ignored (the liveness watchdog owns dead workers)
_BEACON_MAX_AGE_S = 10.0

_DECISION_LOG_CAP = 64


def autoscale_enabled() -> bool:
    return env_bool(ENV_AUTOSCALE)


# -- worker-side load beacons --
def write_load_beacon(
    root: str,
    worker: int,
    *,
    staleness_s: float,
    backlog: float,
    epochs: int,
) -> None:
    """Drop this worker's load reading beside the lease (advisory JSON,
    atomic tmp+rename — torn/missing degrades to 'no reading')."""
    _lease_dir_write_json(
        root,
        f"{LOAD_PREFIX}{worker}",
        {
            "worker": worker,
            "staleness_s": round(float(staleness_s), 3),
            "backlog": round(float(backlog), 1),
            "epochs": int(epochs),
            "at": _time.time(),
        },
    )


def read_load_beacons(root: str, workers: int) -> dict[int, dict]:
    """{worker: beacon} for every fresh, well-formed load beacon."""
    now = _time.time()
    out: dict[int, dict] = {}
    for w in range(workers):
        obj = _lease_dir_read_json(root, f"{LOAD_PREFIX}{w}")
        if (
            obj is not None
            and obj.get("worker") == w
            and isinstance(obj.get("staleness_s"), (int, float))
            and isinstance(obj.get("at"), (int, float))
            and now - obj["at"] <= _BEACON_MAX_AGE_S
        ):
            out[w] = obj
    return out


def clear_load_beacons(root: str, workers: int) -> None:
    """Drop stale beacons before relaunching at a new topology, so the
    first post-rescale poll cannot read the pre-rescale load."""
    for w in range(workers):
        path = os.path.join(root, *f"{LOAD_PREFIX}{w}".split("/"))
        try:
            os.remove(path)
        except OSError:
            pass


def worst_load(beacons: dict[int, dict]) -> tuple[float, float]:
    """(worst staleness, total backlog) over a beacon set — the two
    numbers the controller's policy runs on.  (0, 0) when no beacons
    are fresh: an instrumentation gap must read as 'calm', never as
    'scale!'."""
    if not beacons:
        return 0.0, 0.0
    staleness = max(float(b.get("staleness_s", 0.0)) for b in beacons.values())
    backlog = sum(float(b.get("backlog", 0.0)) for b in beacons.values())
    return staleness, backlog


class ScaleController:
    """Hysteresis + budget + cooldown over (staleness, backlog) readings.

    Pure logic: ``observe`` takes ``now`` explicitly (monotonic-like
    seconds; any consistent clock works) and returns either ``None`` or a
    decision dict ``{"action": "grow"|"shrink", "from": N, "to": N',
    ...provenance}``.  The caller applies the decision; the controller
    optimistically adopts the target as current (the actuator always ends
    at N′ — live handoff when it works, restart fallback when it
    doesn't)."""

    def __init__(
        self,
        *,
        current: int,
        min_workers: int | None = None,
        max_workers: int | None = None,
        staleness_hi_s: float | None = None,
        dwell_s: float | None = None,
        cooldown_s: float | None = None,
        idle_dwell_s: float | None = None,
        budget: int | None = None,
    ):
        def _f(v: float | None, env: str) -> float:
            return float(env_float(env) if v is None else v)

        self.min_workers = max(
            1,
            (
                env_int("PATHWAY_AUTOSCALE_MIN_WORKERS")
                if min_workers is None
                else min_workers
            ),
        )
        self.max_workers = max(
            self.min_workers,
            (
                env_int("PATHWAY_AUTOSCALE_MAX_WORKERS")
                if max_workers is None
                else max_workers
            ),
        )
        self.current = max(1, current)
        self.staleness_hi_s = _f(staleness_hi_s, "PATHWAY_AUTOSCALE_STALENESS_S")
        self.dwell_s = _f(dwell_s, "PATHWAY_AUTOSCALE_DWELL_S")
        self.cooldown_s = _f(cooldown_s, "PATHWAY_AUTOSCALE_COOLDOWN_S")
        self.idle_dwell_s = _f(idle_dwell_s, "PATHWAY_AUTOSCALE_IDLE_S")
        self.budget = (
            env_int("PATHWAY_AUTOSCALE_BUDGET") if budget is None else budget
        )
        self.budget_left = max(0, self.budget)
        # hysteresis state: when the grow/shrink condition STARTED holding
        # continuously (None = not holding), and when the post-rescale
        # cooldown expires
        self._hot_since: float | None = None
        self._idle_since: float | None = None
        self._cooldown_until = 0.0
        self._exhaustion_logged = False
        # bounded provenance log: every decision (applied, suppressed,
        # fallback notes from the supervisor) newest-last
        self.decisions: deque[dict] = deque(maxlen=_DECISION_LOG_CAP)
        # what the supervisor last told us about the actuator ("", then
        # "handoff-requested" / "handoff" / "fallback" / "done")
        self.handoff_state = ""
        self._m_decisions = _registry.get_registry().counter(
            "autoscaler.decisions",
            "scaling decisions fired (grow + shrink)",
        )
        self._m_exhausted = _registry.get_registry().counter(
            "autoscaler.budget.exhausted",
            "scaling decisions suppressed because the rescale budget "
            "was spent",
        )

    # -- policy --
    def observe(
        self, now: float, worst_staleness_s: float, backlog: float
    ) -> dict | None:
        """Feed one (staleness, backlog) reading; maybe return a decision.

        Must be called with a non-decreasing ``now``.  Returns None in the
        overwhelmingly common case (nothing sustained, cooling down, or
        within bounds)."""
        hot = worst_staleness_s > self.staleness_hi_s
        idle = (
            worst_staleness_s < self.staleness_hi_s * 0.5 and backlog <= 0.0
        )
        # dwell clocks run even through cooldown — a spike that persists
        # across a rescale's cooldown fires again the instant the cooldown
        # expires, without re-paying the dwell
        # None-checks, not truthiness: a dwell that started at clock 0.0
        # is still running (the clock is injected; 0.0 is a valid now)
        if hot:
            self._hot_since = now if self._hot_since is None else self._hot_since
        else:
            self._hot_since = None
        if idle:
            self._idle_since = (
                now if self._idle_since is None else self._idle_since
            )
        else:
            self._idle_since = None
        if now < self._cooldown_until:
            return None
        if (
            self._hot_since is not None
            and now - self._hot_since >= self.dwell_s
        ):
            return self._decide(
                now,
                "grow",
                min(self.current + 1, self.max_workers),
                f"staleness {worst_staleness_s:.1f}s > "
                f"{self.staleness_hi_s:.1f}s sustained "
                f"{now - self._hot_since:.1f}s",
                worst_staleness_s,
                backlog,
            )
        if (
            self._idle_since is not None
            and now - self._idle_since >= self.idle_dwell_s
        ):
            return self._decide(
                now,
                "shrink",
                max(self.current - 1, self.min_workers),
                f"idle (staleness {worst_staleness_s:.1f}s, backlog "
                f"{backlog:.0f}) sustained {now - self._idle_since:.1f}s",
                worst_staleness_s,
                backlog,
            )
        return None

    def _decide(
        self,
        now: float,
        action: str,
        target: int,
        reason: str,
        staleness: float,
        backlog: float,
    ) -> dict | None:
        if target == self.current:
            return None  # already pinned at the bound; nothing to do
        entry = {
            "at": now,
            "action": action,
            "from": self.current,
            "to": target,
            "reason": reason,
            "staleness_s": round(staleness, 3),
            "backlog": round(backlog, 1),
            "budget_left": self.budget_left,
        }
        if self.budget_left <= 0:
            # LOUD exhaustion, exactly once — then the controller goes
            # quiet and the topology pins where it is
            entry["action"] = f"suppressed-{action}"
            entry["reason"] = (
                f"rescale budget exhausted ({self.budget} spent); "
                f"wanted {action} {self.current}→{target}: {reason}"
            )
            if not self._exhaustion_logged:
                self._exhaustion_logged = True
                self._m_exhausted.inc()
                self.decisions.append(entry)
                _log.error(
                    "autoscaler: %s — topology pinned at %d worker(s) "
                    "until the next supervisor run",
                    entry["reason"], self.current,
                )
            return None
        self.budget_left -= 1
        self._m_decisions.inc()
        self.decisions.append(entry)
        self._cooldown_until = now + self.cooldown_s
        self._hot_since = self._idle_since = None
        _log.warning(
            "autoscaler: %s %d→%d (%s; budget left %d)",
            action, self.current, target, reason, self.budget_left,
        )
        self.current = target
        return entry

    def note(self, now: float, action: str, **fields: Any) -> None:
        """Append an actuator-side provenance entry (handoff outcome,
        fallback) to the decision log without consuming budget."""
        self.decisions.append({"at": now, "action": action, **fields})

    def cooldown_remaining(self, now: float) -> float:
        return max(0.0, self._cooldown_until - now)

    # -- observability --
    def snapshot(self, now: float) -> dict:
        """The autoscaler panel payload (also persisted as the state file
        the workers' flight-recorder supplier and /status section read)."""
        last = self.decisions[-1] if self.decisions else None
        return {
            "target_workers": self.current,
            "min_workers": self.min_workers,
            "max_workers": self.max_workers,
            "budget": self.budget,
            "budget_left": self.budget_left,
            "cooldown_remaining_s": round(self.cooldown_remaining(now), 2),
            "hot_for_s": round(
                now - self._hot_since if self._hot_since is not None else 0.0,
                2,
            ),
            "idle_for_s": round(
                now - self._idle_since
                if self._idle_since is not None
                else 0.0,
                2,
            ),
            "handoff_state": self.handoff_state,
            "last_decision": last,
            "decisions": list(self.decisions),
        }

    def write_state(self, root: str, now: float) -> None:
        """Persist the panel payload beside the lease (advisory JSON; the
        workers read it back for /status, top, and blackbox dumps)."""
        try:
            _lease_dir_write_json(
                root, STATE_KEY, {**self.snapshot(now), "at": _time.time()}
            )
        except OSError as exc:
            _log.warning(
                "autoscaler: failed to write state file under %s: %s",
                root, exc,
            )


def read_state_file(root: str) -> dict | None:
    """The supervisor-maintained autoscaler state, or None (solo runs,
    autoscaling off, or the file torn mid-write)."""
    return _lease_dir_read_json(root, STATE_KEY)


def clear_state_file(root: str) -> None:
    try:
        os.remove(os.path.join(root, *STATE_KEY.split("/")))
    except OSError:
        pass


def state_metrics(root: str) -> dict[str, float]:
    """Numeric ``autoscaler.*`` gauges derived from the state file — the
    registry collector each worker registers so the panel rides /status
    and /metrics scrapes without new plumbing."""
    state = read_state_file(root)
    if state is None:
        return {}
    phase = 0.0  # 0 steady, 1 hot-dwell, 2 cooldown, 3 handoff in flight
    if state.get("handoff_state") in ("handoff-requested", "handoff"):
        phase = 3.0
    elif float(state.get("cooldown_remaining_s") or 0.0) > 0.0:
        phase = 2.0
    elif float(state.get("hot_for_s") or 0.0) > 0.0:
        phase = 1.0
    out = {
        "autoscaler.target.workers": float(state.get("target_workers", 0)),
        "autoscaler.budget.left": float(state.get("budget_left", 0)),
        "autoscaler.cooldown.remaining.s": float(
            state.get("cooldown_remaining_s") or 0.0
        ),
        "autoscaler.phase": phase,
        "autoscaler.decisions.logged": float(
            len(state.get("decisions") or ())
        ),
    }
    last = state.get("last_decision")
    if isinstance(last, dict) and last.get("action"):
        # the action rides as a label so the text survives the numeric
        # scalar-metrics path into /status and the `top` panel
        out[f"autoscaler.last.decision{{action={last['action']}}}"] = float(
            last.get("to") or 0
        )
    return out


__all__ = [
    "ENV_AUTOSCALE",
    "ScaleController",
    "autoscale_enabled",
    "clear_load_beacons",
    "clear_state_file",
    "read_load_beacons",
    "read_state_file",
    "state_metrics",
    "worst_load",
    "write_load_beacon",
]
