"""Telemetry: periodic process metrics and run spans, license-gated.

Parity target: ``src/engine/telemetry.rs`` — gauges ``process.memory.
usage``, ``process.cpu.utime``, ``process.cpu.stime``, ``latency.input``,
``latency.output`` sampled on a periodic reader (60 s default,
``telemetry.rs:39``), resource attributes ``service.*``/``run.id``/
``root.trace.id``/``license.key`` shortcut, and tracing spans carrying a
``traceparent`` from the Python layer (``graph_runner/telemetry.py``).

Differences by design: the reference exports OTLP/gRPC to
``usage.pathway.com`` by default when the license requires telemetry;
this build has **zero egress**, so nothing is ever sent unless the user
explicitly configures an endpoint (``pw.set_monitoring_config`` /
``TelemetryConfig.create(monitoring_server=...)``).

Wire format: **OTLP/HTTP+JSON** by default (the JSON mapping of the
opentelemetry-proto ``ExportMetricsServiceRequest`` /
``ExportTraceServiceRequest``, POSTed to ``/v1/metrics`` and
``/v1/traces``) — any stock OpenTelemetry collector ingests it, closing
the parity gap with ``telemetry.rs``'s OTLP exporter without needing the
absent opentelemetry wheels.  ``protocol="pathway-json"``
(``PATHWAY_TELEMETRY_PROTOCOL``) keeps the round-3 line-JSON format.
"""

from __future__ import annotations

import json
import logging
import os
import secrets
import threading
import time
import urllib.request
from collections import deque
from contextlib import contextmanager, nullcontext
from dataclasses import dataclass, field
from typing import Any, Callable

from pathway_tpu.engine import metrics as _metrics

PERIODIC_READER_INTERVAL_S = 60.0
EXPORT_TIMEOUT_S = 3.0
# bounded non-blocking export queue: a slow or dead collector must never
# stall the sampler or (worse) a span-emitting caller thread — when the
# queue is full the OLDEST payload is dropped and counted in the
# ``telemetry.export.dropped`` metric (freshness beats completeness)
EXPORT_QUEUE_MAX = 256
# in-process span retention: exported spans also land on ``Telemetry.spans``
# for introspection/tests, but sampled per-epoch spans arrive forever in a
# streaming run — keep only the most recent ones
SPAN_BUFFER_MAX = 1024

PROCESS_MEMORY_USAGE = "process.memory.usage"
PROCESS_CPU_USER_TIME = "process.cpu.utime"
PROCESS_CPU_SYSTEM_TIME = "process.cpu.stime"
INPUT_LATENCY = "latency.input"
OUTPUT_LATENCY = "latency.output"
# checkpoint-commit pipeline gauges (engine/persistence.CommitMetrics):
# cumulative stage seconds under "checkpoint.commit.<stage>" for the
# stages below, plus the in-flight gauges — how much durability work is
# overlapping the epoch loop right now
CHECKPOINT_COMMIT_PREFIX = "checkpoint.commit."
CHECKPOINT_COMMIT_STAGES = ("buffer", "frame", "hash", "upload", "barrier")
CHECKPOINT_INFLIGHT_BYTES = "checkpoint.inflight.bytes"
CHECKPOINT_INFLIGHT_JOBS = "checkpoint.inflight.jobs"
# device-path observability gauges (pathway_tpu/device/telemetry.py):
# exported through the unified registry like every family above, so they
# ride every OTLP metrics sample automatically — named here so the
# ``/status`` device section (engine/http_server.py), the dashboard
# footer (internals/monitoring.py) and `pathway_tpu top` agree on one
# spelling with the exporter
DEVICE_SECTION_PREFIX = "device."
DEVICE_UTILIZATION = "device.utilization"
DEVICE_PADDING_WASTE_FRACTION = "device.padding.waste.fraction"
DEVICE_HBM_BYTES_IN_USE = "device.hbm.bytes_in_use"
DEVICE_HBM_PEAK = "device.hbm.peak"

LOCAL_DEV_NAMESPACE = "local-dev"

logger = logging.getLogger("pathway_tpu.telemetry")


class TelemetryError(RuntimeError):
    pass


@dataclass(frozen=True)
class TelemetryConfig:
    """Where (if anywhere) to deliver metrics/spans for this run."""

    telemetry_enabled: bool = False
    metrics_servers: tuple[str, ...] = ()
    tracing_servers: tuple[str, ...] = ()
    service_name: str = "pathway"
    service_version: str = "0.0.0"
    service_instance_id: str = ""
    service_namespace: str = LOCAL_DEV_NAMESPACE
    run_id: str = ""
    trace_parent: str | None = None
    license_shortcut: str = ""
    protocol: str = "otlp-json"  # or "pathway-json" (legacy line JSON)

    @classmethod
    def create(
        cls,
        *,
        license: Any = None,
        run_id: str | None = None,
        monitoring_server: str | None = None,
        trace_parent: str | None = None,
        protocol: str | None = None,
    ) -> "TelemetryConfig":
        """Mirror of ``TelemetryConfig::create`` (telemetry.rs): a
        monitoring endpoint requires the MONITORING entitlement; with no
        endpoint configured telemetry stays fully off (zero egress)."""
        from pathway_tpu import __version__

        if monitoring_server is not None and license is not None:
            license.check_entitlements(["monitoring"])
        servers = (monitoring_server,) if monitoring_server else ()
        from pathway_tpu.internals.config import env_str

        requested = (
            protocol
            if protocol is not None
            else env_str("PATHWAY_TELEMETRY_PROTOCOL")
        )
        instance_id = env_str("PATHWAY_SERVICE_INSTANCE_ID") or secrets.token_hex(8)
        namespace = env_str("PATHWAY_SERVICE_NAMESPACE") or LOCAL_DEV_NAMESPACE
        return cls(
            telemetry_enabled=bool(servers),
            metrics_servers=tuple(servers),
            tracing_servers=tuple(servers),
            service_name="pathway",
            service_version=__version__,
            service_instance_id=instance_id,
            service_namespace=namespace,
            run_id=run_id or secrets.token_hex(8),
            trace_parent=trace_parent,
            license_shortcut=license.shortcut() if license is not None else "",
            # validate only when something will actually be exported: a
            # typo'd env var must not crash zero-egress runs that never
            # touch the wire format
            protocol=(
                _validate_protocol(requested)
                if servers
                else (requested if requested in _PROTOCOLS else "otlp-json")
            ),
        )

    def resource(self) -> dict[str, str]:
        return {
            "service.name": self.service_name,
            "service.version": self.service_version,
            "service.instance.id": self.service_instance_id,
            "service.namespace": self.service_namespace,
            "run.id": self.run_id,
            "root.trace.id": _root_trace_id(self.trace_parent) or "",
            "license.key": self.license_shortcut,
        }


_PROTOCOLS = ("otlp-json", "pathway-json")


def _validate_protocol(value: str) -> str:
    """Reject unknown wire formats loudly: a typo falling back silently
    would make every export 400 at the collector with only debug logs."""
    if value not in _PROTOCOLS:
        raise TelemetryError(
            f"unknown telemetry protocol {value!r}; expected one of {_PROTOCOLS}"
        )
    return value


def _root_trace_id(trace_parent: str | None) -> str | None:
    """trace-id field of a W3C ``traceparent`` header value."""
    if not trace_parent:
        return None
    parts = trace_parent.split("-")
    return parts[1] if len(parts) >= 3 and len(parts[1]) == 32 else None


def mint_traceparent() -> str:
    """A fresh W3C ``traceparent`` header value (sampled flag set).

    One per run: ``cli spawn`` mints it into the cluster environment and
    worker 0 broadcasts it over the mesh to any worker that missed it
    (``internals/runner.py``), so epoch/commit/recovery spans from every
    worker of the run share one trace id in the collector."""
    return f"00-{secrets.token_hex(16)}-{secrets.token_hex(8)}-01"


def _process_metrics() -> dict[str, float]:
    utime, stime = os.times()[:2]
    metrics = {PROCESS_CPU_USER_TIME: utime, PROCESS_CPU_SYSTEM_TIME: stime}
    try:
        with open("/proc/self/statm") as f:
            metrics[PROCESS_MEMORY_USAGE] = (
                int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
            )
    except (OSError, ValueError, IndexError):
        import resource

        metrics[PROCESS_MEMORY_USAGE] = (
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
        )
    return metrics


# ---------------------------------------------------------------------------
# OTLP/HTTP+JSON encoding — the official JSON mapping of opentelemetry-proto
# (ExportMetricsServiceRequest / ExportTraceServiceRequest), hand-encoded so
# any stock OTel collector ingests our payloads with zero extra wheels.
# ---------------------------------------------------------------------------


def _otlp_value(v) -> dict:
    # bool first: it is an int subclass
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}  # proto JSON maps int64 to string
    if isinstance(v, float):
        return {"doubleValue": v}
    return {"stringValue": str(v)}


def _otlp_attrs(d: dict) -> list[dict]:
    return [{"key": k, "value": _otlp_value(v)} for k, v in d.items()]


def _otlp_metrics(payload: dict) -> dict:
    t_ns = str(int(payload.get("ts", time.time()) * 1e9))
    entries = [
        _metrics.otlp_gauge(name, value, t_ns)
        for name, value in payload["metrics"].items()
    ]
    # registry histograms (epoch latency, step time) map to REAL OTLP
    # histogram datapoints, not flattened gauges — a collector can compute
    # quantiles from the bucket counts
    for point in payload.get("histograms") or ():
        entries.append(_metrics.otlp_histogram(point, t_ns))
    return {
        "resourceMetrics": [
            {
                "resource": {"attributes": _otlp_attrs(payload["resource"])},
                "scopeMetrics": [
                    {
                        "scope": {"name": "pathway_tpu"},
                        "metrics": entries,
                    }
                ],
            }
        ]
    }


def _parent_span_id(trace_parent: str | None) -> str:
    """span-id field of a W3C ``traceparent`` header value."""
    parts = (trace_parent or "").split("-")
    return parts[2] if len(parts) >= 4 and len(parts[2]) == 16 else ""


def _otlp_traces(payload: dict) -> dict:
    span = payload["span"]
    # ids are minted at span CREATION and carried on the record
    # (emit_span); minting here at export time would break parent/child
    # links and exemplar trace-id correlation.  The fallbacks below only
    # serve legacy records constructed outside Telemetry.span().
    trace_id = (
        span.get("trace_id")
        or _root_trace_id(span.get("trace_parent"))
        or payload.get("fallback_trace_id")
        or secrets.token_hex(16)
    )
    parent_span_id = span.get("parent_span_id")
    if parent_span_id is None:
        parent_span_id = _parent_span_id(span.get("trace_parent"))
    start_ns = int(span["start"] * 1e9)
    end_ns = start_ns + int(span["duration_s"] * 1e9)
    return {
        "resourceSpans": [
            {
                "resource": {"attributes": _otlp_attrs(payload["resource"])},
                "scopeSpans": [
                    {
                        "scope": {"name": "pathway_tpu"},
                        "spans": [
                            {
                                "traceId": trace_id,
                                "spanId": span.get("span_id")
                                or secrets.token_hex(8),
                                "parentSpanId": parent_span_id,
                                "name": span["name"],
                                "kind": 1,  # SPAN_KIND_INTERNAL
                                "startTimeUnixNano": str(start_ns),
                                "endTimeUnixNano": str(end_ns),
                                "attributes": _otlp_attrs(
                                    span.get("attributes", {})
                                ),
                            }
                        ],
                    }
                ],
            }
        ]
    }


class Telemetry:
    """Samples metrics on a timer and POSTs them; collects spans.

    One instance per run (``maybe_run_telemetry_thread`` analog).
    ``stats_supplier`` returns the latest ProberStats (or None) — the
    prober feeds it, exactly like the reference's ``ArcSwapOption``.
    """

    def __init__(
        self,
        config: TelemetryConfig,
        stats_supplier: Callable[[], Any] | None = None,
        *,
        interval_s: float = PERIODIC_READER_INTERVAL_S,
        extra_metrics: Callable[[], dict[str, float] | None] | None = None,
        registry: "_metrics.MetricsRegistry | None" = None,
    ):
        self.config = config
        self.stats_supplier = stats_supplier
        # extra gauge supplier (name → value), merged into every sample;
        # the runner wires the persistence CommitMetrics snapshot here so
        # commit-stage timings and in-flight bytes ride the same exports
        self.extra_metrics = extra_metrics
        # the unified metrics registry (engine/metrics.py): its counters/
        # gauges merge into every sample and its histograms export as OTLP
        # histogram datapoints.  None keeps the pre-registry behavior
        # (direct Telemetry constructions in tests).
        self.registry = registry
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # bounded: a streaming run emits sampled epoch spans indefinitely
        self.spans: deque[dict] = deque(maxlen=SPAN_BUFFER_MAX)
        self._span_lock = threading.Lock()
        # one trace per run when no traceparent was propagated: all this
        # run's spans must correlate in the collector
        self._fallback_trace_id = secrets.token_hex(16)
        # bounded non-blocking export queue (metrics AND spans): the
        # sampler/span caller thread never blocks on a slow collector —
        # it enqueues; one daemon thread drains; overflow drops the
        # oldest payload and counts it
        self.dropped_exports = 0
        self._q: deque[tuple[str, dict, tuple[str, ...]]] = deque()
        self._q_cv = threading.Condition()
        self._q_thread: threading.Thread | None = None
        self._q_closing = False

    # -- metrics -----------------------------------------------------------
    def sample(self) -> dict[str, Any]:
        metrics = dict(_process_metrics())
        stats = self.stats_supplier() if self.stats_supplier is not None else None
        if stats is not None:
            if stats.input_stats.lag_ms is not None:
                metrics[INPUT_LATENCY] = stats.input_stats.lag_ms
            if stats.output_stats.lag_ms is not None:
                metrics[OUTPUT_LATENCY] = stats.output_stats.lag_ms
        if self.extra_metrics is not None:
            try:
                metrics.update(self.extra_metrics() or {})
            except Exception as exc:  # noqa: BLE001
                # a gauge supplier must never break the sampler
                logger.debug("extra metrics supplier failed: %s", exc)
        payload: dict[str, Any] = {
            "resource": self.config.resource(),
            "metrics": metrics,
            "ts": time.time(),
        }
        if self.registry is not None:
            try:
                metrics.update(self.registry.scalar_metrics())
                payload["histograms"] = self.registry.histogram_points()
            except Exception as exc:  # noqa: BLE001 - same rule as suppliers
                logger.debug("metrics registry read failed: %s", exc)
        return payload

    def _export(self, kind: str, payload: dict, servers: tuple[str, ...]) -> None:
        if self.config.protocol == "otlp-json":
            body = json.dumps(
                _otlp_metrics(payload) if kind == "metrics" else _otlp_traces(payload)
            ).encode()
        elif self.config.protocol == "pathway-json":
            # legacy line-JSON (round-3 format) — exactly that format:
            # fallback_trace_id and the registry histogram points are
            # otlp-only payload hints, not part of it
            legacy = {
                k: v
                for k, v in payload.items()
                if k not in ("fallback_trace_id", "histograms")
            }
            body = json.dumps({"kind": kind, **legacy}).encode()
        else:
            # a directly-constructed config can bypass create()'s check;
            # never fall back silently to a format the endpoint will 400
            raise TelemetryError(
                f"unknown telemetry protocol {self.config.protocol!r}"
            )
        for endpoint in servers:
            url = endpoint.rstrip("/") + f"/v1/{kind}"
            try:
                req = urllib.request.Request(
                    url, data=body, headers={"Content-Type": "application/json"}
                )
                urllib.request.urlopen(req, timeout=EXPORT_TIMEOUT_S).read()
            except Exception as exc:
                logger.debug("telemetry export to %s failed: %s", url, exc)

    # -- bounded export queue ----------------------------------------------
    def _enqueue_export(
        self, kind: str, payload: dict, servers: tuple[str, ...]
    ) -> None:
        """Queue one export without ever blocking the caller.  Overflow
        drops the OLDEST queued payload (a fresh sample is worth more than
        a stale one) and counts the drop — never silently."""
        if not servers:
            return
        with self._q_cv:
            if self._q_closing:
                return
            if len(self._q) >= EXPORT_QUEUE_MAX:
                self._q.popleft()
                self._record_drop()
            self._q.append((kind, payload, servers))
            if self._q_thread is None or not self._q_thread.is_alive():
                self._q_thread = threading.Thread(
                    target=self._q_loop, name="pathway:telemetry-export",
                    daemon=True,
                )
                self._q_thread.start()
            self._q_cv.notify_all()

    def _record_drop(self) -> None:
        self.dropped_exports += 1
        # the drop is itself a metric: it rides /metrics and the next
        # successful export, so a lossy collector link is visible — on
        # THIS Telemetry's registry when one was wired (isolated-registry
        # constructions must not cross-contaminate the global one)
        (self.registry or _metrics.get_registry()).counter(
            "telemetry.export.dropped",
            "telemetry payloads dropped by the bounded export queue",
        ).inc()

    # pathway-lint: context=telemetry
    def _q_loop(self) -> None:
        while True:
            with self._q_cv:
                while not self._q and not self._q_closing:
                    # timed re-check: producers (_enqueue_export) and the
                    # closer (_drain_queue) notify under this cv, but a
                    # supervised background thread never waits unbounded —
                    # the loop condition decides, the timeout only paces
                    self._q_cv.wait(timeout=1.0)
                if not self._q:
                    return  # closing and drained
                kind, payload, servers = self._q.popleft()
            try:
                self._export(kind, payload, servers)
            finally:
                with self._q_cv:
                    self._q_cv.notify_all()

    def _drain_queue(self, timeout: float = 10.0) -> None:
        deadline = time.monotonic() + timeout
        with self._q_cv:
            self._q_closing = True
            self._q_cv.notify_all()
            while self._q and time.monotonic() < deadline:
                self._q_cv.wait(0.1)
            leftovers = len(self._q)
            self._q.clear()
        for _ in range(leftovers):
            self._record_drop()
        thread = self._q_thread
        if thread is not None:
            thread.join(timeout=max(0.1, deadline - time.monotonic()))
            self._q_thread = None

    # -- spans -------------------------------------------------------------
    @contextmanager
    def span(self, name: str, **attributes: Any):
        # ids are minted HERE, at creation, and carried on the record:
        # an export-time mint could never parent-link two spans of one
        # request or correlate a histogram exemplar back to its trace
        start = time.time()
        span_id = secrets.token_hex(8)
        try:
            yield
        finally:
            self.emit_span(
                {
                    "name": name,
                    "start": start,
                    "duration_s": time.time() - start,
                    "attributes": attributes,
                    "trace_parent": self.config.trace_parent,
                    "trace_id": _root_trace_id(self.config.trace_parent)
                    or self._fallback_trace_id,
                    "span_id": span_id,
                    "parent_span_id": _parent_span_id(
                        self.config.trace_parent
                    ),
                }
            )

    def emit_span(self, record: dict) -> None:
        """Record one finished span (buffer + bounded export queue).

        The record carries its own ``trace_id``/``span_id``/
        ``parent_span_id`` (minted at creation); request-scoped tracing
        (``engine/tracing.py``) feeds pre-built child-span records
        through here so they ride the same bounded queue as run spans."""
        with self._span_lock:
            self.spans.append(record)
        if self.config.telemetry_enabled:
            # spans ride the bounded queue too: a dead collector must
            # not add 3 s per endpoint to the span CALLER's thread
            self._enqueue_export(
                "traces",
                {
                    "resource": self.config.resource(),
                    "span": record,
                    "fallback_trace_id": self._fallback_trace_id,
                },
                self.config.tracing_servers,
            )

    def epoch_span(self, time_: int, index: int, *, every: int = 16):
        """A sampled per-epoch span context: every ``every``-th epoch gets
        a real ``pathway.epoch`` span (correlated into the run's trace via
        the propagated traceparent), the rest cost one modulo.  Only emits
        when telemetry has an endpoint — zero-egress runs must not grow
        the span list by one record per epoch."""
        if not self.config.telemetry_enabled or index % max(1, every):
            return nullcontext()
        return self.span("pathway.epoch", epoch=time_, index=index)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "Telemetry":
        if not self.config.telemetry_enabled:
            return self
        self._thread = threading.Thread(
            target=self._loop, name="pathway:telemetry", daemon=True
        )
        self._thread.start()
        return self

    # pathway-lint: context=telemetry
    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self._enqueue_export(
                "metrics", self.sample(), self.config.metrics_servers
            )

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            # final flush so short runs still report once
            self._enqueue_export(
                "metrics", self.sample(), self.config.metrics_servers
            )
            self._thread.join(timeout=5)
            self._thread = None
        self._drain_queue()


def maybe_run_telemetry_thread(
    config: TelemetryConfig,
    stats_supplier: Callable[[], Any] | None = None,
    *,
    interval_s: float = PERIODIC_READER_INTERVAL_S,
) -> Telemetry | None:
    """Start the telemetry loop when enabled (telemetry.rs glue)."""
    if not config.telemetry_enabled:
        return None
    return Telemetry(config, stats_supplier, interval_s=interval_s).start()
