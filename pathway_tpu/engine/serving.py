"""Serving-path overload robustness: admission, deadlines, shedding, drain.

The REST ingress (``io/http/_server.py``) historically admitted unbounded
concurrent requests, waited on a hardcoded 120 s timeout, and stranded the
client future when the pipeline errored or retracted a row.  This module
is the contract that closes the front door:

* **Admission control** — an :class:`AdmissionController` bounds in-flight
  request count (``PATHWAY_SERVE_INFLIGHT``) and bytes
  (``PATHWAY_SERVE_INFLIGHT_MB``); arrivals beyond the budget wait in a
  deadline-aware pending queue (``PATHWAY_SERVE_QUEUE`` deep), and
  overflow is answered ``429`` with a ``Retry-After`` sized from observed
  ``serve.latency.ms`` — never a stranded socket.
* **Deadline propagation** — every request carries a :class:`Deadline`
  (client ``X-Pathway-Deadline-Ms`` header, default
  ``PATHWAY_SERVE_DEADLINE_MS``).  The deadline is stamped onto the
  request row (``io/_utils.DEADLINE_TS``) and checked at the wait points
  that already exist: connector staging drops expired rows before they
  enter the graph, ``AsyncMicroBatcher`` fails expired waiters before
  coalescing them into a device batch, and ``DeviceExecutor.submit``
  refuses an expired ambient deadline — shed-before-work, answered
  ``504``.
* **Load shedding with graceful degradation** — queue delay sustained
  above ``PATHWAY_SERVE_QUEUE_DELAY_MS`` (CoDel-style; worst output
  staleness from the PR-9 freshness sensors feeds the same signal)
  engages degraded mode with the explicit-``None`` dwell-clock hysteresis
  shape of ``ScaleController``: newest requests are shed (429) and routes
  registered with a ``degraded_handler`` switch to their cheap path under
  the ``serve.degraded`` gauge.
* **Typed error completion + drain** — a pipeline error on a request row
  completes the waiting future as a typed ``500`` (the row lands in a
  bounded quarantine, mirroring the device executor's poisoned-batch
  log) instead of wedging until timeout; :func:`ready_for_handoff` lets
  the runner's live-handoff fence stop-accept (``503``) and drain
  in-flight requests bounded by ``PATHWAY_SERVE_DRAIN_S`` before the
  epoch loop commits its frontier — a rescale drops zero in-flight HTTP
  requests.

Everything is observable: the ``serve.*`` metric families ride /status
(``serving`` section), ``pathway_tpu top`` (serving panel), and
flight-recorder dumps (``set_serving_supplier``).

See ``docs/serving.md`` for the operator-facing contract.
"""

from __future__ import annotations

import contextlib
import contextvars
import math
import threading
import time
from collections import deque
from typing import Any, Callable

from pathway_tpu.engine import metrics as metrics_mod
from pathway_tpu.internals.config import (
    env_bool,
    env_float,
    env_int,
)

# ---------------------------------------------------------------------------
# typed serve errors
# ---------------------------------------------------------------------------


class ServeRejected(Exception):
    """Base of the typed serving rejections.

    Doubles as the *value* a request future is failed with (``fail()``)
    and the *exception* a wait point raises (batcher/device shed) — both
    ends read ``.status``/``.message`` and answer the client promptly.
    """

    status = 500
    reason = "error"

    def __init__(self, message: str, *, retry_after_s: float | None = None):
        super().__init__(message)
        self.message = message
        self.retry_after_s = retry_after_s


class OverloadedError(ServeRejected):
    """Admission budget + pending queue full: shed newest, 429."""

    status = 429
    reason = "overloaded"


class DrainingError(ServeRejected):
    """Webserver stop-accept window (shutdown / live handoff): 503."""

    status = 503
    reason = "draining"


class DeadlineExceededError(ServeRejected):
    """The request's deadline lapsed before an answer existed: 504."""

    status = 504
    reason = "deadline exceeded"


class RequestFailedError(ServeRejected):
    """The pipeline errored on this request's row: typed 500."""

    status = 500
    reason = "request failed"


# ---------------------------------------------------------------------------
# deadlines
# ---------------------------------------------------------------------------


class Deadline:
    """A monotonic point in time a request must be answered by."""

    __slots__ = ("at",)

    def __init__(self, at: float):
        self.at = float(at)

    @classmethod
    def from_ms(cls, ms: float, *, now: float | None = None) -> "Deadline":
        if now is None:
            now = time.monotonic()
        return cls(now + max(0.0, float(ms)) / 1000.0)

    def remaining_s(self, now: float | None = None) -> float:
        if now is None:
            now = time.monotonic()
        return self.at - now

    def expired(self, now: float | None = None) -> bool:
        return self.remaining_s(now) <= 0.0


_AMBIENT: contextvars.ContextVar[Deadline | None] = contextvars.ContextVar(
    "pathway_serve_deadline", default=None
)


def current_deadline() -> Deadline | None:
    """The ambient request deadline of the calling context, if any."""
    return _AMBIENT.get()


@contextlib.contextmanager
def deadline_scope(deadline: Deadline | None):
    """Run a block under an ambient deadline (contextvar-scoped, so it
    propagates into coroutines/tasks created inside the block)."""
    token = _AMBIENT.set(deadline)
    try:
        yield deadline
    finally:
        _AMBIENT.reset(token)


def shed_if_expired(where: str) -> None:
    """Raise :class:`DeadlineExceededError` when the ambient deadline has
    lapsed — the shed-before-work check wait points call before paying
    for dispatch.  No ambient deadline → no-op."""
    ddl = _AMBIENT.get()
    if ddl is not None and ddl.expired():
        note_deadline_shed(where)
        raise DeadlineExceededError(
            f"request deadline lapsed before {where} dispatch "
            "(shed-before-work)"
        )


def note_deadline_shed(where: str) -> None:
    """Count a deadline-driven shed at a named wait point."""
    reg = metrics_mod.get_registry()
    reg.counter(
        "serve.deadline.exceeded",
        "requests answered 504, by where the lapse was caught",
        where=where,
    ).inc()
    reg.counter(
        "serve.shed", "requests shed before pipeline work", reason=where
    ).inc()


# ---------------------------------------------------------------------------
# request registry: pipeline-side typed completion
# ---------------------------------------------------------------------------

# key -> fail callback (status, message) — registered by _RestSubject for
# every in-flight request row, called (threadsafe) by the staging dropper
# and the dataflow row-error hook.  Module-level so the epoch thread can
# reach it without holding a reference to the webserver.
_requests: dict[int, Callable[[int, str], None]] = {}
_requests_lock = threading.Lock()


def register_request(key: int, fail_cb: Callable[[int, str], None]) -> None:
    with _requests_lock:
        _requests[key] = fail_cb


def unregister_request(key: int) -> None:
    with _requests_lock:
        _requests.pop(key, None)


def fail_request(key: int, status: int, message: str) -> bool:
    """Complete the waiting future of request ``key`` with a typed error.

    Called from the epoch thread (row errors, staging drops) — must stay
    cheap when serving is inactive: one falsy dict check."""
    if not _requests:
        return False
    with _requests_lock:
        cb = _requests.get(key)
    if cb is None:
        return False
    try:
        cb(status, message)
    except Exception:  # noqa: BLE001 - a dead event loop must not hurt the epoch
        return False
    return True


def note_row_error(key: int, message: str) -> None:
    """Pipeline errored on row ``key``: if it is a serving request,
    complete it as a typed 500 and quarantine the record (the serving
    analogue of the device executor's poisoned-batch log)."""
    if not _requests:
        return
    if fail_request(key, 500, message):
        c = _controller
        if c is not None:
            c.quarantine(key, message)


def shed_staged(key: int) -> None:
    """Connector staging found an expired request row: never stage it —
    504 the waiting client instead of burning an epoch on it."""
    note_deadline_shed("staging")
    fail_request(
        key, 504, "deadline expired before the request row was staged"
    )


# ---------------------------------------------------------------------------
# admission controller
# ---------------------------------------------------------------------------


class _Ticket:
    """One admitted request's claim on the in-flight budget."""

    __slots__ = ("route", "nbytes", "synthetic", "admitted_at", "trace")

    def __init__(
        self,
        route: str,
        nbytes: int,
        synthetic: bool = False,
        admitted_at: float = 0.0,
    ):
        self.route = route
        self.nbytes = int(nbytes)
        self.synthetic = synthetic
        self.admitted_at = admitted_at
        # RequestTrace attached by ``admit`` (None when tracing is off)
        self.trace = None


class _Waiter:
    __slots__ = ("route", "nbytes", "deadline", "enqueued_at", "loop", "future")

    def __init__(self, route, nbytes, deadline, enqueued_at, loop, future):
        self.route = route
        self.nbytes = nbytes
        self.deadline = deadline
        self.enqueued_at = enqueued_at
        self.loop = loop
        self.future = future


class AdmissionController:
    """Bounded in-flight budget + deadline-aware pending queue + CoDel
    shedder + drain state machine.

    Pure state under one lock, wall clock injected (``clock=``) so the
    hysteresis is unit-testable tick by tick — the ``ScaleController``
    shape.  Async admission waits are parked on per-waiter futures and
    granted via ``call_soon_threadsafe``, so one controller serves
    webserver threads on different event loops.
    """

    def __init__(
        self,
        *,
        inflight_limit: int,
        inflight_bytes: int,
        queue_limit: int,
        target_delay_ms: float,
        shed_dwell_s: float,
        recover_s: float,
        drain_s: float,
        enabled: bool = True,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.inflight_limit = max(1, int(inflight_limit))
        self.inflight_bytes_limit = max(1, int(inflight_bytes))
        self.queue_limit = max(0, int(queue_limit))
        self.target_delay_ms = float(target_delay_ms)
        self.shed_dwell_s = float(shed_dwell_s)
        self.recover_s = float(recover_s)
        self.drain_s = float(drain_s)
        self.enabled = bool(enabled)
        self._clock = clock
        self._lock = threading.Lock()
        self._inflight = 0
        self._inflight_bytes = 0
        self._waiters: deque[_Waiter] = deque()
        self._lat_ms: deque[float] = deque(maxlen=128)
        # shedder hysteresis dwell clocks — explicit None checks (0.0 is a
        # valid injected clock reading; `or` resets a dwell started at 0)
        self._over_since: float | None = None
        self._calm_since: float | None = None
        self._degraded = False
        # drain state
        self._draining = False
        self._drain_started: float | None = None
        self._drain_deadline: float | None = None
        self._drained_evt = threading.Event()
        self._drain_recorded = False
        # typed-500 quarantine (newest kept, device-executor parity)
        self._quarantine: deque[dict[str, Any]] = deque(maxlen=32)
        self._quarantined_total = 0
        # optional external pressure sensor (worst output staleness, s)
        self._pressure: Callable[[], float] | None = None
        # admit-time of every outstanding real ticket (id(ticket) keyed):
        # clamps the staleness pressure signal to the age of the oldest
        # admitted request still unanswered
        self._outstanding: dict[int, float] = {}
        self._reg = metrics_mod.get_registry()

    # -- properties --------------------------------------------------------

    @property
    def inflight(self) -> int:
        return self._inflight

    @property
    def queue_depth(self) -> int:
        return len(self._waiters)

    @property
    def degraded(self) -> bool:
        return self._degraded

    @property
    def draining(self) -> bool:
        return self._draining

    def set_pressure_supplier(self, fn: Callable[[], float] | None) -> None:
        self._pressure = fn

    def oldest_outstanding_age_s(self, now: float | None = None) -> float:
        """Age (seconds) of the oldest admitted request still unanswered;
        0.0 when nothing is outstanding.  The shedder clamps its staleness
        pressure signal to this (idleness is not overload), and the
        default staleness SLO shares the same clamp (idleness is not
        burn) — see ``engine/slo.py``."""
        if now is None:
            now = self._clock()
        with self._lock:
            if not self._outstanding:
                return 0.0
            return max(0.0, now - min(self._outstanding.values()))

    # -- admission ---------------------------------------------------------

    def _has_capacity_locked(self, nbytes: int) -> bool:
        return (
            self._inflight < self.inflight_limit
            and self._inflight_bytes + nbytes <= self.inflight_bytes_limit
        )

    def _grant_locked(self, route: str, nbytes: int, now: float) -> _Ticket:
        self._inflight += 1
        self._inflight_bytes += nbytes
        ticket = _Ticket(route, nbytes, admitted_at=now)
        self._outstanding[id(ticket)] = now
        return ticket

    async def admit(
        self,
        route: str,
        nbytes: int,
        deadline: Deadline,
        trace_parent: str | None = None,
    ):
        """Admit or reject one request.  Returns a ticket to pass to
        :meth:`release`; raises a :class:`ServeRejected` subclass with the
        HTTP status + Retry-After already decided.  Never strands the
        caller: every path answers within the request's own deadline.

        The admission controller is also where the request's
        :class:`~pathway_tpu.engine.tracing.RequestTrace` is born (the
        ingress ``traceparent`` continues a caller's trace; otherwise one
        is minted): the ticket carries it, and the admission wait —
        fast-path or queued — becomes its first child span."""
        from pathway_tpu.engine import tracing

        trace = tracing.begin_request(route, trace_parent)
        started = time.time()
        try:
            ticket = await self._admit(route, nbytes, deadline)
        except ServeRejected as exc:
            if trace is not None:
                trace.finish(status=exc.status, reason=exc.reason)
            raise
        ticket.trace = trace
        if trace is not None:
            trace.add_span(
                "serve.admission",
                started,
                max(0.0, time.time() - started),
                inflight=self._inflight,
            )
        return ticket

    async def _admit(self, route: str, nbytes: int, deadline: Deadline):
        import asyncio

        now = self._clock()
        with self._lock:
            if not self.enabled:
                self._note_delay_locked(0.0, now)
                return self._grant_locked(route, nbytes, now)
            if self._draining:
                raise DrainingError(
                    "webserver is draining (shutdown or live handoff)",
                    retry_after_s=self._retry_after_locked(),
                )
            if self._has_capacity_locked(nbytes) and not self._waiters:
                self._note_delay_locked(0.0, now)
                return self._grant_locked(route, nbytes, now)
            # would queue: degraded mode sheds newest instead of queuing
            if self._degraded:
                retry = self._retry_after_locked()
                self._shed_locked("degraded", route)
                raise OverloadedError(
                    "load shedder engaged (sustained queue delay)",
                    retry_after_s=retry,
                )
            if len(self._waiters) >= self.queue_limit:
                retry = self._retry_after_locked()
                self._shed_locked("queue-full", route)
                raise OverloadedError(
                    "admission queue full", retry_after_s=retry
                )
            loop = asyncio.get_running_loop()
            waiter = _Waiter(
                route, nbytes, deadline, now, loop, loop.create_future()
            )
            self._waiters.append(waiter)
            self._gauge_locked()
        try:
            remaining = max(0.0, deadline.remaining_s(self._clock()))
            return await asyncio.wait_for(waiter.future, timeout=remaining)
        except asyncio.TimeoutError:
            with self._lock:
                try:
                    self._waiters.remove(waiter)
                except ValueError:
                    pass  # granted in the race window; ticket reclaimed below
                self._gauge_locked()
            # the grant callback reclaims the ticket if it lost the race
            # (waiter.future is cancelled by wait_for)
            note_deadline_shed("queue")
            raise DeadlineExceededError(
                "deadline lapsed waiting for an in-flight slot"
            ) from None
        except ServeRejected:
            raise

    def release(
        self,
        ticket: _Ticket,
        *,
        code: int = 200,
        latency_ms: float | None = None,
    ) -> None:
        """Return an admitted request's budget; pump the pending queue."""
        grants: list[tuple[_Waiter, _Ticket]] = []
        now = self._clock()
        with self._lock:
            self._inflight = max(0, self._inflight - 1)
            self._inflight_bytes = max(0, self._inflight_bytes - ticket.nbytes)
            self._outstanding.pop(id(ticket), None)
            if latency_ms is not None and code == 200:
                self._lat_ms.append(float(latency_ms))
            grants = self._pump_locked(now)
            self._gauge_locked()
            self._check_drained_locked(now)
        for waiter, granted in grants:
            self._deliver(waiter, granted)

    def _pump_locked(self, now: float) -> list[tuple[_Waiter, _Ticket]]:
        """Grant queued waiters while capacity lasts; expired waiters are
        failed in place (their slot is never wasted on a dead request)."""
        grants: list[tuple[_Waiter, _Ticket]] = []
        while self._waiters:
            head = self._waiters[0]
            if head.deadline.expired(now):
                self._waiters.popleft()
                self._fail_waiter(head)
                continue
            if not self._has_capacity_locked(head.nbytes):
                break
            self._waiters.popleft()
            waited_ms = max(0.0, (now - head.enqueued_at) * 1000.0)
            self._note_delay_locked(waited_ms, now)
            self._reg.histogram(
                "serve.queue.wait.ms",
                "admission queue wait (ms)",
                buckets=metrics_mod.MS_BUCKETS,
            ).observe(waited_ms)
            grants.append((head, self._grant_locked(head.route, head.nbytes, now)))
        return grants

    def _deliver(self, waiter: _Waiter, ticket: _Ticket) -> None:
        def grant():
            if waiter.future.done():
                # the waiter timed out between grant and delivery: put the
                # budget back and pass it on
                self.release(ticket, code=0)
            else:
                waiter.future.set_result(ticket)

        try:
            waiter.loop.call_soon_threadsafe(grant)
        except RuntimeError:
            # waiter's loop is gone (webserver died): reclaim the budget
            self.release(ticket, code=0)

    def _fail_waiter(self, waiter: _Waiter) -> None:
        note_deadline_shed("queue")

        def fail():
            if not waiter.future.done():
                waiter.future.set_exception(
                    DeadlineExceededError(
                        "deadline lapsed waiting for an in-flight slot"
                    )
                )

        try:
            waiter.loop.call_soon_threadsafe(fail)
        except RuntimeError:
            pass

    # -- shedding hysteresis ----------------------------------------------

    def _effective_delay_ms(self, queue_delay_ms: float, now: float) -> float:
        fn = self._pressure
        if fn is not None:
            try:
                staleness_s = fn()
            except Exception:  # noqa: BLE001 - a sensor must never break admission
                staleness_s = 0.0
            if staleness_s and math.isfinite(staleness_s):
                # an idle gap also grows output staleness (no input ->
                # frozen watermark), and idleness is not overload: the
                # pipeline-pressure signal is clamped to the age of the
                # oldest admitted request still unanswered, so staleness
                # counts only while admitted work has actually been
                # outstanding that long
                if self._outstanding:
                    oldest_s = max(0.0, now - min(self._outstanding.values()))
                    pressure_s = min(staleness_s, oldest_s)
                else:
                    pressure_s = 0.0
                return max(queue_delay_ms, pressure_s * 1000.0)
        return queue_delay_ms

    def _note_delay_locked(self, queue_delay_ms: float, now: float) -> None:
        """CoDel-style: delay sustained above target for ``shed_dwell_s``
        engages degraded mode; back under target for ``recover_s``
        disengages it.  Any dip resets the opposing clock."""
        delay = self._effective_delay_ms(queue_delay_ms, now)
        if delay > self.target_delay_ms:
            self._calm_since = None
            if self._over_since is None:
                self._over_since = now
            elif (
                not self._degraded
                and now - self._over_since >= self.shed_dwell_s
            ):
                self._degraded = True
                self._transition_locked(1.0)
        else:
            self._over_since = None
            if self._degraded:
                if self._calm_since is None:
                    self._calm_since = now
                elif now - self._calm_since >= self.recover_s:
                    self._degraded = False
                    self._calm_since = None
                    self._transition_locked(0.0)

    def observe_pressure(self, now: float | None = None) -> None:
        """Feed the shedder outside an admission event (periodic poll —
        lets sustained *pipeline* pressure engage shedding even while
        the admission queue itself is empty)."""
        if now is None:
            now = self._clock()
        with self._lock:
            self._note_delay_locked(0.0, now)

    def _transition_locked(self, to: float) -> None:
        self._reg.gauge(
            "serve.degraded", "1 while the load shedder is engaged"
        ).set(to)
        self._reg.counter(
            "serve.degraded.transitions", "degraded engage/disengage edges"
        ).inc()

    def _shed_locked(self, reason: str, route: str) -> None:
        self._reg.counter(
            "serve.shed", "requests shed before pipeline work", reason=reason
        ).inc()

    # -- Retry-After -------------------------------------------------------

    def _retry_after_locked(self) -> float:
        """Seconds the client should back off: observed p50 latency scaled
        by how much admitted+queued work is ahead of it, clamped [1, 30]."""
        if self._lat_ms:
            ordered = sorted(self._lat_ms)
            p50_ms = ordered[len(ordered) // 2]
        else:
            p50_ms = 1000.0
        ahead = self._inflight + len(self._waiters) + 1
        est_s = (p50_ms / 1000.0) * ahead / max(1, self.inflight_limit)
        return float(min(30.0, max(1.0, math.ceil(est_s))))

    def retry_after_s(self) -> float:
        with self._lock:
            return self._retry_after_locked()

    # -- drain -------------------------------------------------------------

    def begin_drain(self, now: float | None = None) -> None:
        """Stop accepting (new arrivals get 503) and start the bounded
        in-flight drain window.  Idempotent."""
        if now is None:
            now = self._clock()
        fail: list[_Waiter] = []
        with self._lock:
            if self._draining:
                return
            self._draining = True
            self._drain_started = now
            self._drain_deadline = now + self.drain_s
            # queued waiters cannot be admitted any more: answer them now
            fail = list(self._waiters)
            self._waiters.clear()
            self._reg.gauge(
                "serve.draining", "1 while the webserver is draining"
            ).set(1.0)
            self._check_drained_locked(now)
        for w in fail:
            self._shed_drain_waiter(w)

    def _shed_drain_waiter(self, waiter: _Waiter) -> None:
        self._reg.counter(
            "serve.shed", "requests shed before pipeline work",
            reason="draining",
        ).inc()

        def fail():
            if not waiter.future.done():
                waiter.future.set_exception(
                    DrainingError(
                        "webserver is draining (shutdown or live handoff)"
                    )
                )

        try:
            waiter.loop.call_soon_threadsafe(fail)
        except RuntimeError:
            pass

    def _check_drained_locked(self, now: float) -> None:
        if not self._draining or self._drain_recorded:
            return
        if self._inflight == 0 and not self._waiters:
            self._drain_recorded = True
            self._drained_evt.set()
            started = self._drain_started
            if started is not None:
                self._reg.histogram(
                    "serve.drain.ms",
                    "drain start to last in-flight completion (ms)",
                    buckets=metrics_mod.MS_BUCKETS,
                ).observe(max(0.0, (now - started) * 1000.0))

    def drain_ready(self, now: float | None = None) -> bool:
        """True once the drain may be considered complete: every in-flight
        request answered, or the ``PATHWAY_SERVE_DRAIN_S`` budget blown
        (counted — a handoff must not wait forever on a wedged client)."""
        if now is None:
            now = self._clock()
        with self._lock:
            if not self._draining:
                return False
            self._check_drained_locked(now)
            if self._drained_evt.is_set():
                return True
            if self._drain_deadline is not None and now >= self._drain_deadline:
                self._shed_locked("drain-timeout", "*")
                return True
            return False

    def wait_drained(self, timeout: float) -> bool:
        """Block (bounded) until the in-flight set drains to zero."""
        return self._drained_evt.wait(timeout=timeout)

    def end_drain(self) -> None:
        """Re-open admission (tests / aborted handoff)."""
        with self._lock:
            self._draining = False
            self._drain_started = None
            self._drain_deadline = None
            self._drain_recorded = False
            self._drained_evt.clear()
            self._reg.gauge(
                "serve.draining", "1 while the webserver is draining"
            ).set(0.0)

    # -- chaos: synthetic flood -------------------------------------------

    def inject_flood(self, count: int, hold_s: float) -> None:
        """``request_flood`` chaos: claim ``count`` synthetic in-flight
        slots for ``hold_s`` — competing traffic without real sockets, so
        chaos tests drive deterministic 429/queue behavior."""
        count = max(1, int(count))
        with self._lock:
            self._inflight += count
            self._gauge_locked()
        self._reg.counter(
            "serve.flood.synthetic", "synthetic flood admissions injected"
        ).inc(count)

        def _release():
            grants: list[tuple[_Waiter, _Ticket]] = []
            now = self._clock()
            with self._lock:
                self._inflight = max(0, self._inflight - count)
                grants = self._pump_locked(now)
                self._gauge_locked()
                self._check_drained_locked(now)
            for waiter, granted in grants:
                self._deliver(waiter, granted)

        t = threading.Timer(max(0.0, hold_s), _release)
        t.daemon = True
        t.start()

    # -- quarantine --------------------------------------------------------

    def quarantine(self, key: int, message: str) -> None:
        with self._lock:
            self._quarantine.append(
                {"key": int(key), "error": str(message)[:300], "ts": time.time()}
            )
            self._quarantined_total += 1
        self._reg.counter(
            "serve.quarantined", "request rows failed by the pipeline"
        ).inc()

    # -- observability -----------------------------------------------------

    def _gauge_locked(self) -> None:
        self._reg.gauge(
            "serve.inflight", "admitted, unanswered REST requests"
        ).set(float(self._inflight))
        self._reg.gauge(
            "serve.inflight.bytes", "in-flight request-body bytes"
        ).set(float(self._inflight_bytes))
        self._reg.gauge(
            "serve.queue.depth", "requests waiting for admission"
        ).set(float(len(self._waiters)))

    def state_metrics(self) -> dict[str, float]:
        """Pull-time gauges for the ``serve.state`` collector."""
        with self._lock:
            return {
                "serve.inflight": float(self._inflight),
                "serve.inflight.bytes": float(self._inflight_bytes),
                "serve.queue.depth": float(len(self._waiters)),
                "serve.degraded": 1.0 if self._degraded else 0.0,
                "serve.draining": 1.0 if self._draining else 0.0,
            }

    def snapshot(self) -> dict[str, Any]:
        """Flight-recorder payload: state + knobs + the quarantine tail."""
        with self._lock:
            lat = sorted(self._lat_ms)
            p50 = lat[len(lat) // 2] if lat else None
            return {
                "inflight": self._inflight,
                "inflight_bytes": self._inflight_bytes,
                "queue_depth": len(self._waiters),
                "degraded": self._degraded,
                "draining": self._draining,
                "enabled": self.enabled,
                "latency_p50_ms": p50,
                "limits": {
                    "inflight": self.inflight_limit,
                    "inflight_bytes": self.inflight_bytes_limit,
                    "queue": self.queue_limit,
                    "target_delay_ms": self.target_delay_ms,
                },
                "quarantined_total": self._quarantined_total,
                "quarantine": list(self._quarantine)[-5:],
            }


# ---------------------------------------------------------------------------
# process-global controller
# ---------------------------------------------------------------------------

_controller: AdmissionController | None = None
_controller_lock = threading.Lock()


def get_controller() -> AdmissionController:
    """The process-global admission controller, built from the declared
    ``PATHWAY_SERVE_*`` knobs on first use (the REST ingress path)."""
    global _controller
    c = _controller
    if c is not None:
        return c
    with _controller_lock:
        if _controller is None:
            c = AdmissionController(
                inflight_limit=env_int("PATHWAY_SERVE_INFLIGHT"),
                inflight_bytes=int(
                    env_float("PATHWAY_SERVE_INFLIGHT_MB") * 1024 * 1024
                ),
                queue_limit=env_int("PATHWAY_SERVE_QUEUE"),
                target_delay_ms=env_float("PATHWAY_SERVE_QUEUE_DELAY_MS"),
                shed_dwell_s=env_float("PATHWAY_SERVE_SHED_DWELL_S"),
                recover_s=env_float("PATHWAY_SERVE_RECOVER_S"),
                drain_s=env_float("PATHWAY_SERVE_DRAIN_S"),
                enabled=env_bool("PATHWAY_SERVE_ADMISSION"),
            )
            metrics_mod.get_registry().register_collector(
                "serve.state", c.state_metrics
            )
            _adopt_pending_pressure(c)
            _controller = c
        return _controller


def controller_if_active() -> AdmissionController | None:
    """The controller if any REST route ever initialized it — never
    creates one (non-serving runs must stay zero-cost)."""
    return _controller


def snapshot_or_none() -> dict[str, Any] | None:
    """Flight-recorder serving supplier (runner wires it per run)."""
    c = _controller
    return c.snapshot() if c is not None else None


def set_pressure_supplier(fn: Callable[[], float] | None) -> None:
    """Wire the PR-9 freshness sensor (worst output staleness, seconds)
    into the shedder; the runner sets/clears it around each run."""
    c = _controller
    if c is not None:
        c.set_pressure_supplier(fn)
    global _pending_pressure
    _pending_pressure = fn


# a run may wire the sensor before the first request builds the controller
_pending_pressure: Callable[[], float] | None = None


def _adopt_pending_pressure(c: AdmissionController) -> None:
    if _pending_pressure is not None:
        c.set_pressure_supplier(_pending_pressure)


def ready_for_handoff() -> bool:
    """The runner's live-handoff gate (called at the epoch boundary, so it
    must never block): on first call under an in-flight serving load it
    begins the stop-accept drain and reports False — the epoch loop keeps
    processing so in-flight requests can complete — then True once every
    request is answered or the drain budget lapses.  Without an active
    serving controller it is True immediately."""
    c = _controller
    if c is None:
        return True
    c.begin_drain()
    return c.drain_ready()


def fail_inflight_for_promotion() -> int:
    """A peer died and this worker is unwinding its mesh for an
    in-process promotion rejoin: every registered in-flight request is
    waiting on epochs the poisoned mesh will never run.  Answer them all
    NOW with the typed 503 retry signal — a well-behaved client retries
    after promotion completes (sub-second) instead of timing out across
    the rejoin — and park new arrivals behind the drain gate until
    :func:`resume_after_promotion` re-opens admission.  Returns the
    number of requests answered."""
    c = _controller
    if c is not None:
        c.begin_drain()
    with _requests_lock:
        keys = list(_requests)
    failed = 0
    for key in keys:
        if fail_request(
            key, 503,
            "standby promotion in progress on this worker group; retry",
        ):
            failed += 1
    if failed:
        metrics_mod.get_registry().counter(
            "serve.shed", "requests shed before pipeline work",
            reason="promotion",
        ).inc(failed)
    return failed


def resume_after_promotion() -> None:
    """Re-open admission after a promotion rejoin (the ``run()`` wrapper
    calls this between mesh lifetimes; the controller is process-global
    and survives the rejoin, so its drain gate must be reset here)."""
    c = _controller
    if c is not None:
        c.end_drain()


def reset_for_tests() -> None:
    """Drop the process-global controller + request registry (tests)."""
    global _controller, _pending_pressure
    with _controller_lock:
        if _controller is not None:
            metrics_mod.get_registry().unregister_collector("serve.state")
        _controller = None
        _pending_pressure = None
    with _requests_lock:
        _requests.clear()


# ---------------------------------------------------------------------------
# chaos fault hooks (engine/faults.py kinds: request_flood, slow_handler)
# ---------------------------------------------------------------------------


def maybe_flood(route: str) -> None:
    """``request_flood`` injection site: a firing spec saturates the whole
    admission budget (in-flight limit worth of synthetic requests) for
    ``delay_ms`` (default 1000) — the 2×-capacity wall chaos tests push
    against."""
    from pathway_tpu.engine import faults

    spec = faults.check("request_flood", source=route)
    if spec is None:
        return
    c = get_controller()
    hold_ms = spec.delay_ms if spec.delay_ms else 1000.0
    c.inject_flood(c.inflight_limit, hold_ms / 1000.0)


def slow_handler_delay_s(route: str) -> float:
    """``slow_handler`` injection site: seconds the REST handler should
    stall (async, budget held) before emitting the row — drives queue
    delay up so shedding/degraded paths fire deterministically."""
    from pathway_tpu.engine import faults

    spec = faults.check("slow_handler", source=route)
    if spec is None:
        return 0.0
    return (spec.delay_ms or 0.0) / 1000.0
