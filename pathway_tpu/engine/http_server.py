"""Monitoring HTTP server: ``/status`` (JSON) and ``/metrics`` (OpenMetrics).

Parity target: ``src/engine/http_server.rs:21-215`` — a per-process
endpoint on ``127.0.0.1:(20000 + process_id)`` (override with
``PATHWAY_MONITORING_HTTP_PORT``), serving the latest ``ProberStats``
snapshot in Prometheus text format.  The reference shares the snapshot via
``ArcSwapOption``; here a lock-free attribute swap on the server object
plays that role (the GIL makes the single reference assignment atomic).

Runs in a daemon thread off the worker hot loop, exactly like the
reference keeps hyper off the timely worker threads.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from pathway_tpu.engine import metrics as _metrics
from pathway_tpu.engine.probes import ProberStats

DEFAULT_FIRST_PORT = 20000  # http_server.rs:83


def monitoring_port(process_id: int = 0, override: int | None = None) -> int:
    return override if override is not None else DEFAULT_FIRST_PORT + process_id


# one escaping rule for the whole /metrics body: the ProberStats section
# here and the registry section it appends must never diverge
_esc = _metrics.escape_label


def render_prometheus(
    stats: ProberStats,
    run_id: str | None = None,
    registry: "_metrics.MetricsRegistry | None" = None,
) -> str:
    """OpenMetrics text, gauge names matching the reference's exposition.

    HELP/TYPE headers are emitted once per metric name (strict parsers
    reject duplicates), followed by that metric's samples.  With a
    ``registry`` (the unified metrics registry, ``engine/metrics.py``) its
    exposition — comm/persistence/supervisor counters, epoch histograms —
    is appended before the terminator, so one scrape covers the whole
    worker.
    """
    run_label = f'run_id="{_esc(run_id)}"' if run_id else ""

    def labels(*pairs: str) -> str:
        parts = [p for p in (*pairs, run_label) if p]
        return "{" + ",".join(parts) + "}" if parts else ""

    # metric -> (help text, [(label string, value), ...])
    metrics: dict[str, tuple[str, list[tuple[str, object]]]] = {}

    def gauge(name: str, value, help_: str, label_str: str | None = None) -> None:
        if value is None:
            return
        metrics.setdefault(name, (help_, []))[1].append(
            (labels() if label_str is None else label_str, value)
        )

    gauge("input_latency_ms", stats.input_stats.lag_ms, "input processing lag")
    gauge("output_latency_ms", stats.output_stats.lag_ms, "output processing lag")
    gauge("input_time", stats.input_stats.time, "latest committed input epoch")
    gauge("output_time", stats.output_stats.time, "latest produced output epoch")
    gauge("epochs_total", stats.epochs, "consistent epochs processed")
    gauge(
        "input_rows_total", stats.input_stats.rows_out, "rows ingested across sources"
    )
    gauge(
        "output_rows_total", stats.output_stats.rows_in, "rows delivered across sinks"
    )
    for op_id, op in stats.operator_stats.items():
        op_labels = labels(f'operator="{_esc(op.name)}"', f'id="{op_id}"')
        gauge("operator_rows_in_total", op.rows_in, "rows consumed", op_labels)
        gauge("operator_rows_out_total", op.rows_out, "rows produced", op_labels)
    for op_id, n in stats.row_counts.items():
        gauge(
            "operator_state_rows", n, "rows of maintained state", labels(f'id="{op_id}"')
        )

    lines: list[str] = []
    for name, (help_, samples) in metrics.items():
        lines.append(f"# HELP {name} {help_}")
        lines.append(f"# TYPE {name} gauge")
        for label_str, value in samples:
            lines.append(f"{name}{label_str} {value}")
    if registry is not None:
        registry_text = registry.render_prometheus(
            extra_labels={"run_id": run_id} if run_id else None
        )
        if registry_text:
            lines.append(registry_text.rstrip("\n"))
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def render_status(
    stats: ProberStats,
    run_id: str | None = None,
    registry: "_metrics.MetricsRegistry | None" = None,
) -> str:
    """The ``GET /status`` JSON body: dataflow progress plus — with a
    registry — the data-plane view ``pathway_tpu top`` renders: per-output
    freshness (staleness + e2e latency quantiles), the ``backlog.*``
    backpressure ranking, and epoch-duration quantiles.  Keys are only
    ever added here; existing consumers keep parsing."""

    def op(s):
        return {
            "name": s.name,
            "time": s.time,
            "lag_ms": s.lag_ms,
            "rows_in": s.rows_in,
            "rows_out": s.rows_out,
            "step_ms": s.step_ms,
            "done": s.done,
        }

    payload = {
        "run_id": run_id,
        "epochs": stats.epochs,
        "input": op(stats.input_stats),
        "output": op(stats.output_stats),
        "operators": {str(k): op(v) for k, v in stats.operator_stats.items()},
        "connectors": [
            {"name": c.name, "rows": c.rows, "finished": c.finished}
            for c in stats.connector_stats
        ],
    }
    if registry is not None:
        from pathway_tpu.engine.telemetry import DEVICE_SECTION_PREFIX

        scalars = registry.scalar_metrics()
        payload["freshness"] = {
            k: v
            for k, v in scalars.items()
            if k.startswith(("freshness.", "output.staleness"))
        }
        payload["backlog"] = {
            k: v for k, v in scalars.items() if k.startswith("backlog.")
        }
        payload["epoch"] = {
            k: v
            for k, v in scalars.items()
            if k.startswith("epoch.duration.ms.")
        }
        # the device panel of `pathway_tpu top`: cost/utilization/padding/
        # HBM gauges, dispatch counters and their quantile estimates, plus
        # the jax compile accounting the executor discipline pins against
        payload["device"] = {
            k: v
            for k, v in scalars.items()
            if k.startswith((DEVICE_SECTION_PREFIX, "jax."))
        }
        # columnar execution health: bail counters by op/reason — a
        # pipeline silently running row-wise shows up here and in the
        # `pathway_tpu top` columnar line
        payload["columnar"] = {
            k: v for k, v in scalars.items() if k.startswith("columnar.")
        }
        # the autoscaler panel: target topology, budget, cooldown and
        # handoff phase (gauges derived from lease/autoscaler.json by the
        # collector each supervised worker registers; absent = autoscaling
        # off or solo run)
        payload["autoscaler"] = {
            k: v for k, v in scalars.items() if k.startswith("autoscaler.")
        }
        # the warm-standby panel: pool size, per-standby apply lag, and
        # promotion history (gauges derived from lease/standby.<sid>
        # beacons + lease/promotions.json by the collector each
        # supervised worker registers; absent = no standby pool)
        payload["standby"] = {
            k: v
            for k, v in scalars.items()
            if k.startswith(("standby.", "supervisor.promotions"))
        }
        # the serving panel: admission occupancy, latency quantiles, shed/
        # deadline counters and degraded/draining flags (absent = no REST
        # ingress in this pipeline)
        payload["serving"] = {
            k: v for k, v in scalars.items() if k.startswith("serve.")
        }
        # the generation panel: continuous-batching slot/queue occupancy,
        # page-pool utilization, TTFT and throughput (absent = no
        # decoder generation ran in this process)
        payload["generation"] = {
            k: v for k, v in scalars.items() if k.startswith("generate.")
        }
        # the requests panel (`pathway_tpu requests`): trace.* scalars,
        # the slowest finished traces WITH span trees (waterfall source),
        # and the per-bucket histogram exemplars linking a slow bucket to
        # a real trace id
        from pathway_tpu.engine import tracing as _tracing

        payload["requests"] = {
            "scalars": {
                k: v for k, v in scalars.items() if k.startswith("trace.")
            },
            "slowest": _tracing.slowest_requests(10),
            "recent": _tracing.recent_requests(10),
            "exemplars": registry.exemplar_points(),
        }
        # the SLO panel: declared objectives with burn rates + budgets
        # (the `slo.*` scalars ride the collector; the structured view
        # feeds `pathway_tpu top` and flight-recorder dumps)
        from pathway_tpu.engine import slo as _slo

        payload["slo"] = _slo.get_evaluator().snapshot()
    # default=repr: a span attribute carrying a non-JSON value (a numpy
    # scalar from the device path) must degrade to its repr, never take
    # the whole status endpoint down with a TypeError
    return json.dumps(payload, default=repr)


def _handle_trace(path: str) -> tuple[str, int]:
    """``GET /trace?seconds=N`` → ``(JSON body, HTTP status)``.

    200 with ``{"trace_dir": ..., "seconds": ...}`` on success; 400 on a
    malformed duration; 409 while another capture runs; 503 when capture
    is unavailable here (no ``PATHWAY_DEVICE_TRACE_DIR``, no
    ``jax.profiler``).  Errors carry ``{"error": message}`` so the
    ``pathway_tpu trace`` CLI can relay the reason verbatim."""
    from urllib.parse import parse_qs, urlparse

    from pathway_tpu.device import telemetry as _device_telemetry

    query = parse_qs(urlparse(path).query)
    raw = (query.get("seconds") or ["1.0"])[0]
    try:
        seconds = float(raw)
    except ValueError:
        return json.dumps({"error": f"bad seconds value {raw!r}"}), 400
    try:
        trace_dir = _device_telemetry.capture_trace(seconds)
    except _device_telemetry.TraceBusy as exc:
        return json.dumps({"error": str(exc)}), 409
    except _device_telemetry.TraceUnavailable as exc:
        return json.dumps({"error": str(exc)}), 503
    except Exception as exc:  # noqa: BLE001 - the JSON error contract
        # holds for EVERY failure (unwritable trace dir, a profiler
        # session started outside our lock, ...): the CLI must relay the
        # real reason, never a dead-connection guess
        return json.dumps({"error": repr(exc)}), 500
    return json.dumps({"trace_dir": trace_dir, "seconds": seconds}), 200


class MonitoringServer:
    """Daemon-thread HTTP server exposing the latest stats snapshot."""

    def __init__(
        self,
        *,
        process_id: int = 0,
        port: int | None = None,
        run_id: str | None = None,
        host: str = "127.0.0.1",
        registry: "_metrics.MetricsRegistry | None" = None,
    ):
        self.run_id = run_id
        self._stats = ProberStats()  # swapped whole, never mutated in place
        # the unified registry rides every /metrics scrape by default;
        # pass registry explicitly to serve an isolated one (tests)
        self.registry = registry if registry is not None else _metrics.get_registry()
        self.port = monitoring_port(process_id, port)
        server = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                if self.path.startswith("/metrics"):
                    body = render_prometheus(
                        server._stats, server.run_id, registry=server.registry
                    )
                    ctype = "text/plain; version=0.0.4"
                elif self.path.startswith("/status"):
                    body = render_status(
                        server._stats, server.run_id, registry=server.registry
                    )
                    ctype = "application/json"
                elif self.path.startswith("/trace"):
                    # on-demand jax.profiler capture IN THIS PROCESS (the
                    # live worker owns the device), blocking this handler
                    # thread for the requested duration — the threading
                    # server keeps /status and /metrics responsive
                    body, status = _handle_trace(self.path)
                    ctype = "application/json"
                    self._reply(status, ctype, body)
                    return
                else:
                    self.send_error(404)
                    return
                self._reply(200, ctype, body)

            def _reply(self, status: int, ctype: str, body: str) -> None:
                data = body.encode()
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, *args):  # silence request logging
                pass

        self._httpd = ThreadingHTTPServer((host, self.port), Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="pathway:http", daemon=True
        )

    def start(self) -> "MonitoringServer":
        self._thread.start()
        return self

    def update(self, stats: ProberStats) -> None:
        self._stats = stats

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)
