"""The incremental dataflow engine.

Parity target: ``/root/reference/src/engine/dataflow.rs`` (6,173 LoC) +
``src/engine/graph.rs`` (the ~45-method ``Graph`` trait).  Re-designed rather
than translated:

* The reference schedules fine-grained differential operators cooperatively
  (``worker.step_or_park``).  Here the unit of work is an **epoch batch**: all
  deltas that share a commit timestamp flow through the operator DAG in one
  topologically-ordered pass.  That matches how a TPU program wants to see
  work — large consolidated batches that can be padded to fixed shapes and
  jitted — instead of row-at-a-time callbacks.
* Collections are multisets of ``(key, row, diff)`` with 128-bit keys
  (``engine/types.py``); every operator is delta-correct: retractions
  (diff = -1) flow through joins, groupbys, and indexes exactly as in
  differential dataflow.
* Stateful operators own explicit dict-based arrangements; there is no
  shared-arrangement machinery, which differential needs because operators
  run concurrently — here the per-epoch barrier makes sharing trivial.

The node set mirrors the Graph trait surface (graph.rs:643-986): input,
expression/select, filter, flatten, reindex, update_cells/update_rows,
concat, intersect/difference/restrict, ix, join (all modes), groupby/reduce,
deduplicate, buffer/freeze/forget (temporal behaviors from time_column.rs),
sort (prev/next), external index as-of-now, output/subscribe, iterate,
gradual_broadcast, error log.
"""

from __future__ import annotations

import itertools
from collections import Counter, defaultdict
from time import monotonic as _monotonic
from typing import Any, Callable, Iterable, Sequence

from pathway_tpu.engine.types import (
    ERROR,
    Error,
    Pointer,
    Time,
    as_hashable,
    hash_values,
)

Row = tuple
Delta = tuple  # (key:int, row:Row, diff:int)


def _serving_note_row_error(key: int, message: str) -> None:
    """Poisoned-cell hook: if this row key is an in-flight REST request,
    complete its waiting HTTP future as a typed 500 and quarantine the
    record (engine/serving.py) — a cheap no-op when nothing is serving."""
    from pathway_tpu.engine import serving as _serving

    _serving.note_row_error(key, message)


class CleanDeltas(list):
    """Delta list known to be all-insert (+1) with pairwise-distinct keys.

    Such a list cannot cancel or merge, so ``consolidate`` is the identity
    on it.  Producers whose transformation preserves the property (1:1 maps,
    filters, key-fresh flattens) re-tag their output, letting the ingest-
    heavy epochs skip the O(n) clean-scan at every node boundary — that scan
    was the hottest host-path line at 1M rows/epoch.
    """


_native_consolidate = None
_native_checked = False


_native_module = None


def _get_native_module():
    global _native_module, _native_checked, _native_consolidate
    if not _native_checked:
        _native_checked = True
        try:
            from pathway_tpu import native as _nat

            _native_module = _nat.get()
            _native_consolidate = getattr(
                _native_module, "consolidate_dirty", None
            )
        except Exception:
            _native_module = None
            _native_consolidate = None
    return _native_module


def _get_native_consolidate():
    _get_native_module()
    return _native_consolidate


def consolidate(deltas: Iterable[Delta]) -> list[Delta]:
    if isinstance(deltas, CleanDeltas):
        return deltas
    if not isinstance(deltas, list):
        deltas = list(deltas)
    # fast path: all-distinct-key inserts cannot cancel or merge — CPython's
    # int-set scan beats a C++ hash table here (measured 1.6x), while the
    # native accumulation below wins 2x on retraction-heavy batches
    keys: set[int] = set()
    clean = True
    for key, _, diff in deltas:
        if diff != 1 or key in keys:
            clean = False
            break
        keys.add(key)
    if clean:
        return CleanDeltas(deltas)
    nc = _get_native_consolidate()
    if nc is not None:
        out = nc(deltas)  # precondition: batch proven dirty above
        if out is not None:  # None = diffs beyond int64, use Python path
            return out
    acc: Counter = Counter()
    for key, row, diff in deltas:
        acc[(key, row)] += diff
    # retractions before insertions: stateful consumers replace a row by
    # applying (-old, +new) for the same key — the insert landing first
    # would be popped by the retract and the row silently lost
    out = [(k, r, d) for (k, r), d in acc.items() if d != 0]
    out.sort(key=lambda d: d[2] > 0)
    return out


class EngineError(RuntimeError):
    pass


def _vec_threshold() -> int:
    # single source of truth for the columnar batch threshold
    from pathway_tpu.internals import vector_compiler as vc

    return vc.VEC_THRESHOLD


def _vec_temporal_arrays(node, deltas, op):
    """The temporal operators' shared columnar pre-pass: materialize the
    epoch batch's time/threshold columns once and apply the affine offsets
    (``engine/dataflow.py`` Buffer/Freeze/Forget all lower to ``column +
    const`` time math — see ``Table._temporal_op``).  Returns ``(t, thr)``
    arrays or None on a counted bail; dtype-kind mixes between the columns
    or against the running watermark bail because numpy's promotion would
    compare inexactly where the row path's Python scalars are exact."""
    from pathway_tpu.internals import vector_compiler as vc

    t_idx, t_off, thr_idx, thr_off = node.vec_temporal
    cols = vc.materialize_delta_columns(deltas, {t_idx, thr_idx})
    if cols is None:
        vc.note_bail(op, "dirty-column")
        return None
    try:
        t = vc.affine_values(cols, t_idx, t_off)
        thr = vc.affine_values(cols, thr_idx, thr_off)
    except vc.VecBail:
        vc.note_bail(op, "value-guard")
        return None
    if t.dtype.kind != thr.dtype.kind:
        vc.note_bail(op, "dtype-mix")
        return None
    if t.dtype.kind == "f":
        import numpy as np

        # NaN diverges from the row oracle: t.max() would poison the
        # watermark where the sequential `t > wm` scan skips NaN, and a
        # NaN threshold wedges the forget expiry heap's ordering
        if np.isnan(t).any() or np.isnan(thr).any():
            vc.note_bail(op, "nan-time")
            return None
    wm = node._watermark
    if wm is not None and (
        (t.dtype.kind == "i" and type(wm) is not int)
        or (t.dtype.kind == "f" and type(wm) is not float)
    ):
        vc.note_bail(op, "watermark-dtype")
        return None
    return t, thr


class Node:
    """A dataflow operator. Subclasses implement ``step``."""

    name: str = "node"
    # Execution-path attribution (engine/profiler.py snapshots render each
    # operator as columnar / row / mixed): operators with a columnar fast
    # path bump vec_batches when a batch ran it and row_batches when a
    # batch fell to the row-wise evaluator.  Class-level zeros keep nodes
    # without fast paths attribute-cheap; the first bump shadows them.
    vec_batches: int = 0
    row_batches: int = 0
    # Append-only dataflow analysis (parity: column properties threaded
    # through lowering, python/pathway/internals/column_properties.py,
    # consumed by the engine's append_only_or_deterministic switches,
    # src/engine/dataflow.rs:1741): classes whose output stream is
    # append-only whenever every input stream is set
    # ``preserves_append_only``; ``infer_append_only`` fills the per-node
    # flags after lowering, and stateful operators pick cheaper
    # no-retraction accumulator variants off their input's flag.
    preserves_append_only = False

    def __init__(self, scope: "Scope", inputs: Sequence["Node"] = ()):
        self.append_only = False
        self.scope = scope
        self.inputs = list(inputs)
        self.downstream: list[tuple[Node, int]] = []
        self.pending: dict[int, list[Delta]] = defaultdict(list)
        self.keep_state = False
        self.state: dict[int, Row] = {}
        # key -> plain row (the common single-row multiplicity-1 case) or
        # Counter(row -> multiplicity); `state` holds the positive row
        self._state_rows: dict[int, Row | Counter] = {}
        self.id = scope._register(self)
        for port, inp in enumerate(self.inputs):
            inp.downstream.append((self, port))
        # monitoring counters (ProberStats analog, graph.rs:512)
        self.rows_in = 0
        self.rows_out = 0
        self.step_seconds = 0.0  # cumulative time in step(), probe-read
        # multi-worker exchange declaration (engine/comm.py WorkerContext):
        # port -> routing-key fn (None = route by row key), or gather-to-0
        # for globally-ordered operators.  The exchange point is exactly
        # where the reference reshards before stateful operators
        # (dataflow.rs:1414, shard.rs:15-20).
        self.exchange_routes: dict[int, Callable[[int, Row], int] | None] | None = None
        self.exchange_gather0 = False

    # -- wiring --
    def send(self, deltas: list[Delta], time: Time) -> None:
        if not deltas:
            return
        self.rows_out += len(deltas)
        for node, port in self.downstream:
            cur = node.pending.get(port)
            if not cur:
                # preserve the clean marker while the port holds one chunk;
                # concatenated chunks may collide keys, so they downgrade
                cls = CleanDeltas if isinstance(deltas, CleanDeltas) else list
                node.pending[port] = cls(deltas)
            elif isinstance(cur, CleanDeltas):
                plain = list(cur)
                plain.extend(deltas)
                node.pending[port] = plain
            else:
                cur.extend(deltas)

    def take_pending(self, port: int = 0) -> list[Delta]:
        deltas = self.pending.pop(port, [])
        self.rows_in += len(deltas)
        return deltas

    def _update_state(self, deltas: list[Delta]) -> None:
        """Maintain the per-key row multiset and the live-row view.

        Representation: ``_state_rows[key]`` is a plain row tuple while the
        key holds exactly one row at multiplicity 1 (the overwhelmingly
        common case — measured as the churn-benchmark hot spot when every
        key carried a Counter), and promotes to a ``Counter`` only for
        multi-row / non-unit multiplicities.
        """
        state_rows = self._state_rows
        state = self.state
        for key, row, diff in deltas:
            cur = state_rows.get(key)
            if cur is None:
                if diff == 1:
                    state_rows[key] = row
                    state[key] = row
                    continue
                cur = state_rows[key] = Counter()
            elif not isinstance(cur, Counter):
                if diff == -1 and cur == row:
                    del state_rows[key]
                    state.pop(key, None)
                    continue
                cur = state_rows[key] = Counter({cur: 1})
            cur[row] += diff
            if cur[row] == 0:
                del cur[row]
            if not cur:
                del state_rows[key]
                state.pop(key, None)
            else:
                for r, c in cur.items():
                    if c > 0:
                        state[key] = r
                        break
                else:
                    state.pop(key, None)

    def state_multiset(self) -> Counter:
        """(key, row) -> positive multiplicity of the maintained state."""
        out: Counter = Counter()
        for key, rows in self._state_rows.items():
            if not isinstance(rows, Counter):
                out[(key, rows)] = 1
                continue
            for r, c in rows.items():
                if c > 0:
                    out[(key, r)] = c
        return out

    def step(self, time: Time) -> None:
        """Process this epoch's pending input; emit output deltas."""
        deltas = self.take_pending()
        if self.keep_state:
            self._update_state(deltas)
        self.send(deltas, time)

    def flush(self, time: Time) -> None:
        """Epoch-boundary hook (after every node stepped)."""

    def on_finish(self) -> None:
        """All inputs exhausted; release any remaining buffered work."""

    def final_check(self) -> None:
        """After the finish-quiesce: report errors that only count if they
        survived to end-of-stream (e.g. strict ix dangling pointers)."""

    # -- operator snapshots (persistence/operator_snapshot.rs analog) --
    # subclasses list their arrangement attributes; dumps hold plain
    # picklable data (callables are re-bound by the rebuilt graph)
    _persist_attrs: tuple = ()

    def persist_dump(self):
        data: dict = {}
        if self.keep_state and self._state_rows:
            data["__state_rows"] = self._state_rows
        for a in self._persist_attrs:
            data[a] = getattr(self, a)
        if not data and not self.keep_state:
            return None
        return data

    def persist_load(self, data) -> None:
        for a, v in data.items():
            if a == "__state_rows":
                # snapshots may hold either form: plain row (multiplicity
                # 1) or a Counter/dict of multiplicities
                self._state_rows = {
                    k: Counter(c) if isinstance(c, dict) else c
                    for k, c in v.items()
                }
                self.state = {}
                for k, rows in self._state_rows.items():
                    if not isinstance(rows, Counter):
                        self.state[k] = rows
                        continue
                    for r, c in rows.items():
                        if c > 0:
                            self.state[k] = r
                            break
            else:
                setattr(self, a, v)

    def has_pending(self) -> bool:
        return any(self.pending.values())

    def require_state(self) -> "Node":
        self.keep_state = True
        return self

    def _infer_append_only(self) -> bool:
        return (
            self.preserves_append_only
            and bool(self.inputs)
            and all(i.append_only for i in self.inputs)
        )

    def __repr__(self):
        return f"<{self.__class__.__name__}#{self.id}>"


def infer_append_only(scope: "Scope") -> None:
    """Fill ``Node.append_only`` over a built graph.

    Creation order is topological (inputs exist before their consumers), so
    one forward pass suffices.  Runs after lowering, before any state is
    restored or stepped."""
    for node in scope.nodes:
        node.append_only = node._infer_append_only()


class InputNode(Node):
    """An input session: rows pushed by connectors / static data.

    Mirrors the InputSession+poller pattern (connectors/mod.rs:292, adaptors.rs).
    """

    name = "input"

    def __init__(self, scope: "Scope"):
        super().__init__(scope)
        self._staged: dict[Time, list[Delta]] = defaultdict(list)
        self._staged_wallclock: dict[Time, float] = {}
        # hot-bucket cache: streams insert runs of rows at one time, so
        # the common insert() is a single list append (no dict lookups,
        # no wallclock check).  Invalidate wherever staged lists are
        # popped or re-filed (merge_staged_through / emit_time).
        self._hot_time: Time | None = None
        self._hot_list: list[Delta] | None = None
        self.finished = False
        # ingest low-watermark of the epoch this node last emitted: the
        # earliest staged-row wall-clock folded into that epoch (set by
        # emit_time, read by the freshness tracker's per-operator
        # min-ingest-frontier pass — engine/freshness.py)
        self.epoch_ingest_wallclock: float | None = None
        # upsert sessions key rows and treat same-key insert as replace
        self.upsert = False
        # set by the io layer when the source schema declares append_only
        # (column_definition / schema properties); enforced at insert
        self.declared_append_only = False

    def _infer_append_only(self) -> bool:
        # upsert sessions synthesize retractions for overwritten keys, so a
        # declared-append-only upsert source still is not append-only
        return self.declared_append_only and not self.upsert

    def insert(self, key: int, row: Row, time: Time, diff: int = 1) -> None:
        if diff < 0 and self.append_only:
            raise EngineError(
                "retraction arrived at an append-only input: the schema "
                "declares append_only=True but the source produced a "
                "deletion"
            )
        if time == self._hot_time:
            self._hot_list.append((key, row, diff))
            return
        lst = self._staged[time]
        lst.append((key, row, diff))
        self._hot_time, self._hot_list = time, lst
        if time not in self._staged_wallclock:
            self._staged_wallclock[time] = _monotonic()

    def _invalidate_hot(self) -> None:
        """Drop the hot-bucket insert cache.  EVERY mutation of
        ``_staged`` outside ``insert()`` must call this (directly or via
        take_staged/put_staged/clear_staged) — a stale hot list keeps
        receiving appends into an orphaned object, silently losing rows."""
        self._hot_time = self._hot_list = None

    def take_staged(self, time: Time, default=None):
        """Pop a staged bucket (invalidates the hot-bucket cache)."""
        self._invalidate_hot()
        return self._staged.pop(time, default)

    def put_staged(self, time: Time, deltas: list) -> None:
        """Re-file a bucket (see ``take_staged``)."""
        self._invalidate_hot()
        self._staged[time] = deltas

    def clear_staged(self) -> None:
        """Discard every staged bucket (persistence resume skips static
        re-emission); keeps the hot cache consistent with the dicts."""
        self._invalidate_hot()
        self._staged.clear()
        self._staged_wallclock.clear()

    def pending_times(self) -> list[Time]:
        return sorted(self._staged.keys())

    def merge_staged_through(self, time: Time) -> None:
        """Fold rows staged at earlier times into epoch ``time`` (the runner
        picks one commit timestamp across all inputs), keeping the earliest
        ingest wallclock so latency probes measure from first arrival."""
        self._invalidate_hot()
        below = [st for st in self._staged if st <= time]
        if len(below) == 1:
            # single staged bucket: move the list object itself so a
            # CleanDeltas tag (stage_static's cleanliness proof) survives
            # and emit_time's consolidate becomes O(1)
            st = below[0]
            if st != time:
                self._staged[time] = self._staged.pop(st)
                w = self._staged_wallclock.pop(st, None)
                if w is not None:
                    self._staged_wallclock[time] = w
            return
        merged: list[Delta] = []
        wall: float | None = None
        for staged in sorted(below):
            merged.extend(self._staged.pop(staged))
            w = self._staged_wallclock.pop(staged, None)
            if w is not None:
                wall = w if wall is None else min(wall, w)
        if merged:
            self._staged[time] = merged
        if wall is not None:
            self._staged_wallclock[time] = wall

    def emit_time(self, time: Time) -> None:
        wall = self._staged_wallclock.pop(time, None)
        self.epoch_ingest_wallclock = wall
        if wall is not None:
            ew = self.scope.epoch_wallclock
            ew[time] = min(ew.get(time, wall), wall)
        deltas = self.take_staged(time, [])
        if self.upsert:
            # multiple updates of one key within an epoch must chain
            # (each retracts the PREVIOUS value, not the epoch-start one):
            # `seen` overlays committed state with this epoch's staged rows
            nat = _get_native_module()
            chain = getattr(nat, "upsert_chain", None) if nat else None
            if chain is not None and isinstance(self.state, dict):
                out = chain(deltas, self.state)
            else:
                out = []
                seen: dict[int, Row | None] = {}
                state_get = self.state.get
                _MISS = object()
                for key, row, diff in deltas:
                    prev = seen.get(key, _MISS)
                    if prev is _MISS:
                        prev = state_get(key)
                    if prev is not None:
                        out.append((key, prev, -1))
                    if diff > 0:
                        out.append((key, row, 1))
                        seen[key] = row
                    else:
                        seen[key] = None
            deltas = consolidate(out)
            self._update_state(deltas)
        else:
            deltas = consolidate(deltas)
            if self.keep_state:
                self._update_state(deltas)
        # input rows bypass take_pending, so count them here — monitoring
        # and the operator-snapshot dirty check both key off rows_in
        self.rows_in += len(deltas)
        self.send(deltas, time)

    def close(self) -> None:
        self.finished = True


class StaticNode(InputNode):
    """A table whose rows are known at build time (debug tables)."""

    name = "static"

    def __init__(
        self,
        scope: "Scope",
        rows: Iterable[tuple[int, Row, Time, int]] | None = None,
        *,
        prestaged: "list[Delta] | None" = None,
        prestaged_time: Time = 0,
    ):
        super().__init__(scope)
        now = _monotonic()
        if prestaged is not None:
            # the builder already produced epoch-shaped deltas (and tagged
            # CleanDeltas when provably clean) — stage the object as-is,
            # zero extra passes
            self._staged[prestaged_time] = prestaged
            self._staged_wallclock.setdefault(prestaged_time, now)
            self.finished = True
            self.declared_append_only = isinstance(
                prestaged, CleanDeltas
            ) or all(d >= 0 for (_, _, d) in prestaged)
            return
        # bulk-stage by time: per-row insert() was a measurable share of the
        # static-ingest epoch at 1M rows.  The native partitioner also
        # proves per-bucket cleanliness (unique keys, all diffs +1) so the
        # emit path's consolidate scan collapses to an O(1) tag check.
        stage = None
        nat = _get_native_module()
        if nat is not None:
            stage = getattr(nat, "stage_static", None)
        if stage is not None:
            rows_list = rows if isinstance(rows, list) else list(rows)
            staged = stage(rows_list, CleanDeltas)
            for time, deltas, clean in staged:
                self._staged[time] = deltas  # already CleanDeltas iff clean
                self._staged_wallclock.setdefault(time, now)
        else:
            by_time: dict[Time, list[Delta]] = defaultdict(list)
            for key, row, time, diff in rows:
                by_time[time].append((key, row, diff))
            for time, deltas in by_time.items():
                self._staged[time].extend(deltas)
                self._staged_wallclock.setdefault(time, now)
        self.finished = True
        # build-time rows are fully known: a static table with no deletion
        # diffs is factually append-only, no declaration needed
        self.declared_append_only = all(
            isinstance(ds, CleanDeltas) or all(d >= 0 for (_, _, d) in ds)
            for ds in self._staged.values()
        )


class ExprNode(Node):
    """Row-wise map: select/with_columns — evaluates compiled expressions.

    ``vec_select`` (set by the Lowerer when every output expression compiles
    to column ops) switches large batches to a numpy columnar evaluation —
    the §7.3 "columnar batches instead of row tuples" path.  The vector
    path bails back to the row interpreter on anything it cannot honor
    exactly (mixed/None columns, zero divisors, …).
    """

    name = "select"
    preserves_append_only = True

    def __init__(self, scope, inp: Node, fn: Callable[[int, Row], Row], deps: Sequence[Node] = ()):
        super().__init__(scope, [inp])
        self.fn = fn
        # (needed_col_indices, [fn per out col], [out dtype per out col])
        self.vec_select = None
        # join-select projection spec ((src, idx), ...) — set by the
        # Lowerer when every output is a plain left/right column or id
        # pick over JoinNode payload rows; one native C pass replaces the
        # per-row accessor closures (pure copies — no new Errors possible)
        self.vec_join_project = None
        for d in deps:
            d.require_state()

    def _try_columnar(self, deltas: list[Delta]) -> list[Delta] | None:
        from pathway_tpu.internals import vector_compiler as vc

        if not vc.ENABLED:
            return None
        needed, out_fns, out_dtypes = self.vec_select
        cols = vc.materialize_delta_columns(deltas, needed)
        if cols is None:
            vc.note_bail("select", "dirty-column")
            return None
        n = len(deltas)
        try:
            out_cols = []
            for f, d in zip(out_fns, out_dtypes):
                if isinstance(f, int):  # passthrough: copy from input row
                    out_cols.append(("P", f))
                    continue
                arr = f(cols, n)
                if isinstance(arr, list):  # Python-object column (tuples)
                    if len(arr) != n:
                        vc.note_bail("select", "length-mismatch")
                        return None
                    out_cols.append(("U", arr))
                    continue
                if not vc.result_kind_ok(arr, d):
                    vc.note_bail("select", "result-dtype")
                    return None
                out_cols.append(arr)
        except vc.VecBail:
            vc.note_bail("select", "value-guard")
            return None
        return vc.rebuild_delta_rows(deltas, out_cols, n)

    def step(self, time):
        deltas = self.take_pending()
        clean_in = isinstance(deltas, CleanDeltas)
        out = None
        if self.vec_join_project is not None and deltas:
            from pathway_tpu.internals import vector_compiler as vc

            nat = _get_native_module()
            if vc.ENABLED and nat is not None and hasattr(nat, "project_join_rows"):
                res = nat.project_join_rows(deltas, self.vec_join_project)
                if res is not None:  # None = malformed shape, row path
                    out, err_keys = res
                    for ek in err_keys or ():
                        # row-path parity: copied Error cells are logged
                        self.scope.error_log.append(
                            (
                                self,
                                ek,
                                "expression evaluated to Error (division by "
                                "zero, bad cast, or type error)",
                            )
                        )
                        _serving_note_row_error(
                            ek, "expression evaluated to Error"
                        )
        if out is None and self.vec_select is not None and len(deltas) >= _vec_threshold():
            out = self._try_columnar(deltas)
        if deltas and (
            self.vec_select is not None or self.vec_join_project is not None
        ):
            if out is None:
                self.row_batches += 1
            else:
                self.vec_batches += 1
        if out is None:
            out = []
            for key, row, diff in deltas:
                new_row = self.fn(key, row)
                if (
                    diff > 0
                    and any(isinstance(v, Error) for v in new_row)
                    and not any(isinstance(v, Error) for v in row)
                ):
                    # a NEW Error value (division by zero, bad cast, …):
                    # poison the cell and log it — the error-log tables
                    # (pw.global_error_log) read scope.error_log.  Logged
                    # directly (not report_row_error): cell poisoning is
                    # recoverable via fill_error/remove_errors, so it must
                    # not abort the run even with terminate_on_error=True
                    self.scope.error_log.append(
                        (
                            self,
                            key,
                            "expression evaluated to Error (division by "
                            "zero, bad cast, or type error)",
                        )
                    )
                    _serving_note_row_error(
                        key, "expression evaluated to Error"
                    )
                out.append((key, new_row, diff))
        # a 1:1 map preserves keys and diffs, hence cleanliness
        out = CleanDeltas(out) if clean_in else consolidate(out)
        if self.keep_state:
            self._update_state(out)
        self.send(out, time)


class FilterNode(Node):
    # the Table layer's filter() lowers to its own _PredFilter with the
    # columnar fast path; this plain node serves engine-internal filters
    name = "filter"
    preserves_append_only = True

    def __init__(self, scope, inp: Node, pred: Callable[[int, Row], bool]):
        super().__init__(scope, [inp])
        self.pred = pred

    def step(self, time):
        deltas = self.take_pending()
        out = CleanDeltas() if isinstance(deltas, CleanDeltas) else []
        for key, row, diff in deltas:
            res = self.pred(key, row)
            if isinstance(res, Error):
                self.scope.report_row_error(self, key, "filter predicate returned Error")
                continue
            if res:
                out.append((key, row, diff))  # subset of clean stays clean
        if self.keep_state:
            self._update_state(out)
        self.send(out, time)


class FlattenNode(Node):
    """flatten a column of sequences into multiple rows (dataflow.rs flatten_table)."""

    name = "flatten"
    preserves_append_only = True

    def __init__(
        self,
        scope,
        inp: Node,
        fn: Callable[[int, Row], Iterable[tuple[int, Row]]],
        *,
        key_fresh: bool = False,
    ):
        super().__init__(scope, [inp])
        self.fn = fn
        # set by callers whose fn derives pairwise-distinct new keys from
        # the origin key (e.g. hash(origin, position)); only then can clean
        # input imply clean output
        self.key_fresh = key_fresh
        # (col_idx, with_origin) when fn is the standard Table.flatten
        # shape — the whole per-item loop (incl. the hash-derived fresh
        # keys) then runs in _native.cpp
        self.vec_flatten: tuple[int, bool] | None = None

    def step(self, time):
        deltas = self.take_pending()
        out = None
        if self.vec_flatten is not None and deltas:
            from pathway_tpu.internals import vector_compiler as vc

            nat = _get_native_module()
            if vc.ENABLED and nat is not None and hasattr(nat, "flatten_deltas"):
                col_idx, with_origin = self.vec_flatten
                out = nat.flatten_deltas(deltas, col_idx, with_origin)
        if deltas and self.vec_flatten is not None:
            if out is None:
                self.row_batches += 1
            else:
                self.vec_batches += 1
        if out is None:
            out = []
            for key, row, diff in deltas:
                for new_key, new_row in self.fn(key, row):
                    out.append((new_key, new_row, diff))
        if self.key_fresh and isinstance(deltas, CleanDeltas):
            out = CleanDeltas(out)
        else:
            out = consolidate(out)
        if self.keep_state:
            self._update_state(out)
        self.send(out, time)


class ReindexNode(Node):
    """Change row keys (with_id_from / reindex); detects duplicate new keys."""

    name = "reindex"
    preserves_append_only = True

    def __init__(self, scope, inp: Node, key_fn: Callable[[int, Row], int]):
        super().__init__(scope, [inp])
        self.key_fn = key_fn
        self.require_state()
        # duplicate detection state lives with the owner of the NEW key
        self.exchange_routes = {0: lambda k, r: self.key_fn(k, r)}

    def step(self, time):
        out = []
        for key, row, diff in self.take_pending():
            out.append((self.key_fn(key, row), row, diff))
        out = consolidate(out)
        self._update_state(out)
        self.send(out, time)


class SaltRekeyNode(Node):
    """Deterministic injective rekey: new key = hash(Pointer(key), salt).

    Backs the vectorized sliding-window assignment (one branch per window
    offset, concatenated): distinct inputs at a fixed salt never collide,
    so no duplicate-detection state is needed and cleanliness carries.
    """

    name = "salt_rekey"
    preserves_append_only = True

    def __init__(self, scope, inp: Node, salt: int):
        super().__init__(scope, [inp])
        self.salt = salt
        self.exchange_routes = {
            0: lambda k, r: hash_values([Pointer(k), self.salt])
        }

    def step(self, time):
        deltas = self.take_pending()
        out = None
        nat = _get_native_module()
        if nat is not None and hasattr(nat, "rekey_deltas") and deltas:
            out = nat.rekey_deltas(deltas, self.salt)
        if deltas:
            if out is None:
                self.row_batches += 1
            else:
                self.vec_batches += 1
        if out is None:
            salt = self.salt
            out = [
                (hash_values([Pointer(k), salt]), row, d)
                for k, row, d in deltas
            ]
        # injective key map, diffs unchanged: clean input stays clean
        out = (
            CleanDeltas(out)
            if isinstance(deltas, CleanDeltas)
            else consolidate(out)
        )
        if self.keep_state:
            self._update_state(out)
        self.send(out, time)


class ConcatNode(Node):
    name = "concat"
    preserves_append_only = True

    def __init__(self, scope, inputs: Sequence[Node]):
        super().__init__(scope, inputs)

    def step(self, time):
        out = []
        for port in range(len(self.inputs)):
            out.extend(self.take_pending(port))
        out = consolidate(out)
        if self.keep_state:
            self._update_state(out)
        self.send(out, time)


class UpdateRowsNode(Node):
    """update_rows: rows of the right table override same-key rows of the left
    (dataflow.rs update_rows_table)."""

    name = "update_rows"
    _persist_attrs = ("_left", "_right")


    def __init__(self, scope, left: Node, right: Node):
        super().__init__(scope, [left, right])
        self._left: dict[int, Row] = {}
        self._right: dict[int, Row] = {}
        self.exchange_routes = {0: None, 1: None}  # co-shard both sides by key

    def step(self, time):
        out = []
        dl = consolidate(self.take_pending(0))
        dr = consolidate(self.take_pending(1))
        for key, row, diff in dl:
            overridden = key in self._right
            if diff > 0:
                self._left[key] = row
            else:
                self._left.pop(key, None)
            if not overridden:
                out.append((key, row, diff))
        for key, row, diff in dr:
            if diff > 0:
                prev_r = self._right.get(key)
                if prev_r is not None:
                    out.append((key, prev_r, -1))
                elif key in self._left:
                    out.append((key, self._left[key], -1))
                self._right[key] = row
                out.append((key, row, 1))
            else:
                self._right.pop(key, None)
                out.append((key, row, -1))
                if key in self._left:
                    out.append((key, self._left[key], 1))
        out = consolidate(out)
        if self.keep_state:
            self._update_state(out)
        self.send(out, time)


class UpdateCellsNode(Node):
    """update_cells: override a subset of columns for keys present in right."""

    name = "update_cells"
    _persist_attrs = ("_left", "_right")


    def __init__(self, scope, left: Node, right: Node, merge_fn: Callable[[Row, Row | None], Row]):
        super().__init__(scope, [left, right])
        self._left: dict[int, Row] = {}
        self._right: dict[int, Row] = {}
        self.merge_fn = merge_fn
        self.exchange_routes = {0: None, 1: None}  # co-shard both sides by key

    def _merged(self, key: int) -> Row | None:
        if key not in self._left:
            return None
        return self.merge_fn(self._left[key], self._right.get(key))

    def step(self, time):
        out = []
        touched: set[int] = set()
        before: dict[int, Row | None] = {}
        for port, store in ((0, self._left), (1, self._right)):
            for key, row, diff in consolidate(self.take_pending(port)):
                if key not in before:
                    before[key] = self._merged(key)
                touched.add(key)
                if diff > 0:
                    store[key] = row
                else:
                    store.pop(key, None)
        for key in touched:
            old = before[key]
            new = self._merged(key)
            if old == new:
                continue
            if old is not None:
                out.append((key, old, -1))
            if new is not None:
                out.append((key, new, 1))
        out = consolidate(out)
        if self.keep_state:
            self._update_state(out)
        self.send(out, time)


class IntersectNode(Node):
    """restrict left to keys present in all other inputs (intersect_tables)."""

    name = "intersect"
    _persist_attrs = ("_left", "_present")


    def __init__(self, scope, left: Node, others: Sequence[Node], difference: bool = False):
        super().__init__(scope, [left, *others])
        self._left: dict[int, Row] = {}
        self._present: list[Counter] = [Counter() for _ in others]
        self.difference = difference
        self.exchange_routes = {p: None for p in range(1 + len(others))}

    def _visible(self, key: int) -> bool:
        if self.difference:
            return not any(c[key] > 0 for c in self._present)
        return all(c[key] > 0 for c in self._present)

    def step(self, time):
        out = []
        before: dict[int, tuple[Row | None, bool]] = {}

        def snapshot(key):
            if key not in before:
                row = self._left.get(key)
                before[key] = (row, row is not None and self._visible(key))

        for key, row, diff in consolidate(self.take_pending(0)):
            snapshot(key)
            if diff > 0:
                self._left[key] = row
            else:
                self._left.pop(key, None)
        for i in range(len(self._present)):
            for key, row, diff in self.take_pending(i + 1):
                snapshot(key)
                self._present[i][key] += diff
        for key, (old_row, was_visible) in before.items():
            new_row = self._left.get(key)
            now_visible = new_row is not None and self._visible(key)
            if was_visible and old_row is not None:
                out.append((key, old_row, -1))
            if now_visible and new_row is not None:
                out.append((key, new_row, 1))
        out = consolidate(out)
        if self.keep_state:
            self._update_state(out)
        self.send(out, time)


class IxNode(Node):
    """ix/ix_ref: for each row of the keys table, look up a row of the data
    table by pointer (dataflow.rs ix_table). Emits joined rows; reacts to
    changes on both sides."""

    name = "ix"
    _persist_attrs = ("_keys", "_data", "_by_target", "_unresolved")


    def __init__(
        self,
        scope,
        keys_node: Node,
        data_node: Node,
        key_fn: Callable[[int, Row], Any],
        merge_fn: Callable[[Row, Row | None], Row],
        optional: bool = False,
        strict: bool = True,
    ):
        super().__init__(scope, [keys_node, data_node])
        self._keys: dict[int, tuple[Row, Any]] = {}
        self._data: dict[int, Row] = {}
        self._by_target: dict[Any, set[int]] = defaultdict(set)
        # key-rows whose target is currently absent: a dangling pointer is
        # only an error if it survives to end-of-stream — mid-epoch (and
        # mid-iteration-round) dangling is a normal transient, e.g. an
        # argmax pointer into a groupby output that re-emits next round
        self._unresolved: set[int] = set()
        self.key_fn = key_fn
        self.merge_fn = merge_fn
        self.optional = optional
        self.strict = strict
        # key-rows travel to the owner of the row they point at; data rows
        # stay with their own key's owner — lookups are then local
        self.exchange_routes = {0: self._route_target, 1: None}

    def _route_target(self, key: int, row: Row) -> int:
        target = self.key_fn(key, row)
        if isinstance(target, Pointer):
            return target.value
        if isinstance(target, int):
            return target
        return key  # optional/None targets resolve locally

    def _emit_for(self, key: int, out: list, sign: int):
        row, target = self._keys[key]
        if target is None and self.optional:
            out.append((key, self.merge_fn(row, None), sign))
            return
        data_row = self._data.get(target)
        if data_row is None:
            if sign > 0:
                self._unresolved.add(key)
            else:
                self._unresolved.discard(key)
            return
        if sign > 0:
            self._unresolved.discard(key)
        out.append((key, self.merge_fn(row, data_row), sign))

    def step(self, time):
        out = []
        dk = consolidate(self.take_pending(0))
        dd = consolidate(self.take_pending(1))
        changed_targets = set()
        for key, row, diff in dd:
            changed_targets.add(key)
        # retract outputs of key-rows pointing at changed data (old data value)
        for target in changed_targets:
            for key in list(self._by_target.get(target, ())):
                self._emit_for(key, out, -1)
        for key, row, diff in dd:
            if diff > 0:
                self._data[key] = row
            else:
                self._data.pop(key, None)
        for target in changed_targets:
            for key in list(self._by_target.get(target, ())):
                self._emit_for(key, out, 1)
        for key, row, diff in dk:
            if diff > 0:
                target = self.key_fn(key, row)
                tkey = target.value if isinstance(target, Pointer) else target
                self._keys[key] = (row, tkey)
                self._by_target[tkey].add(key)
                self._emit_for(key, out, 1)
            else:
                if key in self._keys:
                    self._emit_for(key, out, -1)
                    _, tkey = self._keys.pop(key)
                    self._by_target[tkey].discard(key)
        out = consolidate(out)
        if self.keep_state:
            self._update_state(out)
        self.send(out, time)

    def final_check(self):
        # runs after the finish-quiesce so rows released by other nodes'
        # on_finish (e.g. temporal buffers) have already resolved lookups
        if self.strict:
            for key in sorted(self._unresolved):
                _row, target = self._keys.get(key, (None, None))
                self.scope.report_row_error(
                    self, key, f"ix: missing key {target!r}"
                )


class JoinNode(Node):
    """Incremental equi-join, all modes (dataflow.rs join 2740).

    Output rows are ``(left_key, right_key, left_row, right_row)`` tuples
    (either row may be None in outer modes); the Table layer projects them.
    Delta-join rule per epoch: dL⋈R ∪ L'⋈dR where L' already includes dL.
    """

    name = "join"
    _persist_attrs = ("_left_idx", "_right_idx", "_left_matches", "_right_matches")


    def __init__(
        self,
        scope,
        left: Node,
        right: Node,
        left_key_fn: Callable[[int, Row], tuple],
        right_key_fn: Callable[[int, Row], tuple],
        out_key_fn: Callable[[int, int, tuple], int],
        left_outer: bool = False,
        right_outer: bool = False,
        exact_match: bool = False,
    ):
        super().__init__(scope, [left, right])
        self.left_key_fn = left_key_fn
        self.right_key_fn = right_key_fn
        self.out_key_fn = out_key_fn
        self.left_outer = left_outer
        self.right_outer = right_outer
        # both sides co-shard on the join key (dataflow.rs:2744 ShardPolicy)
        self.exchange_routes = {
            0: lambda k, r: self._route_jk(self.left_key_fn, k, r),
            1: lambda k, r: self._route_jk(self.right_key_fn, k, r),
        }
        # join-key → {row_key: (row, count)}
        self._left_idx: dict[tuple, dict[int, Row]] = defaultdict(dict)
        self._right_idx: dict[tuple, dict[int, Row]] = defaultdict(dict)
        # for outer modes: per row match count
        self._left_matches: Counter = Counter()
        self._right_matches: Counter = Counter()
        # native inner-join fast path: the Lowerer sets (l_idxs, r_idxs,
        # okey_mode) when the join keys are plain column picks and the mode
        # is inner; the whole delta-join step then runs in _native.cpp with
        # the SAME semantics (None/Error keys match nothing, 128-bit jk
        # hashing, identical output keys).  Chosen once per node — the two
        # index representations never mix within a run.
        self.native_spec: tuple | None = None
        self._native_idx = None
        self._nat = None
        # batched exchange routing (engine/comm.py): per-port
        # (key column indices, hash_none flag) when the join keys are
        # plain column picks — the per-row key-hash+route loop then runs
        # in one native pass with identical hash_values semantics
        self.exchange_route_cols: dict[int, tuple[tuple, bool]] | None = None

    def _infer_append_only(self) -> bool:
        # inner joins of append-only sides only ever add pairs; outer modes
        # retract their null-padding when a first match arrives
        return (
            not self.left_outer
            and not self.right_outer
            and all(i.append_only for i in self.inputs)
        )

    @staticmethod
    def _route_jk(key_fn, key: int, row: Row) -> int:
        jk = key_fn(key, row)
        if jk is None:
            return key  # unjoined (error) rows resolve locally
        return hash_values(jk)

    def _pair(self, lkey, rkey, lrow, rrow, jk, sign, out):
        okey = self.out_key_fn(lkey, rkey, jk)
        out.append((okey, (lkey, rkey, lrow, rrow), sign))

    def _null_left(self, rkey, rrow, jk, sign, out):
        okey = self.out_key_fn(None, rkey, jk)
        out.append((okey, (None, rkey, None, rrow), sign))

    def _null_right(self, lkey, lrow, jk, sign, out):
        okey = self.out_key_fn(lkey, None, jk)
        out.append((okey, (lkey, None, lrow, None), sign))

    def _native_cap(self):
        if self.native_spec is None:
            return None
        if self._native_idx is None:
            nat = _get_native_module()
            if nat is None or not hasattr(nat, "join_step"):
                from pathway_tpu.internals import vector_compiler as vc

                vc.note_bail("join", "native-unavailable")
                self.native_spec = None
                return None
            self._nat = nat
            self._native_idx = nat.join_new()
            # a snapshot restored into the row-path dicts before the first
            # step (path availability changed across runs): migrate it
            if self._left_idx or self._right_idx:
                l_idxs, r_idxs, _ = self.native_spec
                for side, idx_map, key_idxs in (
                    (0, self._left_idx, l_idxs),
                    (1, self._right_idx, r_idxs),
                ):
                    items = [
                        (k, row)
                        for bucket in idx_map.values()
                        for k, row in bucket.items()
                    ]
                    nat.join_load(self._native_idx, side, items, key_idxs)
                self._left_idx.clear()
                self._right_idx.clear()
        return self._native_idx

    def persist_dump(self):
        if self._native_idx is not None:
            data = super().persist_dump() or {}
            data["__native_join"] = self._nat.join_dump(self._native_idx)
            return data
        return super().persist_dump()

    def persist_load(self, data) -> None:
        data = dict(data)  # callers may reuse the dump; never mutate it
        nj = data.pop("__native_join", None)
        super().persist_load(data)
        if nj is None:
            return
        cap = self._native_cap()
        if cap is not None:
            l_idxs, r_idxs, _ = self.native_spec
            self._nat.join_load(cap, 0, nj[0], l_idxs)
            self._nat.join_load(cap, 1, nj[1], r_idxs)
        else:
            # native unavailable in this run: rebuild the row-path dicts
            for items, idx_map, key_fn in (
                (nj[0], self._left_idx, self.left_key_fn),
                (nj[1], self._right_idx, self.right_key_fn),
            ):
                for key, row in items:
                    jk = key_fn(key, row)
                    if jk is not None:
                        idx_map[jk][key] = row

    def step(self, time):
        cap = self._native_cap()
        if cap is not None:
            dl = consolidate(self.take_pending(0))
            dr = consolidate(self.take_pending(1))
            if dl or dr:
                self.vec_batches += 1
            l_idxs, r_idxs, mode = self.native_spec
            raw, replaced = self._nat.join_step(
                cap, dl, dr, l_idxs, r_idxs, mode,
                int(self.left_outer), int(self.right_outer),
            )
            if (
                mode == 0
                and not replaced
                and not self.left_outer
                and not self.right_outer
                and isinstance(dl, CleanDeltas)
                and isinstance(dr, CleanDeltas)
            ):
                # clean inputs + fresh row keys: every emitted pair
                # (lkey, rkey) is distinct, so the hash-pair okeys are
                # distinct and all diffs are +1 — provably clean output
                out = CleanDeltas(raw)
            else:
                out = consolidate(raw)
            if self.keep_state:
                self._update_state(out)
            self.send(out, time)
            return

        out: list[Delta] = []
        dl = consolidate(self.take_pending(0))
        dr = consolidate(self.take_pending(1))
        if dl or dr:
            self.row_batches += 1

        # apply left deltas against current right index
        for lkey, lrow, diff in dl:
            jk = self.left_key_fn(lkey, lrow)
            if jk is None:
                # a null join key matches nothing (SQL semantics), but the
                # row still survives outer modes with a null-padded partner
                if self.left_outer:
                    self._null_right(lkey, lrow, None, diff, out)
                continue
            matches = self._right_idx.get(jk, {})
            n_matches = len(matches)
            for rkey, rrow in matches.items():
                self._pair(lkey, rkey, lrow, rrow, jk, diff, out)
                if self.right_outer:
                    old = self._right_matches[rkey]
                    self._right_matches[rkey] = old + diff
                    if old == 0 and diff > 0:
                        self._null_left(rkey, rrow, jk, -1, out)
                    elif old + diff == 0:
                        self._null_left(rkey, rrow, jk, 1, out)
            if self.left_outer:
                # a dict-put REPLACE keeps the count: matches tracks live
                # right rows, which a same-key re-insert does not change
                if diff < 0 or lkey not in self._left_idx.get(jk, {}):
                    self._left_matches[lkey] += diff * n_matches
                if n_matches == 0:
                    self._null_right(lkey, lrow, jk, diff, out)
            if diff > 0:
                self._left_idx[jk][lkey] = lrow
            else:
                self._left_idx[jk].pop(lkey, None)
                if not self._left_idx[jk]:
                    del self._left_idx[jk]
                self._left_matches.pop(lkey, None)

        # apply right deltas against updated left index
        for rkey, rrow, diff in dr:
            jk = self.right_key_fn(rkey, rrow)
            if jk is None:
                if self.right_outer:
                    self._null_left(rkey, rrow, None, diff, out)
                continue
            matches = self._left_idx.get(jk, {})
            n_matches = len(matches)
            for lkey, lrow in matches.items():
                self._pair(lkey, rkey, lrow, rrow, jk, diff, out)
                if self.left_outer:
                    old = self._left_matches[lkey]
                    self._left_matches[lkey] = old + diff
                    if old == 0 and diff > 0:
                        self._null_right(lkey, lrow, jk, -1, out)
                    elif old + diff == 0:
                        self._null_right(lkey, lrow, jk, 1, out)
            if self.right_outer:
                if diff < 0 or rkey not in self._right_idx.get(jk, {}):
                    self._right_matches[rkey] += diff * n_matches
                if n_matches == 0:
                    self._null_left(rkey, rrow, jk, diff, out)
            if diff > 0:
                self._right_idx[jk][rkey] = rrow
            else:
                self._right_idx[jk].pop(rkey, None)
                if not self._right_idx[jk]:
                    del self._right_idx[jk]
                self._right_matches.pop(rkey, None)

        out = consolidate(out)
        if self.keep_state:
            self._update_state(out)
        self.send(out, time)


class GroupByNode(Node):
    """Incremental groupby + reduce (dataflow.rs group_by_table 3404)."""

    name = "groupby"

    def __init__(
        self,
        scope,
        inp: Node,
        group_key_fn: Callable[[int, Row], tuple],
        out_key_fn: Callable[[tuple], int],
        reducer_specs: Sequence[tuple[Any, Callable[[int, Row], tuple]]],
        # each spec: (Reducer, args_fn row→tuple of reducer args)
        result_fn: Callable[[tuple, tuple], Row] | None = None,
    ):
        super().__init__(scope, [inp])
        # contributions travel to the owner of the group's output key
        self.exchange_routes = {
            0: lambda k, r: self.out_key_fn(self.group_key_fn(k, r))
        }
        self.group_key_fn = group_key_fn
        self.out_key_fn = out_key_fn
        # batched exchange routing (engine/comm.py): (group-key column
        # indices, hash_none=True) when the group keys are plain column
        # picks — set by the Lowerer alongside vec_group
        self.exchange_route_cols: dict[int, tuple[tuple, bool]] | None = None
        self.reducer_specs = list(reducer_specs)
        self.result_fn = result_fn or (lambda gk, vals: tuple(vals))
        self._groups: dict[tuple, list] = {}
        self._group_counts: Counter = Counter()  # rows per group (for
        # reducer-less reduces: distinct group keys must still emit rows)
        self._last_out: dict[tuple, Row] = {}
        # columnar fast path (set by the Lowerer): (group_col_idx,
        # [(kind, value_col_idx), ...]) with kind in {"count" (idx None),
        # "sum" (also avg), "mm" (min/max)} — batch updates become
        # np.unique grouping + add_bulk per group (count/sum) or
        # per-(group, value) add_pairs into the multiset states (mm)
        self.vec_group = None

    def _make_states(self) -> list:
        # append-only input: non-invertible reducers (min/max/argmin/…)
        # swap their value multisets for O(1) running accumulators — the
        # engine-variant choice the reference drives off column properties
        # (dataflow.rs append_only_or_deterministic)
        if self.inputs[0].append_only:
            return [r.make_append_state() for (r, _) in self.reducer_specs]
        return [r.make_state() for (r, _) in self.reducer_specs]

    def _ensure_group(self, gk):
        states = self._groups.get(gk)
        if states is None:
            states = self._make_states()
            self._groups[gk] = states
        return states

    def _step_columnar(self, deltas: list[Delta], touched: set) -> bool:
        import numpy as np

        from pathway_tpu.internals import vector_compiler as vc

        if not vc.ENABLED:
            return False
        gidx, red_cols = self.vec_group
        multi = isinstance(gidx, tuple)  # multi-column group key
        gvals_list = None
        inv = None
        if multi:
            needed = {vidx for kind, vidx in red_cols if kind != "count"}
            cols = vc.materialize_delta_columns(deltas, needed) if needed else {}
            if needed and cols is None:
                vc.note_bail("groupby", "dirty-column")
                return False
            # group keys are Python tuples straight off the rows — the
            # native hash grouping keys on the same objects the row path's
            # dict does, so equality semantics (incl. NaN identity) match;
            # the per-row tuple build itself is one native pass too
            nat = _get_native_module()
            gather = getattr(nat, "gather_key_rows", None) if nat else None
            if gather is not None:
                keys = gather(deltas, tuple(gidx))
            else:
                keys = [tuple(row[i] for i in gidx) for (_k, row, _d) in deltas]
            gvals_list, inv = vc.group_indices(keys)
        else:
            needed = {gidx} | {vidx for kind, vidx in red_cols if kind != "count"}
            # shared materializer: uniform-Python-type + int64-range checks.
            # Raw form keeps str columns as Python lists so the group keys
            # can hash-group natively (np.unique on a 1M-row U-array pays a
            # full array build plus a sort — the wordcount hot spot).
            raw = vc.materialize_delta_columns_raw(deltas, needed)
            if raw is NotImplemented:
                cols = vc.materialize_delta_columns(deltas, needed)
                if cols is None:
                    vc.note_bail("groupby", "dirty-column")
                    return False
            elif raw is None:
                vc.note_bail("groupby", "dirty-column")
                return False
            else:
                cols = {}
                for i, (kind, payload) in raw.items():
                    if i == gidx and kind == "U":
                        gvals_list, inv = vc.group_indices(payload)
                        cols[i] = payload  # raw list; grouped, never math
                    else:
                        cols[i] = vc.wrap_native_col(kind, payload)
            garr = cols[gidx]
            if gvals_list is None:
                # NaN group keys: np.unique collapses all NaNs into one
                # group while the row path's dict keeps one group per NaN
                # object — bail
                if garr.dtype.kind == "f" and np.isnan(garr).any():
                    vc.note_bail("groupby", "nan-group-key")
                    return False
        val_arrs = [
            None if kind == "count" else cols[vidx] for kind, vidx in red_cols
        ]
        if any(isinstance(v, list) for v in val_arrs):
            # a str group column doubling as a reducer value column: rare —
            # wrap it for the mm path
            val_arrs = [
                np.asarray(v) if isinstance(v, list) else v for v in val_arrs
            ]
        for (kind, _), varr in zip(red_cols, val_arrs):
            # sums need numeric columns; min/max works on any materialized
            # dtype (incl. str) since it only groups and counts
            if kind == "sum" and varr.dtype.kind not in "bif":
                vc.note_bail("groupby", "sum-dtype")
                return False
            # NaN breaks the mm multiset grouping: np.unique collapses all
            # NaNs into one entry while the row path's Counter keeps one
            # entry per object — bail to the row path to keep parity
            if kind == "mm" and varr.dtype.kind == "f" and np.isnan(varr).any():
                vc.note_bail("groupby", "nan-minmax")
                return False
        diffs = vc.delta_diffs(deltas)
        max_diff = vc._abs_bound(diffs)
        for (kind, _), varr in zip(red_cols, val_arrs):
            # per-batch int sums must stay within i64 (state accumulates in
            # Python bignums, so only the numpy partial sums can wrap)
            if (
                kind == "sum"
                and varr.dtype.kind == "i"
                and vc._abs_bound(varr) * max_diff * max(1, len(deltas)) > vc._I64_MAX
            ):
                vc.note_bail("groupby", "sum-overflow")
                return False
        if gvals_list is None:
            uniq, inv = np.unique(garr, return_inverse=True)
            gvals_list = uniq.tolist()
        n_groups = len(gvals_list)
        if n_groups == 0:
            return True
        counts = np.zeros(n_groups, np.int64)
        np.add.at(counts, inv, diffs)
        contribs = []
        for (kind, _), varr in zip(red_cols, val_arrs):
            if kind == "count":
                contribs.append(None)
            elif kind == "mm":
                # per-(group, value) summed diffs for the multiset states
                vu, vinv = np.unique(varr, return_inverse=True)
                combo = inv.astype(np.int64) * len(vu) + vinv
                cu, cinv = np.unique(combo, return_inverse=True)
                pair_counts = np.zeros(len(cu), np.int64)
                np.add.at(pair_counts, cinv, diffs)
                pair_groups = (cu // len(vu)).tolist()
                pair_vals = vu[cu % len(vu)].tolist()
                by_group: dict[int, tuple[list, list]] = {}
                for g, v, c in zip(pair_groups, pair_vals, pair_counts.tolist()):
                    if c:
                        vs, cs = by_group.setdefault(g, ([], []))
                        vs.append(v)
                        cs.append(c)
                contribs.append(("mm", by_group))
            elif varr.dtype.kind == "f":
                contribs.append(np.bincount(inv, weights=varr * diffs, minlength=n_groups))
            else:
                acc = np.zeros(n_groups, np.int64)
                np.add.at(acc, inv, varr.astype(np.int64) * diffs)
                contribs.append(acc)
        gvals = gvals_list
        counts_l = counts.tolist()
        contribs_l = [
            c.tolist() if isinstance(c, np.ndarray) else c for c in contribs
        ]
        for ui, gval in enumerate(gvals):
            gk = gval if multi else (gval,)
            states = self._ensure_group(gk)
            for state, contrib in zip(states, contribs_l):
                if contrib is None:
                    state.add_bulk(counts_l[ui])
                elif isinstance(contrib, tuple):  # ("mm", by_group)
                    pairs = contrib[1].get(ui)
                    if pairs is not None:
                        state.add_pairs(pairs[0], pairs[1])
                else:
                    state.add_bulk(contrib[ui], counts_l[ui])
            self._group_counts[gk] += counts_l[ui]
            touched.add(gk)
        return True

    def step(self, time):
        out = []
        touched: set[tuple] = set()
        deltas = consolidate(self.take_pending())
        handled = False
        if self.vec_group is not None and len(deltas) >= _vec_threshold():
            handled = self._step_columnar(deltas, touched)
        if deltas and self.vec_group is not None:
            if handled:
                self.vec_batches += 1
            else:
                self.row_batches += 1
        if not handled:
            for key, row, diff in deltas:
                gk = self.group_key_fn(key, row)
                states = self._ensure_group(gk)
                for state, (_, args_fn) in zip(states, self.reducer_specs):
                    state.add(args_fn(key, row), diff, time, key)
                self._group_counts[gk] += diff
                touched.add(gk)
        for gk in touched:
            states = self._groups[gk]
            okey = self.out_key_fn(gk)
            old = self._last_out.pop(gk, None)
            if old is not None:
                out.append((okey, old, -1))
            if self._group_counts[gk] > 0:
                values = tuple(s.extract() for s in states)
                new_row = self.result_fn(gk, values)
                out.append((okey, new_row, 1))
                self._last_out[gk] = new_row
            else:
                del self._groups[gk]
                del self._group_counts[gk]
        out = consolidate(out)
        if self.keep_state:
            self._update_state(out)
        self.send(out, time)

    def persist_dump(self):
        data = super().persist_dump() or {}
        data["__groups"] = {
            gk: [st.dump() for st in states] for gk, states in self._groups.items()
        }
        data["__group_counts"] = self._group_counts
        data["__last_out"] = self._last_out
        return data

    def persist_load(self, data):
        groups = data.pop("__groups")
        self._group_counts = Counter(data.pop("__group_counts"))
        self._last_out = dict(data.pop("__last_out"))
        super().persist_load(data)
        self._groups = {}
        for gk, dumps in groups.items():
            states = self._make_states()
            for st, d in zip(states, dumps):
                st.load(d)
            self._groups[gk] = states


class DeduplicateNode(Node):
    """deduplicate with a Python acceptor (dataflow.rs deduplicate 3514)."""

    name = "deduplicate"
    _persist_attrs = ("_current",)


    def __init__(
        self,
        scope,
        inp: Node,
        instance_fn: Callable[[int, Row], Any],
        value_fn: Callable[[int, Row], Any],
        acceptor: Callable[[Any, Any], bool],
        out_key_fn: Callable[[Any], int],
    ):
        super().__init__(scope, [inp])
        self.instance_fn = instance_fn
        self.value_fn = value_fn
        self.acceptor = acceptor
        self.out_key_fn = out_key_fn
        self._current: dict[Any, tuple[Any, Row]] = {}
        # the per-instance "current winner" state lives with the owner of
        # the instance's output key
        self.exchange_routes = {
            0: lambda k, r: self.out_key_fn(self.instance_fn(k, r))
        }

    def step(self, time):
        out = []
        for key, row, diff in consolidate(self.take_pending()):
            if diff <= 0:
                continue  # dedup consumes insertions only (append-only semantics)
            inst = self.instance_fn(key, row)
            value = self.value_fn(key, row)
            prev = self._current.get(inst)
            if prev is None:
                accept = self.acceptor(value, None)
            else:
                accept = self.acceptor(value, prev[0])
            if isinstance(accept, Error):
                self.scope.report_row_error(self, key, "deduplicate acceptor returned Error")
                continue
            if accept:
                okey = self.out_key_fn(inst)
                if prev is not None:
                    out.append((okey, prev[1], -1))
                self._current[inst] = (value, row)
                out.append((okey, row, 1))
        out = consolidate(out)
        if self.keep_state:
            self._update_state(out)
        self.send(out, time)


class BufferNode(Node):
    """Temporal behavior buffer/delay (time_column.rs analog).

    Holds rows until ``threshold_fn(row) <= current watermark column max seen``;
    used by windowby behaviors. The watermark here is the maximum value of the
    time column observed so far (event-time semantics).
    """

    name = "buffer"
    _persist_attrs = ("_held", "_watermark")


    def __init__(self, scope, inp: Node, time_fn, threshold_fn):
        super().__init__(scope, [inp])
        self.time_fn = time_fn
        self.threshold_fn = threshold_fn
        self._held: list[Delta] = []
        self._watermark = None
        self.exchange_routes = {0: None}  # buffer state lives with key owner
        # columnar fast path (set by the Lowerer when time/threshold lower
        # to column + const): (t_idx, t_off, thr_idx, thr_off).  While
        # every ingest batch materializes columnar, _held_thr caches the
        # held rows' thresholds as one array and the release scan becomes
        # a single vector compare + native split; any bail reverts the
        # node to the row path (the oracle) until the buffer drains.
        self.vec_temporal: tuple | None = None
        self._held_thr = None  # np.ndarray | None (None = row mode)

    def _ingest_columnar(self, incoming) -> bool:
        import numpy as np

        from pathway_tpu.internals import vector_compiler as vc

        if self.vec_temporal is None or not vc.ENABLED:
            return False
        if self._held and self._held_thr is None:
            return False  # uncached held rows: stay row-wise until drained
        if not incoming:
            return True
        arrays = _vec_temporal_arrays(self, incoming, "buffer")
        if arrays is None:
            return False
        t, thr = arrays
        held_thr = self._held_thr
        if (
            held_thr is not None
            and len(held_thr)
            and held_thr.dtype.kind != thr.dtype.kind
        ):
            vc.note_bail("buffer", "dtype-mix")
            return False
        tmax = t.max().item()
        if self._watermark is None or tmax > self._watermark:
            self._watermark = tmax
        self._held_thr = (
            thr
            if held_thr is None or not len(held_thr)
            else np.concatenate([held_thr, thr])
        )
        return True

    def step(self, time):
        from pathway_tpu.internals import vector_compiler as vc

        incoming = self.take_pending()
        vec = self._ingest_columnar(incoming)
        if not vec:
            self._held_thr = None
            for key, row, diff in incoming:
                t = self.time_fn(key, row)
                if self._watermark is None or t > self._watermark:
                    self._watermark = t
        self._held.extend(incoming)
        wm = self._watermark
        if vec and self._held_thr is not None:
            if incoming or self._held:
                self.vec_batches += 1
            held_thr = self._held_thr
            release: list[Delta] = []
            if len(held_thr) and wm is not None:
                mask = held_thr <= wm
                if mask.any():
                    release, self._held = vc.split_deltas(self._held, mask)
                    self._held_thr = held_thr[~mask]
        else:
            if incoming or self._held:
                self.row_batches += 1
            release, keep = [], []
            for key, row, diff in self._held:
                thr = self.threshold_fn(key, row)
                if wm is not None and thr <= wm:
                    release.append((key, row, diff))
                else:
                    keep.append((key, row, diff))
            self._held = keep
        release = consolidate(release)
        if self.keep_state:
            self._update_state(release)
        self.send(release, time)

    def on_finish(self):
        release = consolidate(self._held)
        self._held = []
        self._held_thr = None  # empty buffer: columnar mode may resume
        if self.keep_state:
            self._update_state(release)
        self.send(release, self.scope.current_time)


class ForgetNode(Node):
    """Forget (free state for) rows older than the watermark minus a horizon;
    emits retractions downstream (time_column.rs forget)."""

    name = "forget"
    _persist_attrs = ("_alive", "_watermark")


    def __init__(self, scope, inp: Node, time_fn, threshold_fn, mark_forgetting_records: bool = False):
        super().__init__(scope, [inp])
        self.time_fn = time_fn
        self.threshold_fn = threshold_fn
        self._alive: dict[int, Row] = {}
        self._watermark = None
        self.exchange_routes = {0: None}  # alive-set lives with key owner
        # columnar fast path (see BufferNode): batches materialize their
        # time/threshold columns once, and expiry runs off a threshold
        # min-heap (O(expired log n) per epoch) instead of re-evaluating
        # threshold_fn over the whole alive set every epoch.  A bail
        # reverts the node to the legacy full-sweep (the oracle).
        self.vec_temporal: tuple | None = None
        self._expiry: list = []  # min-heap of (thr, seq, key, row)
        self._alive_thr: dict[int, Any] = {}
        self._heap_seq = 0
        self._sweep_legacy = False

    def persist_load(self, data) -> None:
        super().persist_load(data)
        # a restored alive-set has no heap entries; the legacy sweep is
        # the semantics reference and needs none
        self._sweep_legacy = True

    def _ingest_columnar(self, deltas, out) -> bool:
        import heapq

        from pathway_tpu.internals import vector_compiler as vc

        if self.vec_temporal is None or not vc.ENABLED or self._sweep_legacy:
            return False
        if not deltas:
            return True
        arrays = _vec_temporal_arrays(self, deltas, "forget")
        if arrays is None:
            return False
        t, thr = arrays
        tmax = t.max().item()
        if self._watermark is None or tmax > self._watermark:
            self._watermark = tmax
        out.extend(deltas)
        alive = self._alive
        alive_thr = self._alive_thr
        expiry = self._expiry
        seq = self._heap_seq
        for (key, row, diff), thr_v in zip(deltas, thr.tolist()):
            if diff > 0:
                alive[key] = row
                alive_thr[key] = thr_v
                seq += 1
                heapq.heappush(expiry, (thr_v, seq, key, row))
            else:
                alive.pop(key, None)
                alive_thr.pop(key, None)
        self._heap_seq = seq
        return True

    def step(self, time):
        import heapq

        out = []
        deltas = consolidate(self.take_pending())
        vec = self._ingest_columnar(deltas, out)
        if not vec:
            if not self._sweep_legacy:
                # heap entries no longer cover the alive set; the legacy
                # sweep takes over until the alive set drains
                self._sweep_legacy = True
                self._expiry.clear()
                self._alive_thr.clear()
            for key, row, diff in deltas:
                t = self.time_fn(key, row)
                if self._watermark is None or t > self._watermark:
                    self._watermark = t
                out.append((key, row, diff))
                if diff > 0:
                    self._alive[key] = row
                else:
                    self._alive.pop(key, None)
        if deltas:
            if vec:
                self.vec_batches += 1
            else:
                self.row_batches += 1
        wm = self._watermark
        if wm is not None:
            if self._sweep_legacy:
                for key in list(self._alive):
                    row = self._alive[key]
                    if self.threshold_fn(key, row) <= wm:
                        out.append((key, row, -1))
                        del self._alive[key]
                if not self._alive:
                    self._sweep_legacy = False  # drained: fast path resumes
            else:
                expiry = self._expiry
                alive = self._alive
                alive_thr = self._alive_thr
                while expiry and expiry[0][0] <= wm:
                    thr_v, _seq, key, row = heapq.heappop(expiry)
                    if alive.get(key) != row or alive_thr.get(key) != thr_v:
                        continue  # superseded entry (rekeyed or retracted)
                    out.append((key, row, -1))
                    del alive[key]
                    del alive_thr[key]
        out = consolidate(out)
        if self.keep_state:
            self._update_state(out)
        self.send(out, time)


class FreezeNode(Node):
    """Ignore updates to rows older than threshold (exactly-once behaviors)."""

    name = "freeze"
    _persist_attrs = ("_watermark",)


    def __init__(self, scope, inp: Node, time_fn, threshold_fn):
        super().__init__(scope, [inp])
        self.time_fn = time_fn
        self.threshold_fn = threshold_fn
        self._watermark = None
        # columnar fast path (see BufferNode): the admit/advance scan has
        # a sequential data dependence (later rows see earlier KEPT rows'
        # watermark), so it runs as one native freeze_scan pass over the
        # materialized time/threshold columns rather than a numpy op
        self.vec_temporal: tuple | None = None

    def _step_columnar(self, deltas):
        from pathway_tpu.internals import vector_compiler as vc

        # stateless per batch (unlike the buffer's held-threshold cache),
        # so the standard small-batch gate applies: below the threshold
        # the row loop beats materialize + array ops
        if (
            self.vec_temporal is None
            or not vc.ENABLED
            or len(deltas) < _vec_threshold()
        ):
            return None
        arrays = _vec_temporal_arrays(self, deltas, "freeze")
        if arrays is None:
            return None
        t, thr = arrays
        import numpy as np

        mask, new_wm = vc.freeze_scan(t, thr, self._watermark)
        self._watermark = new_wm
        n_cols = len(deltas[0][1])
        return vc.filter_deltas(
            deltas, np.frombuffer(bytes(mask), np.uint8), n_cols
        )

    def step(self, time):
        deltas = consolidate(self.take_pending())
        out = self._step_columnar(deltas)
        if deltas:
            if out is None:
                self.row_batches += 1
            else:
                self.vec_batches += 1
        if out is None:
            out = []
            for key, row, diff in deltas:
                t = self.time_fn(key, row)
                thr = self.threshold_fn(key, row)
                if self._watermark is not None and thr <= self._watermark:
                    continue  # frozen: late data dropped
                if self._watermark is None or t > self._watermark:
                    self._watermark = t
                out.append((key, row, diff))
        out = consolidate(out)
        if self.keep_state:
            self._update_state(out)
        self.send(out, time)


class SortNode(Node):
    """Maintains prev/next pointers for sorted tables (prev_next.rs analog).

    Output rows: (key, instance, prev_key|None, next_key|None).
    Uses a per-instance sorted list: the bidirectional-cursor trick in the
    reference's DD fork exists to walk neighbours cheaply; a host-side sorted
    structure gives the same O(log n) updates here.
    """

    name = "sort"
    _persist_attrs = ("_by_instance", "_rows")


    def __init__(self, scope, inp: Node, key_fn, instance_fn):
        super().__init__(scope, [inp])
        self.key_fn = key_fn
        self.instance_fn = instance_fn
        self._by_instance: dict[Any, list] = defaultdict(list)  # sorted [(sort_key, key)]
        self._rows: dict[int, tuple[Any, Any]] = {}
        # global per-instance ordering: all rows on one worker (the analog
        # of the reference's arranged total order walked by bidirectional
        # cursors; per-shard ordering would give wrong neighbours)
        self.exchange_gather0 = True

    def _neighbors(self, lst, i):
        prev_k = lst[i - 1][1] if i > 0 else None
        next_k = lst[i + 1][1] if i + 1 < len(lst) else None
        return prev_k, next_k

    def step(self, time):
        import bisect

        out = []
        touched_instances = set()
        old_lists: dict[Any, list] = {}
        for key, row, diff in consolidate(self.take_pending()):
            sk = self.key_fn(key, row)
            inst = self.instance_fn(key, row)
            lst = self._by_instance[inst]
            if inst not in old_lists:
                old_lists[inst] = list(lst)
            touched_instances.add(inst)
            if diff > 0:
                bisect.insort(lst, ((_SortWrap(sk)), key))
                self._rows[key] = (sk, inst)
            else:
                try:
                    lst.remove((_SortWrap(sk), key))
                except ValueError:
                    pass
                self._rows.pop(key, None)
        for inst in touched_instances:
            old = old_lists[inst]
            new = self._by_instance[inst]
            old_out = {
                k: self._neighbors(old, i) for i, (_, k) in enumerate(old)
            }
            new_out = {
                k: self._neighbors(new, i) for i, (_, k) in enumerate(new)
            }
            for k, nb in old_out.items():
                if new_out.get(k) != nb:
                    out.append((k, (_ptr(nb[0]), _ptr(nb[1])), -1))
            for k, nb in new_out.items():
                if old_out.get(k) != nb:
                    out.append((k, (_ptr(nb[0]), _ptr(nb[1])), 1))
            if not new:
                del self._by_instance[inst]
        out = consolidate(out)
        if self.keep_state:
            self._update_state(out)
        self.send(out, time)


class _SortWrap:
    """Total order over mixed sort keys."""

    __slots__ = ("v",)

    def __init__(self, v):
        self.v = v

    def _k(self):
        v = self.v
        if isinstance(v, bool):
            return (0, int(v))
        if isinstance(v, (int, float)):
            return (1, v)
        if isinstance(v, str):
            return (2, v)
        if isinstance(v, tuple):
            return (3, tuple(_SortWrap(x)._k() for x in v))
        if isinstance(v, Pointer):
            return (4, v.value)
        return (5, repr(v))

    def __lt__(self, other):
        return self._k() < other._k()

    def __eq__(self, other):
        return isinstance(other, _SortWrap) and self.v == other.v

    def __hash__(self):
        return hash(self._k())


def _ptr(k):
    return Pointer(k) if isinstance(k, int) else k


class GradualBroadcastNode(Node):
    """gradual_broadcast (gradual_broadcast.rs): broadcast a slowly-changing
    scalar (lower/value/upper thresholds) onto every row of the input; updates
    to rows only when the value leaves [lower, upper]."""

    name = "gradual_broadcast"
    _persist_attrs = ("_current_value", "_lower", "_upper", "_rows")


    def __init__(self, scope, inp: Node, threshold_node: Node, lvu_fn):
        super().__init__(scope, [inp, threshold_node])
        self.lvu_fn = lvu_fn
        self._current_value = None
        self._lower = None
        self._upper = None
        self._rows: dict[int, Row] = {}
        # one global slowly-changing scalar: single-owner state
        self.exchange_gather0 = True

    def step(self, time):
        out = []
        new_bounds = None
        for key, row, diff in consolidate(self.take_pending(1)):
            if diff > 0:
                new_bounds = self.lvu_fn(key, row)
        changed = False
        if new_bounds is not None:
            lower, value, upper = new_bounds
            if (
                self._current_value is None
                or value < (self._lower if self._lower is not None else value)
                or value > (self._upper if self._upper is not None else value)
            ):
                self._current_value = value
                self._lower, self._upper = lower, upper
                changed = True
        if changed:
            # retract+re-emit all rows with new broadcast value
            for key, row in list(self._rows.items()):
                out.append((key, row, -1))
                new_row = row[:-1] + (self._current_value,)
                self._rows[key] = new_row
                out.append((key, new_row, 1))
        for key, row, diff in consolidate(self.take_pending(0)):
            new_row = row + (self._current_value,)
            if diff > 0:
                self._rows[key] = new_row
                out.append((key, new_row, 1))
            else:
                stored = self._rows.pop(key, new_row)
                out.append((key, stored, -1))
        out = consolidate(out)
        if self.keep_state:
            self._update_state(out)
        self.send(out, time)


class ExternalIndexNode(Node):
    """as-of-now external index (dataflow/operators/external_index.rs).

    Port 0: index data stream (key, (vector/doc, filter_data)); port 1: query
    stream.  Answers each query against the *current* index contents and
    keeps the answer updated: on index change, affected queries are re-run
    and old answers retracted — the retraction bookkeeping the reference
    implements in external_index.rs:1-163.
    """

    name = "external_index"

    def __init__(self, scope, data_node: Node, query_node: Node, index, res_fn):
        super().__init__(scope, [data_node, query_node])
        self.index = index  # duck-typed: add(key,row), remove(key), search(qrow) -> result value
        self.res_fn = res_fn  # (query_key, query_row, result) -> out Row
        self._queries: dict[int, Row] = {}
        self._answers: dict[int, Row] = {}
        # raw indexed rows: operator snapshots rebuild the (arbitrary,
        # non-picklable) index structure by re-adding these on restore
        self._data_rows: dict[int, Row] = {}
        # the index structure is one logical object: host bookkeeping on
        # worker 0 (its device path still shards the corpus over the mesh —
        # ops/topk.py DeviceIndexCache(mesh))
        self.exchange_gather0 = True

    def _search_many(self, qrows: list) -> list:
        """One batched index scan for the epoch's query rows: a
        ``search_many``-capable index (``stdlib/indexing``) answers every
        row in one bucketed DeviceExecutor dispatch; others fall back to
        per-row search."""
        many = getattr(self.index, "search_many", None)
        if many is not None:
            return many(qrows)
        return [self.index.search(qrow) for qrow in qrows]

    def step(self, time):
        out = []
        dd = consolidate(self.take_pending(0))
        dq = consolidate(self.take_pending(1))
        index_changed = bool(dd)
        for key, row, diff in dd:
            if diff > 0:
                self.index.add(key, row)
                self._data_rows[key] = row
            else:
                self.index.remove(key)
                self._data_rows.pop(key, None)
        # new/removed queries — new ones answered in one epoch batch
        new_queries: list[tuple[int, Row]] = []
        for qkey, qrow, diff in dq:
            if diff > 0:
                self._queries[qkey] = qrow
                new_queries.append((qkey, qrow))
            else:
                self._queries.pop(qkey, None)
                old = self._answers.pop(qkey, None)
                if old is not None:
                    out.append((qkey, old, -1))
        if new_queries:
            results = self._search_many([qrow for _, qrow in new_queries])
            for (qkey, qrow), result in zip(new_queries, results):
                ans = self.res_fn(qkey, qrow, result)
                self._answers[qkey] = ans
                out.append((qkey, ans, 1))
        if index_changed and self._queries:
            fresh = {qkey for qkey, _ in new_queries}
            # new queries were just answered against the post-add index;
            # only pre-existing ones can have a changed answer
            rerun = [
                (qkey, qrow)
                for qkey, qrow in self._queries.items()
                if qkey not in fresh
            ]
            results = self._search_many([qrow for _, qrow in rerun])
            for (qkey, qrow), result in zip(rerun, results):
                ans = self.res_fn(qkey, qrow, result)
                old = self._answers.get(qkey)
                if old != ans:
                    if old is not None:
                        out.append((qkey, old, -1))
                    out.append((qkey, ans, 1))
                    self._answers[qkey] = ans
        out = consolidate(out)
        if self.keep_state:
            self._update_state(out)
        self.send(out, time)

    _persist_attrs = ("_queries", "_answers", "_data_rows")

    def persist_load(self, data):
        super().persist_load(data)
        for key, row in self._data_rows.items():
            self.index.add(key, row)


async def _run_udf_traced(fn, k, r):
    """Run one async-UDF coroutine under the row's request trace, if any.

    The serving handler binds row key → RequestTrace before committing the
    request row (``tracing.bind_key``); this is the epoch-thread hop of the
    trace — ``asyncio.gather`` wraps each coroutine in a Task with a copied
    context, so the scope set here is task-local and concurrent rows never
    bleed traces into each other.
    """
    from pathway_tpu.engine import tracing

    trace = tracing.trace_for_key(k)
    if trace is None:
        return await fn(k, r)
    with tracing.trace_scope(trace):
        return await fn(k, r)


class AsyncValuesNode(Node):
    """Computes extra columns with async functions: all rows of an epoch are
    awaited concurrently under one event loop, with an epoch barrier —
    the semantics of async_apply_table (dataflow.rs:1899-1937,
    executors.py:161-164).  Emits ``row + (v1, v2, ...)``; results are cached
    per (key, input row) so retractions retract the original value even for
    non-deterministic functions.
    """

    name = "async_values"
    _persist_attrs = ("_cache",)


    def __init__(self, scope, inp: Node, coro_fns: Sequence[Callable[[int, Row], Any]]):
        super().__init__(scope, [inp])
        self.coro_fns = list(coro_fns)
        self._cache: dict[tuple[int, Row], tuple] = {}

    def step(self, time):
        import asyncio

        deltas = consolidate(self.take_pending())
        inserts = [(k, r, d) for (k, r, d) in deltas if d > 0]
        others = [(k, r, d) for (k, r, d) in deltas if d <= 0]
        to_run = [(k, r) for (k, r, _) in inserts if (k, r) not in self._cache]

        if to_run:

            async def run_all():
                coros = [
                    _run_udf_traced(fn, k, r)
                    for (k, r) in to_run
                    for fn in self.coro_fns
                ]
                return await asyncio.gather(*coros, return_exceptions=True)

            flat = asyncio.run(run_all())
            n = len(self.coro_fns)
            for i, (k, r) in enumerate(to_run):
                values = []
                for res in flat[i * n : (i + 1) * n]:
                    if isinstance(res, Exception):
                        self.scope.report_row_error(
                            self, k, f"async UDF failed: {res}"
                        )
                        values.append(ERROR)
                    else:
                        values.append(as_hashable(res))
                self._cache[(k, r)] = tuple(values)
        out = []
        for k, r, d in inserts:
            out.append((k, r + self._cache[(k, r)], d))
        for k, r, d in others:
            cached = self._cache.pop((k, r), None)
            if cached is not None:
                out.append((k, r + cached, d))
        out = consolidate(out)
        if self.keep_state:
            self._update_state(out)
        self.send(out, time)


class OutputNode(Node):
    """Terminal: delivers consolidated epoch deltas to a writer/callback
    (output_table dataflow.rs:3979 / subscribe_table :4080)."""

    name = "output"

    def __init__(
        self,
        scope,
        inp: Node,
        on_data: Callable[[int, Row, Time, int], None] | None = None,
        on_time_end: Callable[[Time], None] | None = None,
        on_end: Callable[[], None] | None = None,
        on_frontier: Callable[[Time], None] | None = None,
    ):
        super().__init__(scope, [inp])
        self.on_data = on_data
        self.on_time_end = on_time_end
        self.on_end = on_end
        self.on_frontier = on_frontier
        self._saw_data_this_epoch = False
        # sink label from the registration (runner.run sets it): the
        # per-output identity freshness metrics are keyed by
        self.sink_name: str | None = None
        scope.outputs.append(self)

    def step(self, time):
        deltas = consolidate(self.take_pending())
        if self.keep_state:
            self._update_state(deltas)
        if self.on_data is not None:
            for key, row, diff in deltas:
                self.on_data(key, row, time, diff)
        self._saw_data_this_epoch = bool(deltas)

    def flush(self, time):
        if self.on_time_end is not None:
            self.on_time_end(time)

    def on_finish(self):
        if self.on_end is not None:
            self.on_end()


class IterateNode(Node):
    """Fixed-point iteration (dataflow.rs iterate 4185).

    Holds a sub-scope built by ``body``; per epoch, feeds the epoch's deltas
    into the sub-scope's iteration inputs and loops until quiescence or
    ``limit`` iterations — semi-naive in the sense that each round processes
    only the previous round's deltas.
    """

    name = "iterate"

    def __init__(self, scope, inputs: Sequence[Node], build_body, limit: int | None = None):
        # body builds BEFORE the node registers: any outer node it lowers
        # (scope imports) must get a lower registration id than this node —
        # run_epoch steps nodes in registration order, so an import landing
        # after the IterateNode would deliver its deltas one epoch late
        subscope = Scope(parent=scope)
        iter_inputs = [InputNode(subscope) for _ in inputs]
        # build_body returns (result_nodes, back_pairs, import_pairs):
        #   result_nodes: sub-scope nodes whose accumulated state is the result
        #   back_pairs: list of (input_index, node) — node's output deltas are
        #   fed into iter_inputs[input_index] on the next round
        #   import_pairs: list of (outer_node, sub_input) — outer-scope tables
        #   referenced by the body stream in per outer epoch, NOT part of the
        #   feedback variable (the reference's import/export of collections
        #   between scopes, dataflow.rs:4315-4724)
        result_nodes, back_pairs, import_pairs = build_body(subscope, iter_inputs)

        n_iter = len(inputs)
        super().__init__(scope, list(inputs) + [onode for onode, _ in import_pairs])
        self.limit = limit
        # fixed-point rounds are driven locally: gather all input to one
        # worker; the nested subscope never performs exchanges
        self.exchange_gather0 = True
        self.subscope = subscope
        self.iter_inputs = iter_inputs
        self.result_nodes = result_nodes
        self.back_pairs = back_pairs
        self._import_subinputs: list[tuple[int, InputNode]] = [
            (n_iter + i, sub_in) for i, (_onode, sub_in) in enumerate(import_pairs)
        ]
        for rn in self.result_nodes:
            rn.require_state()
        for _, bn in self.back_pairs:
            bn.require_state()
        self._result_sent: list[dict[tuple[int, Row], int]] = [
            {} for _ in self.result_nodes
        ]
        # everything ever fed into each iteration input (outer + feedback);
        # the back edge REPLACES the variable: we feed state(f(X)) - X, the
        # differential Variable semantics (X_{n+1} := f(X_n), not ∪)
        self._input_acc: list[Counter] = [Counter() for _ in self.iter_inputs]

    def step(self, time):
        # feed epoch deltas in
        had_input = False
        for port, iin in enumerate(self.iter_inputs):
            deltas = self.take_pending(port)
            for key, row, diff in deltas:
                had_input = True
                iin.insert(key, row, 0, diff)
                self._input_acc[port][(key, row)] += diff
        # imported outer collections: plain per-epoch streams into the
        # subscope, not part of the feedback variable
        for port, sub_in in self._import_subinputs:
            for key, row, diff in self.take_pending(port):
                had_input = True
                sub_in.insert(key, row, 0, diff)
        if not had_input:
            # nothing changed this epoch — re-running the rounds would both
            # waste work and (with iteration_limit) advance the fixed point
            # past the requested round budget
            self._last_results = [[] for _ in self.result_nodes]
            return
        rounds = 0
        limit_hit = False
        while True:
            rounds += 1
            for iin in self.iter_inputs:
                iin.emit_time(0)
            for _, sub_in in self._import_subinputs:
                sub_in.emit_time(0)
            self.subscope.run_epoch(0)
            fed_any = False
            for input_idx, bn in self.back_pairs:
                new_state = bn.state_multiset()
                acc = self._input_acc[input_idx]
                delta: list[Delta] = []
                for entry, cnt in new_state.items():
                    d = cnt - acc.get(entry, 0)
                    if d:
                        delta.append((entry[0], entry[1], d))
                for entry, cnt in list(acc.items()):
                    if cnt and entry not in new_state:
                        delta.append((entry[0], entry[1], -cnt))
                if delta:
                    fed_any = True
                    for key, row, d in delta:
                        self.iter_inputs[input_idx].insert(key, row, 0, d)
                        acc[(key, row)] += d
                        if acc[(key, row)] == 0:
                            del acc[(key, row)]
            if not fed_any:
                break
            if self.limit is not None and rounds >= self.limit:
                limit_hit = True
                break
        if limit_hit:
            # the loop fed one round of feedback it will not run — discard it
            # so the variable stays at f^limit(X) instead of leaking into the
            # next epoch (or finish) and exceeding the round budget
            for idx, iin in enumerate(self.iter_inputs):
                acc = self._input_acc[idx]
                for key, row, d in iin.take_staged(0, []):
                    acc[(key, row)] -= d
                    if acc[(key, row)] == 0:
                        del acc[(key, row)]
        # diff accumulated results against last sent
        out_all = []
        for i, rn in enumerate(self.result_nodes):
            current = rn.state_multiset()
            last = self._result_sent[i]
            out = []
            for entry, cnt in current.items():
                delta = cnt - last.get(entry, 0)
                if delta:
                    out.append((entry[0], entry[1], delta))
            for entry, cnt in last.items():
                if entry not in current:
                    out.append((entry[0], entry[1], -cnt))
            self._result_sent[i] = current
            out_all.append(out)
        merged = consolidate(itertools.chain.from_iterable(out_all))
        # tag rows with source result index so Table layer can split
        # — instead we send per-result through port-mapped downstream:
        self.send(merged, time)
        self._last_results = out_all

    # Table layer attaches ResultExtractNodes reading _last_results

    def on_finish(self):
        # end-of-stream propagates into the body: release its buffered work
        # (temporal buffers etc.), re-run the fixed point, and emit any
        # result change so the outer quiesce loop delivers it
        for node in self.subscope.nodes:
            if not isinstance(node, OutputNode):
                node.on_finish()
        self.step(self.scope.current_time)

    def final_check(self):
        for node in self.subscope.nodes:
            node.final_check()

    def persist_dump(self):
        sub = {}
        for node in self.subscope.nodes:
            d = node.persist_dump()
            if d is not None:
                sub[node.id] = d
        return {
            "__sub": sub,
            "__acc": self._input_acc,
            "__result_sent": self._result_sent,
        }

    def persist_load(self, data):
        for nid, d in data["__sub"].items():
            self.subscope.nodes[nid].persist_load(d)
        self._input_acc = [Counter(c) for c in data["__acc"]]
        self._result_sent = [dict(r) for r in data["__result_sent"]]


class IterateResultNode(Node):
    """Extracts the i-th result stream of an IterateNode."""

    name = "iterate_result"

    def __init__(self, scope, iterate_node: IterateNode, index: int):
        super().__init__(scope, [iterate_node])
        self.index = index

    def step(self, time):
        # consume the merged stream (ignored) and use the split results
        self.take_pending()
        it: IterateNode = self.inputs[0]  # type: ignore[assignment]
        out = consolidate(getattr(it, "_last_results", [[]] * (self.index + 1))[self.index])
        if self.keep_state:
            self._update_state(out)
        self.send(out, time)


class Scope:
    """Holds the operator DAG; analog of the engine Scope/Graph
    (python_api.rs Scope pyclass + graph.rs Graph trait)."""

    def __init__(self, parent: "Scope | None" = None):
        self.nodes: list[Node] = []
        self.outputs: list[OutputNode] = []
        self.parent = parent
        self.current_time: Time = 0
        self.error_log: list[tuple[Any, int, str]] = []
        self.terminate_on_error = True
        # epoch -> wallclock of its earliest staged row (latency probes)
        self.epoch_wallclock: dict[Time, float] = {}
        # multi-worker context (engine/comm.py WorkerContext); None =
        # single-process.  Only ever set on the root scope — nested scopes
        # (iterate bodies) always run locally.
        self.worker = None
        # processed-epoch counter: the index fault plans' `crash` specs
        # target (engine/faults.py) — counts run_epoch calls, root scope only
        self.epochs_run = 0

    def _register(self, node: Node) -> int:
        self.nodes.append(node)
        return len(self.nodes) - 1

    def report_row_error(self, node: Node, key: int, message: str) -> None:
        self.error_log.append((node, key, message))
        # a row error on a serving request row completes the waiting HTTP
        # future as a typed 500 NOW (before any terminate_on_error raise
        # can wedge the client until its deadline) — no-op otherwise
        from pathway_tpu.engine import serving as _serving

        _serving.note_row_error(key, message)
        if self.terminate_on_error:
            raise EngineError(f"{node!r} key {Pointer(key)!r}: {message}")

    def run_epoch(self, time: Time) -> None:
        """One topologically-ordered pass (nodes registered in topo order).

        With a worker context, each declared exchange point performs one
        all-to-all right before the owning node steps — every worker walks
        the identical DAG in the same order, so the collectives pair up
        (the BSP superstep form of timely's exchange channels).
        """
        self.current_time = time
        worker = self.worker
        if self.parent is None:
            # epoch-boundary crash injection (chaos tests / soak runs):
            # SIGKILLs the process here when the active fault plan says so —
            # the boundary is where the supervisor's recovery guarantee
            # (resume from the last committed checkpoint) must hold
            from pathway_tpu.engine import faults as _faults

            if _faults.active_plan() is not None:
                _faults.maybe_crash(
                    worker=worker.worker_id if worker is not None else 0,
                    epoch=self.epochs_run,
                )
                # hang injection shares the boundary: a wedged loop is the
                # watchdog's problem, a SIGKILL is the supervisor's
                _faults.maybe_hang(
                    worker=worker.worker_id if worker is not None else 0,
                    epoch=self.epochs_run,
                )
            self.epochs_run += 1
        for node in self.nodes:
            try:
                if worker is not None:
                    worker.exchange_node(node, time)
                t0 = _monotonic()
                node.step(time)
                # cumulative per-operator step time feeds the live
                # dashboard / metrics (progress_reporter.rs analog)
                node.step_seconds += _monotonic() - t0
            except Exception as exc:
                self._note_user_frame(node, exc)
                raise
        for node in self.nodes:
            try:
                node.flush(time)
            except Exception as exc:
                self._note_user_frame(node, exc)
                raise
        if self.epoch_wallclock:
            # processed epochs are read by the prober right after this call;
            # older entries are dead — keep the map bounded on long runs
            self.epoch_wallclock = {
                k: v for k, v in self.epoch_wallclock.items() if k >= time
            }

    @staticmethod
    def _note_user_frame(node: "Node", exc: Exception) -> None:
        """Attach the table-creation site to a run-time operator error so
        the user sees THEIR file:line (reference trace.py user frames)."""
        frame = getattr(node, "user_frame", None)
        if frame is not None:
            from pathway_tpu.internals.trace import add_trace_note

            add_trace_note(exc, frame)

    def finish(self) -> None:
        # release buffered work (temporal buffers etc.), propagate, then
        # signal end-of-stream to outputs — ordering matters so subscribers
        # see the released rows before on_end.  In multi-worker mode the
        # quiesce check is a global any() — a worker with nothing pending
        # must still join its peers' exchange rounds.
        for node in self.nodes:
            if not isinstance(node, OutputNode):
                node.on_finish()
        guard = 0
        while self._any_pending_global(guard):
            self.run_epoch(self.current_time + 2)
            guard += 1
            if guard > 1000:
                raise EngineError("finish() did not quiesce")
        for node in self.nodes:
            node.final_check()
        for out in self.outputs:
            out.on_finish()

    def _any_pending_global(self, round_: int) -> bool:
        local = any(node.has_pending() for node in self.nodes)
        if self.worker is None:
            return local
        mesh = self.worker.mesh
        flags = mesh.gather(("finish", round_), local)
        return mesh.bcast(("finish-go", round_), flags is not None and any(flags))
