"""Dataflow probes: per-operator progress/throughput statistics.

Parity target: the reference's prober layer —
``src/engine/graph.rs:512`` (``ProberStats``/``OperatorStats``),
``src/engine/progress_reporter.rs`` (console stats loop) and the
``attach_prober``/``probe_table`` Graph methods (``graph.rs:969-976``).

TPU-first shape: the engine here is an epoch-stepped host runtime (device
compute happens inside jitted ops), so a probe is a cheap post-epoch scan
over the node arena rather than a timely probe handle.  Each ``Node``
already counts rows in/out; the :class:`Prober` turns those counters into
an immutable :class:`ProberStats` snapshot consumed by the console
dashboard (``internals/monitoring.py``) and the HTTP metrics server
(``engine/http_server.py``) — mirroring how the reference shares stats via
``ArcSwapOption<ProberStats>`` (``src/engine/http_server.rs:21``).
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Callable

if TYPE_CHECKING:  # pragma: no cover
    from pathway_tpu.engine.dataflow import Node, Scope


@dataclass
class OperatorStats:
    """Progress of one operator (graph.rs ``OperatorStats``)."""

    name: str = "node"
    time: int | None = None  # latest epoch this operator processed
    lag_ms: float | None = None  # now - wallclock of that epoch, if known
    rows_in: int = 0
    rows_out: int = 0
    step_ms: float = 0.0  # cumulative time spent in step()
    errors: int = 0  # rows this operator poisoned/logged (error-log count)
    done: bool = False

    def merge(self, other: "OperatorStats") -> "OperatorStats":
        return OperatorStats(
            name=self.name,
            time=max_opt(self.time, other.time),
            lag_ms=max_opt(self.lag_ms, other.lag_ms),
            rows_in=self.rows_in + other.rows_in,
            rows_out=self.rows_out + other.rows_out,
            step_ms=self.step_ms + other.step_ms,
            errors=self.errors + other.errors,
            done=self.done and other.done,
        )


def max_opt(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return max(a, b)


@dataclass
class ConnectorStats:
    """Per-source ingestion stats (connectors/monitoring.rs analog)."""

    name: str = "source"
    rows: int = 0
    finished: bool = False


@dataclass
class ProberStats:
    """One consistent snapshot of the whole dataflow (graph.rs:512)."""

    input_stats: OperatorStats = field(default_factory=OperatorStats)
    output_stats: OperatorStats = field(default_factory=OperatorStats)
    operator_stats: dict[int, OperatorStats] = field(default_factory=dict)
    connector_stats: list[ConnectorStats] = field(default_factory=list)
    epochs: int = 0
    row_counts: dict[int, int] = field(default_factory=dict)


class Prober:
    """Collects :class:`ProberStats` from a :class:`Scope` after each epoch.

    ``callbacks`` mirrors ``attach_prober(callback, ...)`` — every update
    delivers the fresh snapshot; the dashboard and the HTTP server both
    register one.
    """

    def __init__(
        self,
        scope: "Scope",
        callbacks: list[Callable[[ProberStats], None]] | None = None,
        pollers: list | None = None,
    ):
        self.scope = scope
        self.pollers = list(pollers or [])
        self.callbacks: list[Callable[[ProberStats], None]] = list(callbacks or [])
        self.stats = ProberStats()
        # incremental error attribution: only entries appended since the
        # last update are scanned (the log is unbounded on long
        # terminate_on_error=False streams)
        self._err_counts: dict[int, int] = {}
        self._err_scan_pos = 0

    def update(self, *, done: bool = False, epochs: int | None = None) -> ProberStats:
        from pathway_tpu.engine.dataflow import InputNode, OutputNode

        if self.scope is None:  # final snapshot already taken
            return self.stats
        now = _time.monotonic()
        t = self.scope.current_time
        # wallclock of the epoch's earliest staged row, recorded by
        # InputNode.emit_time — lag is real ingest→processed delay
        seen = self.scope.epoch_wallclock.get(t)

        ops: dict[int, OperatorStats] = {}
        inputs = OperatorStats(name="input", done=done)
        outputs = OperatorStats(name="output", done=done)
        row_counts: dict[int, int] = {}
        err_counts = self._err_counts
        log = self.scope.error_log
        for err_node, _key, _msg in log[self._err_scan_pos :]:
            nid = getattr(err_node, "id", None)
            if nid is not None:
                err_counts[nid] = err_counts.get(nid, 0) + 1
        self._err_scan_pos = len(log)
        for node in self.scope.nodes:
            st = OperatorStats(
                name=getattr(node, "name", None) or "node",
                time=t,
                rows_in=node.rows_in,
                rows_out=node.rows_out,
                step_ms=node.step_seconds * 1000.0,
                errors=err_counts.get(node.id, 0),
                done=done or (isinstance(node, InputNode) and node.finished),
            )
            if seen is not None:
                st.lag_ms = (now - seen) * 1000.0
            ops[node.id] = st
            if node.keep_state:
                row_counts[node.id] = len(node.state)
            if isinstance(node, InputNode):
                inputs = inputs.merge(st)
                inputs.done = done or all(
                    n.finished for n in self.scope.nodes if isinstance(n, InputNode)
                )
            if isinstance(node, OutputNode):
                outputs = outputs.merge(st)
                outputs.done = done
        connectors = [
            ConnectorStats(
                name=getattr(p, "name", "source"),
                rows=getattr(getattr(p, "input_node", None), "rows_in", 0),
                finished=bool(getattr(p, "finished", False)),
            )
            for p in self.pollers
        ]
        self.stats = ProberStats(
            input_stats=inputs,
            output_stats=outputs,
            operator_stats=ops,
            connector_stats=connectors,
            # epoch count is owned by the runner's loop when provided; the
            # final done-snapshot re-reads counters, it is not a new epoch
            epochs=(
                epochs
                if epochs is not None
                else self.stats.epochs + (0 if done else 1)
            ),
            row_counts=row_counts,
        )
        for cb in self.callbacks:
            cb(self.stats)
        if done:
            # drop the graph so a retained RunResult.prober doesn't keep
            # every node's state arena alive
            self.scope = None
        return self.stats

    def metrics_snapshot(self) -> dict[str, float]:
        """Flat gauge dict of the latest snapshot for the unified metrics
        registry (``engine/metrics.py``): dataflow progress totals that
        ride the same /metrics scrape and OTLP export as comm/persistence
        health.  (Per-operator rows stay in the richer ProberStats render
        of ``engine/http_server.py``.)"""
        s = self.stats
        out = {
            "dataflow.epochs": float(s.epochs),
            "dataflow.input.rows": float(s.input_stats.rows_out),
            "dataflow.output.rows": float(s.output_stats.rows_in),
            "dataflow.operators": float(len(s.operator_stats)),
            "dataflow.errors": float(
                sum(op.errors for op in s.operator_stats.values())
            ),
        }
        if s.input_stats.lag_ms is not None:
            out["dataflow.input.lag.ms"] = s.input_stats.lag_ms
        if s.output_stats.lag_ms is not None:
            out["dataflow.output.lag.ms"] = s.output_stats.lag_ms
        return out
