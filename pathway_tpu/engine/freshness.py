"""Data-plane observability: freshness tracking and backpressure attribution.

The complement of the performance profiler (``engine/profiler.py``): the
profiler says where CPU burns, this module says **where records wait and
how stale each output is right now** — the question a *live* data
framework exists to answer.

Two surfaces, one tracker:

* **Ingest-time low-watermark propagation** (:class:`FreshnessTracker`).
  Connectors already stamp every staged batch with its ingest wall-clock
  (``InputNode._staged_wallclock``); ``emit_time`` exposes the epoch's
  earliest stamp per input as ``epoch_ingest_wallclock``.  After every
  processed epoch the tracker makes one topologically-ordered pass over
  the node arena and propagates the **min-ingest-time frontier**: each
  operator's watermark is the minimum over its inputs' watermarks, so an
  output's watermark is the ingest time of the *oldest* row contributing
  to the update it just delivered (a low watermark, in the classic
  streaming sense — but over ingest wall-clock, not event time; the
  event-time ``_watermark`` fields of the temporal nodes in
  ``engine/dataflow.py`` are a different, per-operator axis).  From the
  frontier fall out:

  - ``freshness.e2e.ms{output=...}`` — ingest→delivery latency histogram
    per output connector (p50/p95/p99 ride the PR-8 quantile machinery),
  - ``output.staleness.s{output=...}`` — seconds since the ingest stamp
    of the newest data each output reflects, computed at *read* time so
    a stalled pipeline shows growing staleness even while the epoch loop
    idles.  Staleness rising while ``epoch.duration.ms`` stays flat is
    the signature of a starved/stalled *source*; both rising together is
    a slow *pipeline* — the distinction ``docs/observability.md``
    documents.

* **Backpressure attribution** (``backlog.*``).  Queue depth and age at
  every boundary where records wait, under one namespace so one view can
  rank the bottleneck stage: connector reader queues
  (``backlog.connector.queue``), rows staged at inputs awaiting an epoch
  (``backlog.ingest.rows`` / ``backlog.ingest.age.s``), distinct pending
  epoch timestamps (``backlog.epochs.pending``), comm per-peer inboxes
  (``backlog.comm.inbox``, emitted by ``engine/comm.py``), and
  async-commit in-flight state (``backlog.checkpoint.bytes`` / ``.jobs``,
  emitted by ``engine/persistence.py:CommitMetrics``).

Everything exports through the unified registry (``engine/metrics.py``)
— one collector, registered by the runner — so it rides ``/metrics``
scrapes, OTLP export, the ``GET /status`` JSON endpoint
(``engine/http_server.py``), the ``pathway_tpu top`` live view
(``internals/top.py``), the console dashboard footer, and flight-recorder
dumps (final watermark/backlog snapshot, so post-mortems say what was
*stuck*, not just where time went).

Cost: one attribute pass over the node arena per epoch (no locks beyond
the histogram observe, no allocation per node) — priced by
``benchmarks/freshness_overhead.py`` at well under the 2%-of-a-1 ms-epoch
acceptance bound.  ``PATHWAY_FRESHNESS=0`` removes even that.
"""

from __future__ import annotations

import re
from time import monotonic as _monotonic
from typing import Any

from pathway_tpu.engine import metrics as _metrics

__all__ = ["FreshnessTracker", "render_freshness", "safe_label"]


_LABEL_UNSAFE = re.compile(r"[{}=,\n]")


def safe_label(value: Any) -> str:
    """User-supplied names (sink/source registration names from the io
    API) become metric label VALUES in the ``name{k=v,...}`` collector
    key format — strip the characters that would corrupt its parsing.
    The runner dedups sink labels on THIS sanitized form, so distinct
    raw names can never collapse into one metric label silently."""
    return _LABEL_UNSAFE.sub("_", str(value))


class FreshnessTracker:
    """Per-run freshness/backlog tracker (the runner keeps it on
    ``RunResult.freshness``; the registry collector holds it weakly, so
    it dies with the result, exactly like the prober and profiler)."""

    def __init__(self, *, enabled: bool | None = None):
        from pathway_tpu.internals.config import env_bool

        self.enabled = (
            env_bool("PATHWAY_FRESHNESS") if enabled is None else bool(enabled)
        )
        self._pollers: list[Any] = []
        # walk plan, precomputed once per graph shape: (node, kind,
        # input-id tuple) per node in topo order, kind 0=input 1=interior
        # 2=output — the per-epoch pass then does zero isinstance checks
        self._plan: list[tuple[Any, int, tuple[int, ...]]] | None = None
        # node id -> ingest low-watermark of the data that flowed through
        # it in the last processed epoch (monotonic wall-clock seconds);
        # flat list indexed by node id (ids are arena indexes)
        self._frontier: list[float | None] = []
        # output label -> (watermark of the newest delivered update,
        # wall-clock at delivery, output node); staleness derives from it
        # at read time
        self._delivered: dict[str, tuple[float, float, Any]] = {}
        # node id -> the InputNodes upstream of it (plan-build time): an
        # output whose every source has FINISHED is complete, not stale —
        # its gauge must stop aging, or a static side table's export
        # would dominate worst-staleness forever
        self._upstream: list[tuple[Any, ...]] = []
        # output label -> e2e histogram child (resolved once)
        self._e2e: dict[str, Any] = {}
        # mesh worst-staleness gauge child, resolved once: the publisher
        # sits on worker 0's epoch-negotiation path, which must not take
        # the registry family lock every round
        self._mesh_gauge: Any = None
        self.epochs_tracked = 0

    # -- wiring --------------------------------------------------------------
    def attach(self, scope: Any, pollers: list[Any]) -> None:
        """Bind the run's connector pollers (called by the runner after
        lowering, before the event loop).  The scope itself is not
        stored; ``after_epoch`` receives it per call and builds the walk
        plan lazily from it."""
        del scope  # accepted for call-site symmetry with the prober
        # per-poller backlog label, deduped on the sanitized form here
        # (same hazard the runner guards for sink labels): two unnamed
        # sources of one reader class must not overwrite each other's
        # queue/idle gauges — the later one would mask the stalled one
        self._pollers = []
        used: set[str] = set()
        for i, poller in enumerate(pollers or []):
            label = safe_label(getattr(poller, "name", "source"))
            if label in used:
                label = f"{label}#{i}"
            used.add(label)
            self._pollers.append((label, poller))

    def _output_label(self, node: Any) -> str:
        name = getattr(node, "sink_name", None)
        return safe_label(name) if name else f"output#{node.id}"

    def _build_plan(self, scope: Any) -> list[tuple[Any, int, tuple[int, ...]]]:
        """Type checks and input-id resolution paid once per graph shape;
        the per-epoch pass is then pure list indexing."""
        from pathway_tpu.engine.dataflow import InputNode, OutputNode

        plan: list[tuple[Any, int, Any]] = []
        upstream: list[tuple[Any, ...]] = []
        for node in scope.nodes:
            if isinstance(node, InputNode):
                kind = 0
            elif isinstance(node, OutputNode):
                kind = 2
            else:
                kind = 1
            ids = tuple(inp.id for inp in node.inputs)
            # single-input nodes (the vast majority of a lowered graph)
            # store the bare id: the per-epoch pass then does one list
            # index instead of an inner loop
            src: Any = ids[0] if len(ids) == 1 else ids
            plan.append((node, kind, src))
            if kind == 0:
                ups: tuple[Any, ...] = (node,)
            else:
                seen: list[Any] = []
                for i in ids:
                    for inp in upstream[i]:
                        if inp not in seen:
                            seen.append(inp)
                ups = tuple(seen)
            upstream.append(ups)
        self._plan = plan
        self._upstream = upstream
        self._frontier = [None] * len(plan)
        return plan

    # -- epoch hook ----------------------------------------------------------
    def after_epoch(self, scope: Any, now: float | None = None) -> None:
        """One topo pass after a processed epoch: propagate the ingest
        low-watermark and record delivery latency at outputs.  Reads plain
        attributes only — safe on the epoch thread (never a lock beyond
        the histogram observe, never I/O)."""
        if not self.enabled:
            return
        plan = self._plan
        if plan is None or len(plan) != len(scope.nodes):
            plan = self._build_plan(scope)
        if now is None:
            now = _monotonic()
        frontier = self._frontier
        for node, kind, src in plan:
            if kind == 0:
                w = node.epoch_ingest_wallclock
            elif type(src) is int:
                w = frontier[src]
            else:
                w = None
                for i in src:
                    iw = frontier[i]
                    if iw is not None and (w is None or iw < w):
                        w = iw
            frontier[node.id] = w
            if kind == 2 and w is not None and node._saw_data_this_epoch:
                label = self._output_label(node)
                hist = self._e2e.get(label)
                if hist is None:
                    hist = _metrics.get_registry().histogram(
                        "freshness.e2e.ms",
                        "ingest-to-delivery latency of output updates (ms)",
                        buckets=_metrics.MS_BUCKETS,
                        output=label,
                    )
                    self._e2e[label] = hist
                hist.observe(max(0.0, (now - w) * 1000.0))
                self._delivered[label] = (w, now, node)
        self.epochs_tracked += 1

    # -- read-time derivations ----------------------------------------------
    def staleness(self, now: float | None = None) -> dict[str, float]:
        """``{output label: seconds}`` — age of the newest ingest stamp
        each output reflects, measured *now* (so a stalled stream keeps
        aging between epochs).  Outputs whose every upstream source has
        FINISHED are complete, not stale — they drop out rather than age
        forever (a *stalled* source is not finished, so it keeps aging)."""
        if now is None:
            now = _monotonic()
        upstream = self._upstream
        out: dict[str, float] = {}
        # list() snapshot: the engine thread inserts a new label when an
        # output delivers its first epoch, and this runs on scrape/export
        # threads — an unguarded .items() iteration could die mid-resize
        # and silently drop the whole collector output for that scrape
        for label, (watermark, _at, node) in list(self._delivered.items()):
            sources = upstream[node.id] if node.id < len(upstream) else ()
            if sources and all(s.finished for s in sources):
                continue
            out[label] = max(0.0, now - watermark)
        return out

    def worst_staleness(self, now: float | None = None) -> float | None:
        stale = self.staleness(now)
        return max(stale.values()) if stale else None

    def record_mesh_staleness(self, values: list[float | None]) -> None:
        """Worker 0 only: publish the mesh-wide worst output staleness
        gathered from every worker's epoch-negotiation payload (the
        cross-worker aggregation riding the PR-4 mesh paths)."""
        present = [v for v in values if v is not None]
        if not present and self._mesh_gauge is None:
            # never published anything: don't mint a zero gauge
            return
        gauge = self._mesh_gauge
        if gauge is None:
            gauge = self._mesh_gauge = _metrics.get_registry().gauge(
                "freshness.mesh.staleness.s",
                "worst output staleness across the worker mesh",
            )
        # all workers report None (every source finished): clear to zero
        # rather than freezing at the last stall — the per-output gauges
        # drop out at that point, and this one must not keep alerting
        gauge.set(max(present) if present else 0.0)

    def _backlog(self, now: float) -> dict[str, float]:
        """``backlog.*`` gauges for every boundary this tracker can see.
        Runs at scrape/export cadence on a non-engine thread; every read
        is a plain attribute/dict access guarded against concurrent
        mutation by the engine thread (telemetry is best-effort)."""
        out: dict[str, float] = {}
        pending_times: set[int] = set()
        for name, poller in self._pollers:
            q = getattr(poller, "q", None)
            if q is not None:
                try:
                    out[f"backlog.connector.queue{{source={name}}}"] = float(
                        q.qsize()
                    )
                except Exception:  # noqa: BLE001 - best-effort telemetry
                    pass
            node = getattr(poller, "input_node", None)
            if node is None:
                continue
            try:
                staged = sum(len(d) for d in list(node._staged.values()))
                walls = list(node._staged_wallclock.values())
                pending_times.update(node._staged.keys())
            except RuntimeError:  # resized mid-iteration by the engine
                continue
            out[f"backlog.ingest.rows{{source={name}}}"] = float(staged)
            if walls:
                out[f"backlog.ingest.age.s{{source={name}}}"] = max(
                    0.0, now - min(walls)
                )
            # how long this source has been quiet: the one-branch-stall
            # signal.  The low-watermark deliberately excludes idle
            # inputs (Flink's idle-source rule — holding the last stamp
            # would alarm on every legitimately bursty source), so a
            # stalled branch of a multi-source join shows up HERE, not
            # in output.staleness.s while its siblings keep delivering.
            last_row = getattr(poller, "last_row_mono", None)
            if last_row is not None and not getattr(
                poller, "finished", False
            ):
                out[f"backlog.connector.idle.s{{source={name}}}"] = max(
                    0.0, now - last_row
                )
        out["backlog.epochs.pending"] = float(len(pending_times))
        return out

    # -- exports -------------------------------------------------------------
    def metrics_snapshot(self) -> dict[str, float]:
        """Registry collector: staleness + backlog gauges, evaluated at
        pull time (``engine/metrics.py`` holds this weakly)."""
        now = _monotonic()
        out: dict[str, float] = {}
        for label, seconds in self.staleness(now).items():
            out[f"output.staleness.s{{output={label}}}"] = seconds
        out.update(self._backlog(now))
        return out

    def snapshot(self) -> dict[str, Any]:
        """Dump-friendly snapshot for flight-recorder post-mortems: the
        final per-output watermarks/staleness and the backlog ranking —
        what was *stuck* when the worker died."""
        now = _monotonic()
        outputs = {
            label: {
                "staleness_s": round(seconds, 6),
                "delivered_ago_s": round(
                    max(0.0, now - self._delivered[label][1]), 6
                ),
            }
            for label, seconds in self.staleness(now).items()
        }
        for label, (_w, at, node) in list(self._delivered.items()):
            if label not in outputs:
                # completed output (every source finished): still part of
                # the post-mortem story, just not aging
                outputs[label] = {
                    "complete": True,
                    "delivered_ago_s": round(max(0.0, now - at), 6),
                }
        return {
            "epochs_tracked": self.epochs_tracked,
            "outputs": outputs,
            "backlog": {k: v for k, v in self._backlog(now).items() if v},
        }

    def crash_snapshot(self) -> dict[str, Any] | None:
        """Never-raising snapshot for the flight recorder (forensics)."""
        try:
            return self.snapshot()
        except Exception:  # noqa: BLE001 - a dying process must still dump
            return None


def render_freshness(snapshot: dict[str, Any]) -> str:
    """Human-readable render of a :meth:`FreshnessTracker.snapshot` (used
    by ``pathway_tpu blackbox`` on dump payloads; tolerates partial or
    hand-edited artifacts, never raises)."""
    lines = [
        f"freshness: {snapshot.get('epochs_tracked', '?')} epochs tracked"
    ]
    outputs = snapshot.get("outputs") or {}
    for label in sorted(outputs):
        info = outputs[label] or {}
        if info.get("complete"):
            lines.append(
                f"  output {label}: complete (last delivery "
                f"{info.get('delivered_ago_s', '?')} s ago)"
            )
            continue
        lines.append(
            f"  output {label}: staleness "
            f"{info.get('staleness_s', '?')} s (last delivery "
            f"{info.get('delivered_ago_s', '?')} s ago)"
        )
    backlog = snapshot.get("backlog") or {}
    # non-numeric values (hand-edited / damaged-but-parseable artifacts)
    # render verbatim and sort last — this renderer must never raise
    _NUMERIC = object()
    entries = []
    for key, value in backlog.items():
        try:
            entries.append((key, float(value), _NUMERIC))
        except (TypeError, ValueError):
            entries.append((key, float("-inf"), value))
    entries.sort(key=lambda e: -e[1])
    for key, num, raw in entries:
        lines.append(
            f"  {key} = {num:g}" if raw is _NUMERIC else f"  {key} = {raw!r}"
        )
    if not outputs and not backlog:
        lines.append("  (no outputs delivered, no backlog)")
    return "\n".join(lines)
