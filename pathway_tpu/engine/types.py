"""Core value/key model of the TPU-native engine.

Design notes (reference parity):
  * Pathway keys every row with a 128-bit key whose low 16 bits select the data
    shard (``/root/reference/src/engine/value.rs:38,41``).  We keep the same
    128-bit key space and shard mask so multi-worker exchange semantics match,
    but keys live as Python ints host-side (arbitrary-precision, hash-friendly)
    and are split into (hi, lo) uint64 pairs when they cross into device code.
  * ``Value`` in the reference is a Rust enum (``value.rs:207-228``).  Here the
    host runtime is Python, so values are plain Python objects; this module
    pins down the *canonical* representations and the stable hash used for key
    derivation so results are reproducible across workers and processes.

Timestamps: u64, even = original data, odd = retraction-in-progress, matching
``/root/reference/src/timestamp.rs`` semantics (we only ever emit even times
from connectors; odd times are reserved for the retraction machinery).
"""

from __future__ import annotations

import hashlib
import struct
from typing import Any, Iterable

import numpy as np

# --- key space ---------------------------------------------------------------

KEY_BITS = 128
KEY_MASK = (1 << KEY_BITS) - 1
SHARD_BITS = 16
SHARD_MASK = (1 << SHARD_BITS) - 1  # value.rs:38

Time = int  # u64 epoch counter; even = original, odd = retraction
Diff = int  # signed multiplicity

ARTIFICIAL_TIME_ON_REWIND_START = 0


def shard_of(key: int) -> int:
    """Shard field of a 128-bit key (low 16 bits), as in value.rs:76."""
    return key & SHARD_MASK


def shard_to_worker(key: int, worker_count: int) -> int:
    # routing rule: k.shard_as_usize() % worker_count (dataflow.rs:1414)
    return (key & SHARD_MASK) % worker_count


class Pointer:
    """User-visible row id wrapper (mirrors ``pw.Pointer``).

    Compares/hashes by the underlying 128-bit int so it can key dicts and be
    stored in tables like any other value.
    """

    __slots__ = ("value",)

    def __init__(self, value: int):
        self.value = value & KEY_MASK

    def __repr__(self) -> str:  # short, stable, prints like ^XXXX
        return "^" + _b32(self.value)

    def __hash__(self) -> int:
        return hash(self.value)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Pointer) and other.value == self.value

    def __lt__(self, other: "Pointer") -> bool:
        if not isinstance(other, Pointer):
            return NotImplemented
        return self.value < other.value

    def __le__(self, other: "Pointer") -> bool:
        if not isinstance(other, Pointer):
            return NotImplemented
        return self.value <= other.value

    def __gt__(self, other: "Pointer") -> bool:
        if not isinstance(other, Pointer):
            return NotImplemented
        return self.value > other.value

    def __ge__(self, other: "Pointer") -> bool:
        if not isinstance(other, Pointer):
            return NotImplemented
        return self.value >= other.value


_B32 = "0123456789ABCDEFGHIJKLMNOPQRSTUV"


def _b32(v: int) -> str:
    out = []
    for _ in range(8):  # print 40 bits; enough to disambiguate in debug output
        out.append(_B32[v & 31])
        v >>= 5
    return "".join(reversed(out))


class Error:
    """Singleton error value (``Value::Error`` poisoning, value.rs:226)."""

    _instance: "Error | None" = None

    def __new__(cls) -> "Error":
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self) -> str:
        return "Error"

    def __bool__(self) -> bool:
        raise TypeError("cannot use pw Error value in a boolean context")

    def __reduce__(self):
        # keep singleton identity across the worker exchange (pickle)
        return (Error, ())


ERROR = Error()


class Json:
    """Wrapper marking a value as JSON-typed (mirrors pw.Json)."""

    __slots__ = ("value",)

    def __init__(self, value: Any):
        if isinstance(value, Json):
            value = value.value
        self.value = value

    def __repr__(self) -> str:
        import json as _json

        return _json.dumps(self.value)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Json) and other.value == self.value

    def __hash__(self) -> int:
        return hash(repr(self))

    # convenience accessors mirroring pathway's Json API
    def as_int(self):
        return int(self.value)

    def as_float(self):
        return float(self.value)

    def as_str(self):
        return str(self.value)

    def as_bool(self):
        return bool(self.value)

    def as_list(self):
        return list(self.value)

    def as_dict(self):
        return dict(self.value)

    def __getitem__(self, item):
        return Json(self.value[item])

    @staticmethod
    def parse(s: str) -> "Json":
        import json as _json

        return Json(_json.loads(s))

    NULL: "Json"


Json.NULL = Json(None)


class PyObjectWrapper:
    """Opaque Python object carried through the engine (value.rs:228)."""

    __slots__ = ("value", "_serializer")

    def __init__(self, value: Any, *, serializer: Any = None):
        self.value = value
        self._serializer = serializer

    def __repr__(self) -> str:
        return f"PyObjectWrapper({self.value!r})"

    def __eq__(self, other: object) -> bool:
        return isinstance(other, PyObjectWrapper) and other.value == self.value

    def __hash__(self) -> int:
        try:
            return hash(self.value)
        except TypeError:
            return hash(id(self.value))


def wrap_py_object(value: Any, *, serializer: Any = None) -> PyObjectWrapper:
    return PyObjectWrapper(value, serializer=serializer)


class HashableNDArray(np.ndarray):
    """ndarray view hashable/equatable by contents.

    The reference's ``Value::IntArray/FloatArray`` are hashable by contents
    (value.rs HashInto); engine state (consolidation counters, arrangement
    keys) requires the same here.  ``==`` returns a bool (contents equal),
    not an elementwise array — inside engine rows arrays are *values*.
    Arithmetic and numpy ops still work (it is an ndarray view).
    """

    def __hash__(self):  # type: ignore[override]
        return hash(
            (
                self.shape,
                str(self.dtype),
                hashlib.blake2b(
                    np.ascontiguousarray(self).tobytes(), digest_size=8
                ).digest(),
            )
        )

    def __eq__(self, other):  # type: ignore[override]
        # strict: dtype is part of identity, matching __hash__ (hash/eq
        # contract) — dtype coercion normalizes values before they enter rows
        if isinstance(other, np.ndarray):
            return (
                self.shape == other.shape
                and self.dtype == other.dtype
                and bool(np.array_equal(np.asarray(self), np.asarray(other)))
            )
        return NotImplemented

    def __ne__(self, other):  # type: ignore[override]
        res = self.__eq__(other)
        if res is NotImplemented:
            return res
        return not res


def as_hashable(value: Any) -> Any:
    """Wrap ndarrays into the hashable view, recursing into tuples (idempotent)."""
    if isinstance(value, np.ndarray) and not isinstance(value, HashableNDArray):
        return value.view(HashableNDArray)
    if isinstance(value, tuple) and any(
        isinstance(v, (np.ndarray, tuple)) for v in value
    ):
        return tuple(as_hashable(v) for v in value)
    return value


# --- stable hashing / key derivation ----------------------------------------
#
# The reference derives keys with xxh3-128 over a serialized value sequence
# (value.rs "HashInto").  We use blake2b-128 host-side: stable across runs,
# processes and machines, which is the property the engine actually needs.


def _hash_bytes(data: bytes) -> int:
    return int.from_bytes(hashlib.blake2b(data, digest_size=16).digest(), "little")


def _ser_value(v: Any, out: list[bytes]) -> None:
    if v is None:
        out.append(b"\x00")
    elif v is True:
        out.append(b"\x01\x01")
    elif v is False:
        out.append(b"\x01\x00")
    elif isinstance(v, int):
        out.append(b"\x02" + v.to_bytes(16, "little", signed=True))
    elif isinstance(v, float):
        out.append(b"\x03" + struct.pack("<d", v))
    elif isinstance(v, str):
        b = v.encode()
        out.append(b"\x04" + len(b).to_bytes(8, "little") + b)
    elif isinstance(v, bytes):
        out.append(b"\x05" + len(v).to_bytes(8, "little") + v)
    elif isinstance(v, Pointer):
        out.append(b"\x06" + v.value.to_bytes(16, "little"))
    elif isinstance(v, tuple):
        out.append(b"\x07" + len(v).to_bytes(8, "little"))
        for item in v:
            _ser_value(item, out)
    elif isinstance(v, np.ndarray):
        out.append(b"\x08" + str(v.dtype).encode() + str(v.shape).encode())
        out.append(np.ascontiguousarray(v).tobytes())
    elif isinstance(v, Json):
        import json as _json

        b = _json.dumps(v.value, sort_keys=True).encode()
        out.append(b"\x09" + b)
    elif isinstance(v, PyObjectWrapper):
        out.append(b"\x0b" + repr(v.value).encode())
    else:  # datetimes, durations, anything reprable
        out.append(b"\x0a" + type(v).__name__.encode() + b":" + repr(v).encode())


_native_mod: Any = None
_native_checked = False


def _native():
    """The compiled runtime core (pathway_tpu/native), or None."""
    global _native_mod, _native_checked
    if not _native_checked:
        from pathway_tpu import native as _n

        _native_mod = _n.get()
        _native_checked = True
    return _native_mod


def hash_values(values: Iterable[Any]) -> int:
    """Stable 128-bit hash of a value sequence (key derivation)."""
    native = _native()
    if native is not None:
        return native.hash_values(tuple(values))
    out: list[bytes] = []
    for v in values:
        _ser_value(v, out)
    return _hash_bytes(b"".join(out))


def hash_values_py(values: Iterable[Any]) -> int:
    """Pure-Python reference path (native parity tests)."""
    out: list[bytes] = []
    for v in values:
        _ser_value(v, out)
    return _hash_bytes(b"".join(out))


_SEQ_SALT = b"pathway_tpu:sequential"


def ref_scalar(*values: Any, optional: bool = False) -> Pointer:
    """Derive a Pointer from primary-key values (pw api.ref_scalar)."""
    if optional and any(v is None for v in values):
        return None  # type: ignore[return-value]
    return Pointer(hash_values(values))


def sequential_key(seq: int) -> int:
    """Key for auto-numbered rows (connector autogenerate / unsafe_trusted_ids)."""
    return _hash_bytes(_SEQ_SALT + seq.to_bytes(16, "little", signed=True))


def sequential_keys(start: int, count: int) -> list[int]:
    """Bulk ``[sequential_key(start + i) for i in range(count)]`` — the
    native core derives them in one C loop (bulk-ingest hot path)."""
    native = _native()
    if native is not None and hasattr(native, "sequential_keys"):
        return native.sequential_keys(
            _SEQ_SALT, start.to_bytes(16, "little", signed=True), count
        )
    return [sequential_key(start + i) for i in range(count)]


def key_to_u64_pair(key: int) -> tuple[int, int]:
    """Split a 128-bit key into (hi, lo) uint64 for device-side id tensors."""
    return (key >> 64) & 0xFFFFFFFFFFFFFFFF, key & 0xFFFFFFFFFFFFFFFF


def u64_pair_to_key(hi: int, lo: int) -> int:
    return ((int(hi) & 0xFFFFFFFFFFFFFFFF) << 64) | (int(lo) & 0xFFFFFFFFFFFFFFFF)
