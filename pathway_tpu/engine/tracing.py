"""Request-scoped distributed tracing for the serving path.

PR 17/18 built admission control, deadlines and continuous batching; the
span story still stopped at the epoch (``pathway.epoch`` /
``pathway.commit``).  This module adds the per-request layer: a
:class:`RequestTrace` — W3C ``traceparent`` accepted on ingress, minted
otherwise — created by the admission controller
(``engine/serving.py``) and propagated through the REST handler
(``io/http/_server.py``), the connector row stamp (``_pw_trace`` next to
``_pw_deadline_ts``), the coalescing ``AsyncMicroBatcher``
(``utils/batching.py``), ``DeviceExecutor`` submit/dispatch
(``device/executor.py``) and the continuous-batching
``GenerationScheduler`` (``serving/generation.py``).

Every stage records a CHILD span with ids minted at creation (trace id,
span id, parent span id carried on the record — ``engine/telemetry.py``
exports them verbatim), so parent links in a collector are real and a
slow request decomposes into queue wait vs coalesce vs device dispatch
vs generation ticks.  Spans ride the existing bounded telemetry export
queue when an exporter is wired (:func:`set_exporter`); with zero
egress they still land in the in-process ring the ``pathway_tpu
requests`` CLI, the ``/status`` ``requests`` section and flight-recorder
dumps read.

Propagation is ambient (a contextvar scope, mirroring the serving
deadline's ``deadline_scope``) for same-thread stages, and explicit (the
trace rides the batcher entry / device job / generation request) across
thread hops — a coalesced batch serving waiters from two event loops
parents each waiter's spans to its own trace.

``PATHWAY_TRACE_REQUESTS=0`` turns the whole layer off (no trace
objects, no spans, no ring writes) — the lever
``benchmarks/request_trace_overhead.py`` prices (≤ 2 % of request cost).
"""

from __future__ import annotations

import secrets
import threading
import time
from collections import deque
from contextlib import contextmanager, nullcontext
from contextvars import ContextVar
from typing import Any

from pathway_tpu.engine import metrics as _metrics

__all__ = [
    "TRACE_STAMP",
    "RequestTrace",
    "active_trace",
    "begin_request",
    "current_trace",
    "enabled",
    "maybe_trace_storm",
    "recent_requests",
    "reset_for_tests",
    "set_exporter",
    "slowest_requests",
    "snapshot",
    "trace_scope",
]

# the connector row stamp — rides REST rows next to ``_pw_deadline_ts``
# (io/_utils.DEADLINE_TS) so the trace survives the trip through the
# dataflow and an output-side consumer can attribute its row
TRACE_STAMP = "_pw_trace"

# per-trace span cap: a runaway stage (per-chunk prefill of a huge
# prompt, a retry storm) must not grow one trace without bound — overflow
# drops the newest span and counts it
MAX_SPANS_PER_TRACE = 64

# deep-tree shape of one ``trace_storm`` synthetic trace (chained
# parent→child spans), sized so a default burst overflows the bounded
# telemetry export queue (EXPORT_QUEUE_MAX=256) by construction
STORM_TREE_DEPTH = 12
STORM_DEFAULT_TRACES = 64


def enabled() -> bool:
    """Request tracing on? (``PATHWAY_TRACE_REQUESTS``, default on)."""
    from pathway_tpu.internals.config import env_bool

    return env_bool("PATHWAY_TRACE_REQUESTS")


def _buffer_max() -> int:
    from pathway_tpu.internals.config import env_int

    return max(1, int(env_int("PATHWAY_TRACE_BUFFER")))


class RequestTrace:
    """One request's trace: a trace id, a root span, and child spans.

    Created at admission (or at the REST front door when admission is
    off); every serving stage that touches the request records child
    spans on it.  ``finish()`` closes the root ``serve.request`` span
    and moves the trace into the bounded finished-request ring.
    """

    __slots__ = (
        "trace_id", "root_span_id", "parent_span_id", "route", "started",
        "spans", "duration_s", "status", "_lock", "_finished", "_dropped",
        "attributes",
    )

    def __init__(self, route: str, trace_parent: str | None = None):
        from pathway_tpu.engine.telemetry import (
            _parent_span_id,
            _root_trace_id,
        )

        # W3C traceparent accepted on ingress: the caller's trace id and
        # span id become ours / our root's parent; otherwise mint fresh
        self.trace_id = _root_trace_id(trace_parent) or secrets.token_hex(16)
        self.parent_span_id = _parent_span_id(trace_parent)
        self.root_span_id = secrets.token_hex(8)
        self.route = route
        self.started = time.time()
        self.spans: list[dict] = []
        self.duration_s: float | None = None
        self.status: Any = None
        self.attributes: dict[str, Any] = {}
        self._lock = threading.Lock()
        self._finished = False
        self._dropped = 0

    def traceparent(self) -> str:
        """The W3C header value downstream stages propagate — child spans
        of this request parent to ``root_span_id`` under ``trace_id``."""
        return f"00-{self.trace_id}-{self.root_span_id}-01"

    # -- span recording ----------------------------------------------------
    def add_span(
        self,
        name: str,
        start: float,
        duration_s: float,
        parent_span_id: str | None = None,
        **attributes: Any,
    ) -> str:
        """Record one finished child span (explicit timing — stages that
        batch many requests per tick reconstruct per-request timing).
        Returns the minted span id so a caller can chain children."""
        span_id = secrets.token_hex(8)
        record = {
            "name": name,
            "start": start,
            "duration_s": duration_s,
            "attributes": attributes,
            "trace_parent": self.traceparent(),
            "trace_id": self.trace_id,
            "span_id": span_id,
            "parent_span_id": (
                self.root_span_id if parent_span_id is None else parent_span_id
            ),
        }
        with self._lock:
            if len(self.spans) >= MAX_SPANS_PER_TRACE:
                self._dropped += 1
                _metrics.get_registry().counter(
                    "trace.spans.dropped",
                    "request spans dropped by the per-trace span cap",
                ).inc()
                return span_id
            self.spans.append(record)
        _metrics.get_registry().counter(
            "trace.spans", "request-scoped spans recorded"
        ).inc()
        _export(record)
        return span_id

    @contextmanager
    def span(
        self, name: str, parent_span_id: str | None = None, **attributes: Any
    ):
        """Timed child-span scope for same-thread stages."""
        start = time.time()
        try:
            yield
        finally:
            self.add_span(
                name,
                start,
                time.time() - start,
                parent_span_id=parent_span_id,
                **attributes,
            )

    def finish(self, status: Any = None, **attributes: Any) -> None:
        """Close the root ``serve.request`` span and ring-buffer the
        trace.  Idempotent — the first close wins."""
        with self._lock:
            if self._finished:
                return
            self._finished = True
            self.duration_s = time.time() - self.started
            self.status = status
            self.attributes.update(attributes)
        record = {
            "name": "serve.request",
            "start": self.started,
            "duration_s": self.duration_s,
            "attributes": {
                "route": self.route,
                **({"status": status} if status is not None else {}),
                **self.attributes,
            },
            "trace_parent": self.traceparent(),
            "trace_id": self.trace_id,
            # the ROOT span: its id was minted at trace creation so every
            # child recorded before this close already parent-links to it
            "span_id": self.root_span_id,
            "parent_span_id": self.parent_span_id,
        }
        with self._lock:
            self.spans.append(record)
        _export(record)
        with _active_lock:
            _active.pop(self.trace_id, None)
        with _ring_lock:
            _ring.append(self.summary())

    def summary(self) -> dict[str, Any]:
        """JSON-able view of this trace (the ring/dump/CLI shape)."""
        with self._lock:
            spans = list(self.spans)
            dropped = self._dropped
        return {
            "trace_id": self.trace_id,
            "route": self.route,
            "start": self.started,
            "duration_s": self.duration_s,
            "status": self.status,
            "spans": spans,
            "spans_dropped": dropped,
        }


# ---------------------------------------------------------------------------
# Ambient propagation (the deadline_scope pattern, engine/serving.py)
# ---------------------------------------------------------------------------

_AMBIENT: ContextVar[RequestTrace | None] = ContextVar(
    "pathway_request_trace", default=None
)


def trace_scope(trace: RequestTrace | None):
    """Context manager binding ``trace`` as the ambient request trace
    (no-op for ``None`` — disabled tracing costs one branch)."""
    if trace is None:
        return nullcontext()
    return _scope(trace)


@contextmanager
def _scope(trace: RequestTrace):
    token = _AMBIENT.set(trace)
    try:
        yield trace
    finally:
        _AMBIENT.reset(token)


def current_trace() -> RequestTrace | None:
    """The ambient request trace of the calling context, if any."""
    return _AMBIENT.get()


def begin_request(
    route: str, trace_parent: str | None = None
) -> RequestTrace | None:
    """Mint (or adopt) a request trace — ``None`` while tracing is off."""
    if not enabled():
        return None
    trace = RequestTrace(route, trace_parent)
    with _active_lock:
        # bounded by admission (in-flight + queue); the cap is a backstop
        # against a leak ever growing the index without bound
        if len(_active) < _ACTIVE_MAX:
            _active[trace.trace_id] = trace
    _metrics.get_registry().counter(
        "trace.requests", "request traces created by the serving path"
    ).inc()
    return trace


# in-flight traces by trace id: lets a stage that only holds the row
# stamp (connector staging, the device executor on the epoch thread)
# attribute its span to the right trace without an ambient hop
_ACTIVE_MAX = 4096
_active: dict[str, RequestTrace] = {}
_active_lock = threading.Lock()


def active_trace(trace_parent: str | None) -> RequestTrace | None:
    """The in-flight trace a ``_pw_trace`` row stamp refers to, if any."""
    if not trace_parent:
        return None
    from pathway_tpu.engine.telemetry import _root_trace_id

    trace_id = _root_trace_id(trace_parent)
    if not trace_id:
        return None
    with _active_lock:
        return _active.get(trace_id)


# in-flight traces by REQUEST ROW KEY: the REST ingress binds its row's
# key so the dataflow's async-UDF node (engine/dataflow.py) can re-enter
# the request's trace scope on the epoch thread — the hop that connects
# ingress spans to batcher/device/generation spans for pipeline-served
# requests
_by_key: dict[int, RequestTrace] = {}


def bind_key(key: int, trace: RequestTrace | None) -> None:
    if trace is None:
        return
    with _active_lock:
        if len(_by_key) < _ACTIVE_MAX:
            _by_key[key] = trace


def unbind_key(key: int) -> None:
    if not _by_key:
        return
    with _active_lock:
        _by_key.pop(key, None)


def trace_for_key(key: int) -> RequestTrace | None:
    """The trace bound to a request row key — ultra-cheap when serving
    is inactive (one falsy dict check, the ``fail_request`` pattern)."""
    if not _by_key:
        return None
    with _active_lock:
        return _by_key.get(key)


# ---------------------------------------------------------------------------
# Finished-request ring + export hook
# ---------------------------------------------------------------------------

_ring: deque[dict] = deque(maxlen=256)
_ring_lock = threading.Lock()
_exporter: Any = None  # engine.telemetry.Telemetry for this run, if any


def set_exporter(telemetry: Any) -> None:
    """Wire (or clear, with ``None``) the run's Telemetry instance so
    request spans ride its bounded export queue (internals/runner.py —
    same lifetime contract as the flight-recorder suppliers)."""
    global _exporter
    _exporter = telemetry
    # the ring size knob is read when a run wires tracing up, not per
    # request — resizing preserves the newest entries
    global _ring
    with _ring_lock:
        size = _buffer_max()
        if _ring.maxlen != size:
            _ring = deque(list(_ring)[-size:], maxlen=size)


def _export(record: dict) -> None:
    exporter = _exporter
    if exporter is not None:
        try:
            exporter.emit_span(record)
        except Exception:  # noqa: BLE001 - tracing must never fail a request
            pass


def recent_requests(n: int = 20) -> list[dict]:
    """The newest ``n`` finished request traces, newest first."""
    with _ring_lock:
        items = list(_ring)
    return list(reversed(items))[:n]


def slowest_requests(n: int = 10) -> list[dict]:
    """The ``n`` slowest finished request traces, slowest first."""
    with _ring_lock:
        items = list(_ring)
    return sorted(items, key=lambda t: -(t.get("duration_s") or 0.0))[:n]


def requests_state() -> dict[str, float]:
    """Scalar gauges for the ``/status`` ``requests`` section."""
    with _ring_lock:
        items = list(_ring)
    out = {"trace.requests.buffered": float(len(items))}
    if items:
        durations = [t.get("duration_s") or 0.0 for t in items]
        out["trace.requests.slowest.ms"] = max(durations) * 1000.0
        out["trace.requests.newest.ms"] = (
            items[-1].get("duration_s") or 0.0
        ) * 1000.0
    return out


def snapshot() -> dict[str, Any]:
    """The tracing section of a flight-recorder dump: ring occupancy
    plus the slowest and newest traces WITH their span trees, so a
    post-mortem can render waterfalls offline."""
    with _ring_lock:
        buffered = len(_ring)
    return {
        "buffered": buffered,
        "slowest": slowest_requests(10),
        "recent": recent_requests(10),
    }


def reset_for_tests() -> None:
    global _exporter
    _exporter = None
    with _ring_lock:
        _ring.clear()
    with _active_lock:
        _active.clear()
        _by_key.clear()


# the ring gauges ride every scrape (the /status ``requests`` section and
# the OTLP sample) — a plain-function collector, registered once at import
_metrics.get_registry().register_collector(
    "trace.requests.state", requests_state
)


# ---------------------------------------------------------------------------
# trace_storm chaos hook (engine/faults.py)
# ---------------------------------------------------------------------------


def maybe_trace_storm(route: str) -> int:
    """``trace_storm`` fault injection: burst N synthetic traced
    requests, each with a deep chained span tree, through the bounded
    telemetry export queue — proving it drops oldest (counting
    ``telemetry.export.dropped``) without ever blocking the serving
    path.  Returns the number of synthetic traces emitted (0 = no
    fire)."""
    from pathway_tpu.engine import faults

    plan = faults.active_plan()
    if plan is None:
        return 0
    spec = plan.check("trace_storm", source=route)
    if spec is None:
        return 0
    n = int(spec.count or STORM_DEFAULT_TRACES)
    now = time.time()
    for i in range(n):
        trace = RequestTrace(route or "storm")
        parent: str | None = None
        for depth in range(STORM_TREE_DEPTH):
            parent = trace.add_span(
                f"storm.depth.{depth}",
                now,
                0.0,
                parent_span_id=parent,
                synthetic=True,
                storm_index=i,
            )
        trace.finish(status="storm", synthetic=True)
    _metrics.get_registry().counter(
        "trace.storm.synthetic",
        "synthetic traces injected by the trace_storm chaos fault kind",
    ).inc(float(n))
    return n
