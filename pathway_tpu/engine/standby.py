"""Warm-standby workers: tail the persistence root so unplanned worker
loss costs one shard promotion, not a whole-group restart.

A supervised run may spawn K standby processes beside its N workers
(``spawn --supervise --standbys K`` / ``PATHWAY_STANDBY_COUNT``).  A
standby never joins the mesh and never executes the pipeline — it sits
in :func:`standby_main`, tailing the persistence root: every tick it
re-lists each worker's generation manifests and deep-verifies any newly
committed generation (``verify_manifest`` — the PR-2 verify-on-read
machinery), warming its verify cache and the OS page cache with exactly
the artifacts a resume of that shard would read.  Its progress is
published as an apply-cursor beacon (``lease/standby.<sid>``: newest
verified generation per worker + apply lag), which ``pathway_tpu
scrub``/``top`` render and the workers re-export as ``standby.lag.s``.

On a worker death the supervisor posts a PROMOTE request naming one
standby (see ``engine/supervisor.py``).  The chosen standby acks,
adopts the dead worker's identity — process id, per-worker fence token
(``bump_worker_fence``), topology — and returns from
:func:`standby_main` into the normal worker boot path
(``internals/runner.py``), resuming the dead shard from its committed
generations.  Because the tail loop already verified (and page-cached)
everything up to the last commit, the promotion replays only the
uncommitted tail: sub-second where a whole-group restart pays backoff +
full resume.

Everything here is FileBackend/filesystem-root coordination, exactly
like the live-handoff machinery it mirrors; faults ``standby_lag`` and
``promote_crash`` (``engine/faults.py``) inject a starved standby and a
mid-promotion death.
"""

from __future__ import annotations

import logging
import os
import signal
import time as _time

from pathway_tpu.engine import faults as _faults
from pathway_tpu.engine import flight_recorder as _blackbox
from pathway_tpu.engine import persistence as pz

logger = logging.getLogger(__name__)


def standby_id() -> int | None:
    """This process's standby ordinal (``PATHWAY_STANDBY_ID``, exported by
    the supervisor), or None for a normal worker."""
    from pathway_tpu.internals.config import env_int, env_raw

    if env_raw("PATHWAY_STANDBY_ID") is None:
        return None
    return env_int("PATHWAY_STANDBY_ID")


class StandbyTailer:
    """The tail loop's state: per-worker apply cursors + verify cache."""

    def __init__(self, root: str, standby: int):
        self.root = root
        self.standby = standby
        self.backend = pz.FileBackend(root)
        # newest deep-verified generation per worker shard — the apply
        # cursor the beacon publishes and a promotion resumes beyond
        self.cursors: dict[int, int] = {}
        self.verified_chunks = 0
        self.lag_s = 0.0
        self._verify_cache: set[str] = set()

    def tick(self) -> None:
        """One tail pass: verify every generation committed since the
        cursors, then refresh the beacon.  Damage is logged and skipped —
        a standby is an observer; resume-time fallback (and scrub) own
        the damaged-generation story.

        ``lag_s`` is measured at the top of the pass — the age of the
        OLDEST generation committed but not yet verified — so a starved
        standby (``standby_lag`` fault, a slow store) publishes its real
        apply lag rather than 0 the instant it finally catches up."""
        _faults.maybe_standby_lag(standby=self.standby)
        pending: list[tuple[int, int, str]] = []
        oldest_at: float | None = None
        for worker, gens in self._scan().items():
            cursor = self.cursors.get(worker, 0)
            for gen, key in gens:
                if gen <= cursor:
                    continue
                pending.append((worker, gen, key))
                at = self._mtime(key)
                if at is not None:
                    oldest_at = at if oldest_at is None else min(oldest_at, at)
        self.lag_s = (
            max(0.0, _time.time() - oldest_at) if oldest_at is not None
            else 0.0
        )
        held: set[int] = set()
        for worker, gen, key in pending:
            if worker in held:
                continue  # an earlier generation of this worker failed
            manifest, reason = pz._read_manifest(self.backend, key)
            problems = (
                [reason or "manifest unreadable"] if manifest is None
                else pz.verify_manifest(
                    self.backend, worker, manifest,
                    cache=self._verify_cache,
                )
            )
            if problems:
                logger.warning(
                    "standby %d: worker %d generation %d failed "
                    "verification (%s); holding cursor", self.standby,
                    worker, gen, "; ".join(problems[:3]),
                )
                held.add(worker)
                continue
            self.verified_chunks += sum(
                int(meta.get("chunks", 0)) - int(meta.get("chunk_start", 0))
                for meta in (manifest.get("sources") or {}).values()
            )
            self.cursors[worker] = gen
        pz.write_standby_beacon(
            self.root,
            self.standby,
            cursors=self.cursors,
            lag_s=round(self.lag_s, 3),
            verified_chunks=self.verified_chunks,
        )

    def _scan(self) -> dict[int, list[tuple[int, str]]]:
        """{worker: [(generation, key) oldest-first]} for every manifest
        on the root."""
        out: dict[int, list[tuple[int, str]]] = {}
        for key in self.backend.list_keys("manifests/"):
            parts = key.split("/")
            if len(parts) == 3 and parts[1].isdigit() and parts[2].isdigit():
                out.setdefault(int(parts[1]), []).append((int(parts[2]), key))
        for entries in out.values():
            entries.sort()
        return out

    def _mtime(self, key: str) -> float | None:
        try:
            return os.path.getmtime(os.path.join(self.root, *key.split("/")))
        except OSError:
            return None


def state_metrics(root: str) -> dict[str, float]:
    """Numeric ``standby.*`` / ``supervisor.promotions`` gauges derived
    from the root's beacons + promotion history — the registry collector
    each worker registers so the warm-standby panel rides /status,
    /metrics and ``pathway_tpu top`` without new plumbing (the
    supervisor's own registry serves no scrape endpoint)."""
    beacons = pz.read_standby_beacons(root)
    promotions = pz.read_promotions(root)
    if not beacons and not promotions:
        return {}
    out: dict[str, float] = {
        "standby.pool": float(len(beacons)),
        "supervisor.promotions": float(len(promotions)),
    }
    for sid, beacon in sorted(beacons.items()):
        out[f"standby.lag.s{{standby={sid}}}"] = float(
            beacon.get("lag_s") or 0.0
        )
        out[f"standby.verified.chunks{{standby={sid}}}"] = float(
            beacon.get("verified_chunks") or 0
        )
    if promotions:
        last = promotions[-1]
        if isinstance(last.get("worker"), int):
            out["supervisor.promotions.last.worker"] = float(last["worker"])
    return out


def _await_survivor_acks(root: str, req: dict) -> bool:
    """Block until every SURVIVOR has acked promotion ``req`` — i.e. has
    drained its old mesh and is about to rejoin — so the adopting standby
    never dials listeners that still belong to the dying mesh.  Returns
    False when the request is cleared/replaced while waiting (the
    supervisor aborted: fall back to tailing); the supervisor's promote
    deadline bounds the wait from outside."""
    survivors = [w for w in range(req["workers"]) if w != req["worker"]]
    while True:
        acks = pz.read_promote_acks(root, req["workers"])
        if all(
            str(w) in acks and acks[str(w)].get("seq") == req["seq"]
            for w in survivors
        ):
            return True
        live = pz.read_promote_request(root)
        if live is None or live["seq"] != req["seq"]:
            return False
        # bounded 0.05 s poll; the supervisor's promote deadline ends a
        # wedged wait from outside
        _time.sleep(0.05)


def standby_main(root: str, standby: int) -> dict | None:
    """Run the standby tail loop until promoted or told to stop.

    Returns the PROMOTE request dict once this standby has acked it and
    adopted the dead worker's identity (``PATHWAY_PROCESS_ID`` /
    ``PATHWAY_WORKER_FENCE`` / ``PATHWAY_PROCESSES`` re-exported, config
    refreshed) — the caller then falls into the normal worker boot path.
    Returns None on a SIGTERM/SIGINT stop request (supervisor shutdown).
    """
    from pathway_tpu.internals.config import env_float, refresh_config

    poll_s = max(0.05, env_float("PATHWAY_STANDBY_POLL_S"))
    tailer = StandbyTailer(root, standby)
    stop = {"flag": False}

    def _on_stop(signum: int, frame: object) -> None:
        stop["flag"] = True

    prior = {
        sig: signal.signal(sig, _on_stop)
        for sig in (signal.SIGTERM, signal.SIGINT)
    }
    logger.info("standby %d tailing %s (poll %.2fs)", standby, root, poll_s)
    _blackbox.record("standby.start", standby=standby, root=root)
    try:
        next_tick = 0.0
        while not stop["flag"]:
            now = _time.monotonic()
            if now >= next_tick:
                try:
                    tailer.tick()
                except OSError as exc:
                    logger.warning(
                        "standby %d: tail tick failed (%s); retrying",
                        standby, exc,
                    )
                next_tick = now + poll_s
            req = pz.read_promote_request(root)
            if (
                req is not None
                and req["standby"] == standby
                and req["incarnation"] == pz.writer_incarnation()
            ):
                pz.write_promote_ack(
                    root, "standby", seq=req["seq"], worker=req["worker"],
                    incarnation=req["incarnation"],
                )
                # wait for every survivor's drained-and-rejoining ack
                # before binding the dead worker's port: their OLD mesh
                # listeners must be gone before this process dials
                if not _await_survivor_acks(root, req):
                    logger.warning(
                        "standby %d: promotion %d aborted by the "
                        "supervisor while awaiting survivors; resuming "
                        "tail", standby, req["seq"],
                    )
                    continue
                # adopt the dead worker's identity; every config read
                # after refresh_config() sees the promoted topology
                os.environ["PATHWAY_PROCESS_ID"] = str(req["worker"])
                os.environ["PATHWAY_WORKER_FENCE"] = str(req["fence"])
                os.environ["PATHWAY_PROCESSES"] = str(req["workers"])
                os.environ.pop("PATHWAY_STANDBY_ID", None)
                refresh_config()
                # the adopted marker is the supervisor's completion
                # trigger: written strictly after the survivor wait, so
                # the supervisor clearing the promote files can never
                # race this standby's own reads of them
                pz.write_promote_ack(
                    root, "adopted", seq=req["seq"], worker=req["worker"],
                    incarnation=req["incarnation"],
                )
                # the narrowest promote_crash window: ack durable, fence
                # bumped, nothing published yet as the new worker id
                _faults.maybe_crash_promote(
                    standby=standby, worker=req["worker"]
                )
                _blackbox.record(
                    "standby.promoted", standby=standby,
                    worker=req["worker"], seq=req["seq"],
                    fence=req["fence"], lag_s=tailer.lag_s,
                )
                logger.info(
                    "standby %d promoted to worker %d (promotion %d, "
                    "fence %d)", standby, req["worker"], req["seq"],
                    req["fence"],
                )
                return req
            # promote-watch poll, bounded at 0.05 s so a PROMOTE request
            # is seen sub-tick
            _time.sleep(0.05)
    finally:
        for sig, handler in prior.items():
            signal.signal(sig, handler)
    _blackbox.record("standby.stop", standby=standby)
    logger.info("standby %d stopping (supervisor shutdown)", standby)
    return None
