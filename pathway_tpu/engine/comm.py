"""Host-side worker communication: the TCP exchange mesh.

Parity target: timely's communication crate — zero-copy TCP allocator and
exchange channels routed by key shard
(``external/timely-dataflow/communication/src/allocator/zero_copy/tcp.rs``,
``src/engine/dataflow.rs:1414``).  The design here is different and much
smaller because the engine is epoch-batched (BSP), not asynchronous
record-at-a-time dataflow:

* every process runs the identical script → identical operator DAG, so
  node ids agree across workers (the SPMD invariant of
  ``docs/.../10.worker-architecture.md:36-43``);
* each epoch is a superstep: workers agree on the epoch time (worker 0
  sequences), then walk the DAG in the same topological order, performing
  one all-to-all per exchange point;
* routing is by the 16-bit shard field of the 128-bit row key —
  ``shard_to_worker(key, n)`` — exactly the reference's rule.

Wire format: a mutual HMAC-SHA256 handshake (shared secret from
``PATHWAY_COMM_SECRET``; ``cli spawn`` generates a fresh one per run), then
8-byte big-endian length + PWT1-typed ``(tag, payload)`` frames — the same
typed codec the persistence layer uses (``engine/codec.py``, native-
accelerated), matching the reference's typed bincode exchange
(``zero_copy/tcp.rs``) rather than trusting arbitrary object streams.
Unauthenticated or malformed peers are rejected before any frame decode.
Everything rides localhost/DCN TCP; dense device state never crosses here
(it lives in HBM and moves over ICI via XLA collectives — see
``pathway_tpu/parallel/``).
"""

from __future__ import annotations

import hmac as _hmac
import os
import secrets as _secrets
import socket
import struct
import threading
import time
from collections import defaultdict, deque
from typing import Any, Callable, Hashable

from pathway_tpu.engine import codec as _codec
from pathway_tpu.engine.types import shard_to_worker

_FRAME = struct.Struct(">Q")
CONNECT_TIMEOUT_S = 60.0
RECV_TIMEOUT_S = 300.0
HANDSHAKE_TIMEOUT_S = 10.0
# frame-size cap: a corrupt or hostile length field must not OOM the
# worker.  256 MiB default comfortably covers real epoch batches (tune via
# PATHWAY_COMM_MAX_FRAME_MB for enormous-epoch deployments).
MAX_FRAME_BYTES = (
    int(os.environ.get("PATHWAY_COMM_MAX_FRAME_MB", "256") or "256") << 20
)

_MAGIC = b"PWC1"
_NONCE = 16
_TAG = 32  # HMAC-SHA256


class CommError(RuntimeError):
    pass


def _resolve_secret(secret: bytes | str | None) -> bytes:
    """Shared handshake secret: explicit arg, else PATHWAY_COMM_SECRET
    (``cli spawn`` mints one per run).  Deliberately NOT the run id — the
    monitoring endpoints publish it, so it cannot double as an auth token.

    With an empty secret the handshake still runs (frames stay typed and
    framed) but offers no authentication, so frame decode additionally
    refuses pickled values (``decode_row_typed``) — set
    PATHWAY_COMM_SECRET for any mesh that crosses a machine boundary.
    """
    if secret is None:
        secret = os.environ.get("PATHWAY_COMM_SECRET", "")
    if isinstance(secret, str):
        secret = secret.encode()
    return secret


def _auth_tag(secret: bytes, role: bytes, a: bytes, b: bytes) -> bytes:
    return _hmac.new(secret, role + _MAGIC + a + b, "sha256").digest()


def _handshake_dial(sock: socket.socket, my_id: int, secret: bytes) -> None:
    """Dialer side: send hello, verify listener's proof, send ours."""
    nonce_d = _secrets.token_bytes(_NONCE)
    sock.sendall(_MAGIC + _FRAME.pack(my_id) + nonce_d)
    reply = _recv_exact(sock, _NONCE + _TAG)
    nonce_l, tag_l = reply[:_NONCE], reply[_NONCE:]
    if not _hmac.compare_digest(tag_l, _auth_tag(secret, b"l", nonce_d, nonce_l)):
        raise CommError("handshake failed: listener authentication mismatch")
    sock.sendall(_auth_tag(secret, b"d", nonce_l, nonce_d))


def _handshake_accept(sock: socket.socket, secret: bytes) -> int:
    """Listener side: verify dialer; returns the peer worker id."""
    hello = _recv_exact(sock, len(_MAGIC) + _FRAME.size + _NONCE)
    if hello[: len(_MAGIC)] != _MAGIC:
        raise CommError("handshake failed: bad magic")
    (peer,) = _FRAME.unpack(hello[len(_MAGIC) : len(_MAGIC) + _FRAME.size])
    nonce_d = hello[len(_MAGIC) + _FRAME.size :]
    nonce_l = _secrets.token_bytes(_NONCE)
    sock.sendall(nonce_l + _auth_tag(secret, b"l", nonce_d, nonce_l))
    tag_d = _recv_exact(sock, _TAG)
    if not _hmac.compare_digest(tag_d, _auth_tag(secret, b"d", nonce_l, nonce_d)):
        raise CommError("handshake failed: dialer authentication mismatch")
    return peer


def _encode_frame(tag: Hashable, payload: Any) -> bytes:
    blob = _codec.encode_row((tag, payload))
    return _FRAME.pack(len(blob)) + blob


def _decode_frame(blob: bytes, typed_only: bool) -> tuple[Hashable, Any]:
    if typed_only:
        row, _pos = _codec.decode_row_typed(blob)
    else:
        row, _pos = _codec.decode_row(blob)
    if len(row) != 2:
        raise ValueError(f"comm frame: expected (tag, payload), got {len(row)} values")
    return row[0], row[1]


class TcpMesh:
    """Full mesh of TCP links between N worker processes.

    Worker ``i`` listens on ``first_port + i``; workers with higher ids dial
    workers with lower ids, so every pair has exactly one duplex link.
    A reader thread per link demultiplexes frames into per-(src, tag) queues.
    """

    def __init__(
        self,
        worker_id: int,
        worker_count: int,
        first_port: int,
        host: str = "127.0.0.1",
        peer_hosts: list[str] | None = None,
        secret: bytes | str | None = None,
    ):
        self.worker_id = worker_id
        self.worker_count = worker_count
        self.first_port = first_port
        self.host = host
        self.secret = _resolve_secret(secret)
        # multi-host deployments (one process per k8s pod / TPU host):
        # peer_hosts[i] is worker i's hostname; ports stay first_port+i so
        # the same config also works on localhost
        if peer_hosts is not None and len(peer_hosts) != worker_count:
            raise CommError(
                f"peer_hosts has {len(peer_hosts)} entries for "
                f"{worker_count} workers"
            )
        self.peer_hosts = peer_hosts
        self._socks: dict[int, socket.socket] = {}
        self._send_locks: dict[int, threading.Lock] = {}
        self._inbox: dict[tuple[int, Hashable], deque] = defaultdict(deque)
        self._cv = threading.Condition()
        self._closed = False
        self._threads: list[threading.Thread] = []
        self._listener: socket.socket | None = None

    # -- setup -----------------------------------------------------------
    def start(self) -> "TcpMesh":
        if self.worker_count <= 1:
            return self
        listen_host = "" if self.peer_hosts is not None else self.host
        self._listener = socket.create_server(
            (listen_host, self.first_port + self.worker_id), reuse_port=False
        )
        self._listener.settimeout(CONNECT_TIMEOUT_S)
        accept_from = [w for w in range(self.worker_count) if w > self.worker_id]
        dial_to = [w for w in range(self.worker_count) if w < self.worker_id]

        accepted: dict[int, socket.socket] = {}
        acc_err: list[BaseException] = []

        acc_lock = threading.Lock()
        acc_done = threading.Event()

        def handshake_one(sock: socket.socket) -> None:
            # per-connection thread: a stalled or malicious client burns
            # only its own HANDSHAKE_TIMEOUT_S, never the accept loop
            try:
                sock.settimeout(HANDSHAKE_TIMEOUT_S)
                peer = _handshake_accept(sock, self.secret)
                with acc_lock:
                    if peer not in accept_from or peer in accepted:
                        raise CommError(f"unexpected peer id {peer}")
                    sock.settimeout(None)
                    accepted[peer] = sock
                    if len(accepted) == len(accept_from):
                        acc_done.set()
            except (CommError, OSError, EOFError):
                try:
                    sock.close()
                except OSError:
                    pass

        def accept_loop():
            # a connection that fails the handshake (port scanner, stray
            # client, wrong secret) is dropped and accepting continues;
            # only listener-socket errors abort the loop
            try:
                while not acc_done.is_set():
                    try:
                        sock, _addr = self._listener.accept()
                    except TimeoutError:
                        break  # start() reports which peers are missing
                    threading.Thread(
                        target=handshake_one, args=(sock,), daemon=True
                    ).start()
            except BaseException as exc:  # noqa: BLE001 — re-raised by start()
                acc_err.append(exc)

        if not accept_from:
            acc_done.set()
        acceptor = threading.Thread(target=accept_loop, daemon=True)
        acceptor.start()

        for peer in dial_to:
            peer_host = (
                self.peer_hosts[peer] if self.peer_hosts is not None else self.host
            )
            self._socks[peer] = _dial(
                peer_host, self.first_port + peer, self.worker_id, self.secret
            )

        # wait on the completion event, not the thread: the acceptor may
        # still be blocked in accept() (it lingers as a daemon rejecting
        # stray connections until close() shuts the listener)
        done = acc_done.wait(CONNECT_TIMEOUT_S)
        if acc_err:
            raise CommError(f"worker {self.worker_id}: accept failed: {acc_err[0]}")
        if not done or len(accepted) != len(accept_from):
            raise CommError(
                f"worker {self.worker_id}: timed out waiting for peers "
                f"{sorted(set(accept_from) - set(accepted))}"
            )
        self._socks.update(accepted)

        for peer, sock in self._socks.items():
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._send_locks[peer] = threading.Lock()
            t = threading.Thread(
                target=self._reader, args=(peer, sock), daemon=True,
                name=f"pathway:comm-{self.worker_id}<-{peer}",
            )
            t.start()
            self._threads.append(t)
        return self

    def _reader(self, peer: int, sock: socket.socket) -> None:
        try:
            while not self._closed:
                header = _recv_exact(sock, _FRAME.size)
                (size,) = _FRAME.unpack(header)
                if size > MAX_FRAME_BYTES:
                    raise ValueError(f"comm frame of {size} bytes exceeds cap")
                blob = _recv_exact(sock, size)
                # no shared secret = unauthenticated link: refuse pickled
                # values so a reachable port is not code execution
                tag, payload = _decode_frame(blob, typed_only=not self.secret)
                with self._cv:
                    self._inbox[(peer, tag)].append(payload)
                    self._cv.notify_all()
        except Exception as exc:  # noqa: BLE001
            # socket errors AND decode errors land here: a malformed or
            # corrupt frame means framing is lost and the link is unusable,
            # so any failure is treated exactly like a dead peer (the
            # waiting recv() raises CommError; the process survives).
            # Decode refusals are logged — "peer disconnected" alone would
            # hide e.g. the typed-only pickle refusal and its remedy.
            if isinstance(exc, ValueError):
                import logging

                logging.getLogger("pathway_tpu.comm").error(
                    "worker %d: dropping link to peer %d: %s",
                    self.worker_id,
                    peer,
                    exc,
                )
            if not self._closed:
                with self._cv:
                    self._inbox[(peer, _PEER_DEAD)].append(None)
                    self._cv.notify_all()

    # -- point to point --------------------------------------------------
    def send(self, dest: int, tag: Hashable, payload: Any) -> None:
        if dest == self.worker_id:
            # the codec round-trips every value shape exactly (lists stay
            # lists, wrappers stay wrapped), so a self-send can skip it
            with self._cv:
                self._inbox[(dest, tag)].append(payload)
                self._cv.notify_all()
            return
        frame = _encode_frame(tag, payload)
        if len(frame) > MAX_FRAME_BYTES:
            # fail fast on the sender with the actionable message — the
            # receiver would just drop the link as "peer disconnected"
            raise CommError(
                f"comm frame of {len(frame)} bytes exceeds the "
                f"{MAX_FRAME_BYTES}-byte cap; raise PATHWAY_COMM_MAX_FRAME_MB "
                "on every worker for enormous-epoch workloads"
            )
        sock = self._socks[dest]
        with self._send_locks[dest]:
            sock.sendall(frame)

    def recv(self, src: int, tag: Hashable, timeout: float = RECV_TIMEOUT_S) -> Any:
        deadline = time.monotonic() + timeout
        with self._cv:
            while True:
                q = self._inbox.get((src, tag))
                if q:
                    payload = q.popleft()
                    if not q:
                        self._inbox.pop((src, tag), None)
                    return payload
                if self._inbox.get((src, _PEER_DEAD)):
                    raise CommError(
                        f"worker {self.worker_id}: peer {src} disconnected "
                        f"while waiting for {tag!r}"
                    )
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise CommError(
                        f"worker {self.worker_id}: timeout waiting for "
                        f"{tag!r} from worker {src}"
                    )
                self._cv.wait(min(remaining, 1.0))

    # -- collectives -----------------------------------------------------
    def alltoall(self, tag: Hashable, per_dest: list[list]) -> list:
        """Send ``per_dest[w]`` to worker ``w``; return concatenation of what
        every worker sent here (own bucket included), ordered by worker id."""
        for w in range(self.worker_count):
            if w != self.worker_id:
                self.send(w, tag, per_dest[w])
        merged: list = []
        for w in range(self.worker_count):
            if w == self.worker_id:
                merged.extend(per_dest[w])
            else:
                merged.extend(self.recv(w, tag))
        return merged

    def gather(self, tag: Hashable, payload: Any, root: int = 0) -> list | None:
        """Root returns [payload per worker, ordered]; others return None."""
        if self.worker_id == root:
            out = []
            for w in range(self.worker_count):
                out.append(payload if w == root else self.recv(w, tag))
            return out
        self.send(root, tag, payload)
        return None

    def bcast(self, tag: Hashable, payload: Any = None, root: int = 0) -> Any:
        if self.worker_id == root:
            for w in range(self.worker_count):
                if w != root:
                    self.send(w, tag, payload)
            return payload
        return self.recv(root, tag)

    def barrier(self, tag: Hashable) -> None:
        self.gather(("barrier", tag), None)
        self.bcast(("barrier-go", tag))

    def close(self) -> None:
        self._closed = True
        for sock in self._socks.values():
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass


_PEER_DEAD = ("__peer_dead__",)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(n - got)
        if not chunk:
            raise EOFError("peer closed")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def _dial(host: str, port: int, my_id: int, secret: bytes) -> socket.socket:
    deadline = time.monotonic() + CONNECT_TIMEOUT_S
    last: Exception | None = None
    while time.monotonic() < deadline:
        try:
            sock = socket.create_connection((host, port), timeout=5.0)
        except OSError as exc:
            last = exc
            time.sleep(0.1)
            continue
        try:
            sock.settimeout(HANDSHAKE_TIMEOUT_S)
            _handshake_dial(sock, my_id, secret)
            sock.settimeout(None)
            return sock
        except CommError:
            # auth mismatch is fatal, not retryable: the peer is alive but
            # holds a different secret
            sock.close()
            raise
        except (OSError, EOFError) as exc:
            # listener may have dropped us mid-handshake during startup
            # races — retry like a refused connection
            sock.close()
            last = exc
            time.sleep(0.1)
    raise CommError(f"could not reach worker at {host}:{port}: {last}")


class WorkerContext:
    """Per-process view of the worker group, driving exchange + epochs.

    ``exchange_node`` implements the reference's exchange-before-stateful-
    operator pattern: contributions are routed to the worker that owns the
    key the operator's state is sharded on (``dataflow.rs:1414``,
    ``shard.rs:15-20``).  Nodes declare ownership via ``exchange_routes``
    (port → routing-key fn) or ``exchange_gather0`` (all rows to worker 0,
    for globally-ordered operators: sort, iterate, external index).
    """

    def __init__(self, mesh: TcpMesh):
        self.mesh = mesh
        self.worker_id = mesh.worker_id
        self.worker_count = mesh.worker_count

    def owner_of(self, routing_key: int) -> int:
        return shard_to_worker(routing_key, self.worker_count)

    def exchange_deltas(
        self,
        tag: Hashable,
        deltas: list,
        route: Callable[[int, Any], int] | None,
    ) -> list:
        """All-to-all one delta list. ``route(key, row) -> routing key``;
        ``None`` routes by the row key itself."""
        per_dest: list[list] = [[] for _ in range(self.worker_count)]
        for key, row, diff in deltas:
            if route is None:
                rk = key
            else:
                try:
                    rk = route(key, row)
                except Exception:
                    rk = key  # poisoned rows resolve locally; the node's own
                    # step reports the error through the error log
            per_dest[self.owner_of(rk)].append((key, row, diff))
        return self.mesh.alltoall(tag, per_dest)

    def gather0_deltas(self, tag: Hashable, deltas: list) -> list:
        per_dest: list[list] = [[] for _ in range(self.worker_count)]
        per_dest[0] = list(deltas)
        return self.mesh.alltoall(tag, per_dest)

    def exchange_node(self, node: Any, time_: int) -> None:
        """Pre-step exchange for one operator (same call order on every
        worker — the DAG is identical, so collectives pair up)."""
        routes = getattr(node, "exchange_routes", None)
        gather0 = getattr(node, "exchange_gather0", False)
        if routes is None and not gather0:
            return
        n_ports = len(node.inputs) if node.inputs else 1
        for port in range(n_ports):
            pending = node.pending.pop(port, [])
            tag = ("x", node.id, port, time_)
            if gather0:
                merged = self.gather0_deltas(tag, pending)
            else:
                route = routes.get(port) if routes else None
                if route is None and routes is not None and port not in routes:
                    # port not exchanged (already co-located) — but peers
                    # still ran alltoall for declared ports only, so skip
                    node.pending[port] = pending
                    continue
                merged = self.exchange_deltas(tag, pending, route)
            if merged:
                node.pending[port] = merged

    def close(self) -> None:
        self.mesh.close()
