"""Host-side worker communication: the TCP exchange mesh.

Parity target: timely's communication crate — zero-copy TCP allocator and
exchange channels routed by key shard
(``external/timely-dataflow/communication/src/allocator/zero_copy/tcp.rs``,
``src/engine/dataflow.rs:1414``).  The design here is different and much
smaller because the engine is epoch-batched (BSP), not asynchronous
record-at-a-time dataflow:

* every process runs the identical script → identical operator DAG, so
  node ids agree across workers (the SPMD invariant of
  ``docs/.../10.worker-architecture.md:36-43``);
* each epoch is a superstep: workers agree on the epoch time (worker 0
  sequences), then walk the DAG in the same topological order, performing
  one all-to-all per exchange point;
* routing is by the 16-bit shard field of the 128-bit row key —
  ``shard_to_worker(key, n)`` — exactly the reference's rule.

Wire format: a mutual HMAC-SHA256 handshake (shared secret from
``PATHWAY_COMM_SECRET``; ``cli spawn`` generates a fresh one per run), a
16-byte **resume header** ``(incarnation, last-seq-received)``, then
16-byte ``(length, sequence)`` headers framing PWT1-typed ``(tag,
payload)`` bodies — the same typed codec the persistence layer uses
(``engine/codec.py``, native-accelerated), matching the reference's typed
bincode exchange rather than trusting arbitrary object streams.
Unauthenticated or malformed peers are rejected before any frame decode.

Fault tolerance (see ``docs/fault_tolerance.md``): a transient link
failure — TCP reset, dropped/corrupted frame — no longer poisons the
mesh.  Every link keeps a bounded retransmit buffer of unacknowledged
frames; heartbeat frames piggyback cumulative acks and detect hung peers;
a failed link reconnects with bounded exponential backoff + jitter (the
``udfs`` retry schedule — one backoff policy for the whole codebase) and
resynchronizes from the peer's last received sequence number, so deltas
are delivered exactly once across the reconnect.  Only when the reconnect
window is exhausted, or the peer comes back as a **new incarnation**
(respawned process), is the peer declared dead — the supervisor
(``engine/supervisor.py``) then restarts the cluster from the last
committed checkpoint.  Everything rides localhost/DCN TCP; dense device
state never crosses here (it lives in HBM and moves over ICI via XLA
collectives — see ``pathway_tpu/parallel/``).
"""

from __future__ import annotations

import hmac as _hmac
import itertools as _itertools
import logging
import math
import os
import secrets as _secrets
import socket
import struct
import threading
import time
from collections import defaultdict, deque
from typing import Any, Callable, Hashable

from pathway_tpu.engine import codec as _codec
from pathway_tpu.engine import faults as _faults
from pathway_tpu.engine import flight_recorder as _blackbox
from pathway_tpu.engine import metrics as _metrics
from pathway_tpu.engine.types import SHARD_BITS, shard_to_worker

_log = logging.getLogger("pathway_tpu.comm")

_FRAME = struct.Struct(">Q")  # handshake worker id / heartbeat ack body
_HDR = struct.Struct(">QQ")  # (payload length, sequence); sequence 0 = control
_RESUME = struct.Struct(">QQ")  # (incarnation, last sequence received from you)


def _env_float(name: str, default: float) -> float:
    from pathway_tpu.internals.config import env_float

    return env_float(name, default)


CONNECT_TIMEOUT_S = 60.0
# receive timeout default; per-mesh override via PATHWAY_COMM_RECV_TIMEOUT_S
# (read at mesh construction, like the frame cap below)
RECV_TIMEOUT_S = 300.0
HANDSHAKE_TIMEOUT_S = 10.0
# liveness + recovery tunables (all per-mesh, env-overridable):
HEARTBEAT_INTERVAL_S = 2.0  # PATHWAY_COMM_HEARTBEAT_S
HEARTBEAT_TIMEOUT_S = 30.0  # PATHWAY_COMM_HEARTBEAT_TIMEOUT_S
RECONNECT_WINDOW_S = 15.0  # PATHWAY_COMM_RECONNECT_WINDOW_S
SEND_BUFFER_MB = 64  # PATHWAY_COMM_SEND_BUFFER_MB
# PATHWAY_COMM_SEND_DEADLINE_S — deadline on any single blocking socket
# write (SO_SNDTIMEO): a hung peer with a full TCP buffer can otherwise
# park a data-phase sendall forever WHILE it holds send_lock.  0 disables.
# Defaults to the (possibly env-overridden) heartbeat timeout — a peer
# that cannot drain one frame for that long is treated exactly like one
# that stopped acking — so there is no separate module constant.
# frame-size cap: a corrupt or hostile length field must not OOM the
# worker.  256 MiB default comfortably covers real epoch batches (tune via
# PATHWAY_COMM_MAX_FRAME_MB for enormous-epoch deployments).
from pathway_tpu.internals.config import env_int as _env_int  # noqa: E402

MAX_FRAME_BYTES = _env_int("PATHWAY_COMM_MAX_FRAME_MB") << 20

_MAGIC = b"PWC1"
_NONCE = 16
_TAG = 32  # HMAC-SHA256


class CommError(RuntimeError):
    pass


class MeshPoisoned(CommError):
    """This mesh was deliberately abandoned (``TcpMesh.poison``): a peer
    died and the supervisor is promoting a warm standby into its worker
    id, so every surviving worker must leave its blocked collectives NOW
    and rejoin a fresh mesh in-process — waiting out heartbeat timeouts
    (or the reconnect window) on a peer that will come back as a NEW
    process would turn a sub-second promotion into a multi-second stall
    or, worse, a whole-group restart."""


def _resolve_secret(secret: bytes | str | None) -> bytes:
    """Shared handshake secret: explicit arg, else PATHWAY_COMM_SECRET
    (``cli spawn`` mints one per run).  Deliberately NOT the run id — the
    monitoring endpoints publish it, so it cannot double as an auth token.

    With an empty secret the handshake still runs (frames stay typed and
    framed) but offers no authentication, so frame decode additionally
    refuses pickled values (``decode_row_typed``) — set
    PATHWAY_COMM_SECRET for any mesh that crosses a machine boundary.
    """
    if secret is None:
        from pathway_tpu.internals.config import env_str

        secret = env_str("PATHWAY_COMM_SECRET")
    if isinstance(secret, str):
        secret = secret.encode()
    return secret


def _auth_tag(secret: bytes, role: bytes, a: bytes, b: bytes) -> bytes:
    return _hmac.new(secret, role + _MAGIC + a + b, "sha256").digest()


def _handshake_dial(sock: socket.socket, my_id: int, secret: bytes) -> None:
    """Dialer side: send hello, verify listener's proof, send ours."""
    nonce_d = _secrets.token_bytes(_NONCE)
    sock.sendall(_MAGIC + _FRAME.pack(my_id) + nonce_d)
    reply = _recv_exact(sock, _NONCE + _TAG)
    nonce_l, tag_l = reply[:_NONCE], reply[_NONCE:]
    if not _hmac.compare_digest(tag_l, _auth_tag(secret, b"l", nonce_d, nonce_l)):
        raise CommError("handshake failed: listener authentication mismatch")
    sock.sendall(_auth_tag(secret, b"d", nonce_l, nonce_d))


def _handshake_accept(sock: socket.socket, secret: bytes) -> int:
    """Listener side: verify dialer; returns the peer worker id."""
    hello = _recv_exact(sock, len(_MAGIC) + _FRAME.size + _NONCE)
    if hello[: len(_MAGIC)] != _MAGIC:
        raise CommError("handshake failed: bad magic")
    (peer,) = _FRAME.unpack(hello[len(_MAGIC) : len(_MAGIC) + _FRAME.size])
    nonce_d = hello[len(_MAGIC) + _FRAME.size :]
    nonce_l = _secrets.token_bytes(_NONCE)
    sock.sendall(nonce_l + _auth_tag(secret, b"l", nonce_d, nonce_l))
    tag_d = _recv_exact(sock, _TAG)
    if not _hmac.compare_digest(tag_d, _auth_tag(secret, b"d", nonce_l, nonce_d)):
        raise CommError("handshake failed: dialer authentication mismatch")
    return peer


def _encode_frame(tag: Hashable, payload: Any) -> bytes:
    """Legacy 8-byte-length framing, kept for the wire-security tests that
    hand-craft malformed frames; mesh traffic uses ``(length, seq)``
    headers (``_HDR``) stamped in :meth:`TcpMesh.send`."""
    blob = _codec.encode_row((tag, payload))
    return _FRAME.pack(len(blob)) + blob


def _decode_frame(blob: bytes, typed_only: bool) -> tuple[Hashable, Any]:
    if typed_only:
        row, _pos = _codec.decode_row_typed(blob)
    else:
        row, _pos = _codec.decode_row(blob)
    if len(row) != 2:
        raise ValueError(f"comm frame: expected (tag, payload), got {len(row)} values")
    return row[0], row[1]


class _Link:
    """Per-peer duplex link state.

    Locking: ``cv`` guards connection state (sock/gen/ready/dead/
    relinking/recv_seq/last_seen/peer incarnation) and is the condition
    senders and reconnect threads wait on; ``send_lock`` serializes socket
    writes and guards send-side state (send_seq, retransmit buffer).  The
    two are never held nested.
    """

    __slots__ = (
        "peer", "sock", "gen", "ready", "dead", "relinking",
        "relink_deadline", "cv", "send_lock", "send_seq", "sent_buf",
        "sent_bytes", "evicted_seq", "unacked_since", "recv_seq",
        "peer_inc", "last_seen",
    )

    def __init__(self, peer: int):
        self.peer = peer
        self.sock: socket.socket | None = None
        self.gen = 0  # bumped on every (re)attach; stale readers check it
        self.ready = False
        self.dead = False
        self.relinking = False
        self.relink_deadline: float | None = None
        self.cv = threading.Condition()
        self.send_lock = threading.Lock()
        self.send_seq = 0
        self.sent_buf: deque[tuple[int, bytes]] = deque()  # (seq, wire)
        self.sent_bytes = 0
        # highest sequence ever evicted unacked from the buffer: a resync
        # is lossless iff the peer already holds everything up to here
        self.evicted_seq = 0
        self.unacked_since: float | None = None
        self.recv_seq = 0  # highest in-order sequence received
        self.peer_inc: int | None = None  # peer process incarnation
        self.last_seen = time.monotonic()


class TcpMesh:
    """Full mesh of TCP links between N worker processes.

    Worker ``i`` listens on ``first_port + i``; workers with higher ids dial
    workers with lower ids, so every pair has exactly one duplex link.
    A reader thread per link demultiplexes frames into per-(src, tag)
    queues.  Links survive transient failures via the retransmit/resync
    protocol described in the module docstring.
    """

    def __init__(
        self,
        worker_id: int,
        worker_count: int,
        first_port: int,
        host: str = "127.0.0.1",
        peer_hosts: list[str] | None = None,
        secret: bytes | str | None = None,
    ):
        self.worker_id = worker_id
        self.worker_count = worker_count
        self.first_port = first_port
        self.host = host
        self.secret = _resolve_secret(secret)
        # incarnation-fenced handshakes: when the supervisor runs this
        # worker under an incarnation lease (PATHWAY_INCARNATION, see
        # engine/supervisor.py), the handshake secret is derived from
        # (secret, incarnation) — a zombie worker from a superseded
        # restart attempt then FAILS authentication against the respawned
        # cluster's mesh and is dropped before it can exchange a single
        # frame, mirroring the persistence-root fencing.  The base secret
        # keeps deciding typed-only decode (an incarnation number is
        # public, so it must never upgrade an unauthenticated mesh).
        self._auth_secret = self.secret
        # lazy: persistence's env parse is the single authority on what
        # counts as "this process holds an incarnation" (persistence does
        # not import comm, so the import stays one-way)
        from pathway_tpu.engine.persistence import writer_incarnation

        fence_inc = writer_incarnation()
        if self.secret and fence_inc > 0:
            self._auth_secret = _hmac.new(
                self.secret, b"incarnation:%d" % fence_inc, "sha256"
            ).digest()
        # multi-host deployments (one process per k8s pod / TPU host):
        # peer_hosts[i] is worker i's hostname; ports stay first_port+i so
        # the same config also works on localhost
        if peer_hosts is not None and len(peer_hosts) != worker_count:
            raise CommError(
                f"peer_hosts has {len(peer_hosts)} entries for "
                f"{worker_count} workers"
            )
        self.peer_hosts = peer_hosts
        # a fresh random incarnation per mesh instance: after a crash +
        # respawn the peer's resume header proves it is a NEW process, so
        # stale pre-crash frames and sequence state must be discarded
        self.incarnation = int.from_bytes(_secrets.token_bytes(8), "big") or 1
        self.recv_timeout = _env_float("PATHWAY_COMM_RECV_TIMEOUT_S", RECV_TIMEOUT_S)
        self.heartbeat_interval = _env_float(
            "PATHWAY_COMM_HEARTBEAT_S", HEARTBEAT_INTERVAL_S
        )
        self.heartbeat_timeout = _env_float(
            "PATHWAY_COMM_HEARTBEAT_TIMEOUT_S", HEARTBEAT_TIMEOUT_S
        )
        self.reconnect_window = _env_float(
            "PATHWAY_COMM_RECONNECT_WINDOW_S", RECONNECT_WINDOW_S
        )
        self.send_deadline = _env_float(
            "PATHWAY_COMM_SEND_DEADLINE_S",
            max(self.heartbeat_timeout, 1.0),
        )
        # the retransmit buffer must hold at least one max-size frame, or
        # a single legal frame would be evicted the moment it is sent and
        # any reconnect before its ack would falsely declare the peer dead
        self.send_buffer_bytes = max(
            int(_env_float("PATHWAY_COMM_SEND_BUFFER_MB", SEND_BUFFER_MB))
            << 20,
            MAX_FRAME_BYTES + _HDR.size,
        )
        plan = _faults.active_plan()
        self._fault_comm = plan is not None and plan.has(
            "comm_drop", "comm_reset", "comm_corrupt", "comm_delay"
        )
        self._links: dict[int, _Link] = {}
        self._inbox: dict[tuple[int, Hashable], deque] = defaultdict(deque)
        self._cv = threading.Condition()
        self._closed = False
        self._retiring = False  # see retire(): coordinated-teardown mode
        self._poisoned: str | None = None  # see poison(): promotion rejoin
        self._threads: list[threading.Thread] = []
        self._listener: socket.socket | None = None
        self._acceptor: threading.Thread | None = None
        self._hb_stop = threading.Event()
        self._acc_lock = threading.Lock()
        self._accepted: set[int] = set()
        self._acc_done = threading.Event()
        self._acc_err: list[BaseException] = []
        # mesh observability: registered into the process-wide registry so
        # /metrics and the OTLP exporter see comm health without touching
        # the hot path (plain counter adds; see engine/metrics.py)
        reg = _metrics.get_registry()
        wl = {"worker": worker_id}
        self._m_frames_sent = reg.counter(
            "comm.frames.sent", "mesh data frames written", **wl
        )
        self._m_bytes_sent = reg.counter(
            "comm.bytes.sent", "mesh bytes written (headers included)", **wl
        )
        self._m_frames_recv = reg.counter(
            "comm.frames.received", "mesh data frames received", **wl
        )
        self._m_bytes_recv = reg.counter(
            "comm.bytes.received", "mesh bytes received (headers included)", **wl
        )
        self._m_reconnects = reg.counter(
            "comm.reconnects", "link reconnect attempts scheduled", **wl
        )
        self._m_retransmits = reg.counter(
            "comm.retransmits", "frames re-delivered by link resyncs", **wl
        )
        self._m_evictions = reg.counter(
            "comm.retransmit.evictions",
            "unacked frames evicted from the retransmit buffer", **wl,
        )
        self._m_peers_dead = reg.counter(
            "comm.peers.dead", "peers declared dead", **wl
        )
        self._m_staleness = reg.gauge(
            "comm.heartbeat.staleness.s",
            "seconds since the quietest live peer was last heard", **wl,
        )
        # per-peer inbox depth joins the backlog.* backpressure namespace
        # (engine/freshness.py) at pull time — a receiver whose epoch loop
        # falls behind its peers shows up here, ranked against every other
        # place records wait.  WeakMethod registration: dies with the mesh.
        reg.register_collector(
            f"comm.inbox.worker{worker_id}", self._backlog_snapshot
        )

    def _reconnect_delays(self):
        """Bounded backoff schedule for link reconnects — the udfs
        ``ExponentialBackoffRetryStrategy`` (one policy codebase-wide),
        preceded by one immediate attempt."""
        from pathway_tpu.internals.udfs.retries import (
            ExponentialBackoffRetryStrategy,
        )

        strategy = ExponentialBackoffRetryStrategy(
            max_retries=12, initial_delay=50, backoff_factor=1.7, jitter_ms=50
        )
        return _itertools.chain([0.0], strategy.delays())

    # -- setup -----------------------------------------------------------
    def start(self) -> "TcpMesh":
        if self.worker_count <= 1:
            return self
        try:
            return self._start()
        except BaseException:
            # a failed start must release the listener port and every
            # half-open link — callers retry with a fresh mesh
            self.close()
            raise

    def _start(self) -> "TcpMesh":
        listen_host = "" if self.peer_hosts is not None else self.host
        self._listener = socket.create_server(
            (listen_host, self.first_port + self.worker_id), reuse_port=False
        )
        self._listener.settimeout(1.0)
        accept_from = [w for w in range(self.worker_count) if w > self.worker_id]
        dial_to = [w for w in range(self.worker_count) if w < self.worker_id]
        for w in accept_from + dial_to:
            self._links[w] = _Link(w)

        if not accept_from:
            self._acc_done.set()
        self._acceptor = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"pathway:comm-accept-{self.worker_id}",
        )
        self._acceptor.start()

        for peer in dial_to:
            sock = _dial(
                self._peer_host(peer), self.first_port + peer,
                self.worker_id, self._auth_secret,
            )
            self._attach(peer, sock)

        done = self._acc_done.wait(CONNECT_TIMEOUT_S)
        if self._acc_err:
            raise CommError(
                f"worker {self.worker_id}: accept failed: {self._acc_err[0]}"
            )
        if not done:
            with self._acc_lock:
                missing = sorted(set(accept_from) - self._accepted)
            raise CommError(
                f"worker {self.worker_id}: timed out waiting for peers "
                f"{missing}"
            )
        hb = threading.Thread(
            target=self._heartbeat_loop, daemon=True,
            name=f"pathway:comm-hb-{self.worker_id}",
        )
        hb.start()
        self._threads.append(hb)
        return self

    def _peer_host(self, peer: int) -> str:
        return self.peer_hosts[peer] if self.peer_hosts is not None else self.host

    def _accept_loop(self) -> None:
        # runs for the life of the mesh: initial peers handshake here, and
        # so do RECONNECTING peers after a link failure.  A connection that
        # fails the handshake (port scanner, stray client, wrong secret) is
        # dropped and accepting continues; only listener-socket errors end
        # the loop.
        while not self._closed:
            try:
                sock, _addr = self._listener.accept()
            except TimeoutError:
                continue
            except OSError:
                return  # listener closed (close()) or broken
            except BaseException as exc:  # noqa: BLE001 — surfaced by start()
                self._acc_err.append(exc)
                return
            threading.Thread(
                target=self._handshake_one, args=(sock,), daemon=True
            ).start()

    def _handshake_one(self, sock: socket.socket) -> None:
        # per-connection thread: a stalled or malicious client burns only
        # its own HANDSHAKE_TIMEOUT_S, never the accept loop
        try:
            sock.settimeout(HANDSHAKE_TIMEOUT_S)
            peer = _handshake_accept(sock, self._auth_secret)
            if peer <= self.worker_id or peer not in self._links:
                raise CommError(f"unexpected peer id {peer}")
            sock.settimeout(None)
            self._attach(peer, sock)
            with self._acc_lock:
                self._accepted.add(peer)
                expect = sum(1 for w in self._links if w > self.worker_id)
                if len(self._accepted) == expect:
                    self._acc_done.set()
        except (CommError, OSError, EOFError):
            try:
                sock.close()
            except OSError:
                pass

    def _attach(self, peer: int, sock: socket.socket) -> None:
        """Install (or replace) the socket of a link and start its reader.
        The reader performs the resume exchange before the link goes ready."""
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        if self.send_deadline > 0:
            # SO_SNDTIMEO bounds each blocking WRITE syscall only (recv
            # stays governed by its own timeouts), so a data-phase sendall
            # to a hung peer errors out instead of parking forever while it
            # holds send_lock.  The frame stays in the retransmit buffer;
            # the failed link is cycled and resync re-delivers it.
            try:
                sec = int(self.send_deadline)
                usec = int((self.send_deadline - sec) * 1e6)
                sock.setsockopt(
                    socket.SOL_SOCKET, socket.SO_SNDTIMEO,
                    struct.pack("ll", sec, usec),
                )
            except (OSError, struct.error):
                pass  # platform without SO_SNDTIMEO: keep the old behavior
        link = self._links[peer]
        with link.cv:
            link.gen += 1
            gen = link.gen
            old = link.sock
            link.sock = sock
            link.ready = False
            if old is not None:
                _close_quietly(old)  # stale reader exits via gen check
        t = threading.Thread(
            target=self._reader, args=(peer, link, sock, gen), daemon=True,
            name=f"pathway:comm-{self.worker_id}<-{peer}",
        )
        t.start()
        # prune finished readers so a flaky network (a new reader per
        # reconnect) cannot grow this list without bound
        self._threads = [x for x in self._threads if x.is_alive()]
        self._threads.append(t)

    # -- per-link reader / resume ---------------------------------------
    def _reader(self, peer: int, link: _Link, sock: socket.socket, gen: int) -> None:
        try:
            self._resume_link(peer, link, sock, gen)
            sock.settimeout(None)
            while not self._closed:
                header = _recv_exact(sock, _HDR.size)
                size, seq = _HDR.unpack(header)
                if size > MAX_FRAME_BYTES:
                    raise ValueError(f"comm frame of {size} bytes exceeds cap")
                blob = _recv_exact(sock, size)
                self._m_bytes_recv.inc(_HDR.size + size)
                if seq != 0:
                    self._m_frames_recv.inc()
                # every mutation below re-checks gen under the owning lock:
                # a superseded reader (its socket replaced by a reconnect)
                # must not write stale seq/ack/inbox state over the state
                # the new link's resume just (re)established
                with link.cv:
                    if link.gen != gen:
                        return
                    link.last_seen = time.monotonic()
                if seq == 0:
                    # control frame: heartbeat carrying the peer's
                    # cumulative ack — retire acknowledged frames
                    if size >= _FRAME.size:
                        (ack,) = _FRAME.unpack(blob[: _FRAME.size])
                        with link.send_lock:
                            if link.gen == gen:
                                self._trim_acked(link, ack)
                    continue
                with link.cv:
                    if link.gen != gen:
                        return
                    if seq <= link.recv_seq:
                        continue  # duplicate from a resync retransmit
                    if seq != link.recv_seq + 1:
                        # a frame vanished from the stream (injected drop /
                        # half-written frame before a reset): framing is
                        # intact but data is missing — force a resync
                        raise ValueError(
                            f"sequence gap from worker {peer}: got {seq}, "
                            f"expected {link.recv_seq + 1}"
                        )
                # no shared secret = unauthenticated link: refuse pickled
                # values so a reachable port is not code execution
                tag, payload = _decode_frame(blob, typed_only=not self.secret)
                with link.cv:
                    if link.gen != gen:
                        return
                    link.recv_seq = seq
                    # nested cv → _cv is the one lock-nesting order used
                    # anywhere, so the advance + enqueue stay atomic w.r.t.
                    # a concurrent purge/resume
                    with self._cv:
                        self._inbox[(peer, tag)].append(payload)
                        self._cv.notify_all()
        except Exception as exc:  # noqa: BLE001
            # socket errors AND decode errors land here: a malformed or
            # corrupt frame means framing is lost and the link is unusable
            # as-is.  Unlike the pre-recovery design this is no longer
            # instantly fatal — the link re-handshakes and resynchronizes
            # from the last acked sequence; only an exhausted reconnect
            # window (or an unrecoverable resync) declares the peer dead.
            if isinstance(exc, ValueError):
                _log.error(
                    "worker %d: link to peer %d failed: %s",
                    self.worker_id, peer, exc,
                )
            self._on_link_failure(peer, link, sock, gen, exc)

    def _resume_link(
        self, peer: int, link: _Link, sock: socket.socket, gen: int
    ) -> None:
        """Post-handshake resume exchange; sets the link ready on success."""
        sock.settimeout(HANDSHAKE_TIMEOUT_S)
        with link.cv:
            my_ack = link.recv_seq
        with link.send_lock:
            sock.sendall(_RESUME.pack(self.incarnation, my_ack))
        peer_inc, peer_ack = _RESUME.unpack(_recv_exact(sock, _RESUME.size))
        with link.cv:
            if link.gen != gen or self._closed:
                raise OSError("link superseded during resume")
            # first connect, or the peer is a respawned process: no
            # cross-incarnation delivery — reset both directions and
            # purge frames queued from the previous incarnation so a
            # rejoined worker never consumes pre-crash data
            new_inc = peer_inc != link.peer_inc
            if link.dead and not new_inc:
                # the death purged this peer's inbox, so our recv_seq
                # over-reports what survived — a same-incarnation resume
                # would silently skip those frames.  Only a respawned
                # (new-incarnation) peer may revive a dead link.
                raise OSError("peer was declared dead; refusing resume")
            purge = new_inc and link.peer_inc is not None
            link.peer_inc = peer_inc
            if new_inc:
                link.recv_seq = 0
                link.dead = False
        if purge:
            self._purge_inbox(peer, notify=True)
        resend: list[bytes] = []
        with link.send_lock:
            if new_inc:
                link.send_seq = 0
                link.sent_buf.clear()
                link.sent_bytes = 0
                link.evicted_seq = 0
                link.unacked_since = None
            elif peer_ack < link.send_seq and (
                peer_ack < link.evicted_seq
                or (link.sent_buf and link.sent_buf[0][0] > peer_ack + 1)
            ):
                raise CommError(
                    f"cannot resync link to worker {peer}: frames past the "
                    f"{self.send_buffer_bytes >> 20} MiB retransmit buffer "
                    "were lost (raise PATHWAY_COMM_SEND_BUFFER_MB)"
                )
            else:
                self._trim_acked(link, peer_ack)
                resend = [wire for _s, wire in link.sent_buf]
        if not resend:
            if not self._set_ready(link, gen):
                raise OSError("link superseded during resume")
            return
        # Retransmit OFF the reader thread: the reader must reach its frame
        # loop and drain the peer's (symmetric) retransmission while this
        # backlog is written, or two peers with large bidirectional backlogs
        # deadlock against full kernel socket buffers.  Ordering is safe:
        # normal senders wait for `ready`, which is set only after this
        # thread holds send_lock — nothing can interleave ahead of the
        # backlog.
        def retransmit() -> None:
            try:
                with link.send_lock:
                    if not self._set_ready(link, gen):
                        return
                    for wire in resend:
                        sock.sendall(wire)
                self._m_retransmits.inc(len(resend))
                # retransmitted wire bytes really crossed the link again
                # (frames.sent already counted them at first send; the
                # retransmits counter reconciles the difference)
                self._m_bytes_sent.inc(sum(len(w) for w in resend))
                _log.info(
                    "worker %d: link to peer %d resynced, retransmitted "
                    "%d frame(s)", self.worker_id, peer, len(resend),
                )
            except OSError as exc:
                self._on_link_failure(peer, link, sock, gen, exc)

        threading.Thread(
            target=retransmit, daemon=True,
            name=f"pathway:comm-resend-{self.worker_id}-{peer}",
        ).start()

    def _set_ready(self, link: _Link, gen: int) -> bool:
        with link.cv:
            if link.gen != gen or self._closed:
                return False
            link.ready = True
            link.relinking = False
            link.relink_deadline = None
            link.last_seen = time.monotonic()
            link.cv.notify_all()
            return True

    @staticmethod
    def _trim_acked(link: _Link, ack: int) -> None:
        """Retire buffered frames the peer confirmed (call with send_lock)."""
        trimmed = False
        while link.sent_buf and link.sent_buf[0][0] <= ack:
            _seq, wire = link.sent_buf.popleft()
            link.sent_bytes -= len(wire)
            trimmed = True
        if trimmed:
            link.unacked_since = None if not link.sent_buf else time.monotonic()

    # -- failure handling / reconnect ------------------------------------
    def _on_link_failure(
        self,
        peer: int,
        link: _Link,
        sock: socket.socket,
        gen: int,
        exc: BaseException,
    ) -> None:
        _close_quietly(sock)
        if self._retiring:
            # coordinated teardown: peers are LEAVING, not failing — no
            # reconnect threads, no alarms; just mark the link down so any
            # straggling recv unblocks on the dead sentinel
            self._mark_dead(peer, link, "retired (coordinated handoff)")
            return
        if isinstance(exc, CommError):
            with link.cv:
                if self._closed or link.dead or link.gen != gen:
                    return  # a superseded reader must not kill the new link
            # resync refused (retransmit gap / auth): unrecoverable
            self._mark_dead(peer, link, str(exc))
            return
        with link.cv:
            if self._closed or link.dead or link.gen != gen:
                return
            link.ready = False
            now = time.monotonic()
            if link.relink_deadline is None:
                link.relink_deadline = now + self.reconnect_window
            expired = now > link.relink_deadline
            if not expired:
                if link.relinking:
                    return  # an active reconnect thread owns this link
                link.relinking = True
        if expired:
            self._mark_dead(
                peer, link,
                f"reconnect window ({self.reconnect_window:g}s) exhausted: {exc}",
            )
            return
        _log.warning(
            "worker %d: link to peer %d dropped (%s); reconnecting",
            self.worker_id, peer, exc,
        )
        self._m_reconnects.inc()
        _blackbox.record(
            "comm.reconnect", worker=self.worker_id, peer=peer, error=str(exc)
        )
        if peer < self.worker_id:
            target = self._redial_loop  # we dialed this peer originally
        else:
            target = self._await_reaccept  # the peer dials us back
        threading.Thread(
            target=target, args=(peer, link), daemon=True,
            name=f"pathway:comm-relink-{self.worker_id}-{peer}",
        ).start()

    def _redial_loop(self, peer: int, link: _Link) -> None:
        with link.cv:
            deadline = link.relink_deadline or (
                time.monotonic() + self.reconnect_window
            )
        for delay in self._reconnect_delays():
            if self._closed or link.dead:
                return
            if delay:
                time.sleep(min(delay, max(0.0, deadline - time.monotonic())))
            if time.monotonic() > deadline:
                break
            try:
                sock = _dial(
                    self._peer_host(peer), self.first_port + peer,
                    self.worker_id, self._auth_secret,
                    deadline_s=min(5.0, max(0.5, deadline - time.monotonic())),
                )
            except CommError as exc:
                if getattr(exc, "retryable", False):
                    continue  # peer unreachable this attempt; keep trying
                # auth mismatch: peer is alive but holds a different
                # secret — retrying cannot help
                self._mark_dead(peer, link, str(exc))
                return
            except OSError:
                continue
            self._attach(peer, sock)
            with link.cv:
                link.cv.wait_for(
                    lambda: link.ready or link.dead or self._closed,
                    timeout=HANDSHAKE_TIMEOUT_S + 1.0,
                )
                if link.ready or link.dead or self._closed:
                    return
            # resume failed; loop for another attempt
        self._mark_dead(peer, link, "reconnect attempts exhausted")

    def _await_reaccept(self, peer: int, link: _Link) -> None:
        # listener side of the link: the peer re-dials us; the accept loop
        # re-attaches and the reader resumes — we just enforce the window
        with link.cv:
            deadline = link.relink_deadline or (
                time.monotonic() + self.reconnect_window
            )
            link.cv.wait_for(
                lambda: link.ready or link.dead or self._closed,
                timeout=max(0.0, deadline - time.monotonic()),
            )
            if link.ready or link.dead or self._closed:
                return
        self._mark_dead(peer, link, "peer did not reconnect in time")

    def _mark_dead(self, peer: int, link: _Link, why: str) -> None:
        with link.cv:
            if link.dead:
                return
            link.dead = True
            link.ready = False
            link.relinking = False
            if link.sock is not None:
                _close_quietly(link.sock)
            link.cv.notify_all()
        if self._retiring:
            # expected departure during a coordinated handoff — keep the
            # inbox purge + dead sentinel below (stragglers must still
            # unblock) but none of the partition alarms
            _log.debug(
                "worker %d: peer %d retired: %s", self.worker_id, peer, why
            )
        else:
            _log.error(
                "worker %d: peer %d declared dead: %s",
                self.worker_id, peer, why,
            )
            self._m_peers_dead.inc()
            _blackbox.record(
                "comm.peer_dead", worker=self.worker_id, peer=peer, why=why
            )
        with self._cv:
            # stale frames from the dead incarnation must not be consumed
            # by anyone (least of all a respawned peer's exchange rounds)
            self._purge_inbox(peer, notify=False)
            self._inbox[(peer, _PEER_DEAD)].append(None)
            self._cv.notify_all()

    def _purge_inbox(self, peer: int, *, notify: bool) -> None:
        def drop() -> None:
            for key in [k for k in self._inbox if k[0] == peer]:
                del self._inbox[key]

        if notify:
            with self._cv:
                drop()
                self._cv.notify_all()
        else:
            drop()  # caller holds self._cv

    def _backlog_snapshot(self) -> dict[str, float]:
        """Pull-time collector: frames waiting per peer inbox, in the
        ``backlog.*`` backpressure namespace (``engine/freshness.py``).
        Runs at scrape/export cadence off the hot path; the brief ``_cv``
        hold is the same one every recv already takes."""
        # every peer gets a series, zero included — a drained inbox must
        # report 0, not vanish and leave the scraper serving its last
        # (possibly huge) value for the staleness window
        counts: dict[int, int] = {
            peer: 0 for peer in range(self.worker_count)
            if peer != self.worker_id
        }
        with self._cv:
            for (peer, tag), q in self._inbox.items():
                if tag is _PEER_DEAD:
                    continue
                counts[peer] = counts.get(peer, 0) + len(q)
        return {
            f"backlog.comm.inbox{{peer={peer},worker={self.worker_id}}}":
                float(n)
            for peer, n in counts.items()
        }

    # -- heartbeats -------------------------------------------------------
    # pathway-lint: context=heartbeat
    def _heartbeat_loop(self) -> None:
        """Per-link liveness: send heartbeat+ack frames; force-fail links
        whose peer went silent or stopped acking (a hung process looks
        healthy to TCP — only traffic proves liveness).

        This loop must NEVER block on a link's ``send_lock``: a data-phase
        ``sendall`` to a hung peer can hold that lock for up to the send
        deadline, and one such peer must not stall staleness detection —
        or heartbeats — for every OTHER peer.  So staleness is computed
        from lock-free reads (worst case one interval stale), the force-
        close happens outside any lock, and the heartbeat write itself is
        skipped when the lock is busy (an in-progress data send is itself
        evidence the link is being driven; the ack rides the next tick)."""
        while not self._hb_stop.wait(self.heartbeat_interval):
            if self._closed:
                return
            now = time.monotonic()
            max_stale = 0.0
            for link in self._links.values():
                with link.cv:
                    if not link.ready or link.dead:
                        continue
                    sock = link.sock
                    ack = link.recv_seq
                    staleness = now - link.last_seen
                    max_stale = max(max_stale, staleness)
                    silent = staleness > self.heartbeat_timeout
                # unacked_since is read WITHOUT send_lock: a torn read costs
                # at most one stale interval, while taking the lock could
                # block behind a sendall stuck on this very hung peer
                unacked_since = link.unacked_since
                stalled = (
                    unacked_since is not None
                    and now - unacked_since > self.heartbeat_timeout
                )
                if silent or stalled:
                    # reader wakes with an error → reconnect path decides
                    _log.warning(
                        "worker %d: peer %d %s for >%gs; cycling link",
                        self.worker_id, link.peer,
                        "silent" if silent else "not acking",
                        self.heartbeat_timeout,
                    )
                    _close_quietly(sock)
                    continue
                hb = _HDR.pack(_FRAME.size, 0) + _FRAME.pack(ack)
                # BOUNDED wait for the lock: a wedged data sendall costs at
                # most 50 ms per tick (vs. blocking forever, the PR-1
                # residue), while sustained back-to-back data sends — which
                # release the lock between frames — cannot starve the
                # heartbeat indefinitely: acks ride only on heartbeat
                # frames, and a peer that stopped receiving them would
                # force-fail a perfectly healthy link as "not acking"
                if not link.send_lock.acquire(timeout=0.05):
                    continue  # truly wedged; retry next tick
                try:
                    sock.sendall(hb)
                    # bytes symmetry with the receive side, which counts
                    # control frames too (it cannot tell them apart until
                    # after the header is read)
                    self._m_bytes_sent.inc(len(hb))
                except OSError:
                    # includes a send-deadline expiry: progress on the
                    # socket is unknowable, so cycle the link promptly
                    # instead of waiting for the reader to notice
                    _close_quietly(sock)
                finally:
                    link.send_lock.release()
            self._m_staleness.set(max_stale)

    # -- point to point --------------------------------------------------
    def send(self, dest: int, tag: Hashable, payload: Any) -> None:
        self._check_poison()
        if dest == self.worker_id:
            # the codec round-trips every value shape exactly (lists stay
            # lists, wrappers stay wrapped), so a self-send can skip it
            with self._cv:
                self._inbox[(dest, tag)].append(payload)
                self._cv.notify_all()
            return
        blob = _codec.encode_row((tag, payload))
        if len(blob) > MAX_FRAME_BYTES:
            # fail fast on the sender with the actionable message — the
            # receiver would just drop the link as "peer disconnected"
            raise CommError(
                f"comm frame of {len(blob)} bytes exceeds the "
                f"{MAX_FRAME_BYTES}-byte cap; raise PATHWAY_COMM_MAX_FRAME_MB "
                "on every worker for enormous-epoch workloads"
            )
        link = self._links[dest]
        deadline = time.monotonic() + self.reconnect_window + HANDSHAKE_TIMEOUT_S
        with link.cv:
            link.cv.wait_for(
                lambda: link.ready or link.dead or self._closed
                or self._poisoned is not None,
                timeout=max(0.0, deadline - time.monotonic()),
            )
            self._check_poison()
            if link.dead:
                raise CommError(
                    f"worker {self.worker_id}: peer {dest} disconnected "
                    f"while sending {tag!r}"
                )
            if not link.ready:
                raise CommError(
                    f"worker {self.worker_id}: link to peer {dest} not "
                    f"ready within {self.reconnect_window:g}s"
                )
        drop = corrupt = reset = None
        if self._fault_comm:
            spec = _faults.check("comm_delay", worker=self.worker_id, peer=dest)
            if spec is not None:
                time.sleep(spec.delay_ms / 1000.0)
            drop = _faults.check("comm_drop", worker=self.worker_id, peer=dest)
            if drop is None:
                corrupt = _faults.check(
                    "comm_corrupt", worker=self.worker_id, peer=dest
                )
                if corrupt is None:
                    reset = _faults.check(
                        "comm_reset", worker=self.worker_id, peer=dest
                    )
        with link.send_lock:
            link.send_seq += 1
            wire = _HDR.pack(len(blob), link.send_seq) + blob
            link.sent_buf.append((link.send_seq, wire))
            if not link.unacked_since:
                link.unacked_since = time.monotonic()
            link.sent_bytes += len(wire)
            self._m_frames_sent.inc()
            self._m_bytes_sent.inc(len(wire))
            while link.sent_bytes > self.send_buffer_bytes and link.sent_buf:
                evicted, old = link.sent_buf.popleft()
                link.sent_bytes -= len(old)
                self._m_evictions.inc()
                # resync below this seq is now impossible; if the link
                # drops before the peer acks past it, the peer is dead
                link.evicted_seq = max(link.evicted_seq, evicted)
                _log.warning(
                    "worker %d: retransmit buffer to peer %d overflowed; "
                    "evicted unacked frame %d (raise "
                    "PATHWAY_COMM_SEND_BUFFER_MB to keep reconnects "
                    "lossless)",
                    self.worker_id, dest, evicted,
                )
            out: bytes | None = wire
            if drop is not None:
                out = None  # the frame vanishes, as if eaten by a reset
            elif corrupt is not None:
                # bit-flip the payload on the wire only — the retransmit
                # buffer keeps the pristine frame for the resync
                out = wire[: _HDR.size] + bytes(b ^ 0xFF for b in blob)
            sock = link.sock
            if out is not None and sock is not None:
                try:
                    sock.sendall(out)
                except OSError:
                    # the link just failed under us — including a send-
                    # deadline expiry on a hung peer (SO_SNDTIMEO), where
                    # how much of the frame left the kernel is unknowable.
                    # The frame is in the retransmit buffer; close the
                    # socket so the reader fails over NOW (a deadline
                    # expiry alone would never wake it) and the resync
                    # re-delivers from the last acked sequence.
                    _close_quietly(sock)
            if (drop is not None or reset is not None) and sock is not None:
                _close_quietly(sock)  # injected TCP reset

    def recv(
        self, src: int, tag: Hashable, timeout: float | None = None
    ) -> Any:
        if timeout is None:
            timeout = self.recv_timeout
        deadline = time.monotonic() + timeout
        with self._cv:
            while True:
                self._check_poison()
                q = self._inbox.get((src, tag))
                if q:
                    payload = q.popleft()
                    if not q:
                        self._inbox.pop((src, tag), None)
                    return payload
                if self._inbox.get((src, _PEER_DEAD)):
                    raise CommError(
                        f"worker {self.worker_id}: peer {src} disconnected "
                        f"while waiting for {tag!r}"
                    )
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise CommError(
                        f"worker {self.worker_id}: timeout after {timeout:g}s "
                        f"(PATHWAY_COMM_RECV_TIMEOUT_S) waiting for "
                        f"{tag!r} from worker {src}"
                    )
                self._cv.wait(min(remaining, 1.0))

    # -- collectives -----------------------------------------------------
    def alltoall(self, tag: Hashable, per_dest: list[list]) -> list:
        """Send ``per_dest[w]`` to worker ``w``; return concatenation of what
        every worker sent here (own bucket included), ordered by worker id."""
        for w in range(self.worker_count):
            if w != self.worker_id:
                self.send(w, tag, per_dest[w])
        merged: list = []
        for w in range(self.worker_count):
            if w == self.worker_id:
                merged.extend(per_dest[w])
            else:
                merged.extend(self.recv(w, tag))
        return merged

    def gather(self, tag: Hashable, payload: Any, root: int = 0) -> list | None:
        """Root returns [payload per worker, ordered]; others return None."""
        if self.worker_id == root:
            out = []
            for w in range(self.worker_count):
                out.append(payload if w == root else self.recv(w, tag))
            return out
        self.send(root, tag, payload)
        return None

    def bcast(self, tag: Hashable, payload: Any = None, root: int = 0) -> Any:
        if self.worker_id == root:
            for w in range(self.worker_count):
                if w != root:
                    self.send(w, tag, payload)
            return payload
        return self.recv(root, tag)

    def barrier(self, tag: Hashable) -> None:
        self.gather(("barrier", tag), None)
        self.bcast(("barrier-go", tag))

    def poison(self, reason: str) -> None:
        """Abandon this mesh: every blocked (and future) ``send``/``recv``
        — and through them every collective — raises :class:`MeshPoisoned`
        promptly instead of waiting out link timeouts.

        The worker-side promotion sentinel calls this from its watcher
        thread when the supervisor posts a PROMOTE request for a dead
        peer: the epoch loop is parked inside a positionally-paired
        collective that can never complete (the dead peer will return as
        a NEW process with a fresh mesh incarnation), so the only correct
        exit is to unwind, drain-commit the consistent frontier, and
        rejoin a fresh mesh in-process.  Idempotent; the first reason
        sticks."""
        with self._cv:
            if self._poisoned is not None:
                return
            self._poisoned = reason
            self._cv.notify_all()
        for link in self._links.values():
            with link.cv:
                link.cv.notify_all()

    def _check_poison(self) -> None:
        if self._poisoned is not None:
            raise MeshPoisoned(
                f"worker {self.worker_id}: mesh poisoned: {self._poisoned}"
            )

    def retire(self) -> None:
        """Enter coordinated-teardown mode: this mesh is going away ON
        PURPOSE (live shard handoff — every peer drains, barriers, and
        exits together), so link failures from here on are the expected
        sound of peers leaving, not faults.  Reconnect threads stop
        spawning and peer-death goes quiet (no error logs, no
        ``comm.peers.dead`` counts) — a handoff must not light up the
        same alarms a real partition does."""
        self._retiring = True

    def close(self) -> None:
        self._closed = True
        self._hb_stop.set()
        for link in self._links.values():
            with link.cv:
                if link.sock is not None:
                    try:
                        link.sock.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    _close_quietly(link.sock)
                link.cv.notify_all()
        if self._listener is not None:
            try:
                # wake an accept() blocked in the acceptor thread so the
                # port is actually released, not merely marked for close
                self._listener.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._listener.close()
            except OSError:
                pass
        acceptor = self._acceptor
        if acceptor is not None and acceptor is not threading.current_thread():
            acceptor.join(3.0)
        with self._cv:
            # per-peer inbox state dies with the mesh: a later mesh (or a
            # respawned worker joining one) must never see pre-close frames
            self._inbox.clear()
            self._cv.notify_all()


_PEER_DEAD = ("__peer_dead__",)


def moving_shards(n_old: int, n_new: int) -> int:
    """How many of the 2**SHARD_BITS shard slots change owner when the
    routing rule ``shard % n`` goes from ``n_old`` to ``n_new`` workers.

    The cost model of a rescale decision: only these slots' state actually
    migrates in a live handoff (the successor replays them filtered by
    ``shard_to_worker(key, n_new)``), so the autoscaler's provenance log
    records it alongside every grow/shrink — ``shard % n`` is not a
    consistent hash, and this number says what that choice costs."""
    n_old, n_new = max(1, n_old), max(1, n_new)
    if n_old == n_new:
        return 0
    span = 1 << SHARD_BITS
    # shard s moves iff s % n_old != s % n_new, which is periodic in
    # lcm(n_old, n_new): count one period, scale to the 16-bit space
    period = math.lcm(n_old, n_new)
    moved_per_period = sum(
        1 for s in range(period) if s % n_old != s % n_new
    )
    full, rem = divmod(span, period)
    return full * moved_per_period + sum(
        1 for s in range(rem) if s % n_old != s % n_new
    )


def _close_quietly(sock: socket.socket) -> None:
    try:
        sock.close()
    except OSError:
        pass


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(n - got)
        if not chunk:
            raise EOFError("peer closed")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def _dial(
    host: str,
    port: int,
    my_id: int,
    secret: bytes,
    deadline_s: float = CONNECT_TIMEOUT_S,
) -> socket.socket:
    deadline = time.monotonic() + deadline_s
    last: Exception | None = None
    while time.monotonic() < deadline:
        try:
            sock = socket.create_connection((host, port), timeout=5.0)
        except OSError as exc:
            last = exc
            time.sleep(0.1)
            continue
        try:
            sock.settimeout(HANDSHAKE_TIMEOUT_S)
            _handshake_dial(sock, my_id, secret)
            sock.settimeout(None)
            return sock
        except CommError:
            # auth mismatch is fatal, not retryable: the peer is alive but
            # holds a different secret
            sock.close()
            raise
        except (OSError, EOFError) as exc:
            # listener may have dropped us mid-handshake during startup
            # races — retry like a refused connection
            sock.close()
            last = exc
            time.sleep(0.1)
    err = CommError(f"could not reach worker at {host}:{port}: {last}")
    err.retryable = True  # unreachable ≠ unauthorized: reconnects may retry
    raise err


class WorkerContext:
    """Per-process view of the worker group, driving exchange + epochs.

    ``exchange_node`` implements the reference's exchange-before-stateful-
    operator pattern: contributions are routed to the worker that owns the
    key the operator's state is sharded on (``dataflow.rs:1414``,
    ``shard.rs:15-20``).  Nodes declare ownership via ``exchange_routes``
    (port → routing-key fn) or ``exchange_gather0`` (all rows to worker 0,
    for globally-ordered operators: sort, iterate, external index).
    """

    def __init__(self, mesh: TcpMesh):
        self.mesh = mesh
        self.worker_id = mesh.worker_id
        self.worker_count = mesh.worker_count

    def owner_of(self, routing_key: int) -> int:
        return shard_to_worker(routing_key, self.worker_count)

    def exchange_deltas(
        self,
        tag: Hashable,
        deltas: list,
        route: Callable[[int, Any], int] | None,
        route_cols: "tuple[tuple, bool] | None" = None,
    ) -> list:
        """All-to-all one delta list. ``route(key, row) -> routing key``;
        ``None`` routes by the row key itself.  ``route_cols`` = (key
        column indices, hash_none) batches the key-hash+route loop into
        one native pass (``route_deltas``) with identical semantics —
        the per-row Python loop below is the oracle and the fallback."""
        per_dest: list[list] | None = None
        if route_cols is not None and deltas:
            from pathway_tpu.engine.types import _native
            from pathway_tpu.internals import vector_compiler as vc

            nat = _native()
            if vc.ENABLED and nat is not None and hasattr(nat, "route_deltas"):
                idxs, hash_none = route_cols
                per_dest = nat.route_deltas(
                    list(deltas), idxs, self.worker_count, hash_none
                )
        if per_dest is None:
            per_dest = [[] for _ in range(self.worker_count)]
            for key, row, diff in deltas:
                if route is None:
                    rk = key
                else:
                    try:
                        rk = route(key, row)
                    except Exception:
                        rk = key  # poisoned rows resolve locally; the node's
                        # own step reports the error through the error log
                per_dest[self.owner_of(rk)].append((key, row, diff))
        return self.mesh.alltoall(tag, per_dest)

    def gather0_deltas(self, tag: Hashable, deltas: list) -> list:
        per_dest: list[list] = [[] for _ in range(self.worker_count)]
        per_dest[0] = list(deltas)
        return self.mesh.alltoall(tag, per_dest)

    def exchange_node(self, node: Any, time_: int) -> None:
        """Pre-step exchange for one operator (same call order on every
        worker — the DAG is identical, so collectives pair up)."""
        routes = getattr(node, "exchange_routes", None)
        gather0 = getattr(node, "exchange_gather0", False)
        if routes is None and not gather0:
            return
        n_ports = len(node.inputs) if node.inputs else 1
        for port in range(n_ports):
            pending = node.pending.pop(port, [])
            tag = ("x", node.id, port, time_)
            if gather0:
                merged = self.gather0_deltas(tag, pending)
            else:
                route = routes.get(port) if routes else None
                if route is None and routes is not None and port not in routes:
                    # port not exchanged (already co-located) — but peers
                    # still ran alltoall for declared ports only, so skip
                    node.pending[port] = pending
                    continue
                specs = getattr(node, "exchange_route_cols", None)
                route_cols = (
                    specs.get(port)
                    if specs is not None and route is not None
                    else None
                )
                merged = self.exchange_deltas(
                    tag, pending, route, route_cols=route_cols
                )
            if merged:
                node.pending[port] = merged

    def close(self) -> None:
        self.mesh.close()
