"""Binary codec for engine values, rows and snapshot events.

Parity target: the reference serializes snapshot entries with bincode over
its ``Value`` enum (``/root/reference/src/persistence/input_snapshot.rs:32-36``,
``src/engine/value.rs:207-228``).  This is the TPU build's equivalent wire
format: a compact tagged binary encoding covering every engine value type.
The framing is deliberately simple (tag byte + little-endian fixed ints +
length-prefixed payloads) so the hot paths can be implemented in the native
C++ runtime (``native/``) behind the same interface.

Events (the snapshot log unit, input_snapshot.rs Event enum):
  Insert(key, values) / Delete(key, values) / AdvanceTime(t) / Finished.
"""

from __future__ import annotations

import datetime as _dt
import io as _io
import json as _json
import pickle
import struct
from typing import Any, Iterable

import numpy as np

from pathway_tpu.engine.types import (
    ERROR,
    Error,
    Json,
    Pointer,
    PyObjectWrapper,
    as_hashable,
)

MAGIC = b"PWT1"  # codec version tag; bump on format change

# value tags
_T_NONE = 0
_T_FALSE = 1
_T_TRUE = 2
_T_INT = 3  # 8-byte signed
_T_BIGINT = 4  # length-prefixed signed big int
_T_FLOAT = 5
_T_STR = 6
_T_BYTES = 7
_T_POINTER = 8  # 16-byte little-endian u128
_T_TUPLE = 9
_T_NDARRAY = 10
_T_JSON = 11
_T_DATETIME_NAIVE = 12  # microseconds since epoch, 8-byte signed
_T_DATETIME_UTC = 13
_T_DURATION = 14  # microseconds, 8-byte signed
_T_ERROR = 15
_T_PYOBJECT = 16  # pickled (opaque fallback; decodes to the raw object)
_T_DATE = 17
_T_LIST = 18  # same layout as _T_TUPLE; decodes back to a list
_T_PYOBJECT_WRAPPED = 19  # pickled PyObjectWrapper.value; re-wrapped on decode

_U64 = struct.Struct("<Q")
_I64 = struct.Struct("<q")
_F64 = struct.Struct("<d")

_EPOCH_NAIVE = _dt.datetime(1970, 1, 1)
_EPOCH_UTC = _dt.datetime(1970, 1, 1, tzinfo=_dt.timezone.utc)


def _w_len(out: _io.BytesIO, n: int) -> None:
    out.write(_U64.pack(n))


def encode_value(v: Any, out: _io.BytesIO) -> None:
    if v is None:
        out.write(bytes([_T_NONE]))
    elif v is True:
        out.write(bytes([_T_TRUE]))
    elif v is False:
        out.write(bytes([_T_FALSE]))
    elif isinstance(v, int):
        if -(2**63) <= v < 2**63:
            out.write(bytes([_T_INT]))
            out.write(_I64.pack(v))
        else:
            b = v.to_bytes((v.bit_length() + 8) // 8 + 1, "little", signed=True)
            out.write(bytes([_T_BIGINT]))
            _w_len(out, len(b))
            out.write(b)
    elif isinstance(v, float):
        out.write(bytes([_T_FLOAT]))
        out.write(_F64.pack(v))
    elif isinstance(v, str):
        b = v.encode()
        out.write(bytes([_T_STR]))
        _w_len(out, len(b))
        out.write(b)
    elif isinstance(v, bytes):
        out.write(bytes([_T_BYTES]))
        _w_len(out, len(v))
        out.write(v)
    elif isinstance(v, Pointer):
        out.write(bytes([_T_POINTER]))
        out.write(v.value.to_bytes(16, "little"))
    elif isinstance(v, (tuple, list)):
        # lists get their own tag (same layout) so they round-trip as
        # lists: falling to the pickle tail would make delta buckets
        # opaque on the comm wire, and decoding them as tuples would make
        # value shapes differ between local and exchanged rows
        out.write(bytes([_T_TUPLE if isinstance(v, tuple) else _T_LIST]))
        _w_len(out, len(v))
        for item in v:
            encode_value(item, out)
    elif isinstance(v, np.ndarray):
        arr = np.ascontiguousarray(v)
        dts = arr.dtype.str.encode()
        shape = arr.shape
        out.write(bytes([_T_NDARRAY]))
        _w_len(out, len(dts))
        out.write(dts)
        _w_len(out, len(shape))
        for s in shape:
            out.write(_U64.pack(s))
        payload = arr.tobytes()
        _w_len(out, len(payload))
        out.write(payload)
    elif isinstance(v, Json):
        b = _json.dumps(v.value, sort_keys=True).encode()
        out.write(bytes([_T_JSON]))
        _w_len(out, len(b))
        out.write(b)
    elif isinstance(v, _dt.datetime):
        if v.tzinfo is None:
            out.write(bytes([_T_DATETIME_NAIVE]))
            micros = round((v - _EPOCH_NAIVE).total_seconds() * 1e6)
        else:
            out.write(bytes([_T_DATETIME_UTC]))
            micros = round((v - _EPOCH_UTC).total_seconds() * 1e6)
        out.write(_I64.pack(micros))
    elif isinstance(v, _dt.date):
        out.write(bytes([_T_DATE]))
        out.write(_I64.pack(v.toordinal()))
    elif isinstance(v, _dt.timedelta):
        out.write(bytes([_T_DURATION]))
        out.write(_I64.pack(round(v.total_seconds() * 1e6)))
    elif isinstance(v, Error):
        out.write(bytes([_T_ERROR]))
    elif isinstance(v, PyObjectWrapper):
        # distinct tag so decode re-wraps: wrapper equality must survive a
        # round trip (an exchanged retraction has to cancel a local insert)
        b = pickle.dumps(v.value)
        out.write(bytes([_T_PYOBJECT_WRAPPED]))
        _w_len(out, len(b))
        out.write(b)
    else:  # last resort: opaque pickle (keeps UDF-produced objects alive)
        b = pickle.dumps(v)
        out.write(bytes([_T_PYOBJECT]))
        _w_len(out, len(b))
        out.write(b)


def _r_len(buf: memoryview, pos: int) -> tuple[int, int]:
    return _U64.unpack_from(buf, pos)[0], pos + 8


def _take(buf: memoryview, pos: int, n: int) -> tuple[memoryview, int]:
    # subtraction form: corrupted length fields near u64::MAX must not
    # silently produce a short slice (matches the native Cursor::need)
    if pos > len(buf) or n > len(buf) - pos:
        raise ValueError("codec: truncated buffer")
    return buf[pos : pos + n], pos + n


def decode_value(
    buf: memoryview, pos: int, *, allow_pyobject: bool = True
) -> tuple[Any, int]:
    tag = buf[pos]
    pos += 1
    if tag in (_T_PYOBJECT, _T_PYOBJECT_WRAPPED) and not allow_pyobject:
        raise ValueError(
            "codec: python-object (pickled) value refused by typed-only "
            "decode — on the comm mesh this means a PyObjectWrapper row "
            "crossed an unauthenticated link; set PATHWAY_COMM_SECRET"
        )
    if tag == _T_NONE:
        return None, pos
    if tag == _T_TRUE:
        return True, pos
    if tag == _T_FALSE:
        return False, pos
    if tag == _T_INT:
        return _I64.unpack_from(buf, pos)[0], pos + 8
    if tag == _T_BIGINT:
        n, pos = _r_len(buf, pos)
        b, pos = _take(buf, pos, n)
        return int.from_bytes(b, "little", signed=True), pos
    if tag == _T_FLOAT:
        return _F64.unpack_from(buf, pos)[0], pos + 8
    if tag == _T_STR:
        n, pos = _r_len(buf, pos)
        b, pos = _take(buf, pos, n)
        return bytes(b).decode(), pos
    if tag == _T_BYTES:
        n, pos = _r_len(buf, pos)
        b, pos = _take(buf, pos, n)
        return bytes(b), pos
    if tag == _T_POINTER:
        b, pos = _take(buf, pos, 16)
        return Pointer(int.from_bytes(b, "little")), pos
    if tag in (_T_TUPLE, _T_LIST):
        n, pos = _r_len(buf, pos)
        items = []
        for _ in range(n):
            item, pos = decode_value(buf, pos, allow_pyobject=allow_pyobject)
            items.append(item)
        return (tuple(items) if tag == _T_TUPLE else items), pos
    if tag == _T_NDARRAY:
        n, pos = _r_len(buf, pos)
        b, pos = _take(buf, pos, n)
        dts = bytes(b).decode()
        ndim, pos = _r_len(buf, pos)
        shape = []
        for _ in range(ndim):
            shape.append(_U64.unpack_from(buf, pos)[0])
            pos += 8
        n, pos = _r_len(buf, pos)
        b, pos = _take(buf, pos, n)
        arr = np.frombuffer(b, dtype=np.dtype(dts)).reshape(shape)
        return as_hashable(arr.copy()), pos
    if tag == _T_JSON:
        n, pos = _r_len(buf, pos)
        b, pos = _take(buf, pos, n)
        return Json(_json.loads(bytes(b).decode())), pos
    if tag == _T_DATETIME_NAIVE:
        micros = _I64.unpack_from(buf, pos)[0]
        return _EPOCH_NAIVE + _dt.timedelta(microseconds=micros), pos + 8
    if tag == _T_DATETIME_UTC:
        micros = _I64.unpack_from(buf, pos)[0]
        return _EPOCH_UTC + _dt.timedelta(microseconds=micros), pos + 8
    if tag == _T_DATE:
        return _dt.date.fromordinal(_I64.unpack_from(buf, pos)[0]), pos + 8
    if tag == _T_DURATION:
        micros = _I64.unpack_from(buf, pos)[0]
        return _dt.timedelta(microseconds=micros), pos + 8
    if tag == _T_ERROR:
        return ERROR, pos
    if tag == _T_PYOBJECT:
        n, pos = _r_len(buf, pos)
        b, pos = _take(buf, pos, n)
        return pickle.loads(bytes(b)), pos
    if tag == _T_PYOBJECT_WRAPPED:
        n, pos = _r_len(buf, pos)
        b, pos = _take(buf, pos, n)
        return PyObjectWrapper(pickle.loads(bytes(b))), pos
    raise ValueError(f"codec: unknown value tag {tag}")


def encode_row_py(values: Iterable[Any]) -> bytes:
    out = _io.BytesIO()
    vals = tuple(values)
    _w_len(out, len(vals))
    for v in vals:
        encode_value(v, out)
    return out.getvalue()


def decode_row_py(
    data: bytes | memoryview, pos: int = 0, *, allow_pyobject: bool = True
) -> tuple[tuple, int]:
    buf = memoryview(data)
    try:
        n, pos = _r_len(buf, pos)
        items = []
        for _ in range(n):
            item, pos = decode_value(buf, pos, allow_pyobject=allow_pyobject)
            items.append(item)
    except ValueError:
        raise
    except MemoryError:
        raise
    except Exception as exc:
        # any other decode failure is buffer corruption (bit-rotted dtype
        # strings hit np.dtype's TypeError, mangled pickles raise
        # UnpicklingError, short fixed reads raise struct.error/IndexError)
        # — surface the single documented, catchable error the native
        # decoder also raises
        raise ValueError(f"codec: corrupt buffer ({exc})") from exc
    return tuple(items), pos


def encode_row(values: Iterable[Any]) -> bytes:
    from pathway_tpu.engine.types import _native

    native = _native()
    if native is not None:
        return native.encode_row(tuple(values))
    return encode_row_py(values)


def decode_row(data: bytes | memoryview, pos: int = 0) -> tuple[tuple, int]:
    from pathway_tpu.engine.types import _native

    native = _native()
    if native is not None:
        return native.decode_row(data, pos)
    return decode_row_py(data, pos)


def decode_row_typed(data: bytes | memoryview, pos: int = 0) -> tuple[tuple, int]:
    """Typed-only decode: raises ValueError on pickled (PYOBJECT) values.

    Used by the comm mesh for links without a handshake secret, where a
    pickle payload from the network would be arbitrary code execution.
    Always the Python decoder — the native one has no refusal hook.
    """
    return decode_row_py(data, pos, allow_pyobject=False)


# --- snapshot events ---------------------------------------------------------

EV_INSERT = 1
EV_DELETE = 2
EV_ADVANCE_TIME = 3
EV_FINISHED = 4


def encode_event(kind: int, key: int = 0, row: tuple = (), time: int = 0) -> bytes:
    out = _io.BytesIO()
    out.write(bytes([kind]))
    if kind in (EV_INSERT, EV_DELETE):
        # keys live in the 128-bit key space (value.rs Key = u128); mask
        # defensively so out-of-range ints cannot abort the event loop
        out.write((key & ((1 << 128) - 1)).to_bytes(16, "little", signed=False))
        payload = encode_row(row)
        _w_len(out, len(payload))
        out.write(payload)
    elif kind == EV_ADVANCE_TIME:
        out.write(_U64.pack(time))
    return out.getvalue()


def decode_events(data: bytes):
    """Yield (kind, key, row, time) tuples from a chunk of encoded events."""
    buf = memoryview(data)
    pos = 0
    end = len(buf)
    while pos < end:
        kind = buf[pos]
        pos += 1
        if kind in (EV_INSERT, EV_DELETE):
            key = int.from_bytes(buf[pos : pos + 16], "little")
            pos += 16
            n, pos = _r_len(buf, pos)
            row, _ = decode_row(buf, pos)
            pos += n
            yield kind, key, row, 0
        elif kind == EV_ADVANCE_TIME:
            t = _U64.unpack_from(buf, pos)[0]
            pos += 8
            yield kind, 0, (), t
        elif kind == EV_FINISHED:
            yield kind, 0, (), 0
        else:
            raise ValueError(f"codec: unknown event kind {kind}")
