"""Binary codec for engine values, rows and snapshot events.

Parity target: the reference serializes snapshot entries with bincode over
its ``Value`` enum (``/root/reference/src/persistence/input_snapshot.rs:32-36``,
``src/engine/value.rs:207-228``).  This is the TPU build's equivalent wire
format: a compact tagged binary encoding covering every engine value type.
The framing is deliberately simple (tag byte + little-endian fixed ints +
length-prefixed payloads) so the hot paths can be implemented in the native
C++ runtime (``native/``) behind the same interface.

Events (the snapshot log unit, input_snapshot.rs Event enum):
  Insert(key, values) / Delete(key, values) / AdvanceTime(t) / Finished.
"""

from __future__ import annotations

import datetime as _dt
import io as _io
import json as _json
import pickle
import struct
import threading as _threading
from typing import Any, Iterable

import numpy as np

from pathway_tpu.engine.types import (
    ERROR,
    Error,
    Json,
    Pointer,
    PyObjectWrapper,
    as_hashable,
)

MAGIC = b"PWT1"  # codec version tag; bump on format change


# --- integrity framing -------------------------------------------------------
#
# Every blob the persistence layer writes (snapshot chunks, generation
# manifests, operator dumps) is wrapped in a self-checking frame so a torn
# write, a truncation, or a bit-flip on the storage medium is DETECTED at
# read time instead of silently corrupting recovered state:
#
#   magic "PWF1" | version u8 | payload length u64 LE | CRC32C u32 LE | payload
#
# CRC32C (Castagnoli) matches what object stores expose natively
# (x-goog-hash / x-amz-checksum-crc32c), so a future backend can delegate
# the check to the store.  The polynomial also guarantees detection of any
# single-bit flip and any burst shorter than 32 bits.

FRAME_MAGIC = b"PWF1"
FRAME_VERSION = 1
_FRAME_HEADER = struct.Struct("<4sBQI")
FRAME_OVERHEAD = _FRAME_HEADER.size


class IntegrityError(ValueError):
    """A persisted artifact failed its integrity frame check."""


_CRC32C_POLY = 0x82F63B78  # reflected Castagnoli
_crc32c_table: list[int] | None = None


def _crc32c_make_table() -> list[int]:
    table = []
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ _CRC32C_POLY if crc & 1 else crc >> 1
        table.append(crc)
    return table


class _Crc32cEngine:
    """Vectorized CRC-32C.

    A pure-python byte loop runs at ~4 MB/s — far too slow to frame every
    checkpoint chunk on the hot commit path.  CRC is *linear* over GF(2):
    with the reflected update ``step(s, b) = step0(s) ^ T[b]`` (where
    ``step0`` advances the register by one zero byte and ``T`` is the
    byte table, itself linear), the register after a K-byte block is

        step0^K(s_in)  XOR  XOR_j step0^(K-1-j)(T[b_j])

    so per block we need (a) one gather over K precomputed *positional*
    tables — a single numpy fancy-index + xor-reduce — and (b) the linear
    operator ``step0^K`` applied to the carried register via its images of
    the 32 basis bits.  Measured ~60 MB/s on MB-scale blobs (~15x the byte
    loop), exact CRC-32C semantics (verified against the canonical check
    value in the test suite).
    """

    # positional-table span: 512 keeps typical snapshot chunks (sub-KB) on
    # the vectorized path while the table set stays at 512 KB; measured
    # ~60 MB/s on MB-scale blobs vs ~4 MB/s for the plain byte loop
    BLOCK = 512
    _SLAB = 512  # blocks gathered per numpy call: bounds scratch at ~1 MB

    def __init__(self):
        self.table = np.array(_crc32c_make_table(), dtype=np.uint32)
        self.pos_tables: Any = None  # (BLOCK, 256) uint32, built lazily
        self.advance_basis: Any = None  # step0^BLOCK images of the 32 bits
        # reentrant: CRC verification runs on the SIGUSR1 flight-recorder
        # dump path (lease read → unframe), which may interrupt the main
        # thread mid-build while it holds this lock.  The build is
        # idempotent and publishes pos_tables LAST, so a reentrant
        # rebuild is wasted work, never a torn table — while a plain
        # Lock here would deadlock the handler (the PR-3 lazy-table race,
        # signal edition).
        self._build_lock = _threading.RLock()

    def _step0_vec(self, v):
        return (v >> np.uint32(8)) ^ self.table[v & np.uint32(0xFF)]

    def _build(self) -> None:
        tabs = np.empty((self.BLOCK, 256), dtype=np.uint32)
        cur = self.table.copy()  # contribution of the block's LAST byte
        tabs[self.BLOCK - 1] = cur
        for j in range(self.BLOCK - 2, -1, -1):
            cur = self._step0_vec(cur)
            tabs[j] = cur
        basis = np.array([1 << i for i in range(32)], dtype=np.uint32)
        for _ in range(self.BLOCK):
            basis = self._step0_vec(basis)
        self.advance_basis = [int(x) for x in basis]
        # publish pos_tables LAST: concurrent update() calls gate on it, so
        # advance_basis must already be visible (the checkpoint writer pool
        # frames chunks from several threads at once)
        self.pos_tables = tabs

    def _advance(self, state: int) -> int:
        """Apply ``step0^BLOCK`` to a 32-bit register via its basis images."""
        out = 0
        basis = self.advance_basis
        i = 0
        while state:
            if state & 1:
                out ^= basis[i]
            state >>= 1
            i += 1
        return out

    def update_bytes(self, state: int, data) -> int:
        """The classic per-byte loop (used for tails and small inputs)."""
        table = self.table
        for b in data:
            state = int(table[(state ^ b) & 0xFF]) ^ (state >> 8)
        return state

    def update(self, state: int, data: bytes) -> int:
        n_blocks, tail = divmod(len(data), self.BLOCK)
        if n_blocks == 0:
            return self.update_bytes(state, data)
        if self.pos_tables is None:
            with self._build_lock:
                if self.pos_tables is None:
                    self._build()
        arr = np.frombuffer(data, dtype=np.uint8, count=n_blocks * self.BLOCK)
        arr = arr.reshape(n_blocks, self.BLOCK)
        pos = np.arange(self.BLOCK)[None, :]
        contribs = np.empty(n_blocks, dtype=np.uint32)
        for lo in range(0, n_blocks, self._SLAB):
            hi = min(lo + self._SLAB, n_blocks)
            gathered = self.pos_tables[pos, arr[lo:hi]]
            contribs[lo:hi] = np.bitwise_xor.reduce(gathered, axis=1)
        for c in contribs:
            state = self._advance(state) ^ int(c)
        if tail:
            state = self.update_bytes(state, data[n_blocks * self.BLOCK :])
        return state


_crc32c_engine: _Crc32cEngine | None = None
# reentrant: crc32c() is reachable from the SIGUSR1 handler (see
# _ResolvingTable note above) — engine construction is idempotent
_crc32c_engine_lock = _threading.RLock()


def crc32c(data: bytes | memoryview, crc: int = 0) -> int:
    """CRC-32C (Castagnoli) of ``data``; chainable via the ``crc`` arg.
    Native path: hardware SSE4.2 CRC with the GIL released (GB/s — the
    writer pool frames chunks truly concurrently with the epoch loop);
    the vectorized-numpy engine below is the fallback.  Thread-safe:
    engines and their lazy tables build exactly once."""
    from pathway_tpu.engine.types import _native

    native = _native()
    if native is not None and hasattr(native, "crc32c"):
        # no bytes() copy: the native side takes any C-contiguous buffer
        # ("y*"), and copying MB-scale chunks here (under the GIL) would
        # re-serialize the writer-pool threads the native path unblocks
        return native.crc32c(data, crc)
    global _crc32c_engine
    engine = _crc32c_engine
    if engine is None:
        with _crc32c_engine_lock:
            if _crc32c_engine is None:
                _crc32c_engine = _Crc32cEngine()
            engine = _crc32c_engine
    state = ~crc & 0xFFFFFFFF
    state = engine.update(state, bytes(data))
    return ~state & 0xFFFFFFFF


def frame_blob(payload: bytes) -> bytes:
    """Wrap ``payload`` in the self-checking integrity frame."""
    return (
        _FRAME_HEADER.pack(
            FRAME_MAGIC, FRAME_VERSION, len(payload), crc32c(payload)
        )
        + payload
    )


def unframe_blob(
    data: bytes,
    *,
    what: str = "blob",
    allow_legacy: bool = False,
    verify_crc: bool = True,
) -> bytes:
    """Validate and strip the integrity frame; raises :class:`IntegrityError`.

    ``allow_legacy=True`` passes through blobs written before framing
    existed (no magic) unchanged — used only on migration read paths where
    the manifest records no digest for the artifact.

    ``verify_crc=False`` still validates the header/length (torn writes)
    but skips the checksum — for callers that already compared the blob
    against its manifest-pinned SHA-256 digest, which is strictly stronger
    than the frame CRC.
    """
    if len(data) < FRAME_OVERHEAD or data[:4] != FRAME_MAGIC:
        if allow_legacy and len(data) > 0 and data[:4] != FRAME_MAGIC:
            # legacy artifacts are never empty (chunks always hold >= 1
            # event): a zero-byte blob is a torn create, not legacy data
            return data
        raise IntegrityError(
            f"codec: {what}: missing or mangled integrity frame header "
            f"({len(data)} byte(s), magic {bytes(data[:4])!r})"
        )
    _magic, version, length, crc = _FRAME_HEADER.unpack_from(data)
    if version != FRAME_VERSION:
        raise IntegrityError(
            f"codec: {what}: unsupported frame version {version} "
            f"(this build reads version {FRAME_VERSION})"
        )
    payload = data[FRAME_OVERHEAD:]
    if len(payload) != length:
        raise IntegrityError(
            f"codec: {what}: torn or truncated payload — frame declares "
            f"{length} byte(s), found {len(payload)}"
        )
    if not verify_crc:
        return payload
    actual = crc32c(payload)
    if actual != crc:
        raise IntegrityError(
            f"codec: {what}: CRC32C mismatch (stored {crc:#010x}, "
            f"computed {actual:#010x}) — bit rot or a torn write"
        )
    return payload

# value tags
_T_NONE = 0
_T_FALSE = 1
_T_TRUE = 2
_T_INT = 3  # 8-byte signed
_T_BIGINT = 4  # length-prefixed signed big int
_T_FLOAT = 5
_T_STR = 6
_T_BYTES = 7
_T_POINTER = 8  # 16-byte little-endian u128
_T_TUPLE = 9
_T_NDARRAY = 10
_T_JSON = 11
_T_DATETIME_NAIVE = 12  # microseconds since epoch, 8-byte signed
_T_DATETIME_UTC = 13
_T_DURATION = 14  # microseconds, 8-byte signed
_T_ERROR = 15
_T_PYOBJECT = 16  # pickled (opaque fallback; decodes to the raw object)
_T_DATE = 17
_T_LIST = 18  # same layout as _T_TUPLE; decodes back to a list
_T_PYOBJECT_WRAPPED = 19  # pickled PyObjectWrapper.value; re-wrapped on decode

_U64 = struct.Struct("<Q")
_I64 = struct.Struct("<q")
_F64 = struct.Struct("<d")

_EPOCH_NAIVE = _dt.datetime(1970, 1, 1)
_EPOCH_UTC = _dt.datetime(1970, 1, 1, tzinfo=_dt.timezone.utc)


def _w_len(out: _io.BytesIO, n: int) -> None:
    out.write(_U64.pack(n))


def encode_value(v: Any, out: _io.BytesIO) -> None:
    if v is None:
        out.write(bytes([_T_NONE]))
    elif v is True:
        out.write(bytes([_T_TRUE]))
    elif v is False:
        out.write(bytes([_T_FALSE]))
    elif isinstance(v, int):
        if -(2**63) <= v < 2**63:
            out.write(bytes([_T_INT]))
            out.write(_I64.pack(v))
        else:
            b = v.to_bytes((v.bit_length() + 8) // 8 + 1, "little", signed=True)
            out.write(bytes([_T_BIGINT]))
            _w_len(out, len(b))
            out.write(b)
    elif isinstance(v, float):
        out.write(bytes([_T_FLOAT]))
        out.write(_F64.pack(v))
    elif isinstance(v, str):
        b = v.encode()
        out.write(bytes([_T_STR]))
        _w_len(out, len(b))
        out.write(b)
    elif isinstance(v, bytes):
        out.write(bytes([_T_BYTES]))
        _w_len(out, len(v))
        out.write(v)
    elif isinstance(v, Pointer):
        out.write(bytes([_T_POINTER]))
        out.write(v.value.to_bytes(16, "little"))
    elif isinstance(v, (tuple, list)):
        # lists get their own tag (same layout) so they round-trip as
        # lists: falling to the pickle tail would make delta buckets
        # opaque on the comm wire, and decoding them as tuples would make
        # value shapes differ between local and exchanged rows
        out.write(bytes([_T_TUPLE if isinstance(v, tuple) else _T_LIST]))
        _w_len(out, len(v))
        for item in v:
            encode_value(item, out)
    elif isinstance(v, np.ndarray):
        arr = np.ascontiguousarray(v)
        dts = arr.dtype.str.encode()
        shape = arr.shape
        out.write(bytes([_T_NDARRAY]))
        _w_len(out, len(dts))
        out.write(dts)
        _w_len(out, len(shape))
        for s in shape:
            out.write(_U64.pack(s))
        payload = arr.tobytes()
        _w_len(out, len(payload))
        out.write(payload)
    elif isinstance(v, Json):
        b = _json.dumps(v.value, sort_keys=True).encode()
        out.write(bytes([_T_JSON]))
        _w_len(out, len(b))
        out.write(b)
    elif isinstance(v, _dt.datetime):
        if v.tzinfo is None:
            out.write(bytes([_T_DATETIME_NAIVE]))
            micros = round((v - _EPOCH_NAIVE).total_seconds() * 1e6)
        else:
            out.write(bytes([_T_DATETIME_UTC]))
            micros = round((v - _EPOCH_UTC).total_seconds() * 1e6)
        out.write(_I64.pack(micros))
    elif isinstance(v, _dt.date):
        out.write(bytes([_T_DATE]))
        out.write(_I64.pack(v.toordinal()))
    elif isinstance(v, _dt.timedelta):
        out.write(bytes([_T_DURATION]))
        out.write(_I64.pack(round(v.total_seconds() * 1e6)))
    elif isinstance(v, Error):
        out.write(bytes([_T_ERROR]))
    elif isinstance(v, PyObjectWrapper):
        # distinct tag so decode re-wraps: wrapper equality must survive a
        # round trip (an exchanged retraction has to cancel a local insert)
        b = pickle.dumps(v.value)
        out.write(bytes([_T_PYOBJECT_WRAPPED]))
        _w_len(out, len(b))
        out.write(b)
    else:  # last resort: opaque pickle (keeps UDF-produced objects alive)
        b = pickle.dumps(v)
        out.write(bytes([_T_PYOBJECT]))
        _w_len(out, len(b))
        out.write(b)


def _r_len(buf: memoryview, pos: int) -> tuple[int, int]:
    return _U64.unpack_from(buf, pos)[0], pos + 8


def _take(buf: memoryview, pos: int, n: int) -> tuple[memoryview, int]:
    # subtraction form: corrupted length fields near u64::MAX must not
    # silently produce a short slice (matches the native Cursor::need)
    if pos > len(buf) or n > len(buf) - pos:
        raise ValueError("codec: truncated buffer")
    return buf[pos : pos + n], pos + n


def decode_value(
    buf: memoryview, pos: int, *, allow_pyobject: bool = True
) -> tuple[Any, int]:
    tag = buf[pos]
    pos += 1
    if tag in (_T_PYOBJECT, _T_PYOBJECT_WRAPPED) and not allow_pyobject:
        raise ValueError(
            "codec: python-object (pickled) value refused by typed-only "
            "decode — on the comm mesh this means a PyObjectWrapper row "
            "crossed an unauthenticated link; set PATHWAY_COMM_SECRET"
        )
    if tag == _T_NONE:
        return None, pos
    if tag == _T_TRUE:
        return True, pos
    if tag == _T_FALSE:
        return False, pos
    if tag == _T_INT:
        return _I64.unpack_from(buf, pos)[0], pos + 8
    if tag == _T_BIGINT:
        n, pos = _r_len(buf, pos)
        b, pos = _take(buf, pos, n)
        return int.from_bytes(b, "little", signed=True), pos
    if tag == _T_FLOAT:
        return _F64.unpack_from(buf, pos)[0], pos + 8
    if tag == _T_STR:
        n, pos = _r_len(buf, pos)
        b, pos = _take(buf, pos, n)
        return bytes(b).decode(), pos
    if tag == _T_BYTES:
        n, pos = _r_len(buf, pos)
        b, pos = _take(buf, pos, n)
        return bytes(b), pos
    if tag == _T_POINTER:
        b, pos = _take(buf, pos, 16)
        return Pointer(int.from_bytes(b, "little")), pos
    if tag in (_T_TUPLE, _T_LIST):
        n, pos = _r_len(buf, pos)
        items = []
        for _ in range(n):
            item, pos = decode_value(buf, pos, allow_pyobject=allow_pyobject)
            items.append(item)
        return (tuple(items) if tag == _T_TUPLE else items), pos
    if tag == _T_NDARRAY:
        n, pos = _r_len(buf, pos)
        b, pos = _take(buf, pos, n)
        dts = bytes(b).decode()
        ndim, pos = _r_len(buf, pos)
        shape = []
        for _ in range(ndim):
            shape.append(_U64.unpack_from(buf, pos)[0])
            pos += 8
        n, pos = _r_len(buf, pos)
        b, pos = _take(buf, pos, n)
        arr = np.frombuffer(b, dtype=np.dtype(dts)).reshape(shape)
        return as_hashable(arr.copy()), pos
    if tag == _T_JSON:
        n, pos = _r_len(buf, pos)
        b, pos = _take(buf, pos, n)
        return Json(_json.loads(bytes(b).decode())), pos
    if tag == _T_DATETIME_NAIVE:
        micros = _I64.unpack_from(buf, pos)[0]
        return _EPOCH_NAIVE + _dt.timedelta(microseconds=micros), pos + 8
    if tag == _T_DATETIME_UTC:
        micros = _I64.unpack_from(buf, pos)[0]
        return _EPOCH_UTC + _dt.timedelta(microseconds=micros), pos + 8
    if tag == _T_DATE:
        return _dt.date.fromordinal(_I64.unpack_from(buf, pos)[0]), pos + 8
    if tag == _T_DURATION:
        micros = _I64.unpack_from(buf, pos)[0]
        return _dt.timedelta(microseconds=micros), pos + 8
    if tag == _T_ERROR:
        return ERROR, pos
    if tag == _T_PYOBJECT:
        n, pos = _r_len(buf, pos)
        b, pos = _take(buf, pos, n)
        return pickle.loads(bytes(b)), pos
    if tag == _T_PYOBJECT_WRAPPED:
        n, pos = _r_len(buf, pos)
        b, pos = _take(buf, pos, n)
        return PyObjectWrapper(pickle.loads(bytes(b))), pos
    raise ValueError(f"codec: unknown value tag {tag}")


def encode_row_py(values: Iterable[Any]) -> bytes:
    out = _io.BytesIO()
    vals = tuple(values)
    _w_len(out, len(vals))
    for v in vals:
        encode_value(v, out)
    return out.getvalue()


def decode_row_py(
    data: bytes | memoryview, pos: int = 0, *, allow_pyobject: bool = True
) -> tuple[tuple, int]:
    buf = memoryview(data)
    try:
        n, pos = _r_len(buf, pos)
        items = []
        for _ in range(n):
            item, pos = decode_value(buf, pos, allow_pyobject=allow_pyobject)
            items.append(item)
    except ValueError:
        raise
    except MemoryError:
        raise
    except Exception as exc:
        # any other decode failure is buffer corruption (bit-rotted dtype
        # strings hit np.dtype's TypeError, mangled pickles raise
        # UnpicklingError, short fixed reads raise struct.error/IndexError)
        # — surface the single documented, catchable error the native
        # decoder also raises
        raise ValueError(f"codec: corrupt buffer ({exc})") from exc
    return tuple(items), pos


def encode_row(values: Iterable[Any]) -> bytes:
    from pathway_tpu.engine.types import _native

    native = _native()
    if native is not None:
        return native.encode_row(tuple(values))
    return encode_row_py(values)


def decode_row(data: bytes | memoryview, pos: int = 0) -> tuple[tuple, int]:
    from pathway_tpu.engine.types import _native

    native = _native()
    if native is not None:
        return native.decode_row(data, pos)
    return decode_row_py(data, pos)


def decode_row_typed(data: bytes | memoryview, pos: int = 0) -> tuple[tuple, int]:
    """Typed-only decode: raises ValueError on pickled (PYOBJECT) values.

    Used by the comm mesh for links without a handshake secret, where a
    pickle payload from the network would be arbitrary code execution.
    Always the Python decoder — the native one has no refusal hook.
    """
    return decode_row_py(data, pos, allow_pyobject=False)


# --- snapshot events ---------------------------------------------------------

EV_INSERT = 1
EV_DELETE = 2
EV_ADVANCE_TIME = 3
EV_FINISHED = 4


def encode_event(kind: int, key: int = 0, row: tuple = (), time: int = 0) -> bytes:
    out = _io.BytesIO()
    out.write(bytes([kind]))
    if kind in (EV_INSERT, EV_DELETE):
        # keys live in the 128-bit key space (value.rs Key = u128); mask
        # defensively so out-of-range ints cannot abort the event loop
        out.write((key & ((1 << 128) - 1)).to_bytes(16, "little", signed=False))
        payload = encode_row(row)
        _w_len(out, len(payload))
        out.write(payload)
    elif kind == EV_ADVANCE_TIME:
        out.write(_U64.pack(time))
    return out.getvalue()


def encode_events(events: Iterable[tuple]) -> bytes:
    """Encode ``(kind, key, row, time)`` tuples into one chunk payload —
    the batched form of :func:`encode_event` (single buffer, native-
    accelerated).  The checkpoint writer pool encodes whole raw-event
    batches through this so the epoch loop never pays the serializer."""
    from pathway_tpu.engine.types import _native

    native = _native()
    if native is not None and hasattr(native, "encode_events"):
        return native.encode_events(
            events if isinstance(events, (list, tuple)) else list(events)
        )
    out = _io.BytesIO()
    for kind, key, row, time in events:
        out.write(encode_event(kind, key, row, time))
    return out.getvalue()


def decode_events(data: bytes):
    """Yield (kind, key, row, time) tuples from a chunk of encoded events.

    Any malformed input — truncation mid-event, a mangled length field, a
    bit-rotted payload — raises the single documented ``ValueError`` the
    snapshot replay path catches; no other exception type escapes.
    """
    buf = memoryview(data)
    pos = 0
    end = len(buf)
    while pos < end:
        try:
            kind = buf[pos]
            pos += 1
            if kind in (EV_INSERT, EV_DELETE):
                piece, pos = _take(buf, pos, 16)
                key = int.from_bytes(piece, "little")
                n, pos = _r_len(buf, pos)
                if n > end - pos:
                    raise ValueError(
                        "codec: event row length field exceeds the chunk "
                        f"({n} > {end - pos} remaining byte(s))"
                    )
                row, row_end = decode_row(buf, pos)
                if row_end != pos + n:
                    # a mangled length field must never silently skip or
                    # swallow trailing events
                    raise ValueError(
                        "codec: event row length field disagrees with the "
                        f"decoded row ({n} declared, {row_end - pos} decoded)"
                    )
                pos = row_end
                yield kind, key, row, 0
            elif kind == EV_ADVANCE_TIME:
                t = _U64.unpack_from(buf, pos)[0]
                pos += 8
                yield kind, 0, (), t
            elif kind == EV_FINISHED:
                yield kind, 0, (), 0
            else:
                raise ValueError(f"codec: unknown event kind {kind}")
        except ValueError:
            raise
        except MemoryError:
            raise
        except Exception as exc:
            # short fixed-width reads raise struct.error/IndexError —
            # surface the one catchable corruption error (decode_row_py
            # applies the same rule per row)
            raise ValueError(f"codec: corrupt event chunk ({exc})") from exc
