"""Unified runtime metrics registry: counters, gauges, histograms.

Parity target: ``src/engine/telemetry.rs`` registers process gauges into
one OTel meter and ``http_server.rs`` serves the latest ``ProberStats``;
this module is the layer both lean on here — ONE registry per process
that the comm mesh (``engine/comm.py``), the persistence pipeline
(``engine/persistence.py``), the supervisor (``engine/supervisor.py``)
and the runner/probes (``internals/runner.py``) all register into, and
that every exporter reads from:

* Prometheus text exposition — appended to ``/metrics`` on the
  monitoring HTTP server (``engine/http_server.py``),
* OTLP/HTTP+JSON — scalar metrics ride the gauge datapoints and
  histograms map to real OTLP histogram datapoints
  (``engine/telemetry.py``),
* the console dashboard footer (``internals/monitoring.py``).

Design constraints, in order:

1. **Lock-cheap on hot paths.**  ``Counter.inc`` / ``Gauge.set`` are a
   guarded float add / store — no lock.  CPython's GIL makes the single
   ``+=`` on an instance slot atomic enough for telemetry (a torn
   increment under free-threaded builds would cost one count, never a
   crash); ``Histogram.observe`` takes a per-child lock because its
   bucket-array update is multi-step, and it is called at epoch/commit
   cadence, not per row.
2. **Labels are first-class** but resolved once: ``family.labels(...)``
   returns a child handle the caller keeps, so steady-state updates
   never touch a dict.
3. **Disable switch**: ``set_enabled(False)`` (or
   ``PATHWAY_METRICS_DISABLED=1``) turns every update into an immediate
   return — the lever ``benchmarks/telemetry_overhead.py`` uses to
   price the instrumentation itself.

Metric names are canonical **dotted** OTel-style names
(``comm.bytes.sent``); the Prometheus renderer derives the exposition
name by prefixing ``pathway_`` and mapping dots to underscores
(``pathway_comm_bytes_sent``).
"""

from __future__ import annotations

import math
import threading
import time as _time
import weakref
from bisect import bisect_left
from typing import Any, Callable, Iterable

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "get_registry",
    "set_enabled",
    "otlp_gauge",
    "otlp_histogram",
    "escape_label",
    "DEFAULT_BUCKETS",
]

# Default histogram bounds (seconds-ish / ms-ish magnitudes): wide enough
# for µs frame encodes and multi-second commit barriers alike.
DEFAULT_BUCKETS = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 50.0, 100.0,
    500.0, 1000.0, 5000.0,
)

# Millisecond-scale bounds for the epoch/commit/profiler histograms: host
# epochs and manifest publishes cluster in 0.1–100 ms, where the default
# bounds collapse everything into two buckets and flatten the quantile
# estimates derived from them (Histogram.quantile).
MS_BUCKETS = (
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
    500.0, 1000.0, 2500.0, 5000.0, 10000.0,
)

# Occupancy-fraction bounds for the device bucket-efficiency histogram
# (device/executor.py): each dispatched bucket observes real_rows/bucket
# in (0, 1] — 1.0 means a full bucket, low buckets mean padding waste.
OCCUPANCY_BUCKETS = (
    0.0625, 0.125, 0.1875, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0,
)

# Quantiles derived from every histogram's fixed buckets at read time,
# surfaced as synthetic gauges (`<name>.p50` …) in the Prometheus
# exposition, OTLP export, and the console dashboard footer.
QUANTILES = (("p50", 0.50), ("p95", 0.95), ("p99", 0.99))

# ---------------------------------------------------------------------------
# The declared metric-name registry.
#
# Every dotted metric name the process exports — registered directly
# (counter/gauge/histogram), emitted by a pull-time collector, or merged
# into telemetry samples — is declared here: name -> (kind, doc).
# ``pathway_tpu lint`` enforces it (rule ``metric-undeclared``): a
# registration under an undeclared literal, or under a name the checker
# cannot resolve statically (``metric-nonliteral``), fails the gate, so
# dashboards and alerts can trust this table to be the complete,
# stable namespace.  Kinds: counter | gauge | histogram | collector
# (collector = a register_collector() supplier name; its emitted gauges
# are declared individually as kind "gauge").
# ---------------------------------------------------------------------------

METRICS: dict[str, tuple[str, str]] = {
    # comm mesh (engine/comm.py)
    "comm.frames.sent": ("counter", "data/control frames written to peers"),
    "comm.frames.received": ("counter", "frames read from peers"),
    "comm.bytes.sent": ("counter", "bytes written to peers (headers included)"),
    "comm.bytes.received": ("counter", "bytes read from peers"),
    "comm.reconnects": ("counter", "link reconnect attempts"),
    "comm.retransmits": ("counter", "frames retransmitted after a resync"),
    "comm.retransmit.evictions": (
        "counter", "unacked frames evicted from a full retransmit buffer"),
    "comm.peers.dead": ("counter", "peers declared dead past the reconnect window"),
    "comm.heartbeat.staleness.s": (
        "gauge", "max seconds since any live peer was last heard from"),
    # epoch loop / dataflow (internals/runner.py, engine/probes.py)
    "epoch.duration.ms": ("histogram", "wall time of one processed epoch (ms)"),
    "commit.duration.ms": (
        "histogram", "wall time of one generation-manifest publish (ms)"),
    "dataflow.prober": ("collector", "dataflow progress totals supplier"),
    "dataflow.epochs": ("gauge", "epochs processed by this worker"),
    "dataflow.input.rows": ("gauge", "rows ingested across input nodes"),
    "dataflow.output.rows": ("gauge", "rows delivered across output nodes"),
    "dataflow.operators": ("gauge", "operator count of the lowered graph"),
    "dataflow.errors": ("gauge", "rows poisoned/logged by operators"),
    "dataflow.input.lag.ms": ("gauge", "input-side processing lag"),
    "dataflow.output.lag.ms": ("gauge", "output-side processing lag"),
    # persistence commit pipeline (engine/persistence.py, CommitMetrics)
    "persistence.fenced": (
        "counter", "commit-point writes rejected: a newer incarnation owns the root"),
    "persistence.scrub.runs": ("counter", "offline scrub audits run"),
    "persistence.scrub.damaged": (
        "counter", "scrub audits that found damage"),
    # elastic rescale (engine/persistence.py repartition resume)
    "persistence.repartition.sources": (
        "counter", "base sources re-partitioned by a topology-rescale resume"),
    "persistence.repartition.rows": (
        "counter", "rows replayed from superseded-topology logs (post shard "
        "filter)"),
    "persistence.repartition.chunks": (
        "counter", "superseded-topology chunks read during refs replay"),
    "checkpoint.commit.buffer": ("gauge", "cumulative encode/join seconds"),
    "checkpoint.commit.frame": ("gauge", "cumulative integrity-framing seconds"),
    "checkpoint.commit.hash": ("gauge", "cumulative SHA-256 seconds"),
    "checkpoint.commit.upload": ("gauge", "cumulative blob upload seconds"),
    "checkpoint.commit.barrier": ("gauge", "cumulative commit-barrier seconds"),
    "checkpoint.commit.backpressure": (
        "gauge", "seconds the epoch thread stalled on the in-flight byte cap"),
    "checkpoint.inflight.bytes": ("gauge", "snapshot bytes in flight to the store"),
    "checkpoint.inflight.jobs": ("gauge", "artifact writes in flight"),
    "checkpoint.inflight.bytes.max": ("gauge", "high-water mark of in-flight bytes"),
    "checkpoint.artifacts": ("gauge", "artifacts durably written"),
    "checkpoint.bytes": ("gauge", "artifact bytes durably written"),
    "checkpoint.commits": ("gauge", "generation manifests published"),
    "checkpoint.commits.noop": ("gauge", "commits confirmed as no-ops"),
    "checkpoint.gc.runs": ("gauge", "deferred-GC sweeps run"),
    "checkpoint.gc.deleted": ("gauge", "artifacts deleted by GC"),
    "checkpoint.gc.deferred": (
        "gauge", "GC sweeps deferred: newest generation failed read-back"),
    # supervisor (engine/supervisor.py)
    "supervisor.restarts": (
        "counter", "cluster rollback-and-respawn recoveries performed"),
    "supervisor.rescales": (
        "counter", "degraded-mode cluster rescales performed (worker-loss "
        "shrink)"),
    "supervisor.watchdog.kills": (
        "counter", "hung workers killed by the progress watchdog"),
    "supervisor.handoffs": (
        "counter", "planned rescales completed by LIVE shard handoff "
        "(coordinated drain + relaunch, no recovery rollback)"),
    "supervisor.handoff.fallbacks": (
        "counter", "live handoffs that faulted mid-flight and fell back "
        "to the restart-based rescale"),
    # warm-standby promotion (engine/standby.py, engine/supervisor.py)
    "supervisor.promotions": (
        "counter", "standby promotions performed (worker loss absorbed "
        "without a group restart)"),
    "supervisor.promotion.fallbacks": (
        "counter", "standby promotions that aborted and fell back to a "
        "whole-group restart"),
    "standby.state": (
        "collector", "warm-standby panel gauge supplier (reads the "
        "root's lease/standby.<sid> beacons + promotion history): "
        "standby.pool, standby.lag.s{standby=}, "
        "standby.verified.chunks{standby=}, supervisor.promotions and "
        "supervisor.promotions.last.worker"),
    "standby.pool": (
        "gauge", "standbys currently publishing an apply-cursor beacon"),
    "standby.lag.s": (
        "gauge", "age of the oldest committed generation the standby "
        "has not yet verified, by standby= (0 = within one commit of "
        "every shard)"),
    "standby.verified.chunks": (
        "gauge", "event-chunks deep-verified by the standby's tail "
        "loop since it started, by standby="),
    "supervisor.promotions.last.worker": (
        "gauge", "worker id adopted by the newest completed promotion"),
    # load-adaptive autoscaler (engine/autoscaler.py)
    "autoscaler.decisions": (
        "counter", "scaling decisions fired (grow + shrink)"),
    "autoscaler.budget.exhausted": (
        "counter", "scaling decisions suppressed because the rescale "
        "budget was spent"),
    "autoscaler.state": (
        "collector", "autoscaler panel gauge supplier (reads the "
        "supervisor-maintained lease/autoscaler.json state file)"),
    "autoscaler.target.workers": (
        "gauge", "the worker count the scale controller currently targets"),
    "autoscaler.budget.left": (
        "gauge", "rescale decisions remaining in this supervisor run's "
        "budget"),
    "autoscaler.cooldown.remaining.s": (
        "gauge", "seconds until the controller may fire again after the "
        "last rescale"),
    "autoscaler.phase": (
        "gauge", "controller phase: 0 steady, 1 hot-dwell, 2 cooldown, "
        "3 handoff in flight"),
    "autoscaler.decisions.logged": (
        "gauge", "entries in the bounded scaling-decision provenance log"),
    "autoscaler.last.decision": (
        "gauge", "target worker count of the newest decision, labelled "
        "with its action (grow/shrink/suppressed-*)"),
    "worker.restart.attempt": (
        "gauge", "supervisor restarts performed before this worker launch"),
    "worker.last_progress.age_s": (
        "gauge", "seconds since the worker's last epoch-progress beacon"),
    # serving-path robustness (engine/serving.py, io/http/_server.py)
    "serve.requests": (
        "counter", "REST requests answered, by code= (200/400/429/500/"
        "503/504) and route= — the by-status view of the serving front "
        "door"),
    "serve.inflight": (
        "gauge", "REST requests admitted into the pipeline and not yet "
        "answered (the count axis of the admission budget)"),
    "serve.inflight.bytes": (
        "gauge", "summed request-body bytes of in-flight REST requests "
        "(the bytes axis of the admission budget)"),
    "serve.queue.depth": (
        "gauge", "REST requests waiting in the admission pending queue"),
    "serve.queue.wait.ms": (
        "histogram", "time a request spent queued before admission (ms) "
        "— the CoDel-style delay signal the shedder watches"),
    "serve.latency.ms": (
        "histogram", "admitted-request end-to-end latency by route= (ms); "
        "its p50 sizes the Retry-After hint on 429/503 rejects"),
    "serve.shed": (
        "counter", "requests shed before doing pipeline work, by reason= "
        "(queue-full/degraded/queue-deadline/staged-expired/batcher/"
        "device/draining/drain-timeout)"),
    "serve.deadline.exceeded": (
        "counter", "requests answered 504, by where= the deadline lapse "
        "was caught (handler/queue/staging/batcher/device/"
        "generate-queue/decode)"),
    "serve.degraded": (
        "gauge", "1 while the load shedder is engaged (sustained queue "
        "delay above PATHWAY_SERVE_QUEUE_DELAY_MS); degraded-handler "
        "routes serve their cheap path while set"),
    "serve.degraded.transitions": (
        "counter", "degraded-mode engage/disengage edges (flapping here "
        "means the hysteresis knobs are too tight)"),
    "serve.degraded.served": (
        "counter", "requests answered by a registered degraded_handler "
        "instead of the full pipeline, by route="),
    "serve.draining": (
        "gauge", "1 while the webserver is draining (stop-accept 503; "
        "shutdown or live-handoff fence)"),
    "serve.drain.ms": (
        "histogram", "wall time from drain start to the last in-flight "
        "request completing (ms)"),
    "serve.quarantined": (
        "counter", "request rows failed by the pipeline (poisoned cells "
        "or row errors) completed as typed 500s and quarantined"),
    "serve.flood.synthetic": (
        "counter", "synthetic admissions injected by the request_flood "
        "chaos fault kind"),
    "serve.state": (
        "collector", "serving admission/shedder/drain state gauge "
        "supplier (engine/serving.py controller)"),
    # continuous-batching generation (serving/generation.py)
    "generate.requests": (
        "counter", "generation requests accepted into the continuous-"
        "batching queue"),
    "generate.queue.depth": (
        "gauge", "requests waiting for a generation slot (bounded by "
        "PATHWAY_GENERATE_QUEUE; overflow answers 429)"),
    "generate.slots.active": (
        "gauge", "generation slots occupied by a prefilling or decoding "
        "request"),
    "generate.slots.total": (
        "gauge", "configured generation slot count "
        "(PATHWAY_GENERATE_SLOTS — the device batch width)"),
    "generate.pages.used": (
        "gauge", "KV pool pages holding live tokens (page 0, the null "
        "page, is never counted)"),
    "generate.pages.total": (
        "gauge", "allocatable KV pool pages (PATHWAY_GENERATE_PAGES "
        "minus the reserved null page)"),
    "generate.kv.bytes.live": (
        "gauge", "bytes of KV pool backing live tokens — the paged "
        "cache's actual footprint, vs generate.kv.bytes.dense"),
    "generate.kv.bytes.peak": (
        "gauge", "high-water mark of generate.kv.bytes.live since "
        "scheduler start"),
    "generate.kv.bytes.dense": (
        "gauge", "what a dense slots x max_cache KV layout would hold "
        "resident — the baseline the paged pool is measured against"),
    "generate.tokens": (
        "counter", "tokens generated across all requests (EOS not "
        "counted)"),
    "generate.tokens_per_s": (
        "gauge", "sustained decode throughput over the trailing 5 s "
        "window"),
    "generate.ttft.ms": (
        "histogram", "request submit to first generated token (ms) — "
        "the latency continuous batching exists to bound under churn"),
    "generate.prefill.chunks": (
        "counter", "chunked-prefill programs dispatched (fixed "
        "PATHWAY_GENERATE_PREFILL_CHUNK width, interleaved with decode "
        "ticks)"),
    "generate.decode.steps": (
        "counter", "continuous decode ticks dispatched (one token per "
        "active slot per tick)"),
    "generate.churn.synthetic": (
        "counter", "synthetic burst requests injected by the "
        "request_churn chaos fault kind"),
    # columnar execution path (internals/vector_compiler.py)
    "columnar.bail.count": (
        "counter", "columnar fast-path batches that fell back to the "
        "row-wise evaluator, by op= and reason= (a silently bailing "
        "pipeline runs at row speed while benchmarking columnar)"),
    # per-operator epoch profiler (engine/profiler.py)
    "profiler.operators": (
        "collector", "top-N per-operator attribution snapshot supplier"),
    "profiler.operator.seconds": (
        "gauge", "cumulative step seconds of a top-N operator"),
    "profiler.operator.rows": (
        "gauge", "cumulative rows consumed by a top-N operator"),
    "profiler.epochs.sampled": (
        "gauge", "profiler sampling passes taken this run"),
    # JAX device accounting (engine/profiler.py jax.monitoring listeners)
    "jax.compile.count": (
        "counter", "XLA backend compilations observed in this process"),
    "jax.compile.seconds": (
        "counter", "cumulative XLA backend compile wall seconds"),
    "jax.cache.miss": (
        "counter", "jit cache misses (fresh jaxpr traces) observed"),
    "jax.transfer.h2d.bytes": (
        "counter", "explicit host-to-device transfer bytes (device_put)"),
    "jax.transfer.d2h.bytes": (
        "counter", "explicit device-to-host transfer bytes (device_get)"),
    # data-plane freshness & backpressure (engine/freshness.py)
    "freshness.tracker": (
        "collector", "freshness/backlog gauge supplier (the run's tracker)"),
    "freshness.e2e.ms": (
        "histogram", "ingest-to-delivery latency of output updates (ms)"),
    "output.staleness.s": (
        "gauge", "seconds since the ingest stamp of the newest data an "
        "output reflects"),
    "freshness.mesh.staleness.s": (
        "gauge", "worst output staleness across the worker mesh (worker 0)"),
    "backlog.connector.queue": (
        "gauge", "items waiting in a connector's reader queue"),
    "backlog.connector.idle.s": (
        "gauge", "seconds since an unfinished source last staged a row "
        "(the one-branch-stall signal)"),
    "backlog.ingest.rows": (
        "gauge", "rows staged at an input, not yet folded into an epoch"),
    "backlog.ingest.age.s": (
        "gauge", "age of the oldest staged row waiting at an input"),
    "backlog.epochs.pending": (
        "gauge", "distinct staged epoch timestamps awaiting processing"),
    "backlog.comm.inbox": (
        "gauge", "frames waiting in per-peer mesh inboxes (engine/comm.py)"),
    "backlog.checkpoint.bytes": (
        "gauge", "snapshot bytes in flight to the store (backlog alias of "
        "checkpoint.inflight.bytes)"),
    "backlog.checkpoint.jobs": (
        "gauge", "artifact writes in flight (backlog alias of "
        "checkpoint.inflight.jobs)"),
    # device executor (pathway_tpu/device/executor.py)
    "device.dispatch.batches": (
        "counter", "fixed-shape device batches dispatched by the executor"),
    "device.dispatch.rows": (
        "counter", "real rows dispatched through the executor"),
    "device.dispatch.ms": (
        "histogram", "wall time of one dispatched device call (ms)"),
    "device.job.ms": (
        "histogram", "wall time of one async host-side batch job (ms) — "
        "host prep included, unlike device.dispatch.ms"),
    "device.pad.rows": (
        "counter", "padding rows added by batch bucketing"),
    "device.cache.cold": (
        "counter", "first dispatches of a new compile-cache key (a cold "
        "compile paid in the serving path rather than by warmup)"),
    "device.warmup.compiles": (
        "counter", "compile-cache keys paid ahead of traffic by warmup()"),
    "device.jobs": (
        "counter", "async host-side batch jobs run by the dispatch thread"),
    "device.backpressure.s": (
        "counter", "seconds submitters stalled on the executor's in-flight "
        "budget"),
    "device.executor": (
        "collector", "device-dispatch backlog gauge supplier (the process "
        "executor)"),
    "backlog.device.queue": (
        "gauge", "batch jobs queued or running on the device-dispatch "
        "thread"),
    "backlog.device.bytes": (
        "gauge", "submitted batch bytes in flight through the dispatch "
        "queue"),
    "backlog.device.age.s": (
        "gauge", "age of the oldest batch job still in the dispatch queue"),
    # device cost accounting / roofline / HBM (pathway_tpu/device/telemetry.py)
    "device.flops.total": (
        "counter", "cost-analysis FLOPs moved by dispatched device batches"),
    "device.bytes.accessed": (
        "counter", "cost-analysis bytes accessed by dispatched device "
        "batches (XLA's HBM-traffic estimate)"),
    "device.achieved.flops_per_s": (
        "gauge", "cumulative FLOPs over cumulative device-call wall seconds"),
    "device.utilization": (
        "gauge", "roofline utilization estimate: achieved FLOP/s over the "
        "configured/auto-detected per-device peak"),
    "device.peak.flops_per_s": (
        "gauge", "the roofline denominator in use (PATHWAY_DEVICE_PEAK_FLOPS "
        "or the device-kind table; CPU gets a measured-peak default)"),
    "device.bucket.occupancy": (
        "histogram", "real-row fraction of each dispatched bucket (1.0 = "
        "no padding)"),
    "device.padding.waste.rows": (
        "gauge", "cumulative padding rows this executor dispatched"),
    "device.padding.waste.fraction": (
        "gauge", "padding rows over all dispatched rows — the bucket-set "
        "efficiency `pathway_tpu buckets` optimizes"),
    "device.batch.rows": (
        "gauge", "observed ragged batch-size distribution (rows= label; "
        "top sizes only) — the `pathway_tpu buckets` live feed"),
    "device.batch.max": (
        "gauge", "the default bucket-policy cap this process runs with "
        "(PATHWAY_DEVICE_MAX_BATCH) — `pathway_tpu buckets` replays "
        "against the analyzed run's value, not the analyst's env"),
    "device.hbm.bytes_in_use": (
        "gauge", "device memory in use: allocator memory_stats() where "
        "available, the executor's in-flight footprint elsewhere"),
    "device.hbm.peak": (
        "gauge", "peak device memory observed (same source rules as "
        "device.hbm.bytes_in_use)"),
    "device.trace.captures": (
        "counter", "on-demand jax.profiler traces captured (GET /trace, "
        "`pathway_tpu trace`)"),
    # device fault tolerance (pathway_tpu/device/resilience.py)
    "device.failures": (
        "counter", "classified device-path failures observed, labeled by "
        "kind (transient/oom/compile/hang)"),
    "device.retry.attempts": (
        "counter", "transient device failures retried by the dispatch "
        "wrapper (bounded jittered backoff)"),
    "device.oom.splits": (
        "counter", "RESOURCE_EXHAUSTED chunks split onto smaller buckets "
        "by the OOM ratchet"),
    "device.bucket.cap": (
        "gauge", "largest bucket a callable may plan after OOM ratcheting "
        "(callable= label; absent while uncapped)"),
    "device.breaker.state": (
        "gauge", "per-callable circuit-breaker state (callable= label): "
        "0 closed, 0.5 half-open, 1 open"),
    "device.breaker.trips": (
        "counter", "circuit-breaker open transitions (K consecutive "
        "device failures, or a failed half-open probe)"),
    "device.fallback.batches": (
        "counter", "batches served by the un-jitted host-fallback path "
        "while a breaker is open (or after retries failed)"),
    "device.fallback.rows": (
        "counter", "real rows served by the host fallback"),
    "device.fallback.ms": (
        "histogram", "wall time of one host-fallback batch execution (ms)"),
    "device.quarantine.batches": (
        "counter", "poisoned batches quarantined: device retries AND host "
        "fallback failed (waiters get DeviceQuarantinedError)"),
    "device.quarantine.records": (
        "gauge", "quarantine records currently retained "
        "(PATHWAY_DEVICE_QUARANTINE_KEEP newest)"),
    "device.dispatch.restarts": (
        "counter", "dispatch threads torn down and respawned after a "
        "hard dispatch-deadline hang (PATHWAY_DEVICE_DISPATCH_DEADLINE_S)"),
    # request-scoped tracing (engine/tracing.py)
    "trace.requests": (
        "counter", "request traces created by the serving path (W3C "
        "traceparent adopted on ingress, minted otherwise)"),
    "trace.spans": (
        "counter", "request-scoped spans recorded (admission, coalesce, "
        "device dispatch, generation stages)"),
    "trace.spans.dropped": (
        "counter", "request spans dropped by the per-trace span cap"),
    "trace.storm.synthetic": (
        "counter", "synthetic traces injected by the trace_storm chaos "
        "fault kind"),
    "trace.requests.state": (
        "collector", "finished-request ring gauge supplier "
        "(engine/tracing.py)"),
    "trace.requests.buffered": (
        "gauge", "finished request traces held in the bounded ring the "
        "`pathway_tpu requests` CLI reads"),
    "trace.requests.slowest.ms": (
        "gauge", "duration of the slowest buffered request trace (ms)"),
    "trace.requests.newest.ms": (
        "gauge", "duration of the newest buffered request trace (ms)"),
    # SLO engine (engine/slo.py)
    "slo.state": (
        "collector", "declared-SLO evaluation supplier (engine/slo.py)"),
    "slo.budget.remaining": (
        "gauge", "error-budget fraction remaining over the SLO's window, "
        "by slo= (1 = untouched, 0 = exhausted, negative = overspent)"),
    "slo.burn.rate": (
        "gauge", "error-budget burn rate by slo= and window= (1.0 = "
        "burning exactly the budget; sustained >1 exhausts it before the "
        "window ends)"),
    "slo.violations": (
        "counter", "burn-rate threshold crossings (burn > 1 rising edges) "
        "by slo= — each one also lands a flight-recorder slo.violation "
        "event"),
    # telemetry (engine/telemetry.py)
    "telemetry.export.dropped": (
        "counter", "telemetry payloads dropped by the bounded export queue"),
    "process.memory.usage": ("gauge", "resident set size in bytes"),
    "process.cpu.utime": ("gauge", "user CPU seconds"),
    "process.cpu.stime": ("gauge", "system CPU seconds"),
    "latency.input": ("gauge", "input lag of the latest ProberStats (ms)"),
    "latency.output": ("gauge", "output lag of the latest ProberStats (ms)"),
}


class _Enabled:
    """Shared mutable on/off flag — one attribute read per update."""

    __slots__ = ("on",)

    def __init__(self, on: bool):
        self.on = on


def _label_key(labels: dict[str, str]) -> tuple[tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Counter:
    """Monotonic counter child (one label set)."""

    __slots__ = ("_value", "_enabled")

    def __init__(self, enabled: _Enabled):
        self._value = 0.0
        self._enabled = enabled

    def inc(self, amount: float = 1.0) -> None:
        if self._enabled.on:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Point-in-time gauge child (one label set)."""

    __slots__ = ("_value", "_enabled")

    def __init__(self, enabled: _Enabled):
        self._value = 0.0
        self._enabled = enabled

    def set(self, value: float) -> None:
        if self._enabled.on:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        if self._enabled.on:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        if self._enabled.on:
            self._value -= amount

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Fixed-bucket histogram child (one label set).

    Buckets are cumulative-on-read (Prometheus ``le`` semantics) but
    stored per-interval, so ``observe`` touches exactly one slot.
    """

    __slots__ = (
        "_enabled", "_bounds", "_counts", "_sum", "_count", "_lock",
        "_exemplars",
    )

    def __init__(self, enabled: _Enabled, bounds: tuple[float, ...]):
        self._enabled = enabled
        self._bounds = bounds
        self._counts = [0] * (len(bounds) + 1)  # last slot = +Inf
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()
        # bucket index -> (trace_id, value, unix ts): the LAST traced
        # observation per bucket, rendered as an OpenMetrics exemplar
        # (`# {trace_id=...}`) so a slow bucket links to a real request
        # trace.  Lazily allocated — untraced histograms pay nothing.
        self._exemplars: dict[int, tuple[str, float, float]] | None = None

    def observe(self, value: float, trace_id: str | None = None) -> None:
        if not self._enabled.on:
            return
        i = bisect_left(self._bounds, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1
            if trace_id:
                if self._exemplars is None:
                    self._exemplars = {}
                self._exemplars[i] = (trace_id, value, _time.time())

    def snapshot(self) -> tuple[tuple[float, ...], list[int], float, int]:
        """(bounds, per-interval counts, sum, count) — a consistent read."""
        with self._lock:
            return self._bounds, list(self._counts), self._sum, self._count

    def exemplars(self) -> dict[int, tuple[str, float, float]]:
        """``{bucket index: (trace_id, value, ts)}`` — the +Inf bucket is
        index ``len(bounds)``."""
        with self._lock:
            return dict(self._exemplars) if self._exemplars else {}

    def quantile(self, q: float) -> float | None:
        """Estimate the ``q``-quantile from the fixed buckets (linear
        interpolation within the holding bucket — Prometheus
        ``histogram_quantile`` semantics).  Observations in the +Inf
        bucket clamp to the highest finite bound; ``None`` when empty."""
        bounds, counts, _total, n = self.snapshot()
        if n == 0 or not bounds:
            return None
        rank = q * n
        cum = 0
        lo = 0.0
        for bound, c in zip(bounds, counts):
            if c and cum + c >= rank:
                return lo + (rank - cum) / c * (bound - lo)
            cum += c
            lo = bound
        return float(bounds[-1])


class _Family:
    """One named metric family holding children keyed by label set."""

    __slots__ = ("name", "help", "kind", "buckets", "_children", "_enabled", "_lock")

    def __init__(
        self,
        name: str,
        help_: str,
        kind: str,
        enabled: _Enabled,
        buckets: tuple[float, ...] | None = None,
    ):
        self.name = name
        self.help = help_
        self.kind = kind  # "counter" | "gauge" | "histogram"
        self.buckets = buckets
        self._children: dict[tuple, Any] = {}
        self._enabled = enabled
        # reentrant: counters are registered from the SIGUSR1 flight-
        # recorder path (persistence.fenced via FlightRecorder._fenced),
        # and the handler may interrupt the main thread inside labels() —
        # a plain Lock would deadlock the worker in the handler.  The
        # worst reentrant outcome is a double-created child (one lost
        # count), never a crash.
        self._lock = threading.RLock()

    def labels(self, **labels: Any):
        key = _label_key(labels)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.get(key)
                if child is None:
                    if self.kind == "counter":
                        child = Counter(self._enabled)
                    elif self.kind == "gauge":
                        child = Gauge(self._enabled)
                    else:
                        child = Histogram(self._enabled, self.buckets or DEFAULT_BUCKETS)
                    self._children[key] = child
        return child

    def items(self) -> list[tuple[tuple, Any]]:
        with self._lock:
            return list(self._children.items())


class MetricsRegistry:
    """Process-wide registry of metric families + pull-time collectors.

    ``collector`` functions return flat ``{dotted-name: float}`` gauge
    dicts read at render/export time — the bridge for subsystems that
    already keep their own counters (``persistence.CommitMetrics``) and
    for snapshot suppliers (``ProberStats`` totals).  They are held via
    weakref to their owner, so a storage or prober that dies simply
    drops out of the exposition.
    """

    def __init__(self, *, enabled: bool | None = None):
        if enabled is None:
            from pathway_tpu.internals.config import env_bool

            enabled = not env_bool("PATHWAY_METRICS_DISABLED")
        self._enabled = _Enabled(enabled)
        self._families: dict[str, _Family] = {}
        # reentrant for the same reason as _Family._lock: the SIGUSR1
        # handler's fence-counter registration may interrupt a frame that
        # already holds this lock (a torn double-create loses one count;
        # a plain Lock loses the worker)
        self._lock = threading.RLock()
        # name -> weakref-able callable returning {name: value}
        self._collectors: dict[str, Any] = {}

    # -- family accessors --------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._enabled.on

    def set_enabled(self, on: bool) -> None:
        self._enabled.on = bool(on)

    def _family(
        self,
        name: str,
        help_: str,
        kind: str,
        buckets: tuple[float, ...] | None = None,
    ) -> _Family:
        fam = self._families.get(name)
        if fam is None:
            with self._lock:
                fam = self._families.get(name)
                if fam is None:
                    fam = _Family(name, help_, kind, self._enabled, buckets)
                    self._families[name] = fam
        if fam.kind != kind:
            raise ValueError(
                f"metric {name!r} already registered as a {fam.kind}, "
                f"not a {kind}"
            )
        return fam

    def family(self, name: str) -> _Family | None:
        """Read-only family lookup — ``None`` when nothing has touched
        the name yet (the SLO evaluator reads families without creating
        them, so a never-observed metric stays absent from exposition)."""
        return self._families.get(name)

    def counter(self, name: str, help_: str = "", **labels: Any) -> Counter:
        return self._family(name, help_, "counter").labels(**labels)

    def gauge(self, name: str, help_: str = "", **labels: Any) -> Gauge:
        return self._family(name, help_, "gauge").labels(**labels)

    def histogram(
        self,
        name: str,
        help_: str = "",
        buckets: Iterable[float] | None = None,
        **labels: Any,
    ) -> Histogram:
        bounds = tuple(buckets) if buckets is not None else None
        return self._family(name, help_, "histogram", bounds).labels(**labels)

    # -- collectors --------------------------------------------------------
    def register_collector(
        self, name: str, fn: Callable[[], dict[str, float] | None]
    ) -> None:
        """Register a pull-time gauge supplier under a unique name
        (re-registering the name replaces the previous supplier).  Bound
        methods are held through a ``WeakMethod`` so the collector dies
        with its owner."""
        ref: Any
        try:
            ref = weakref.WeakMethod(fn)  # bound method: weak to the owner
        except TypeError:
            ref = lambda f=fn: f  # plain function/lambda: hold strongly
        with self._lock:
            self._collectors[name] = ref

    def unregister_collector(self, name: str) -> None:
        with self._lock:
            self._collectors.pop(name, None)

    def collect(self) -> dict[str, float]:
        """Evaluate every live collector into one flat gauge dict."""
        with self._lock:
            refs = list(self._collectors.items())
        out: dict[str, float] = {}
        dead: list[tuple[str, Any]] = []
        for name, ref in refs:
            fn = ref()
            if fn is None:
                dead.append((name, ref))
                continue
            try:
                out.update(fn() or {})
            except Exception:  # noqa: BLE001 - a supplier must never break export
                continue
        if dead:
            with self._lock:
                for name, ref in dead:
                    if self._collectors.get(name) is ref:  # unchanged slot
                        self._collectors.pop(name, None)
        return out

    # -- reads -------------------------------------------------------------
    def scalar_metrics(self) -> dict[str, float]:
        """Flat ``{name[{labels}]: value}`` of counters/gauges + collector
        output — the form the OTLP gauge exporter and the dashboard eat.
        Labeled children get a ``name{k=v,...}`` suffix so distinct label
        sets stay distinct.  Histogram quantile estimates ride along as
        derived ``<name>.p50/.p95/.p99`` gauges, so every scalar surface
        (OTLP, dashboard) sees latency percentiles for free."""
        out: dict[str, float] = {}
        with self._lock:
            families = list(self._families.values())
        for fam in families:
            if fam.kind == "histogram":
                continue
            for key, child in fam.items():
                if key:
                    label_str = ",".join(f"{k}={v}" for k, v in key)
                    out[f"{fam.name}{{{label_str}}}"] = child.value
                else:
                    out[fam.name] = child.value
        out.update(self.histogram_quantiles())
        out.update(self.collect())
        return out

    def histogram_quantiles(self) -> dict[str, float]:
        """Derived ``{name.pXX[{labels}]: value}`` gauges for every
        non-empty histogram child (see :data:`QUANTILES`)."""
        out: dict[str, float] = {}
        with self._lock:
            families = [f for f in self._families.values() if f.kind == "histogram"]
        for fam in families:
            for key, child in fam.items():
                for suffix, q in QUANTILES:
                    value = child.quantile(q)
                    if value is None:
                        continue
                    name = f"{fam.name}.{suffix}"
                    if key:
                        label_str = ",".join(f"{k}={v}" for k, v in key)
                        name = f"{name}{{{label_str}}}"
                    out[name] = value
        return out

    def histogram_points(self) -> list[dict[str, Any]]:
        """Histogram snapshots in exporter-neutral form:
        ``{name, labels, bounds, bucket_counts (per-interval), sum, count}``."""
        points: list[dict[str, Any]] = []
        with self._lock:
            families = [f for f in self._families.values() if f.kind == "histogram"]
        for fam in families:
            for key, child in fam.items():
                bounds, counts, total, n = child.snapshot()
                points.append(
                    {
                        "name": fam.name,
                        "labels": dict(key),
                        "bounds": list(bounds),
                        "bucket_counts": counts,
                        "sum": total,
                        "count": n,
                    }
                )
        return points

    # -- Prometheus text exposition ---------------------------------------
    def render_prometheus(self, extra_labels: dict[str, str] | None = None) -> str:
        """Exposition-format text for every family + collector gauge.

        No trailing ``# EOF`` — the caller composing a full scrape body
        (``engine/http_server.py``) appends it once."""
        lines: list[str] = []
        extra = _label_key(extra_labels or {})
        with self._lock:
            families = sorted(self._families.values(), key=lambda f: f.name)
        for fam in families:
            prom = _prom_name(fam.name)
            items = fam.items()
            if not items:
                continue
            lines.append(f"# HELP {prom} {fam.help or fam.name}")
            lines.append(f"# TYPE {prom} {fam.kind}")
            for key, child in items:
                label_str = _prom_labels(key + extra)
                if fam.kind == "histogram":
                    bounds, counts, total, n = child.snapshot()
                    exemplars = child.exemplars()
                    cum = 0
                    for i, (bound, c) in enumerate(zip(bounds, counts)):
                        cum += c
                        le = _prom_labels(
                            key + extra + (("le", _format_bound(bound)),)
                        )
                        lines.append(
                            f"{prom}_bucket{le} {cum}"
                            + _format_exemplar(exemplars.get(i))
                        )
                    cum += counts[-1]
                    le = _prom_labels(key + extra + (("le", "+Inf"),))
                    lines.append(
                        f"{prom}_bucket{le} {cum}"
                        + _format_exemplar(exemplars.get(len(bounds)))
                    )
                    lines.append(f"{prom}_sum{label_str} {_format_value(total)}")
                    lines.append(f"{prom}_count{label_str} {n}")
                else:
                    lines.append(
                        f"{prom}{label_str} {_format_value(child.value)}"
                    )
            if fam.kind == "histogram":
                # bucket-derived quantile gauges, one synthetic family per
                # quantile — scrapers that can't run histogram_quantile()
                # (and the dashboard footer) read percentiles directly
                for suffix, q in QUANTILES:
                    qsamples = [
                        (key, child.quantile(q)) for key, child in items
                    ]
                    qsamples = [(k, v) for k, v in qsamples if v is not None]
                    if not qsamples:
                        continue
                    lines.append(
                        f"# HELP {prom}_{suffix} {suffix} estimate of "
                        f"{fam.help or fam.name}"
                    )
                    lines.append(f"# TYPE {prom}_{suffix} gauge")
                    for key, value in qsamples:
                        lines.append(
                            f"{prom}_{suffix}{_prom_labels(key + extra)} "
                            f"{_format_value(value)}"
                        )
        collected = self.collect()
        if collected:
            # collector keys may carry a "{k=v,...}" label suffix (the
            # profiler's per-operator gauges do): split it into real
            # Prometheus labels — mangling it into the metric NAME would
            # mint a new family per label set (unbounded name cardinality)
            grouped: dict[str, list[tuple[tuple, float]]] = {}
            for name in sorted(collected):
                base, labels = split_labeled_name(name)
                grouped.setdefault(base, []).append(
                    (_label_key(labels), collected[name])
                )
            for base, samples in grouped.items():
                prom = _prom_name(base)
                lines.append(f"# HELP {prom} {base}")
                lines.append(f"# TYPE {prom} gauge")
                for key, value in samples:
                    lines.append(
                        f"{prom}{_prom_labels(key + extra)} "
                        f"{_format_value(value)}"
                    )
        return "\n".join(lines) + ("\n" if lines else "")

    def exemplar_points(self) -> dict[str, list[dict[str, Any]]]:
        """Every histogram child's bucket exemplars, keyed by the
        ``name{labels}`` scalar form — the ``/status`` exemplar view
        (``engine/http_server.py``'s ``requests`` section)."""
        out: dict[str, list[dict[str, Any]]] = {}
        with self._lock:
            families = [f for f in self._families.values() if f.kind == "histogram"]
        for fam in families:
            for key, child in fam.items():
                exemplars = child.exemplars()
                if not exemplars:
                    continue
                bounds = child.snapshot()[0]
                name = fam.name
                if key:
                    label_str = ",".join(f"{k}={v}" for k, v in key)
                    name = f"{name}{{{label_str}}}"
                out[name] = [
                    {
                        "le": (
                            _format_bound(bounds[i])
                            if i < len(bounds)
                            else "+Inf"
                        ),
                        "trace_id": trace_id,
                        "value": value,
                        "ts": ts,
                    }
                    for i, (trace_id, value, ts) in sorted(exemplars.items())
                ]
        return out

    # -- OTLP mapping ------------------------------------------------------
    def otlp_metrics(self, ts: float | None = None) -> list[dict]:
        """This registry's families as OTLP JSON ``metrics`` entries —
        scalars as gauge datapoints, histograms as histogram datapoints
        (the opentelemetry-proto JSON mapping).  The caller wraps them in
        its ``resourceMetrics`` envelope (``engine/telemetry.py``)."""
        t_ns = str(int((ts if ts is not None else _time.time()) * 1e9))
        out: list[dict] = []
        for name, value in self.scalar_metrics().items():
            out.append(otlp_gauge(name, value, t_ns))
        for point in self.histogram_points():
            out.append(otlp_histogram(point, t_ns))
        return out


def otlp_gauge(name: str, value: float, t_ns: str) -> dict:
    """One scalar metric as an OTLP JSON gauge ``metrics`` entry.  A
    ``"{k=v,...}"`` label suffix on the name (the ``scalar_metrics`` form)
    becomes datapoint attributes — OTLP wants the clean base name."""
    base, labels = split_labeled_name(name)
    dp: dict[str, Any] = {"asDouble": float(value), "timeUnixNano": t_ns}
    if labels:
        dp["attributes"] = [
            {"key": k, "value": {"stringValue": v}} for k, v in labels.items()
        ]
    return {"name": base, "gauge": {"dataPoints": [dp]}}


def otlp_histogram(point: dict[str, Any], t_ns: str) -> dict:
    """One exporter-neutral histogram point (``histogram_points`` form) as
    an OTLP JSON ``metrics`` entry with a real histogram datapoint."""
    dp: dict[str, Any] = {
        "startTimeUnixNano": t_ns,
        "timeUnixNano": t_ns,
        "count": str(point["count"]),
        "sum": point["sum"],
        "bucketCounts": [str(c) for c in point["bucket_counts"]],
        "explicitBounds": list(point["bounds"]),
    }
    if point.get("labels"):
        dp["attributes"] = [
            {"key": k, "value": {"stringValue": str(v)}}
            for k, v in point["labels"].items()
        ]
    return {
        "name": point["name"],
        "histogram": {
            "dataPoints": [dp],
            "aggregationTemporality": 2,  # CUMULATIVE
        },
    }


def _prom_name(name: str) -> str:
    safe = "".join(c if c.isalnum() or c == "_" else "_" for c in name)
    return safe if safe.startswith("pathway_") else f"pathway_{safe}"


def escape_label(value: str) -> str:
    """Escape a Prometheus label value per the exposition format."""
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _prom_labels(key: tuple[tuple[str, str], ...]) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{escape_label(str(v))}"' for k, v in key)
    return "{" + inner + "}"


def _format_bound(bound: float) -> str:
    if bound == int(bound):
        return str(int(bound)) + ".0"
    return repr(bound)


def _format_exemplar(ex: tuple[str, float, float] | None) -> str:
    """OpenMetrics exemplar suffix for one bucket line (empty when the
    bucket never saw a traced observation):
    ``# {trace_id="..."} <value> <ts>``."""
    if ex is None:
        return ""
    trace_id, value, ts = ex
    return (
        f' # {{trace_id="{escape_label(str(trace_id))}"}} '
        f"{_format_value(value)} {ts:.3f}"
    )


def _format_value(value: float) -> str:
    if isinstance(value, float) and math.isfinite(value) and value == int(value):
        return str(int(value))
    return repr(float(value))


def split_labeled_name(name: str) -> tuple[str, dict[str, str]]:
    """``"a.b{k=v,k2=v2}"`` → ``("a.b", {"k": "v", "k2": "v2"})``."""
    if not name.endswith("}") or "{" not in name:
        return name, {}
    base, _, rest = name.partition("{")
    labels: dict[str, str] = {}
    for pair in rest[:-1].split(","):
        k, _, v = pair.partition("=")
        if k:
            labels[k] = v
    return base, labels


# ---------------------------------------------------------------------------
# Process-wide default registry
# ---------------------------------------------------------------------------

_registry: MetricsRegistry | None = None
# reentrant: get_registry() sits on the SIGUSR1 flight-recorder path and
# may interrupt a first-call construction on the main thread
_registry_lock = threading.RLock()


def get_registry() -> MetricsRegistry:
    """The process-wide registry every subsystem registers into."""
    global _registry
    if _registry is None:
        with _registry_lock:
            if _registry is None:
                _registry = MetricsRegistry()
    return _registry


def set_enabled(on: bool) -> None:
    """Flip instrumentation on/off process-wide (benchmark lever)."""
    get_registry().set_enabled(on)
