"""Crash flight recorder: a bounded ring of structured runtime events.

An aircraft-style black box for the worker runtime: every notable state
transition — epoch starts, commit-barrier publishes, comm link drops and
reconnects, injected faults, restart attempts — is appended to a bounded
in-memory ring (cheap: one dict + deque append under a lock).  When the
worker crashes or a fault fires, the ring is dumped as JSON to
``<persistence root>/blackbox/`` so the supervisor
(``engine/supervisor.py``) can gather every worker's last seconds into
``SupervisorResult.post_mortem`` and the ``pathway_tpu blackbox`` CLI can
pretty-print them long after the processes are gone.

The recorder is process-global (one worker process = one recorder) and
always records in memory; **dumping** requires a configured filesystem
root (the runner wires it when the run persists to a ``FileBackend``).
SIGKILL-style injected crashes dump *before* the kill
(``engine/faults.py``); real uncaught failures dump from the runner's
failure path.  A genuine external SIGKILL leaves no dump — exactly like
a real black box losing power — but the supervisor still reconstructs
the restart story from exit codes and checkpoint provenance.

Events deliberately carry wall-clock AND monotonic stamps: wall clock
correlates across workers (and with the run's trace), monotonic orders
events within one process even across clock steps.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any

DEFAULT_CAPACITY = 512
_DUMP_DIR = "blackbox"


class FlightRecorder:
    """Bounded ring of ``{"seq", "ts", "mono", "kind", ...}`` events."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._ring: deque[dict[str, Any]] = deque(maxlen=capacity)
        # reentrant: the watchdog's SIGUSR1 handler (internals/runner.py)
        # calls record()/dump() on the MAIN thread and may interrupt a
        # record() that already holds this lock — a plain Lock would
        # deadlock the whole worker inside the signal handler
        self._lock = threading.RLock()
        self._seq = 0
        # dump context, set by configure(): without a root, record() still
        # works (post-mortems via the in-process API) but dump() no-ops
        self.root: str | None = None
        self.worker = 0
        self.run_id: str | None = None
        self.trace_parent: str | None = None
        self.attempt = 0
        # cluster incarnation of this process (PATHWAY_INCARNATION); when
        # set, dump() is FENCED like every other write to the persistence
        # root — a zombie from a superseded restart attempt must not drop
        # its stale story into the live cluster's blackbox/ directory
        # (the supervisor's post-mortem gather would misattribute it)
        self.incarnation = 0
        self._dumped: str | None = None  # path of the last dump, if any
        # optional profiler supplier (engine/profiler.py): when set, every
        # dump carries a final top-N operator attribution snapshot, so a
        # post-mortem says where the time went, not just what happened
        self._profile_supplier: Any = None
        # optional freshness supplier (engine/freshness.py): final
        # watermark/backlog snapshot — what was STUCK, not just slow
        self._freshness_supplier: Any = None
        # optional device supplier (pathway_tpu/device/executor.py): final
        # DeviceExecutor snapshot (cost/utilization/padding/HBM/queue) —
        # what the DEVICE was doing when the process died
        self._device_supplier: Any = None
        # optional autoscaler supplier (engine/autoscaler.py): the scale
        # controller's decision log + panel state — post-mortems say WHY
        # a rescale fired (or why one was suppressed)
        self._autoscaler_supplier: Any = None
        # optional serving supplier (engine/serving.py): the admission
        # controller's final state (in-flight/queue depth, degraded/
        # draining, quarantine tail) — post-mortems say what the SERVING
        # edge was refusing when the process died
        self._serving_supplier: Any = None
        # optional generation supplier (serving/generation.py): the
        # continuous-batching scheduler's slot/page-pool occupancy —
        # post-mortems say what the GENERATION loop was holding when
        # the process died
        self._generation_supplier: Any = None
        # optional tracing supplier (engine/tracing.py): the finished-
        # request ring (trace ids, durations, span trees) — post-mortems
        # carry the last requests' waterfalls, and `pathway_tpu requests`
        # can re-render them from the dump alone
        self._tracing_supplier: Any = None
        # optional SLO supplier (engine/slo.py): declared objectives with
        # their burn rates and remaining budgets — post-mortems say which
        # promises were being broken, not just which gauges moved
        self._slo_supplier: Any = None

    # -- recording ---------------------------------------------------------
    def record(self, kind: str, **fields: Any) -> None:
        event = {
            "ts": time.time(),
            "mono": time.monotonic(),
            "kind": kind,
        }
        event.update(fields)
        with self._lock:
            self._seq += 1
            event["seq"] = self._seq
            self._ring.append(event)

    def events(self) -> list[dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    def configure(
        self,
        *,
        root: str | None = None,
        worker: int | None = None,
        run_id: str | None = None,
        trace_parent: str | None = None,
        attempt: int | None = None,
        incarnation: int | None = None,
    ) -> None:
        """Attach dump context; each keyword only overwrites when given."""
        with self._lock:
            if root is not None:
                self.root = root
            if worker is not None:
                self.worker = worker
            if run_id is not None:
                self.run_id = run_id
            if trace_parent is not None:
                self.trace_parent = trace_parent
            if attempt is not None:
                self.attempt = attempt
            if incarnation is not None:
                self.incarnation = incarnation

    def set_profile_supplier(self, fn: Any) -> None:
        """Attach (or clear, with ``None``) the callable whose snapshot
        dict rides every subsequent dump under the ``profiler`` key.  The
        runner sets it for the run's lifetime and clears it on exit, so
        the global recorder never outlives a run's node arena."""
        self._profile_supplier = fn

    def set_freshness_supplier(self, fn: Any) -> None:
        """Attach (or clear) the callable whose watermark/backlog snapshot
        rides every subsequent dump under the ``freshness`` key (same
        lifetime contract as :meth:`set_profile_supplier`)."""
        self._freshness_supplier = fn

    def set_device_supplier(self, fn: Any) -> None:
        """Attach (or clear) the callable whose DeviceExecutor snapshot
        rides every subsequent dump under the ``device`` key (same
        lifetime contract as :meth:`set_profile_supplier`) — post-mortems
        say what the device was doing, not just the host."""
        self._device_supplier = fn

    def set_autoscaler_supplier(self, fn: Any) -> None:
        """Attach (or clear) the callable whose autoscaler state (decision
        log, target topology, handoff phase) rides every subsequent dump
        under the ``autoscaler`` key (same lifetime contract as
        :meth:`set_profile_supplier`) — post-mortems say why the cluster
        was scaling, not just that it died mid-rescale."""
        self._autoscaler_supplier = fn

    def set_serving_supplier(self, fn: Any) -> None:
        """Attach (or clear) the callable whose admission-controller
        snapshot (in-flight/queue occupancy, degraded/draining flags,
        quarantine tail) rides every subsequent dump under the
        ``serving`` key (same lifetime contract as
        :meth:`set_profile_supplier`) — post-mortems say what the serving
        edge was shedding, not just that clients saw errors."""
        self._serving_supplier = fn

    def set_generation_supplier(self, fn: Any) -> None:
        """Attach (or clear) the callable whose generation-scheduler
        snapshot (slot occupancy, page-pool utilization, queue depth,
        live/peak KV bytes) rides every subsequent dump under the
        ``generation`` key (same lifetime contract as
        :meth:`set_profile_supplier`) — post-mortems say which requests
        held slots and pages, not just that tokens stopped."""
        self._generation_supplier = fn

    def set_tracing_supplier(self, fn: Any) -> None:
        """Attach (or clear) the callable whose finished-request-ring
        snapshot (trace ids, durations, span trees) rides every
        subsequent dump under the ``requests`` key (same lifetime
        contract as :meth:`set_profile_supplier`) — ``pathway_tpu
        requests <dump.json>`` re-renders the waterfalls offline."""
        self._tracing_supplier = fn

    def set_slo_supplier(self, fn: Any) -> None:
        """Attach (or clear) the callable whose SLO snapshot (objectives,
        burn rates, remaining budgets) rides every subsequent dump under
        the ``slo`` key (same lifetime contract as
        :meth:`set_profile_supplier`)."""
        self._slo_supplier = fn

    # -- dumping -----------------------------------------------------------
    def dump(self, reason: str, *, suffix: str | None = None) -> str | None:
        """Write the ring to ``<root>/blackbox/worker-<id>.attempt-<n>.json``
        and return the path; None when no root is configured or the write
        fails (a dying process must never die *harder* because its black
        box could not be written).  The write is staged + renamed so the
        gatherer never reads a torn dump.

        ``suffix`` gives a dump its own file (``...attempt-<n>.<suffix>``)
        so it cannot clobber — or be clobbered by — the attempt's crash
        dump: the watchdog's SIGUSR1 dump uses it, because a worker that
        stalls, gets dumped, and is then killed must leave BOTH stories.

        Fenced like every persistence-root write: when this process
        carries an incarnation and the root's lease shows a newer one, the
        dump is refused — a zombie's stale ring must not pollute the live
        cluster's post-mortems."""
        with self._lock:
            root = self.root
            if not root:
                return None
            payload = {
                "worker": self.worker,
                "attempt": self.attempt,
                "run_id": self.run_id,
                "trace_parent": self.trace_parent,
                "incarnation": self.incarnation,
                "reason": reason,
                "pid": os.getpid(),
                "dumped_at": time.time(),
                "events": list(self._ring),
            }
            supplier = self._profile_supplier
            freshness_supplier = self._freshness_supplier
            device_supplier = self._device_supplier
            autoscaler_supplier = self._autoscaler_supplier
            serving_supplier = self._serving_supplier
            generation_supplier = self._generation_supplier
            tracing_supplier = self._tracing_supplier
            slo_supplier = self._slo_supplier
        if supplier is not None:
            # outside the lock (the supplier scans the node arena) and
            # never fatal: a dump without a profile beats no dump
            try:
                profile = supplier()
            except Exception:  # noqa: BLE001 - forensics must never fail
                profile = None
            if profile:
                payload["profiler"] = profile
        if freshness_supplier is not None:
            # same contract: the watermark/backlog story is best-effort
            try:
                freshness = freshness_supplier()
            except Exception:  # noqa: BLE001 - forensics must never fail
                freshness = None
            if freshness:
                payload["freshness"] = freshness
        if device_supplier is not None:
            # ...and what the DEVICE was doing: cost/utilization/padding/
            # HBM/queue at dump time (best-effort like the others)
            try:
                device = device_supplier()
            except Exception:  # noqa: BLE001 - forensics must never fail
                device = None
            if device:
                payload["device"] = device
        if autoscaler_supplier is not None:
            # ...and why the cluster was SCALING: the controller's
            # decision log + handoff phase (best-effort like the others)
            try:
                autoscaler = autoscaler_supplier()
            except Exception:  # noqa: BLE001 - forensics must never fail
                autoscaler = None
            if autoscaler:
                payload["autoscaler"] = autoscaler
        if serving_supplier is not None:
            # ...and what the SERVING edge was refusing: admission
            # occupancy + shed/drain state (best-effort like the others)
            try:
                serving_state = serving_supplier()
            except Exception:  # noqa: BLE001 - forensics must never fail
                serving_state = None
            if serving_state:
                payload["serving"] = serving_state
        if generation_supplier is not None:
            # ...and what the GENERATION loop held: slot + page-pool
            # occupancy at dump time (best-effort like the others)
            try:
                generation_state = generation_supplier()
            except Exception:  # noqa: BLE001 - forensics must never fail
                generation_state = None
            if generation_state:
                payload["generation"] = generation_state
        if tracing_supplier is not None:
            # ...and the last REQUESTS' stories: the finished-trace ring
            # with span trees (best-effort like the others)
            try:
                tracing_state = tracing_supplier()
            except Exception:  # noqa: BLE001 - forensics must never fail
                tracing_state = None
            if tracing_state:
                payload["requests"] = tracing_state
        if slo_supplier is not None:
            # ...and which PROMISES were being broken: declared SLOs with
            # burn rates + budgets (best-effort like the others)
            try:
                slo_state = slo_supplier()
            except Exception:  # noqa: BLE001 - forensics must never fail
                slo_state = None
            if slo_state:
                payload["slo"] = slo_state
        if payload["incarnation"] and self._fenced(
            root, payload["incarnation"], payload["worker"]
        ):
            return None
        try:
            dump_dir = os.path.join(root, _DUMP_DIR)
            os.makedirs(dump_dir, exist_ok=True)
            name = f"worker-{payload['worker']}.attempt-{payload['attempt']}"
            name += f".{suffix}.json" if suffix else ".json"
            path = os.path.join(dump_dir, name)
            tmp = f"{path}.tmp"
            with open(tmp, "w") as f:
                # default=repr: an event carrying a non-JSON value must
                # degrade to its repr, never take the dump (or the
                # injected SIGKILL behind it) down with a TypeError
                json.dump(payload, f, default=repr)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            self._dumped = path
            return path
        except (OSError, ValueError):
            return None

    @staticmethod
    def _fenced(root: str, incarnation: int, worker: int) -> bool:
        """True when the root's lease shows a newer incarnation than ours.
        Best-effort and never raising: a dying process must still get its
        dump out when the lease is unreadable — only a POSITIVE newer-lease
        reading fences.  (Lazy import: persistence imports this module at
        load, so the dependency must stay one-way at import time.)"""
        try:
            from pathway_tpu.engine import persistence as _pz

            lease = _pz.read_lease(_pz.FileBackend(root))
            if lease is not None and lease["incarnation"] > incarnation:
                from pathway_tpu.engine import metrics as _metrics

                # same labeled series persistence._check_fence counts into
                _metrics.get_registry().counter(
                    "persistence.fenced",
                    "commit-point writes rejected because a newer "
                    "incarnation owns the root",
                    worker=worker,
                ).inc()
                return True
        except Exception:  # noqa: BLE001 - forensics must never fail
            pass
        return False

    @property
    def last_dump(self) -> str | None:
        return self._dumped


# ---------------------------------------------------------------------------
# Gathering (supervisor / CLI side)
# ---------------------------------------------------------------------------


def gather_dumps(root: str) -> dict[int, list[dict[str, Any]]]:
    """Read every flight-recorder dump under ``root`` into
    ``{worker: [dump payloads, oldest attempt first]}``.  Torn or
    unparseable files are skipped — post-mortem data is best-effort."""
    out: dict[int, list[dict[str, Any]]] = {}
    dump_dir = os.path.join(root, _DUMP_DIR)
    try:
        names = sorted(os.listdir(dump_dir))
    except OSError:
        return out
    for name in names:
        if not name.endswith(".json"):
            continue
        path = os.path.join(dump_dir, name)
        try:
            with open(path) as f:
                payload = json.load(f)
        except (OSError, ValueError):
            continue
        payload["path"] = path
        try:
            worker = int(payload.get("worker", -1))
        except (TypeError, ValueError):
            worker = -1  # hand-edited/foreign dump: keep it, unattributed
        out.setdefault(worker, []).append(payload)
    for dumps in out.values():
        dumps.sort(key=lambda d: (d.get("attempt", 0), d.get("dumped_at", 0.0)))
    return out


def summarize_dumps(
    dumps: dict[int, list[dict[str, Any]]], *, tail: int = 5
) -> dict[str, Any]:
    """Compact ``SupervisorResult.post_mortem`` form of gathered dumps:
    per-worker dump files, reasons, and the last few events of the most
    recent dump — enough to read the crash story without reopening the
    files (the full rings stay on disk for ``pathway_tpu blackbox``)."""
    workers: dict[int, dict[str, Any]] = {}
    for worker, payloads in sorted(dumps.items()):
        last = payloads[-1]
        events = last.get("events") or []
        workers[worker] = {
            "dumps": [p["path"] for p in payloads],
            "reasons": [p.get("reason") for p in payloads],
            "attempt": last.get("attempt"),
            "events_recorded": len(events),
            "last_events": [
                {
                    k: v
                    for k, v in ev.items()
                    if k not in ("mono",)
                }
                for ev in events[-tail:]
            ],
        }
    return {"workers": workers}


# ---------------------------------------------------------------------------
# Process-wide recorder
# ---------------------------------------------------------------------------

_recorder: FlightRecorder | None = None
# reentrant like FlightRecorder._lock: the SIGUSR1 handler calls
# get_recorder() on the main thread and may interrupt a first-call
# construction already inside this lock (reentry double-creates a
# recorder whose events are lost; a plain Lock deadlocks the handler)
_recorder_lock = threading.RLock()


def get_recorder() -> FlightRecorder:
    global _recorder
    if _recorder is None:
        with _recorder_lock:
            if _recorder is None:
                _recorder = FlightRecorder()
    return _recorder


def record(kind: str, **fields: Any) -> None:
    """Append one event to the process-wide ring (always cheap)."""
    get_recorder().record(kind, **fields)


def configure(**kwargs: Any) -> None:
    get_recorder().configure(**kwargs)


def dump(reason: str) -> str | None:
    """Dump the process-wide ring; see :meth:`FlightRecorder.dump`."""
    return get_recorder().dump(reason)
