"""Declared SLOs evaluated over the live metric families.

The serving path already *measures* everything (``serve.latency.ms``,
``generate.ttft.ms`` histograms, ``output.staleness.s`` freshness
gauges); this module adds the judgment layer: operators declare
objectives over those existing families and a cheap pull-time evaluator
turns them into error-budget arithmetic —

    ``slo.budget.remaining{slo=}``   fraction of the window's error
                                     budget left (1 untouched, 0
                                     exhausted, negative overspent)
    ``slo.burn.rate{slo=,window=}``  multi-window burn rates (1.0 =
                                     burning exactly the budget;
                                     sustained >1 exhausts it early)
    ``slo.violations``               rising-edge counter per SLO, each
                                     edge also lands a flight-recorder
                                     ``slo.violation`` event

Declaration grammar (``PATHWAY_SLOS``, semicolon-separated)::

    name: metric [pNN] < threshold[ms|s] over <duration>

    serve-latency: serve.latency.ms p95 < 250ms over 5m
    ttft:          generate.ttft.ms p95 < 500ms over 5m
    staleness:     output.staleness.s p95 < 5s over 5m

``pNN`` names the objective percentile — "95% of events must be good" —
so the error-budget fraction is ``1 - NN/100`` (default p95).  A *good*
event is an observation at or under the threshold.  Histogram-backed
SLOs count real observations from the family's cumulative buckets;
gauge-backed SLOs sample the gauge once per evaluation tick, so their
"events" are evaluation samples, not requests.

Burn-rate semantics (the multi-window SRE alerting shape): for each SLO
the evaluator keeps a ring of cumulative ``(ts, total, bad)`` snapshots
and reports ``bad_fraction / budget_fraction`` over a SHORT window
(``max(60s, window/5)`` — fast detection) and the declared LONG window
(sustained truth).  A violation edge fires only when BOTH exceed 1.0 —
short-only spikes are noise, long-only residue is history.

The evaluator is a registry collector (``slo.state``): it runs at scrape
time, throttled to at most once per second, so an idle process pays one
dict lookup per scrape and nothing between scrapes.
"""

from __future__ import annotations

import re
import threading
import time
from collections import deque
from typing import Any

from pathway_tpu.engine import metrics as em

__all__ = [
    "SLO",
    "SLOEvaluator",
    "parse_slo",
    "parse_slos",
    "install",
    "get_evaluator",
    "default_declarations",
    "reset_for_tests",
]

# evaluator output cache lifetime: scrapes inside this interval reuse the
# previous evaluation (the "cheap collector" contract)
EVAL_INTERVAL_S = 1.0
# short-window floor: below this, one slow request dominates the burn
SHORT_WINDOW_FLOOR_S = 60.0

DEFAULT_DECLARATIONS = (
    "serve-latency: serve.latency.ms p95 < 250ms over 5m; "
    "ttft: generate.ttft.ms p95 < 500ms over 5m; "
    "staleness: output.staleness.s p95 < 5s over 5m"
)

# objectives over this family are serving-path staleness objectives:
# their input is clamped to the oldest outstanding admitted request age
# (the admission shedder's clamp) so an idle pipeline's frozen watermark
# never reads as burn — see _evaluate_one
STALENESS_METRIC = "output.staleness.s"


def _oldest_outstanding_age_s() -> float | None:
    """Age of the oldest admitted-but-unanswered serving request: 0.0
    when the serving path is live but idle, None when no admission
    controller exists in this process (a batch/non-serving pipeline —
    staleness then keeps its plain watermark meaning, unclamped)."""
    from pathway_tpu.engine import serving as _serving

    c = _serving.controller_if_active()
    if c is None:
        return None
    try:
        return c.oldest_outstanding_age_s()
    except Exception:  # noqa: BLE001 - the evaluator must never break a scrape
        return None

_DECL_RE = re.compile(
    r"""
    ^\s*(?P<name>[A-Za-z0-9_.-]+)\s*:\s*
    (?P<metric>[A-Za-z0-9_.]+)
    (?:\s+p(?P<pct>\d{1,2}(?:\.\d+)?))?
    \s*<\s*
    (?P<threshold>\d+(?:\.\d+)?)\s*(?P<unit>ms|s)?
    \s+over\s+
    (?P<win>\d+(?:\.\d+)?)\s*(?P<winunit>s|m|h)
    \s*$
    """,
    re.VERBOSE,
)

_WINDOW_UNITS = {"s": 1.0, "m": 60.0, "h": 3600.0}


class SLO:
    """One declared objective: ``pNN`` of ``metric`` events at or under
    ``threshold`` (native metric unit) over ``window_s`` seconds."""

    __slots__ = ("name", "metric", "target", "threshold", "window_s")

    def __init__(
        self,
        name: str,
        metric: str,
        threshold: float,
        window_s: float,
        target: float = 0.95,
    ):
        if not 0.0 < target < 1.0:
            raise ValueError(f"SLO target must be in (0, 1), got {target}")
        if window_s <= 0:
            raise ValueError(f"SLO window must be positive, got {window_s}")
        self.name = name
        self.metric = metric
        self.threshold = float(threshold)
        self.window_s = float(window_s)
        self.target = float(target)

    @property
    def budget_fraction(self) -> float:
        """Tolerated bad-event fraction: ``1 - target``."""
        return 1.0 - self.target

    @property
    def short_window_s(self) -> float:
        return max(SHORT_WINDOW_FLOOR_S, self.window_s / 5.0)

    def describe(self) -> str:
        return (
            f"{self.metric} p{self.target * 100:g} < {self.threshold:g} "
            f"over {self.window_s:g}s"
        )

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"SLO({self.name!r}: {self.describe()})"


def _native_threshold(metric: str, value: float, unit: str | None) -> float:
    """Convert a declared threshold into the metric's native unit.

    Families carry their unit in the name suffix (``.ms`` / ``.s`` — the
    repo's convention), so ``< 250ms`` against a ``.s`` family and
    ``< 1.5s`` against a ``.ms`` family both mean what they say."""
    if unit is None:
        return value
    native_ms = metric.endswith(".ms")
    native_s = metric.endswith(".s")
    if unit == "ms":
        if native_s:
            return value / 1000.0
        return value
    # unit == "s"
    if native_ms:
        return value * 1000.0
    if native_s or not native_ms:
        return value
    return value


def parse_slo(text: str) -> SLO:
    """Parse one ``name: metric [pNN] < threshold[ms|s] over <dur>``
    declaration; raises ``ValueError`` with the offending text."""
    m = _DECL_RE.match(text)
    if m is None:
        raise ValueError(
            f"unparseable SLO declaration {text!r} (expected "
            f"'name: metric [pNN] < threshold[ms|s] over <Ns|Nm|Nh>')"
        )
    metric = m.group("metric")
    target = 0.95 if m.group("pct") is None else float(m.group("pct")) / 100.0
    threshold = _native_threshold(
        metric, float(m.group("threshold")), m.group("unit")
    )
    window_s = float(m.group("win")) * _WINDOW_UNITS[m.group("winunit")]
    return SLO(m.group("name"), metric, threshold, window_s, target=target)


def parse_slos(text: str) -> list[SLO]:
    """Parse a semicolon-separated declaration list; empty segments are
    skipped, duplicate names keep the LAST declaration (operator
    overrides of a default win)."""
    by_name: dict[str, SLO] = {}
    for part in text.split(";"):
        part = part.strip()
        if not part:
            continue
        slo = parse_slo(part)
        by_name[slo.name] = slo
    return list(by_name.values())


def default_declarations() -> str:
    """The effective declaration string: built-in defaults, with
    ``PATHWAY_SLOS`` appended so same-named operator declarations
    override the defaults (see :func:`parse_slos`)."""
    from pathway_tpu.internals.config import env_str

    extra = env_str("PATHWAY_SLOS")
    if not extra:
        return DEFAULT_DECLARATIONS
    return f"{DEFAULT_DECLARATIONS}; {extra}"


class _SLOState:
    """Per-SLO cumulative counters + snapshot ring (the burn math)."""

    __slots__ = ("slo", "ring", "sample_total", "sample_bad", "violating")

    def __init__(self, slo: SLO):
        self.slo = slo
        # (ts, cumulative total events, cumulative bad events); pruned to
        # the long window + one baseline entry beyond it
        self.ring: deque[tuple[float, float, float]] = deque()
        # gauge-backed SLOs: cumulative evaluation-sample counts
        self.sample_total = 0.0
        self.sample_bad = 0.0
        self.violating = False  # for rising-edge detection


def _window_delta(
    ring: deque[tuple[float, float, float]], now: float, window_s: float
) -> tuple[float, float]:
    """(delta_total, delta_bad) between the newest snapshot and the
    newest snapshot at least ``window_s`` old (or the oldest held)."""
    if len(ring) < 2:
        return 0.0, 0.0
    newest = ring[-1]
    cutoff = now - window_s
    baseline = ring[0]
    for entry in ring:
        if entry[0] <= cutoff:
            baseline = entry
        else:
            break
    return newest[1] - baseline[1], newest[2] - baseline[2]


class SLOEvaluator:
    """Evaluates declared SLOs against the registry at scrape time."""

    def __init__(
        self,
        slos: list[SLO] | None = None,
        registry: em.MetricsRegistry | None = None,
    ):
        self._registry = registry or em.get_registry()
        self._states: dict[str, _SLOState] = {}
        self._lock = threading.Lock()
        self._last_eval = 0.0
        self._cached: dict[str, float] = {}
        self._in_eval = False  # reentrancy guard: we call collect() below
        for slo in slos if slos is not None else parse_slos(
            default_declarations()
        ):
            self._states[slo.name] = _SLOState(slo)

    @property
    def slos(self) -> list[SLO]:
        return [st.slo for st in self._states.values()]

    # -- sampling ----------------------------------------------------------
    def _histogram_counts(self, slo: SLO) -> tuple[float, float] | None:
        """Cumulative (total, bad) from a histogram family, summed over
        every label set; None when the family doesn't exist (yet)."""
        fam = self._registry.family(slo.metric)
        if fam is None or fam.kind != "histogram":
            return None
        total = 0.0
        bad = 0.0
        for _key, child in fam.items():
            bounds, counts, _sum, n = child.snapshot()
            total += n
            good = 0
            for bound, c in zip(bounds, counts):
                if bound <= slo.threshold:
                    good += c
                else:
                    break
            bad += n - good
        return total, bad

    def _gauge_value(self, slo: SLO, scalars: dict[str, float]) -> float | None:
        """Current value of a gauge-backed SLO metric: the worst (max)
        across label sets, from direct gauge families or collector
        output (``output.staleness.s{output=...}`` lives in the
        freshness collector, not a Gauge child)."""
        worst: float | None = None
        fam = self._registry.family(slo.metric)
        if fam is not None and fam.kind == "gauge":
            for _key, child in fam.items():
                v = child.value
                worst = v if worst is None else max(worst, v)
        prefix = slo.metric + "{"
        for key, v in scalars.items():
            if key == slo.metric or key.startswith(prefix):
                worst = v if worst is None else max(worst, v)
        return worst

    # -- evaluation --------------------------------------------------------
    def evaluate(self, now: float | None = None) -> dict[str, float]:
        """One evaluation pass → the ``slo.*`` gauge dict.  Safe to call
        directly (tests, ``/status``); the registered collector throttles
        it to :data:`EVAL_INTERVAL_S`."""
        if now is None:
            now = time.time()
        with self._lock:
            if self._in_eval:
                # collect() below re-enters us through the slo.state
                # collector; serve the previous answer instead of recursing
                return dict(self._cached)
            self._in_eval = True
        try:
            needs_scalars = any(
                (fam := self._registry.family(st.slo.metric)) is None
                or fam.kind != "histogram"
                for st in self._states.values()
            )
            scalars: dict[str, float] = {}
            if needs_scalars:
                # other collectors' output (freshness staleness gauges
                # live there); our own collector short-circuits via the
                # _in_eval guard above
                scalars = self._registry.collect()
            out: dict[str, float] = {}
            violations: list[tuple[SLO, float, float]] = []
            with self._lock:
                for st in self._states.values():
                    self._evaluate_one(st, now, scalars, out, violations)
                self._cached = out
                self._last_eval = now
        finally:
            with self._lock:
                self._in_eval = False
        for slo, burn_short, burn_long in violations:
            reg = self._registry
            reg.counter(
                "slo.violations",
                em.METRICS["slo.violations"][1],
                slo=slo.name,
            ).inc()
            from pathway_tpu.engine import flight_recorder as _blackbox

            _blackbox.record(
                "slo.violation",
                slo=slo.name,
                objective=slo.describe(),
                burn_short=round(burn_short, 3),
                burn_long=round(burn_long, 3),
            )
        return dict(out)

    def _evaluate_one(
        self,
        st: _SLOState,
        now: float,
        scalars: dict[str, float],
        out: dict[str, float],
        violations: list,
    ) -> None:
        slo = st.slo
        counts = self._histogram_counts(slo)
        if counts is None:
            value = self._gauge_value(slo, scalars)
            if value is not None and slo.metric == STALENESS_METRIC:
                # an idle gap also grows output staleness (no input →
                # frozen watermark), and idleness is not an SLO breach:
                # when a serving admission controller is live the
                # staleness objective shares its clamp — it counts only
                # while an admitted request has actually been
                # outstanding that long (0 when the serving path is
                # idle), so sparse/idle pipelines stop burning budget
                # under the defaults.  Without a controller (batch or
                # non-serving pipelines) staleness keeps its plain
                # watermark meaning, unclamped.
                oldest = _oldest_outstanding_age_s()
                if oldest is not None:
                    value = min(value, oldest)
            if value is not None:
                st.sample_total += 1.0
                if value > slo.threshold:
                    st.sample_bad += 1.0
            counts = (st.sample_total, st.sample_bad)
        st.ring.append((now, counts[0], counts[1]))
        # prune: keep one baseline entry beyond the long window
        cutoff = now - slo.window_s
        while len(st.ring) > 2 and st.ring[1][0] <= cutoff:
            st.ring.popleft()
        budget = slo.budget_fraction
        burns: dict[str, float] = {}
        frac_long = 0.0
        for label, w in (
            (_fmt_window(slo.short_window_s), slo.short_window_s),
            (_fmt_window(slo.window_s), slo.window_s),
        ):
            total, bad = _window_delta(st.ring, now, w)
            frac = bad / total if total > 0 else 0.0
            burns[label] = frac / budget if budget > 0 else 0.0
            if w == slo.window_s:
                frac_long = frac
        for label, burn in burns.items():
            out[f"slo.burn.rate{{slo={slo.name},window={label}}}"] = round(
                burn, 4
            )
        remaining = 1.0 - (frac_long / budget if budget > 0 else 0.0)
        out[f"slo.budget.remaining{{slo={slo.name}}}"] = round(remaining, 4)
        burn_values = list(burns.values())
        violating = all(b > 1.0 for b in burn_values) and bool(burn_values)
        if violating and not st.violating:
            violations.append(
                (slo, burn_values[0], burn_values[-1])
            )
        st.violating = violating

    # -- collector + surfaces ----------------------------------------------
    def collect_state(self) -> dict[str, float]:
        """The ``slo.state`` registry collector: cached inside
        :data:`EVAL_INTERVAL_S`, one full evaluation otherwise."""
        now = time.time()
        with self._lock:
            if now - self._last_eval < EVAL_INTERVAL_S and self._cached:
                return dict(self._cached)
        return self.evaluate(now)

    def snapshot(self) -> dict[str, Any]:
        """Structured form for ``/status`` and flight-recorder dumps."""
        gauges = self.collect_state()
        slos = []
        with self._lock:
            states = list(self._states.values())
        for st in states:
            slo = st.slo
            prefix_burn = f"slo.burn.rate{{slo={slo.name},window="
            slos.append({
                "name": slo.name,
                "objective": slo.describe(),
                "metric": slo.metric,
                "threshold": slo.threshold,
                "target": slo.target,
                "window_s": slo.window_s,
                "budget_remaining": gauges.get(
                    f"slo.budget.remaining{{slo={slo.name}}}", 1.0
                ),
                "burn": {
                    key[len(prefix_burn):-1]: value
                    for key, value in gauges.items()
                    if key.startswith(prefix_burn)
                },
                "violating": st.violating,
            })
        return {"slos": slos}


def _fmt_window(seconds: float) -> str:
    if seconds % 3600 == 0 and seconds >= 3600:
        return f"{int(seconds // 3600)}h"
    if seconds % 60 == 0 and seconds >= 60:
        return f"{int(seconds // 60)}m"
    return f"{seconds:g}s"


# ---------------------------------------------------------------------------
# Process-wide evaluator
# ---------------------------------------------------------------------------

_evaluator: SLOEvaluator | None = None
_evaluator_lock = threading.Lock()


def get_evaluator() -> SLOEvaluator:
    global _evaluator
    if _evaluator is None:
        with _evaluator_lock:
            if _evaluator is None:
                _evaluator = SLOEvaluator()
    return _evaluator


def install(registry: em.MetricsRegistry | None = None) -> SLOEvaluator:
    """Register the process evaluator's collector (idempotent — the
    runner calls this per run; re-registering replaces the slot)."""
    evaluator = get_evaluator()
    reg = registry or em.get_registry()
    reg.register_collector("slo.state", evaluator.collect_state)
    return evaluator


def reset_for_tests() -> None:
    global _evaluator
    with _evaluator_lock:
        _evaluator = None
