"""Supervised crash recovery for the multi-worker runtime.

The reference framework's ancestor survives worker death because its
persistence layer can always rewind a worker group to the last committed
snapshot frontier (``src/persistence/tracker.rs``).  This module is the
process-level half of that story for this engine: a **supervisor** that
watches the N SPMD worker processes of a cluster and, on a confirmed
worker death, rolls the whole group back to the last committed
checkpoint and replays.

Why whole-group restart (and not patching one worker back in)?  The
epoch loop is BSP: every worker walks the identical DAG in lockstep and
the collectives pair up positionally.  When a worker dies mid-epoch, the
survivors hold in-memory operator state for epochs the dead worker never
committed — state a respawned worker cannot reproduce.  The only
consistent rollback point every worker agrees on is the last committed
checkpoint (``engine/persistence.py`` commits are per-worker atomic
metadata writes gated on processed epochs).  So the supervisor:

1. detects the death (nonzero or signal exit);
2. terminates the surviving workers (their un-committed progress is
   exactly what must be rolled back — killing them IS the rollback);
3. respawns all N workers with the same run id, ports, comm secret and
   persistence root, after a backoff (the shared ``udfs`` retry
   schedule).  Each worker resumes from its own committed snapshot
   shard: committed events replay into the input sessions, readers seek
   to the stored offset frontier, and the mesh re-forms.

Sinks re-open their output files on restart, so the recovered run's
final output is identical to an unfaulted run's — the property the
kill-and-restart test in ``tests/test_supervised_recovery.py`` pins.

Whole-group restart is the **fallback tier**.  With warm standbys armed
(``spawn --supervise --standbys K`` / ``PATHWAY_STANDBY_COUNT``) the
supervisor first tries a **standby promotion** (``engine/standby.py``):
K extra processes tail the persistence root, deep-verifying every newly
committed generation, so each standby stays within one commit of any
shard.  On a worker death the supervisor bumps the dead worker's
per-worker fence token (``bump_worker_fence`` — the dead worker's
zombie can never publish again), posts a PROMOTE request on the root,
and the chosen standby adopts the dead worker's identity while the
SURVIVORS NEVER RESTART: each survivor's promote-watch poisons its mesh
(``TcpMesh.poison``), drains to a consistent commit, and rejoins a
fresh mesh in-process (``internals/runner.py``).  Only the dead
worker's uncommitted tail is replayed — sub-second where the restart
tier pays backoff plus a full N-worker resume.  A promotion that cannot
start (no live standby, no root/lease, spent
``PATHWAY_STANDBY_PROMOTIONS`` budget) or that faults mid-flight
(standby death, a second worker death, a blown
``PATHWAY_STANDBY_PROMOTE_DEADLINE_S``) falls back to the whole-group
restart below — the two-tier recovery contract
``tests/test_standby_promotion.py`` pins.

Restarted workers do not trust the newest checkpoint blindly: the
persistence layer (``engine/persistence.py``) verifies each generation's
integrity frames + digests and falls back generation-by-generation to
the newest VERIFIED one.  When the supervisor knows the persistence root
(``checkpoint_root``), it reads the per-worker provenance back after the
run and surfaces it on ``SupervisorResult.recovery`` for post-mortems,
alongside ``last_failure`` (why the last restart happened).

Restart attempts are announced to workers via ``PATHWAY_RESTART_ATTEMPT``
(the fault plan's ``attempt`` filter keys off it, so chaos tests can
inject a crash on attempt 0 and let attempt 1 run clean).

Two hazards the restart loop alone cannot handle, both covered here:

* **Split-brain zombies.**  A worker from a superseded attempt that is
  not actually dead yet (partitioned, SIGKILL in flight, wedged past its
  send deadline) could publish a stale checkpoint generation into the
  same persistence root the respawned cluster now writes.  Before every
  (re)launch the supervisor therefore bumps an **incarnation lease** on
  the root (``engine/persistence.py:acquire_lease``) and exports the new
  incarnation to the workers via ``PATHWAY_INCARNATION``; every
  commit-point write in the persistence layer re-checks the lease and a
  stale writer gets ``FencedError`` instead of a publish.

* **Silent hangs.**  A live-but-stuck worker (deadlocked epoch loop,
  wedged blob I/O) produces no exit code, so the death-watch never fires.
  Workers touch a progress beacon (``<root>/lease/progress.<id>``) from
  their epoch loop; the watch loop doubles as a **progress watchdog**:
  when a beacon goes stale past ``PATHWAY_EPOCH_DEADLINE_S`` the hung
  worker is sent SIGUSR1 (flight-recorder dump to ``<root>/blackbox/``),
  then SIGTERM, then SIGKILL — converting the hang into an ordinary
  supervised restart, with the hang recorded on
  ``SupervisorResult.last_failure`` and the dump in ``post_mortem``.

Worker handles are duck-typed: ``multiprocessing.Process`` (tests,
in-repo harnesses) and ``subprocess.Popen`` (``pathway spawn
--supervise``) both work.
"""

from __future__ import annotations

import inspect
import json
import logging
import os
import random
import signal as _signal_mod
import time
from typing import Any, Callable, Sequence

_log = logging.getLogger("pathway_tpu.supervisor")

# one constant for the restart-attempt protocol: the fault plan's
# `attempt` filter and the jax coordinator-port offset read the same var
from pathway_tpu.engine.faults import ENV_ATTEMPT  # noqa: E402,F401
from pathway_tpu.engine import metrics as _metrics  # noqa: E402

# mirrors persistence.ENV_INCARNATION (pinned equal by a test) — a literal
# here keeps this module's import-time persistence dependency lazy, like
# every other persistence touch in this file
ENV_INCARNATION = "PATHWAY_INCARNATION"

ENV_EPOCH_DEADLINE = "PATHWAY_EPOCH_DEADLINE_S"
# escalation pacing: SIGUSR1 (dump request) → this grace → SIGTERM; the
# SIGTERM → SIGKILL grace reuses the supervisor's grace_s
WATCHDOG_DUMP_GRACE_S = 1.0
# before a worker's FIRST beacon touch of an attempt, allow at least this
# long: worker startup (interpreter, jax import, mesh formation) produces
# no progress yet and must not read as a hang under a tight epoch deadline
WATCHDOG_BOOT_GRACE_S = 30.0


def _epoch_deadline_from_env() -> float | None:
    """``PATHWAY_EPOCH_DEADLINE_S`` as a positive float, else None (the
    watchdog stays off — a run with long legitimate gaps between epochs
    must opt in with a deadline that fits its cadence)."""
    from pathway_tpu.internals.config import env_raw

    raw = env_raw(ENV_EPOCH_DEADLINE) or ""
    try:
        value = float(raw) if raw else 0.0
    except ValueError:
        return None
    return value if value > 0 else None


class SupervisorError(RuntimeError):
    """The cluster kept failing past the restart budget.

    ``post_mortem`` carries the flight-recorder summary gathered from the
    persistence root (same shape as ``SupervisorResult.post_mortem``) —
    a crash loop is exactly the case the black box exists for.
    """

    post_mortem: dict = {}


class SupervisorResult:
    __slots__ = (
        "attempts", "restarts", "exit_codes", "history", "recovery",
        "last_failure", "post_mortem", "rescales", "promotions",
    )

    def __init__(
        self,
        attempts: int,
        restarts: int,
        exit_codes: list[int],
        history: list[list[int | None]],
        recovery: dict[int, dict] | None = None,
        last_failure: str | None = None,
        post_mortem: dict | None = None,
        rescales: list[dict] | None = None,
        promotions: list[dict] | None = None,
    ):
        self.attempts = attempts  # launches performed (>= 1)
        self.restarts = restarts  # recoveries performed (attempts - 1)
        self.exit_codes = exit_codes  # final attempt's per-worker codes
        # per-attempt worker exit codes at teardown time (negative =
        # signal, e.g. -9 for the SIGKILL that triggered the recovery)
        self.history = history
        # post-mortem info read back from the persistence root (when the
        # supervisor knows it): per-worker checkpoint provenance —
        # {worker: {"generation", "recovered_from", "rejected", "attempt"}}.
        # "recovered_from" is the generation the final attempt VERIFIED and
        # resumed from; "rejected" lists [generation, reason] pairs the
        # integrity scan refused (torn/corrupt/missing artifacts).
        self.recovery = recovery or {}
        # human-readable reason for the last recovery, e.g.
        # "worker 1 exited -9 on attempt 0" — None for a clean first run
        self.last_failure = last_failure
        # flight-recorder post-mortem gathered from the persistence root
        # (engine/flight_recorder.py): {"workers": {wid: {"dumps": [...],
        # "reasons": [...], "last_events": [...]}}} — the last seconds of
        # every worker that dumped its black box before dying.  {} when no
        # root is known or no worker dumped.  ``pathway_tpu blackbox ROOT``
        # renders the full dumps.
        self.post_mortem = post_mortem or {}
        # degraded-mode shrink provenance: one entry per rescale performed
        # by this run — {"from", "to", "lost_worker", "attempt", "reason"}.
        # Empty for a run that never lost a worker permanently.
        self.rescales = rescales or []
        # warm-standby promotion provenance: one entry per COMPLETED
        # promotion — {"worker", "standby", "seq", "fence", "attempt",
        # "duration_s", "reason"}.  A worker loss absorbed here never
        # shows up in ``restarts``; aborted promotions fall back to the
        # restart tier and are counted there instead.
        self.promotions = promotions or []

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"SupervisorResult(attempts={self.attempts}, "
            f"restarts={self.restarts}, exit_codes={self.exit_codes}, "
            f"last_failure={self.last_failure!r})"
        )


# -- handle duck-typing (multiprocessing.Process | subprocess.Popen) -------


def _exitcode(handle: Any) -> int | None:
    if hasattr(handle, "exitcode"):  # multiprocessing.Process
        return handle.exitcode
    return handle.poll()  # subprocess.Popen


def _alive(handle: Any) -> bool:
    return _exitcode(handle) is None


def _join(handle: Any, timeout: float) -> None:
    if hasattr(handle, "join"):
        handle.join(timeout)
        return
    try:
        handle.wait(timeout)
    except Exception:  # subprocess.TimeoutExpired
        pass


def _signal(handle: Any, *, hard: bool) -> None:
    try:
        if hard:
            handle.kill()
        else:
            handle.terminate()
    except (OSError, ValueError):
        pass  # already gone


def _pid(handle: Any) -> int | None:
    """OS pid of a worker handle (both Process and Popen expose .pid)."""
    return getattr(handle, "pid", None)


class _ProgressWatchdog:
    """Hung-worker detection riding the supervisor's watch loop.

    Workers touch a progress beacon — ``<root>/lease/progress.<id>``,
    mtime refreshed from the epoch loop (``internals/runner.py``) — so
    "no progress" is an on-disk fact the supervisor can read without any
    channel to the worker.  When a live worker's beacon age exceeds the
    epoch deadline, escalate:

    1. SIGUSR1 — the worker's runner dumps its flight recorder to
       ``<root>/blackbox/`` (a hang leaves no crash dump otherwise: the
       black box must be pulled OUT of the wreck before it is made one);
    2. after ``WATCHDOG_DUMP_GRACE_S``: SIGTERM;
    3. after the supervisor's ``grace_s``: SIGKILL.

    The death is then picked up by the ordinary death-watch and routed
    through the restart budget; the hang description lands in
    ``Supervisor._hangs`` so ``last_failure`` tells the real story.

    The beacon clock for a worker starts at attempt launch (a fresh
    worker has not touched anything yet), so the deadline must exceed
    worker startup time.  State is per-attempt: a new `_watch` call gets
    a new watchdog.
    """

    def __init__(self, supervisor: "Supervisor"):
        self.sup = supervisor
        self.deadline = float(supervisor.epoch_deadline_s or 0.0)
        self.started_at = time.time()
        # wid -> (phase, phase_entered_at); phases: sigusr1 -> term -> kill
        self._phase: dict[int, tuple[str, float]] = {}
        reg = _metrics.get_registry()
        self._kills = reg.counter(
            "supervisor.watchdog.kills",
            "hung workers killed by the progress watchdog",
        )
        self._age_gauges = {
            w: reg.gauge(
                "worker.last_progress.age_s",
                "seconds since the worker's last epoch-progress beacon",
                worker=w,
            )
            for w in range(supervisor.n_workers)
        }

    def _beacon_age(self, wid: int) -> tuple[float, bool]:
        """(seconds since last progress, touched-this-attempt).  A beacon
        older than the attempt start (or missing) belongs to a previous
        attempt: the clock then runs from attempt launch, and the boot
        grace applies."""
        path = os.path.join(
            self.sup.checkpoint_root, "lease", f"progress.{wid}"
        )
        try:
            mtime = os.path.getmtime(path)
        except OSError:
            mtime = 0.0
        touched = mtime > self.started_at
        return time.time() - max(mtime, self.started_at), touched

    def poll(self, handles: Sequence[Any]) -> None:
        now = time.time()
        for wid, handle in enumerate(handles):
            if _exitcode(handle) is not None:
                continue  # dead workers are the death-watch's business
            age, touched = self._beacon_age(wid)
            gauge = self._age_gauges.get(wid)
            if gauge is not None:
                gauge.set(age)
            state = self._phase.get(wid)
            if state is None:
                threshold = (
                    self.deadline
                    if touched
                    else max(self.deadline, WATCHDOG_BOOT_GRACE_S)
                )
                if age <= threshold:
                    continue
                # stall confirmed: ask for the black box FIRST — the hung
                # process can often still run a signal handler even when
                # its epoch loop never returns
                reason = (
                    f"no epoch progress for {age:.1f}s "
                    f"(deadline {self.deadline:.1f}s)"
                )
                self.sup._hangs[wid] = reason
                _log.warning(
                    "watchdog: worker %d is hung (%s) — requesting a "
                    "flight-recorder dump (SIGUSR1), then killing it into "
                    "a supervised restart", wid, reason,
                )
                pid = _pid(handle)
                if pid is not None:
                    try:
                        os.kill(pid, _signal_mod.SIGUSR1)
                    except (OSError, ValueError):
                        pass
                self._phase[wid] = ("sigusr1", now)
            elif state[0] == "sigusr1":
                if age <= self.deadline:
                    # the worker resumed touching its beacon during the
                    # dump grace — a slow epoch, not a hang: stand down
                    # before anything lethal (only the SIGUSR1 dump
                    # happened, which is harmless forensics)
                    _log.warning(
                        "watchdog: worker %d resumed progress "
                        "(beacon age %.1fs) — aborting the kill escalation",
                        wid, age,
                    )
                    del self._phase[wid]
                    self.sup._hangs.pop(wid, None)
                elif now - state[1] >= WATCHDOG_DUMP_GRACE_S:
                    self._kills.inc()
                    _signal(handle, hard=False)
                    self._phase[wid] = ("term", now)
            elif state[0] == "term":
                if now - state[1] >= self.sup.grace_s:
                    _signal(handle, hard=True)
                    self._phase[wid] = ("kill", now)


class Supervisor:
    """Run one SPMD worker group to completion, restarting it on failure.

    ``spawn(worker_id, attempt)`` must start worker ``worker_id`` of the
    group and return its handle; it is responsible for wiring the cluster
    env (``PATHWAY_PROCESSES``/``PROCESS_ID``/``FIRST_PORT``/…) and for
    exporting ``PATHWAY_RESTART_ATTEMPT=attempt`` into the worker.
    """

    def __init__(
        self,
        spawn: Callable[[int, int], Any],
        n_workers: int,
        *,
        max_restarts: int = 3,
        grace_s: float = 5.0,
        poll_interval_s: float = 0.05,
        restart_jitter_s: float = 0.5,
        checkpoint_root: str | None = None,
        epoch_deadline_s: float | None = None,
        shrink_on_loss: bool | None = None,
        autoscale: bool | None = None,
        standbys: int | None = None,
    ):
        self.spawn = spawn
        self.n_workers = n_workers
        self.max_restarts = max_restarts
        # load-adaptive autoscaling (opt-in): a ScaleController rides the
        # watch loop, reading the workers' load beacons and triggering
        # grow/shrink rescales via live shard handoff (with restart
        # fallback).  None reads the PATHWAY_AUTOSCALE knob.  Needs a
        # checkpoint root — both the sensor feed (lease/load.<w>) and the
        # actuator (lease/HANDOFF + repartition resume) live there.
        from pathway_tpu.internals.config import env_bool, env_float, env_int

        if autoscale is None:
            autoscale = env_bool("PATHWAY_AUTOSCALE")
        self.autoscale = bool(autoscale)
        self.handoff_deadline_s = env_float(
            "PATHWAY_AUTOSCALE_HANDOFF_DEADLINE_S"
        )
        self._controller: Any = None
        # outcome of a handoff the last _watch call observed:
        # {"kind": "live", ...} = all workers drained + acked, relaunch at
        # the target without charging the restart budget; {"kind":
        # "fallback", ...} = the handoff faulted mid-flight, fall back to
        # a restart-based rescale at the same target topology
        self._handoff_outcome: dict[str, Any] | None = None
        self._as_last_observe = 0.0
        self._as_last_state = 0.0
        # degraded-mode shrink (opt-in): when the SAME worker failed on
        # every attempt of a spent restart budget — the permanently-lost-
        # host signature, not an ordinary crash loop — rescale the cluster
        # to the surviving count instead of failing the run.  The resumed
        # workers re-partition checkpointed state by shard range
        # (engine/persistence.py repartition resume).  None reads the
        # PATHWAY_DEGRADED_SHRINK knob.
        if shrink_on_loss is None:
            from pathway_tpu.internals.config import env_bool

            shrink_on_loss = env_bool("PATHWAY_DEGRADED_SHRINK")
        self.shrink_on_loss = bool(shrink_on_loss)
        # rescale provenance (mirrored onto SupervisorResult.rescales)
        self.rescales: list[dict] = []
        # warm-standby pool (tier-one recovery): K extra processes tail
        # the persistence root (engine/standby.py) so a worker death can
        # be absorbed by promoting one instead of restarting the group.
        # None reads the PATHWAY_STANDBY_COUNT knob; needs a checkpoint
        # root (the PROMOTE protocol and the tail both live there).
        if standbys is None:
            standbys = env_int("PATHWAY_STANDBY_COUNT")
        self.standbys = max(0, int(standbys or 0))
        self.promote_deadline_s = env_float(
            "PATHWAY_STANDBY_PROMOTE_DEADLINE_S"
        )
        self.max_promotions = env_int("PATHWAY_STANDBY_PROMOTIONS")
        # completed-promotion provenance (mirrored onto
        # SupervisorResult.promotions and the root's promotion history)
        self.promotions: list[dict] = []
        self._standby_handles: dict[int, Any] = {}
        # the in-flight promotion's bookkeeping (None when idle) — also
        # the autoscaler gate: no scale decisions while a shard changes
        # owners via promotion
        self._promotion: dict[str, Any] | None = None
        self._promote_seq = 0
        self._attempt = 0
        # does the spawn callback accept the CURRENT cluster size?  A
        # shrink changes n_workers between attempts, and the spawner must
        # export the new PATHWAY_PROCESSES; two-arg spawners (fixed-size
        # callers, older tests) keep working unchanged.
        try:
            params = inspect.signature(spawn).parameters
            self._spawn_takes_workers = any(
                p.kind is inspect.Parameter.VAR_KEYWORD or name == "n_workers"
                for name, p in params.items()
            )
        except (TypeError, ValueError):
            self._spawn_takes_workers = False
        self.grace_s = grace_s
        self.poll_interval_s = poll_interval_s
        # extra uniform jitter on top of the backoff schedule: when many
        # supervised clusters share infrastructure (one storage service,
        # one k8s node pool), a correlated failure must not produce a
        # thundering herd of simultaneous restarts
        self.restart_jitter_s = restart_jitter_s
        # filesystem persistence root (when known): lets the supervisor
        # read back per-worker checkpoint provenance for post-mortems,
        # own the incarnation lease, and watch the progress beacons
        self.checkpoint_root = checkpoint_root
        # progress-watchdog deadline: a worker whose epoch loop makes no
        # progress for this long is dumped (SIGUSR1) and then killed into
        # an ordinary supervised restart.  None (and no env override)
        # disables the watchdog.  The deadline must exceed worker startup
        # time: the clock for a worker starts at attempt launch until its
        # first beacon touch.
        self.epoch_deadline_s = (
            epoch_deadline_s
            if epoch_deadline_s is not None
            else _epoch_deadline_from_env()
        )
        # the incarnation this attempt's workers were launched under
        # (None when no checkpoint root is known — fencing needs a root)
        self.incarnation: int | None = None
        # {worker id: hang description} for the CURRENT attempt — filled
        # by the watchdog when it starts killing a stalled worker, read by
        # run() to put hang provenance on last_failure
        self._hangs: dict[int, str] = {}

    def _backoff_delays(self):
        # the udfs backoff schedule — the same policy the comm mesh uses
        # for link reconnects, applied between cluster restart attempts
        from pathway_tpu.internals.udfs.retries import (
            ExponentialBackoffRetryStrategy,
        )

        return ExponentialBackoffRetryStrategy(
            max_retries=max(self.max_restarts, 1),
            initial_delay=200,
            backoff_factor=2,
            jitter_ms=100,
        ).delays()

    def _recovery_info(self) -> dict[int, dict]:
        """Per-worker checkpoint provenance from the persistence root; {}
        when the root is unknown or unreadable — post-mortem data is
        best-effort.

        The authoritative record is the newest readable generation
        MANIFEST (provenance fields ride every commit); the advisory
        ``metadata.json.<worker>`` pointer is only a fallback, since its
        refresh is best-effort and may lag the real commit."""
        if not self.checkpoint_root:
            return {}
        try:
            import os

            if not os.path.isdir(self.checkpoint_root):
                # read-only forensics must not create a (possibly mistyped)
                # root as a side effect of FileBackend's makedirs
                _log.warning(
                    "checkpoint root %s does not exist; no recovery "
                    "provenance available", self.checkpoint_root,
                )
                return {}
            from pathway_tpu.engine.persistence import (
                METADATA_FILE,
                FileBackend,
                _read_manifest,
            )

            backend = FileBackend(self.checkpoint_root)
            out: dict[int, dict] = {}
            manifests: dict[int, list[str]] = {}
            pointers: dict[int, str] = {}
            for key in backend.list_keys(""):
                parts = key.split("/")
                if (
                    parts[0] == "manifests"
                    and len(parts) == 3
                    and parts[1].isdigit()
                    and parts[2].isdigit()
                ):
                    manifests.setdefault(int(parts[1]), []).append(key)
                elif len(parts) == 1 and parts[0].startswith(
                    METADATA_FILE + "."
                ):
                    tail = parts[0].rsplit(".", 1)[-1]
                    if tail.isdigit():
                        pointers[int(tail)] = key
            for wid in sorted(set(manifests) | set(pointers)):
                obj = None
                for key in sorted(manifests.get(wid, []), reverse=True):
                    obj, _reason = _read_manifest(backend, key)
                    if obj is not None:
                        break
                if obj is None and wid in pointers:
                    raw = backend.get(pointers[wid])
                    if raw is not None:
                        try:
                            obj = json.loads(raw.decode())
                        except ValueError:
                            obj = None
                if obj is not None and "generation" in obj:
                    out[wid] = {
                        "generation": obj.get("generation"),
                        "recovered_from": obj.get("recovered_from"),
                        "rejected": obj.get("rejected") or [],
                        "attempt": obj.get("attempt"),
                        # elastic-rescale provenance: the topology this
                        # worker last committed under, and the superseded
                        # topology it re-partitioned from (None = never
                        # rescaled)
                        "topology": obj.get("topology"),
                        "repartitioned_from": obj.get("repartitioned_from"),
                    }
            return out
        except Exception:  # noqa: BLE001 - never fail a run for forensics
            return {}

    def _post_mortem(self) -> dict:
        """Flight-recorder dumps gathered from the persistence root into
        the compact ``SupervisorResult.post_mortem`` form; {} when no root
        is known or nothing dumped — like recovery provenance, post-mortem
        data is best-effort and must never fail a run."""
        if not self.checkpoint_root:
            return {}
        try:
            from pathway_tpu.engine.flight_recorder import (
                gather_dumps,
                summarize_dumps,
            )

            dumps = gather_dumps(self.checkpoint_root)
            # only THIS run's dumps: anything written before run() started
            # (or missing its stamp — an older format) is a previous run's
            # story and would misattribute old crashes to a clean run
            cutoff = getattr(self, "_run_started_at", 0.0)
            dumps = {
                w: [p for p in ps if p.get("dumped_at", 0.0) >= cutoff]
                for w, ps in dumps.items()
            }
            dumps = {w: ps for w, ps in dumps.items() if ps}
            if not dumps:
                return {}
            summary = summarize_dumps(dumps)
            for wid, info in sorted(summary.get("workers", {}).items()):
                _log.info(
                    "worker %d left %d flight-recorder dump(s); last "
                    "reason: %s", wid, len(info.get("dumps", [])),
                    (info.get("reasons") or [None])[-1],
                )
            return summary
        except Exception:  # noqa: BLE001 - forensics only
            return {}

    def _settle_checkpoints(self) -> None:
        """Settle async-commit residue on the persistence root after the
        whole group is confirmed dead, before the restart is accounted.

        A worker killed mid-pipelined-commit can leave two kinds of debris:
        ``*.tmp`` staging files from a ``put_atomic`` that never renamed
        (invisible to resume — ``list_keys`` skips them — but accumulating
        across restarts), and unreferenced partial generations (chunks
        whose manifest never published).  The staging files are swept here;
        partial generations are deliberately left alone — the respawned
        workers overwrite the orphaned chunk slots in place and operator GC
        collects unreferenced dumps, and deleting them here would race a
        slow-dying writer thread's last put."""
        if not self.checkpoint_root:
            return
        import os

        if not os.path.isdir(self.checkpoint_root):
            return
        removed = 0
        for dirpath, _dirs, files in os.walk(self.checkpoint_root):
            for name in files:
                if not name.endswith(".tmp"):
                    continue
                try:
                    os.remove(os.path.join(dirpath, name))
                    removed += 1
                except OSError:
                    pass  # best-effort sweep, never fail a restart for it
        if removed:
            _log.info(
                "settled %d stale checkpoint staging file(s) under %s "
                "before restart", removed, self.checkpoint_root,
            )

    def _acquire_incarnation(self, attempt: int) -> None:
        """Bump the root's incarnation lease for this attempt and export it
        to the workers about to spawn (``PATHWAY_INCARNATION`` — fork-based
        spawners inherit the supervisor's environ; ``cli spawn`` copies it
        into the subprocess env explicitly).  Acquired BEFORE the group
        launches, so by the time any new worker can write, every writer of
        a previous attempt is already fenced.  Best-effort: a root that
        cannot hold a lease (read-only, no root at all) degrades to the
        pre-fencing behavior with a warning rather than refusing to run."""
        self._hangs = {}
        if not self.checkpoint_root:
            return
        try:
            from pathway_tpu.engine import persistence as pz

            self.incarnation = pz.acquire_lease(
                pz.FileBackend(self.checkpoint_root),
                owner=f"supervisor pid {os.getpid()} attempt {attempt}",
                # the lease records the TARGET TOPOLOGY of this attempt:
                # workers verify PATHWAY_PROCESSES against it at boot (the
                # topology handshake), and scrub renders the rescale
                # history it accumulates
                workers=self.n_workers,
            )
            os.environ[ENV_INCARNATION] = str(self.incarnation)
            _log.info(
                "attempt %d runs as incarnation %d over %d worker(s) "
                "(lease on %s)",
                attempt, self.incarnation, self.n_workers,
                self.checkpoint_root,
            )
        except Exception as exc:  # noqa: BLE001 - fencing is best-effort
            _log.warning(
                "could not acquire the incarnation lease on %s (%s); "
                "zombie-writer fencing is OFF for this run",
                self.checkpoint_root, exc,
            )

    def _spawn_one(self, worker_id: int, attempt: int) -> Any:
        if self._spawn_takes_workers:
            return self.spawn(worker_id, attempt, n_workers=self.n_workers)
        return self.spawn(worker_id, attempt)

    def _spawn_standbys(self, attempt: int) -> None:
        """(Re)fill the warm-standby pool: any missing/dead standby is
        respawned through the ordinary spawn callback with an id above
        the worker range; the ``PATHWAY_STANDBY_ID`` export (same
        env-export trick as the incarnation) flips the process into the
        tail loop (``engine/standby.py``) instead of the worker boot
        path.  Standbys are per-incarnation — a restart-all stops and
        respawns them so their inherited ``PATHWAY_INCARNATION`` matches
        the new lease and they honor its PROMOTE requests."""
        if not self.standbys or not self.checkpoint_root:
            return
        for sid in range(self.standbys):
            handle = self._standby_handles.get(sid)
            if handle is not None and _alive(handle):
                continue
            os.environ["PATHWAY_STANDBY_ID"] = str(sid)
            try:
                self._standby_handles[sid] = self._spawn_one(
                    self.n_workers + sid, attempt
                )
            except Exception as exc:  # noqa: BLE001 - a missing standby
                # only narrows recovery to the restart tier; never fail
                # the run for it
                _log.warning("could not spawn standby %d: %s", sid, exc)
            finally:
                os.environ.pop("PATHWAY_STANDBY_ID", None)

    def _stop_standbys(self) -> None:
        handles = list(self._standby_handles.values())
        self._standby_handles.clear()
        if handles:
            self._stop_all(handles)

    def _begin_promotion(
        self, wid: int, handles: Sequence[Any]
    ) -> dict[str, Any] | None:
        """Tier-one recovery: try to hand dead worker ``wid``'s shard to
        a warm standby.  Bumps the dead worker's per-worker fence (its
        zombie can never publish again) and posts the PROMOTE request
        the standby and the survivors coordinate on.  Returns the
        in-flight promotion's bookkeeping, or None when promotion cannot
        start — no root/lease, no live standby, spent promotion budget —
        in which case the caller takes the restart tier."""
        if not self.checkpoint_root or self.incarnation is None:
            return None
        if len(self.promotions) >= self.max_promotions:
            _log.warning(
                "promotion budget spent (%d); worker %d's death takes "
                "the restart tier", self.max_promotions, wid,
            )
            return None
        live = {
            sid: h for sid, h in self._standby_handles.items() if _alive(h)
        }
        if not live:
            return None
        from pathway_tpu.engine import persistence as pz

        try:
            beacons = pz.read_standby_beacons(self.checkpoint_root)
        except Exception:  # noqa: BLE001 - advisory files, never fatal
            beacons = {}
        # freshest standby first: the smallest published apply lag means
        # the least uncommitted tail to replay (no beacon sorts last)
        sid = min(
            live,
            key=lambda s: (
                s not in beacons,
                float(beacons.get(s, {}).get("lag_s") or 0.0),
                s,
            ),
        )
        reason = (
            f"worker {wid} exited {_exitcode(handles[wid])} on attempt "
            f"{self._attempt}"
        )
        try:
            fence = pz.bump_worker_fence(
                pz.FileBackend(self.checkpoint_root), wid
            )
            self._promote_seq += 1
            seq = self._promote_seq
            pz.post_promote_request(
                self.checkpoint_root,
                incarnation=self.incarnation,
                worker=wid,
                standby=sid,
                fence=fence,
                seq=seq,
                workers=self.n_workers,
                reason=reason,
            )
        except Exception as exc:  # noqa: BLE001 - unleased/read-only root
            _log.warning(
                "could not post a promotion for worker %d (%s); taking "
                "the restart tier", wid, exc,
            )
            return None
        now = time.monotonic()
        _log.warning(
            "%s — promoting standby %d into its place (promotion %d, "
            "fence %d, deadline %.1fs); survivors rejoin in place",
            reason, sid, seq, fence, self.promote_deadline_s,
        )
        return {
            "worker": wid,
            "standby": sid,
            "handle": live[sid],
            "seq": seq,
            "fence": fence,
            "reason": reason,
            "started": now,
            "deadline": now + self.promote_deadline_s,
        }

    def _poll_promotion(
        self, promo: dict[str, Any], handles: list[Any]
    ) -> dict[str, Any] | None:
        """One watch-loop poll of the in-flight promotion.  Returns None
        once the standby has adopted (its handle is swapped into the dead
        worker's slot and the pool refilled); returns ``promo`` while
        still pending, with ``promo["failed"]`` set after an abort
        (standby death, blown deadline) — the caller then routes the
        original death through the restart tier."""
        from pathway_tpu.engine import persistence as pz

        now = time.monotonic()
        try:
            acks = pz.read_promote_acks(self.checkpoint_root, self.n_workers)
        except Exception:  # noqa: BLE001 - advisory files, never fatal
            acks = {}
        adopted = acks.get("adopted")
        if adopted is not None and adopted.get("seq") == promo["seq"]:
            # the adopted marker is written strictly after the standby's
            # survivor wait, so clearing the coordination files here can
            # never race the standby's own reads of them
            wid, sid = promo["worker"], promo["standby"]
            handles[wid] = promo["handle"]
            self._standby_handles.pop(sid, None)
            record = {
                "worker": wid,
                "standby": sid,
                "seq": promo["seq"],
                "fence": promo["fence"],
                "attempt": self._attempt,
                "duration_s": round(now - promo["started"], 3),
                "reason": promo["reason"],
            }
            self.promotions.append(record)
            try:
                pz.append_promotion(self.checkpoint_root, record)
                pz.clear_promote(self.checkpoint_root, self.n_workers)
            except Exception:  # noqa: BLE001 - advisory files
                pass
            _metrics.get_registry().counter(
                "supervisor.promotions",
                "standby promotions performed (worker loss absorbed "
                "without a group restart)",
            ).inc()
            _log.warning(
                "standby %d adopted worker %d in %.3fs (%s); the group "
                "never restarted", sid, wid, record["duration_s"],
                promo["reason"],
            )
            self._spawn_standbys(self._attempt)  # refill the pool
            return None
        abort = None
        standby_code = _exitcode(promo["handle"])
        if standby_code is not None:
            abort = (
                f"standby {promo['standby']} died mid-promotion "
                f"(exit {standby_code})"
            )
        elif now >= promo["deadline"]:
            abort = (
                f"not adopted within {self.promote_deadline_s:.1f}s"
            )
        if abort is not None:
            self._abort_promotion(promo, abort)
            promo["failed"] = abort
        return promo

    def _abort_promotion(self, promo: dict[str, Any], why: str) -> None:
        """Fall from the promotion tier to the restart tier: kill the
        chosen standby (it may be mid-adoption holding the dead worker's
        identity) and clear the coordination files so nothing half-done
        outlives the abort.  The bumped fence needs no undo — the next
        attempt's ``acquire_lease`` rewrites the lease without it."""
        from pathway_tpu.engine import persistence as pz

        _metrics.get_registry().counter(
            "supervisor.promotion.fallbacks",
            "standby promotions that aborted and fell back to a "
            "whole-group restart",
        ).inc()
        _log.warning(
            "promotion %d (standby %d -> worker %d) aborted: %s; "
            "falling back to a whole-group restart",
            promo["seq"], promo["standby"], promo["worker"], why,
        )
        handle = promo["handle"]
        if _alive(handle):
            _signal(handle, hard=True)
            _join(handle, 2.0)
        self._standby_handles.pop(promo["standby"], None)
        try:
            pz.clear_promote(self.checkpoint_root, self.n_workers)
        except Exception:  # noqa: BLE001 - advisory files, never fatal
            pass

    def run(self) -> SupervisorResult:
        delays = self._backoff_delays()
        history: list[list[int | None]] = []
        attempt = 0
        handles: list[Any] = []
        last_failure: str | None = None
        # degraded-mode shrink bookkeeping: the attempt the current restart
        # budget started at (a shrink grants the smaller cluster a fresh
        # budget), and the same-worker failure streak that distinguishes a
        # permanently lost host from an ordinary crash loop
        budget_anchor = 0
        last_failed: int | None = None
        same_fail_streak = 0
        # post_mortem cutoff: dumps already on the root when THIS run
        # starts belong to a previous run and must not be re-attributed
        # to it (they stay on disk for `pathway_tpu blackbox`)
        self._run_started_at = time.time()
        self._controller = None
        if self.autoscale and self.checkpoint_root:
            from pathway_tpu.engine.autoscaler import ScaleController

            self._controller = ScaleController(current=self.n_workers)
            _log.info(
                "autoscaler armed: %d..%d worker(s), staleness threshold "
                "%.1fs, rescale budget %d",
                self._controller.min_workers, self._controller.max_workers,
                self._controller.staleness_hi_s, self._controller.budget,
            )
        try:
            while True:
                self._acquire_incarnation(attempt)
                self._attempt = attempt
                # the standby pool is per-incarnation: spawned after the
                # lease bump so each standby inherits THIS attempt's
                # PATHWAY_INCARNATION and honors its PROMOTE requests
                self._spawn_standbys(attempt)
                handles = []
                spawn_failure: tuple[int, BaseException] | None = None
                for w in range(self.n_workers):
                    try:
                        handles.append(self._spawn_one(w, attempt))
                    except Exception as exc:  # noqa: BLE001 - a dead host
                        # a spawn that cannot even launch (host gone,
                        # scheduler refusal) is a worker failure, not a
                        # supervisor crash: route it through the restart /
                        # shrink machinery like any other death
                        spawn_failure = (w, exc)
                        break
                first_failed = (
                    self._watch(handles)
                    if spawn_failure is None
                    else spawn_failure[0]
                )
                if first_failed is None:
                    outcome = self._handoff_outcome
                    self._handoff_outcome = None
                    if outcome is not None:
                        # planned rescale, not a crash: every worker
                        # exited 0.  Live = drain + ack completed, just
                        # relaunch at N'; fallback = split exit (some
                        # drained, some finished), restart at N' anyway.
                        live = outcome["kind"] == "live"
                        codes = [_exitcode(h) for h in handles]
                        history.append(codes)
                        self._settle_checkpoints()
                        self._finish_handoff(
                            outcome, attempt, live=live,
                            failure=None if live else (
                                "split exit: worker(s) "
                                f"{outcome.get('partial_acks')} drained for "
                                "the handoff while the rest finished"
                            ),
                        )
                        # a planned rescale never charges the restart
                        # budget: the resized cluster starts fresh
                        budget_anchor = attempt + 1
                        last_failed, same_fail_streak = None, 0
                        delays = self._backoff_delays()
                        attempt += 1
                        continue  # no backoff: relaunch immediately
                    if self._controller is not None and self.checkpoint_root:
                        # clean finish with the autoscaler armed: drop any
                        # unanswered request + beacons, persist the final
                        # decision log for post-run inspection
                        try:
                            from pathway_tpu.engine import autoscaler as _as
                            from pathway_tpu.engine import persistence as pz

                            pz.clear_handoff(
                                self.checkpoint_root, self.n_workers
                            )
                            _as.clear_load_beacons(
                                self.checkpoint_root, self.n_workers
                            )
                            self._controller.write_state(
                                self.checkpoint_root, time.monotonic()
                            )
                        except Exception:  # noqa: BLE001 - advisory only
                            pass
                    codes = [_exitcode(h) for h in handles]
                    history.append(codes)
                    recovery = self._recovery_info()
                    for wid, info in sorted(recovery.items()):
                        if info.get("rejected"):
                            _log.warning(
                                "worker %d recovered from VERIFIED generation "
                                "%s after rejecting damaged generation(s) %s",
                                wid, info.get("recovered_from"),
                                [g for g, _ in info["rejected"]],
                            )
                    return SupervisorResult(
                        attempt + 1, attempt, codes, history,  # type: ignore[arg-type]
                        recovery=recovery, last_failure=last_failure,
                        post_mortem=self._post_mortem(),
                        rescales=list(self.rescales),
                        promotions=list(self.promotions),
                    )
                hang = self._hangs.get(first_failed)
                if spawn_failure is not None:
                    last_failure = (
                        f"worker {first_failed} failed to spawn on attempt "
                        f"{attempt}: {spawn_failure[1]}"
                    )
                elif hang is not None:
                    # the exit code alone would read like an ordinary crash;
                    # the restart was actually the watchdog converting a
                    # silent stall into a supervised recovery
                    last_failure = (
                        f"worker {first_failed} hung ({hang}) on attempt "
                        f"{attempt}; watchdog killed it (exit "
                        f"{_exitcode(handles[first_failed])})"
                    )
                else:
                    last_failure = (
                        f"worker {first_failed} exited "
                        f"{_exitcode(handles[first_failed])} on attempt "
                        f"{attempt}"
                    )
                outcome = self._handoff_outcome
                self._handoff_outcome = None
                if outcome is not None:
                    # the live handoff faulted mid-flight (a death during
                    # the drain, or the ack deadline blew): fall back to
                    # the restart-based rescale at the SAME target
                    # topology.  Still a planned rescale — the resized
                    # cluster gets a fresh restart budget, like
                    # degraded-mode shrink does.
                    last_failure = (
                        f"live handoff to {outcome['to']} worker(s) "
                        f"faulted ({last_failure}); falling back to a "
                        f"restart-based rescale"
                    )
                    _log.warning("%s", last_failure)
                    self._stop_all(handles)
                    self._stop_standbys()
                    self._settle_checkpoints()
                    codes = [_exitcode(h) for h in handles]
                    codes += [None] * (self.n_workers - len(codes))
                    history.append(codes)
                    self._finish_handoff(
                        outcome, attempt, live=False, failure=last_failure
                    )
                    budget_anchor = attempt + 1
                    last_failed, same_fail_streak = None, 0
                    delays = self._backoff_delays()
                    time.sleep(
                        next(delays)
                        + random.uniform(0, self.restart_jitter_s)
                    )
                    attempt += 1
                    continue
                _metrics.get_registry().counter(
                    "supervisor.restarts",
                    "cluster rollback-and-respawn recoveries performed",
                ).inc()
                _log.warning(
                    "worker %d failed (%s) on attempt %d; rolling the "
                    "group back to the last committed checkpoint",
                    first_failed, last_failure, attempt,
                )
                self._stop_all(handles)
                # standbys are per-incarnation: stop them too so the next
                # attempt's respawn hands them the bumped incarnation
                self._stop_standbys()
                # every worker process is dead: in-flight async commits are
                # drained by construction, so settle their residue on the
                # root BEFORE this attempt is accounted and the respawn
                # resumes from what actually landed
                self._settle_checkpoints()
                codes = [_exitcode(h) for h in handles]
                codes += [None] * (self.n_workers - len(codes))
                history.append(codes)
                if first_failed == last_failed:
                    same_fail_streak += 1
                else:
                    last_failed, same_fail_streak = first_failed, 1
                if attempt - budget_anchor >= self.max_restarts:
                    # restart budget spent.  The permanently-lost-host
                    # signature — the SAME worker failed every attempt of
                    # the budget — can be absorbed by degraded-mode shrink
                    # (opt-in); anything else is a crash loop and fails.
                    consistent_loss = (
                        same_fail_streak >= attempt - budget_anchor + 1
                    )
                    if (
                        self.shrink_on_loss
                        and self.n_workers > 1
                        and consistent_loss
                    ):
                        new_n = self.n_workers - 1
                        self.rescales.append(
                            {
                                "from": self.n_workers,
                                "to": new_n,
                                "lost_worker": first_failed,
                                "attempt": attempt,
                                "reason": last_failure,
                            }
                        )
                        _metrics.get_registry().counter(
                            "supervisor.rescales",
                            "degraded-mode cluster rescales performed "
                            "(worker-loss shrink)",
                        ).inc()
                        _log.warning(
                            "worker %d failed on every attempt of the spent "
                            "restart budget — treating it as permanently "
                            "lost and rescaling the cluster %d -> %d "
                            "worker(s); checkpointed state re-partitions by "
                            "shard range on resume",
                            first_failed, self.n_workers, new_n,
                        )
                        self.n_workers = new_n
                        budget_anchor = attempt + 1
                        last_failed, same_fail_streak = None, 0
                        delays = self._backoff_delays()  # fresh schedule
                    else:
                        hint = (
                            " (the same worker failed every attempt — a "
                            "permanently lost host can be absorbed with "
                            "degraded-mode shrink: PATHWAY_DEGRADED_SHRINK=1 "
                            "or `spawn --supervise --shrink-on-loss`)"
                            if consistent_loss
                            and not self.shrink_on_loss
                            and self.n_workers > 1
                            else ""
                        )
                        err = SupervisorError(
                            f"cluster failed {attempt + 1} time(s) "
                            f"(restart budget {self.max_restarts}); last exit "
                            f"codes {history[-1]}; last failure: "
                            f"{last_failure}{hint}"
                        )
                        # a crash loop is exactly when the black box matters
                        # most: the dumps ride the exception so callers (and
                        # `spawn --supervise`) can point the operator at them
                        err.post_mortem = self._post_mortem()
                        raise err
                time.sleep(
                    next(delays) + random.uniform(0, self.restart_jitter_s)
                )
                attempt += 1
        finally:
            # any escape — Ctrl-C in _watch, a spawn() failure partway
            # through launching the group — must not orphan live workers
            # (they would wait on mesh peers forever); redundant stops of
            # already-exited workers are no-ops
            self._stop_all(handles)
            self._stop_standbys()
            # do not leak THIS run's incarnation into the host process:
            # later (unsupervised) runs in the same process would stamp
            # and fence against a lease they do not participate in
            if self.incarnation is not None:
                os.environ.pop(ENV_INCARNATION, None)

    # pathway-lint: context=watchdog
    def _watch(self, handles: Sequence[Any]) -> int | None:
        """Block until all workers exit 0 (None) or one fails (its id).

        The loop doubles as the progress watchdog: each poll also checks
        every live worker's progress beacon and escalates
        SIGUSR1 → SIGTERM → SIGKILL on a stalled one, whose death the
        death-watch above then routes through the ordinary restart path.

        When autoscaling is armed it is ALSO the scale controller's
        sensor→actuator tick: each poll reads the workers' load beacons,
        feeds them to the controller, and — on a decision — posts the
        handoff request the workers drain against.  A pending handoff's
        outcome is reported out-of-band on ``self._handoff_outcome``
        (the int/None return keeps its original failure meaning)."""
        watchdog = (
            _ProgressWatchdog(self)
            if self.epoch_deadline_s and self.checkpoint_root
            else None
        )
        self._handoff_outcome = None
        self._promotion = None
        controller = self._controller
        pending: dict[str, Any] | None = None
        if controller is not None:
            # re-sync after any rescale (ours or degraded-mode shrink)
            controller.current = self.n_workers
            controller.handoff_state = ""
        while True:
            all_done = True
            promo = self._promotion
            for wid, handle in enumerate(handles):
                code = _exitcode(handle)
                if code is None:
                    all_done = False
                elif promo is not None and wid == promo["worker"]:
                    # tier-one recovery in flight for this very death:
                    # the dead handle stays in its slot until the chosen
                    # standby adopts (or the promotion aborts below)
                    all_done = False
                elif code != 0:
                    if pending is not None:
                        # a death while the handoff drains poisons it:
                        # all-or-nothing, so fall back to a restart rescale
                        pending["kind"] = "fallback"
                        self._handoff_outcome = pending
                        return wid
                    if promo is not None:
                        # a SECOND death while a promotion drains: the
                        # survivors' rejoin can never complete — abort
                        # the promotion, take the restart tier for both
                        self._abort_promotion(
                            promo,
                            f"worker {wid} also died (exit {code}) while "
                            f"promotion {promo['seq']} was in flight",
                        )
                        self._promotion = None
                        return wid
                    # a death with no handoff pending: try the promotion
                    # tier first; only when it cannot start does the
                    # death surface to run()'s restart machinery
                    self._promotion = promo = self._begin_promotion(
                        wid, handles
                    )
                    if promo is None:
                        return wid
                    all_done = False
            if all_done:
                if pending is not None:
                    self._classify_handoff_exit(pending)
                return None
            if promo is not None:
                promo = self._poll_promotion(promo, handles)
                if promo is not None and promo.get("failed"):
                    self._promotion = None
                    return promo["worker"]
                self._promotion = promo
            if watchdog is not None:
                watchdog.poll(handles)
            if (
                controller is not None
                and self.incarnation is not None
                # no scale decisions while a shard changes owners via
                # promotion: the two actuators share the worker set and
                # must not interleave (the race tests pin both orders)
                and self._promotion is None
            ):
                pending = self._autoscale_tick(controller, pending)
                if pending is not None and pending.get("expired"):
                    # deadline blown: a worker is wedged mid-drain.
                    # Convert the wedge into an ordinary failure; run()
                    # applies the target topology via the restart path
                    # (the fallback contract).
                    wid = int(pending.get("straggler", 0))
                    self._hangs[wid] = (
                        f"handoff to {pending['to']} worker(s) not "
                        f"acknowledged within {self.handoff_deadline_s:.1f}s"
                    )
                    pending["kind"] = "fallback"
                    self._handoff_outcome = pending
                    return wid
            time.sleep(self.poll_interval_s)

    def _autoscale_tick(
        self, controller: Any, pending: dict[str, Any] | None
    ) -> dict[str, Any] | None:
        """One sensor→actuator poll: read load beacons, feed the
        controller, post a handoff request on a decision.  Returns the
        pending-handoff bookkeeping (None when no handoff is in flight)."""
        now = time.monotonic()
        if pending is not None:
            # actuation in flight: no new decisions until it settles —
            # just watch the deadline
            if now >= pending["deadline"] and "expired" not in pending:
                pending["expired"] = True
                pending["straggler"] = self._first_unacked(pending["to"])
            return pending
        if now - self._as_last_observe < 0.1:
            return None
        self._as_last_observe = now
        from pathway_tpu.engine import autoscaler as _as

        beacons = _as.read_load_beacons(self.checkpoint_root, self.n_workers)
        decision = None
        if beacons:
            # no fresh beacons = booting or torn-down workers, not calm:
            # feed the controller only when the sensors are live, so an
            # instrumentation gap can never read as sustained idleness
            staleness_s, backlog = _as.worst_load(beacons)
            decision = controller.observe(now, staleness_s, backlog)
        if decision is not None:
            from pathway_tpu.engine import persistence as pz

            to_n = int(decision["to"])
            pz.post_handoff_request(
                self.checkpoint_root,
                incarnation=self.incarnation,
                from_workers=self.n_workers,
                to_workers=to_n,
                reason=str(decision.get("reason", "")),
            )
            controller.handoff_state = "handoff-requested"
            _log.warning(
                "autoscaler: posted live handoff request %d -> %d "
                "worker(s) (%s; deadline %.1fs)",
                self.n_workers, to_n, decision.get("reason", ""),
                self.handoff_deadline_s,
            )
            pending = {
                "to": to_n,
                "decision": decision,
                "deadline": now + self.handoff_deadline_s,
            }
        if decision is not None or now - self._as_last_state >= 0.5:
            self._as_last_state = now
            controller.write_state(self.checkpoint_root, now)
        return pending

    def _ack_valid(self, ack: dict | None, to_n: int) -> bool:
        return (
            ack is not None
            and ack.get("incarnation") == self.incarnation
            and ack.get("to_workers") == to_n
        )

    def _handoff_acks(self, to_n: int) -> list[int]:
        """Worker ids that wrote a valid ack for the pending handoff."""
        try:
            from pathway_tpu.engine import persistence as pz

            acks = pz.read_handoff_acks(self.checkpoint_root, self.n_workers)
        except Exception:  # noqa: BLE001 - advisory files, never fatal
            acks = {}
        return [
            w
            for w in range(self.n_workers)
            if self._ack_valid(acks.get(w), to_n)
        ]

    def _first_unacked(self, to_n: int) -> int:
        acked = set(self._handoff_acks(to_n))
        for w in range(self.n_workers):
            if w not in acked:
                return w
        return 0

    def _classify_handoff_exit(self, pending: dict[str, Any]) -> None:
        """All workers exited 0 with a handoff pending — decide what
        actually happened from the acks on the root."""
        acked = self._handoff_acks(pending["to"])
        if len(acked) == self.n_workers:
            # every worker fenced, committed and acked: the live handoff
            # completed — relaunch at the target picks the frontier up
            pending["kind"] = "live"
            self._handoff_outcome = pending
        elif acked:
            # split exit: some workers drained for the handoff, others
            # finished for real.  The topology must still land at the
            # target, but only a restart rescale can take it there.
            pending["kind"] = "fallback"
            pending["partial_acks"] = acked
            self._handoff_outcome = pending
        # zero acks: the sources finished before any worker saw the
        # request — a genuine clean finish; run() clears the residue

    def _finish_handoff(
        self,
        outcome: dict[str, Any],
        attempt: int,
        *,
        live: bool,
        failure: str | None = None,
    ) -> None:
        """Account a settled handoff (either path) and adopt the target
        topology: rescale provenance + counters, decision-log note,
        coordination-file cleanup, and ``self.n_workers = N'``."""
        from pathway_tpu.engine import autoscaler as _as
        from pathway_tpu.engine import comm as _comm
        from pathway_tpu.engine import persistence as pz

        from_n, to_n = self.n_workers, int(outcome["to"])
        decision = outcome.get("decision") or {}
        self.rescales.append(
            {
                "kind": "autoscale" if live else "autoscale-fallback",
                "from": from_n,
                "to": to_n,
                "attempt": attempt,
                "reason": failure or str(decision.get("reason", "")),
                "action": str(decision.get("action", "")),
                "moving_shards": _comm.moving_shards(from_n, to_n),
            }
        )
        if live:
            _metrics.get_registry().counter(
                "supervisor.handoffs",
                "live shard-range handoffs completed (rescale without a "
                "rollback restart)",
            ).inc()
        else:
            _metrics.get_registry().counter(
                "supervisor.handoff.fallbacks",
                "live handoffs that faulted mid-flight and fell back to "
                "a restart-based rescale",
            ).inc()
        now = time.monotonic()
        controller = self._controller
        if controller is not None:
            controller.current = to_n
            controller.handoff_state = "done" if live else "fallback"
            if live:
                controller.note(now, "handoff-complete", to=to_n)
            else:
                controller.note(
                    now, "handoff-fallback", to=to_n, failure=failure or ""
                )
        scope = max(from_n, to_n)
        try:
            pz.clear_handoff(self.checkpoint_root, scope)
            _as.clear_load_beacons(self.checkpoint_root, scope)
        except Exception:  # noqa: BLE001 - advisory files, never fatal
            pass
        if controller is not None and self.checkpoint_root:
            controller.write_state(self.checkpoint_root, now)
        _log.warning(
            "rescaled %d -> %d worker(s) via %s (attempt %d); %d of %d "
            "shard(s) change owners on resume",
            from_n, to_n,
            "live handoff" if live else "handoff fallback (restart)",
            attempt, self.rescales[-1]["moving_shards"],
            1 << _comm.SHARD_BITS,
        )
        self.n_workers = to_n

    def _stop_all(self, handles: Sequence[Any]) -> None:
        """Terminate survivors: their uncommitted progress IS the rollback."""
        for handle in handles:
            if _alive(handle):
                _signal(handle, hard=False)
        deadline = time.monotonic() + self.grace_s
        for handle in handles:
            _join(handle, max(0.1, deadline - time.monotonic()))
        for handle in handles:
            if _alive(handle):
                _signal(handle, hard=True)
                _join(handle, 2.0)
