"""Supervised crash recovery for the multi-worker runtime.

The reference framework's ancestor survives worker death because its
persistence layer can always rewind a worker group to the last committed
snapshot frontier (``src/persistence/tracker.rs``).  This module is the
process-level half of that story for this engine: a **supervisor** that
watches the N SPMD worker processes of a cluster and, on a confirmed
worker death, rolls the whole group back to the last committed
checkpoint and replays.

Why whole-group restart (and not patching one worker back in)?  The
epoch loop is BSP: every worker walks the identical DAG in lockstep and
the collectives pair up positionally.  When a worker dies mid-epoch, the
survivors hold in-memory operator state for epochs the dead worker never
committed — state a respawned worker cannot reproduce.  The only
consistent rollback point every worker agrees on is the last committed
checkpoint (``engine/persistence.py`` commits are per-worker atomic
metadata writes gated on processed epochs).  So the supervisor:

1. detects the death (nonzero or signal exit);
2. terminates the surviving workers (their un-committed progress is
   exactly what must be rolled back — killing them IS the rollback);
3. respawns all N workers with the same run id, ports, comm secret and
   persistence root, after a backoff (the shared ``udfs`` retry
   schedule).  Each worker resumes from its own committed snapshot
   shard: committed events replay into the input sessions, readers seek
   to the stored offset frontier, and the mesh re-forms.

Sinks re-open their output files on restart, so the recovered run's
final output is identical to an unfaulted run's — the property the
kill-and-restart test in ``tests/test_supervised_recovery.py`` pins.

Restart attempts are announced to workers via ``PATHWAY_RESTART_ATTEMPT``
(the fault plan's ``attempt`` filter keys off it, so chaos tests can
inject a crash on attempt 0 and let attempt 1 run clean).

Worker handles are duck-typed: ``multiprocessing.Process`` (tests,
in-repo harnesses) and ``subprocess.Popen`` (``pathway spawn
--supervise``) both work.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Callable, Sequence

_log = logging.getLogger("pathway_tpu.supervisor")

# one constant for the restart-attempt protocol: the fault plan's
# `attempt` filter and the jax coordinator-port offset read the same var
from pathway_tpu.engine.faults import ENV_ATTEMPT  # noqa: E402,F401


class SupervisorError(RuntimeError):
    """The cluster kept failing past the restart budget."""


class SupervisorResult:
    __slots__ = ("attempts", "restarts", "exit_codes", "history")

    def __init__(
        self,
        attempts: int,
        restarts: int,
        exit_codes: list[int],
        history: list[list[int | None]],
    ):
        self.attempts = attempts  # launches performed (>= 1)
        self.restarts = restarts  # recoveries performed (attempts - 1)
        self.exit_codes = exit_codes  # final attempt's per-worker codes
        # per-attempt worker exit codes at teardown time (negative =
        # signal, e.g. -9 for the SIGKILL that triggered the recovery)
        self.history = history

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"SupervisorResult(attempts={self.attempts}, "
            f"restarts={self.restarts}, exit_codes={self.exit_codes})"
        )


# -- handle duck-typing (multiprocessing.Process | subprocess.Popen) -------


def _exitcode(handle: Any) -> int | None:
    if hasattr(handle, "exitcode"):  # multiprocessing.Process
        return handle.exitcode
    return handle.poll()  # subprocess.Popen


def _alive(handle: Any) -> bool:
    return _exitcode(handle) is None


def _join(handle: Any, timeout: float) -> None:
    if hasattr(handle, "join"):
        handle.join(timeout)
        return
    try:
        handle.wait(timeout)
    except Exception:  # subprocess.TimeoutExpired
        pass


def _signal(handle: Any, *, hard: bool) -> None:
    try:
        if hard:
            handle.kill()
        else:
            handle.terminate()
    except (OSError, ValueError):
        pass  # already gone


class Supervisor:
    """Run one SPMD worker group to completion, restarting it on failure.

    ``spawn(worker_id, attempt)`` must start worker ``worker_id`` of the
    group and return its handle; it is responsible for wiring the cluster
    env (``PATHWAY_PROCESSES``/``PROCESS_ID``/``FIRST_PORT``/…) and for
    exporting ``PATHWAY_RESTART_ATTEMPT=attempt`` into the worker.
    """

    def __init__(
        self,
        spawn: Callable[[int, int], Any],
        n_workers: int,
        *,
        max_restarts: int = 3,
        grace_s: float = 5.0,
        poll_interval_s: float = 0.05,
    ):
        self.spawn = spawn
        self.n_workers = n_workers
        self.max_restarts = max_restarts
        self.grace_s = grace_s
        self.poll_interval_s = poll_interval_s

    def _backoff_delays(self):
        # the udfs backoff schedule — the same policy the comm mesh uses
        # for link reconnects, applied between cluster restart attempts
        from pathway_tpu.internals.udfs.retries import (
            ExponentialBackoffRetryStrategy,
        )

        return ExponentialBackoffRetryStrategy(
            max_retries=max(self.max_restarts, 1),
            initial_delay=200,
            backoff_factor=2,
            jitter_ms=100,
        ).delays()

    def run(self) -> SupervisorResult:
        delays = self._backoff_delays()
        history: list[list[int | None]] = []
        attempt = 0
        handles: list[Any] = []
        try:
            while True:
                handles = []
                for w in range(self.n_workers):
                    handles.append(self.spawn(w, attempt))
                first_failed = self._watch(handles)
                if first_failed is None:
                    codes = [_exitcode(h) for h in handles]
                    history.append(codes)
                    return SupervisorResult(attempt + 1, attempt, codes, history)  # type: ignore[arg-type]
                _log.warning(
                    "worker %d died (exit %s) on attempt %d; rolling the "
                    "group back to the last committed checkpoint",
                    first_failed, _exitcode(handles[first_failed]), attempt,
                )
                self._stop_all(handles)
                history.append([_exitcode(h) for h in handles])
                if attempt >= self.max_restarts:
                    raise SupervisorError(
                        f"cluster failed {attempt + 1} time(s) "
                        f"(restart budget {self.max_restarts}); last exit "
                        f"codes {history[-1]}"
                    )
                time.sleep(next(delays))
                attempt += 1
        finally:
            # any escape — Ctrl-C in _watch, a spawn() failure partway
            # through launching the group — must not orphan live workers
            # (they would wait on mesh peers forever); redundant stops of
            # already-exited workers are no-ops
            self._stop_all(handles)

    def _watch(self, handles: Sequence[Any]) -> int | None:
        """Block until all workers exit 0 (None) or one fails (its id)."""
        while True:
            all_done = True
            for wid, handle in enumerate(handles):
                code = _exitcode(handle)
                if code is None:
                    all_done = False
                elif code != 0:
                    return wid
            if all_done:
                return None
            time.sleep(self.poll_interval_s)

    def _stop_all(self, handles: Sequence[Any]) -> None:
        """Terminate survivors: their uncommitted progress IS the rollback."""
        for handle in handles:
            if _alive(handle):
                _signal(handle, hard=False)
        deadline = time.monotonic() + self.grace_s
        for handle in handles:
            _join(handle, max(0.1, deadline - time.monotonic()))
        for handle in handles:
            if _alive(handle):
                _signal(handle, hard=True)
                _join(handle, 2.0)
