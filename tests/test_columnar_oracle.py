"""Differential fuzz: the columnar relational fast paths vs the row oracle.

ISSUE 14's exactness guard.  Randomized delta STREAMS (multiple epochs,
mixed dtypes, Nones, retractions, key collisions) run through join /
groupby / windowby-with-behavior pipelines twice — vector compiler ON and
OFF — and must produce identical outputs.  The columnar paths are allowed
to bail to the row-wise evaluator (that is what ``columnar.bail.count``
makes visible); what they may never do is produce different values.

Also pins the PR 14 native kernels directly (``split_deltas``,
``freeze_scan``, ``route_deltas``) against their Python references, the
bail counter, and the profiler's columnar/row path attribution.
"""

from __future__ import annotations

import random

import pytest

import pathway_tpu as pw
from pathway_tpu.internals import vector_compiler as vc
from tests.utils import run_with_vector_mode

# epochs comfortably above VEC_THRESHOLD so the columnar paths engage
N_PER_EPOCH = max(200, vc.VEC_THRESHOLD * 2)
N_EPOCHS = 3


def _norm(rows_map):
    out = []
    for r in rows_map.values():
        out.append(
            tuple(
                "nan" if isinstance(v, float) and v != v else v for v in r
            )
        )
    out.sort(key=repr)
    return out


def _run(build, columnar: bool):
    return _norm(run_with_vector_mode(build, columnar))


def _stream_rows(rng: random.Random, n_cols_fn, retract_frac=0.2):
    """Rows for ``table_from_rows(is_stream=True)``: epochs of inserts with
    a fraction retracted (same values, later epoch) — the delta-stream
    shape the incremental operators must stay exact on."""
    rows = []
    live = []
    for epoch in range(N_EPOCHS):
        t = epoch * 2
        for _ in range(N_PER_EPOCH):
            vals = n_cols_fn(epoch)
            rows.append((*vals, t, 1))
            live.append(vals)
        if epoch and retract_frac:
            k = int(len(live) * retract_frac / N_EPOCHS)
            for _ in range(k):
                vals = live.pop(rng.randrange(len(live)))
                rows.append((*vals, t, -1))
    return rows


@pytest.mark.parametrize("seed", range(4))
@pytest.mark.parametrize("mode", ["inner", "left", "outer"])
def test_join_stream_parity(seed, mode):
    rng = random.Random(100 * seed + hash(mode) % 97)

    class FactSchema(pw.Schema):
        fid: int = pw.column_definition(primary_key=True)
        k: int
        tag: str
        v: int

    class DimSchema(pw.Schema):
        did: int = pw.column_definition(primary_key=True)
        k: int
        w: int

    fid = [0]

    def fact(epoch):
        fid[0] += 1
        return (
            fid[0],
            rng.randrange(0, 40),  # dense keys: collisions guaranteed
            rng.choice(["a", "bb", ""]),
            rng.randrange(-50, 50),
        )

    did = [0]

    def dim(epoch):
        did[0] += 1
        return (did[0], rng.randrange(0, 55), rng.randrange(0, 9))

    facts = _stream_rows(rng, fact)
    dims = _stream_rows(rng, dim, retract_frac=0.3)

    def build():
        ft = pw.debug.table_from_rows(FactSchema, facts, is_stream=True)
        dt = pw.debug.table_from_rows(DimSchema, dims, is_stream=True)
        how = {
            "inner": pw.JoinMode.INNER,
            "left": pw.JoinMode.LEFT,
            "outer": pw.JoinMode.OUTER,
        }[mode]
        return ft.join(dt, ft.k == dt.k, how=how).select(
            k=pw.left.k,
            tag=pw.left.tag,
            v=pw.left.v,
            w=pw.right.w,
        )

    assert _run(build, True) == _run(build, False), (seed, mode)


@pytest.mark.parametrize("seed", range(3))
def test_join_none_keys_parity(seed):
    """Optional join keys: None never matches (SQL) and routes/bails must
    agree between the batched and per-row key-hash paths."""
    rng = random.Random(500 + seed)

    class L(pw.Schema):
        i: int = pw.column_definition(primary_key=True)
        k: int | None
        v: int

    class R(pw.Schema):
        j: int = pw.column_definition(primary_key=True)
        k: int | None
        w: int

    i = [0]

    def lrow(epoch):
        i[0] += 1
        return (
            i[0],
            None if rng.random() < 0.2 else rng.randrange(0, 30),
            rng.randrange(0, 100),
        )

    j = [0]

    def rrow(epoch):
        j[0] += 1
        return (
            j[0],
            None if rng.random() < 0.2 else rng.randrange(0, 30),
            rng.randrange(0, 100),
        )

    ls = _stream_rows(rng, lrow)
    rs = _stream_rows(rng, rrow)

    def build():
        lt = pw.debug.table_from_rows(L, ls, is_stream=True)
        rt = pw.debug.table_from_rows(R, rs, is_stream=True)
        return lt.join(rt, lt.k == rt.k, how=pw.JoinMode.LEFT).select(
            k=pw.left.k, v=pw.left.v, w=pw.right.w
        )

    assert _run(build, True) == _run(build, False), seed


@pytest.mark.parametrize("seed", range(4))
def test_groupby_stream_parity(seed):
    rng = random.Random(1000 + seed)

    class S(pw.Schema):
        rid: int = pw.column_definition(primary_key=True)
        g: int
        s: str
        v: int
        f: float

    rid = [0]

    def row(epoch):
        rid[0] += 1
        return (
            rid[0],
            rng.randrange(0, 25),
            rng.choice(["x", "yy", "z", ""]),
            rng.choice([0, 1, -1, 2**60, 7]) if rng.random() < 0.1
            else rng.randrange(-100, 100),
            rng.choice([0.0, -1.5, 1e300]) if rng.random() < 0.1
            else rng.uniform(-50, 50),
        )

    rows = _stream_rows(rng, row, retract_frac=0.3)

    def build():
        t = pw.debug.table_from_rows(S, rows, is_stream=True)
        return t.groupby(pw.this.g, pw.this.s).reduce(
            g=pw.this.g,
            s=pw.this.s,
            n=pw.reducers.count(),
            tot=pw.reducers.sum(pw.this.v),
            ftot=pw.reducers.sum(pw.this.f),
            lo=pw.reducers.min(pw.this.v),
            hi=pw.reducers.max(pw.this.f),
        )

    assert _run(build, True) == _run(build, False), seed


@pytest.mark.parametrize("seed", range(4))
@pytest.mark.parametrize("shape", ["tumbling", "sliding"])
def test_windowby_behavior_stream_parity(seed, shape):
    """The PR 14 tentpole pin: windowby with a temporal behavior drives
    Buffer/Freeze/Forget over multi-epoch streams — the columnar pane
    admit/expiry paths must match the row oracle byte-for-byte."""
    rng = random.Random(2000 + 10 * seed + (shape == "sliding"))

    class S(pw.Schema):
        rid: int = pw.column_definition(primary_key=True)
        at: int
        inst: int
        v: int

    rid = [0]

    def row(epoch):
        rid[0] += 1
        # event times drift forward with jitter and stragglers, so panes
        # open, fill late, freeze, and expire across epochs
        base = epoch * 400
        return (
            rid[0],
            base + rng.randrange(-300, 400),
            rng.randrange(0, 3),
            rng.randrange(0, 100),
        )

    rows = _stream_rows(rng, row, retract_frac=0.15)
    window = (
        pw.temporal.tumbling(duration=100)
        if shape == "tumbling"
        else pw.temporal.sliding(hop=50, duration=150)
    )
    behavior = pw.temporal.common_behavior(
        delay=rng.choice([0, 60]),
        cutoff=rng.choice([100, 300]),
        keep_results=rng.random() < 0.5,
    )

    def build():
        t = pw.debug.table_from_rows(S, rows, is_stream=True)
        return t.windowby(
            pw.this.at,
            window=window,
            behavior=behavior,
            instance=pw.this.inst,
        ).reduce(
            start=pw.this._pw_window_start,
            inst=pw.this._pw_instance,
            n=pw.reducers.count(),
            tot=pw.reducers.sum(pw.this.v),
        )

    assert _run(build, True) == _run(build, False), (seed, shape)


@pytest.mark.parametrize("seed", range(2))
def test_windowby_exactly_once_stream_parity(seed):
    rng = random.Random(3000 + seed)

    class S(pw.Schema):
        rid: int = pw.column_definition(primary_key=True)
        at: int
        v: int

    rid = [0]

    def row(epoch):
        rid[0] += 1
        return (rid[0], epoch * 300 + rng.randrange(0, 500), rng.randrange(0, 50))

    rows = _stream_rows(rng, row, retract_frac=0.0)

    def build():
        t = pw.debug.table_from_rows(S, rows, is_stream=True)
        return t.windowby(
            pw.this.at,
            window=pw.temporal.tumbling(duration=100),
            behavior=pw.temporal.exactly_once_behavior(shift=20),
        ).reduce(
            start=pw.this._pw_window_start,
            n=pw.reducers.count(),
        )

    assert _run(build, True) == _run(build, False), seed


@pytest.mark.parametrize("seed", range(3))
def test_session_windowby_stream_parity(seed):
    """ISSUE 18 satellite: gap-based session assignment takes the
    vectorized merge (numpy diff/split) when the vector path is on and
    the reference per-pair loop when it is off — outputs must be
    byte-identical across multi-epoch streams with retractions."""
    rng = random.Random(4000 + seed)

    class S(pw.Schema):
        rid: int = pw.column_definition(primary_key=True)
        at: int
        inst: int
        v: int

    rid = [0]

    def row(epoch):
        rid[0] += 1
        # clustered bursts with dead gaps so sessions split and merge as
        # retractions rearrange chain boundaries across epochs
        burst = rng.randrange(0, 12) * 100
        return (
            rid[0],
            epoch * 1200 + burst + rng.randrange(0, 30),
            rng.randrange(0, 3),
            rng.randrange(0, 100),
        )

    rows = _stream_rows(rng, row, retract_frac=0.2)

    def build():
        t = pw.debug.table_from_rows(S, rows, is_stream=True)
        return t.windowby(
            pw.this.at,
            window=pw.temporal.session(max_gap=40),
            instance=pw.this.inst,
        ).reduce(
            start=pw.this._pw_window_start,
            end=pw.this._pw_window_end,
            inst=pw.this._pw_instance,
            n=pw.reducers.count(),
            tot=pw.reducers.sum(pw.this.v),
        )

    assert _run(build, True) == _run(build, False), seed


def test_session_predicate_bails_with_dedicated_reason():
    """A custom merge predicate cannot vectorize: the assignment must be
    classified under its own bail reason (op=session reason=predicate-
    merge), not lost in a generic bucket — and stay exact."""

    class S(pw.Schema):
        rid: int = pw.column_definition(primary_key=True)
        at: int
        v: int

    rows = [(i, (i // 5) * 100 + i % 5, i, 0, 1) for i in range(40)]

    def build():
        t = pw.debug.table_from_rows(S, rows, is_stream=True)
        return t.windowby(
            pw.this.at,
            window=pw.temporal.session(predicate=lambda a, b: b - a <= 10),
        ).reduce(
            start=pw.this._pw_window_start,
            n=pw.reducers.count(),
        )

    before = vc.BAIL_COUNTS.get(("session", "predicate-merge"), 0)
    assert _run(build, True) == _run(build, False)
    assert vc.BAIL_COUNTS[("session", "predicate-merge")] > before


def test_session_gap_vectorized_merge_is_exact():
    """The numpy gap merge vs the reference loop, directly: random time
    sets (duplicates, bursts, singletons) must split into identical
    (start, end) session tuples."""
    from pathway_tpu.stdlib.temporal._window import (
        SessionWindow,
        _sessions_of_loop,
    )
    import numpy as np

    rng = random.Random(11)
    for gap in (0, 1, 7, 40):
        win = SessionWindow(max_gap=gap)
        for _ in range(20):
            times = tuple(
                rng.randrange(0, 500) for _ in range(rng.randrange(0, 60))
            )
            ref = _sessions_of_loop(win, times)
            if not times:
                assert ref == ()
                continue
            arr = np.sort(np.asarray(times, dtype=np.int64))
            breaks = np.flatnonzero(np.diff(arr) > gap)
            starts = arr[np.concatenate(([0], breaks + 1))]
            ends = arr[np.concatenate((breaks, [arr.size - 1]))]
            got = tuple(zip(starts.tolist(), ends.tolist()))
            assert got == ref, (gap, times)


def test_buffer_dirty_column_bails_and_counts():
    """A None in the time column cannot materialize: the buffer must fall
    back to the row path (identical output) and count the bail."""

    class S(pw.Schema):
        rid: int = pw.column_definition(primary_key=True)
        at: int | None
        v: int

    n = max(100, vc.VEC_THRESHOLD + 10)
    rows = [(i, (i * 7) % 500 if i % 17 else None, i % 50, 0, 1) for i in range(n)]

    def build():
        t = pw.debug.table_from_rows(S, rows, is_stream=True)
        t = t.filter(pw.this.at.is_not_none())
        # coalesce keeps the optional dtype out but values stay clean;
        # the windowby runs on a plain int column
        t = t.select(at=pw.coalesce(pw.this.at, 0), v=pw.this.v)
        return t.windowby(
            pw.this.at,
            window=pw.temporal.tumbling(duration=100),
            behavior=pw.temporal.common_behavior(delay=50),
        ).reduce(start=pw.this._pw_window_start, n=pw.reducers.count())

    assert _run(build, True) == _run(build, False)


@pytest.mark.parametrize("seed", range(2))
def test_temporal_nan_time_parity(seed):
    """NaN in a float time column must not diverge: t.max() would poison
    the watermark (the row path's sequential `t > wm` scan skips NaN) and
    a NaN threshold would wedge the forget expiry heap — the columnar
    temporal path must bail (reason nan-time) and match the oracle."""
    rng = random.Random(7000 + seed)

    class S(pw.Schema):
        rid: int = pw.column_definition(primary_key=True)
        at: float
        v: int

    rid = [0]

    def row(epoch):
        rid[0] += 1
        at = (
            float("nan")
            if rng.random() < 0.02
            else float(epoch * 300 + rng.randrange(0, 500))
        )
        return (rid[0], at, rng.randrange(0, 50))

    rows = _stream_rows(rng, row, retract_frac=0.0)
    # at least one NaN per epoch, deterministically
    rows[0] = (rows[0][0], float("nan"), rows[0][2], rows[0][3], rows[0][4])

    def build():
        t = pw.debug.table_from_rows(S, rows, is_stream=True)
        return t.windowby(
            pw.this.at,
            window=pw.temporal.tumbling(duration=100.0),
            behavior=pw.temporal.common_behavior(
                delay=50.0, cutoff=200.0, keep_results=False
            ),
        ).reduce(start=pw.this._pw_window_start, n=pw.reducers.count())

    assert _run(build, True) == _run(build, False), seed


def test_bail_counter_increments():
    """note_bail feeds both the process Counter (profiler snapshots) and
    the declared registry family columnar.bail.count{op=,reason=}."""
    from pathway_tpu.engine import metrics as _metrics

    before = vc.BAIL_COUNTS.get(("test-op", "test-reason"), 0)
    vc.note_bail("test-op", "test-reason")
    vc.note_bail("test-op", "test-reason")
    assert vc.BAIL_COUNTS[("test-op", "test-reason")] == before + 2
    scalars = _metrics.get_registry().scalar_metrics()
    labeled = [
        k
        for k in scalars
        if k.startswith("columnar.bail.count") and "test-op" in k
    ]
    assert labeled and scalars[labeled[0]] >= 2
    # ask for the full tally: earlier tests in the process may have
    # accumulated real bails that would push test-op out of a top-8 cut
    snap = vc.bail_snapshot(top=len(vc.BAIL_COUNTS))
    assert any(
        b["op"] == "test-op" and b["reason"] == "test-reason" for b in snap
    )


def test_profiler_path_attribution():
    """Profiler snapshots tag operators columnar / row / mixed."""
    from pathway_tpu.engine.profiler import EpochProfiler, render_snapshot

    class _Node:
        def __init__(self, nid, name, vec, row):
            self.id = nid
            self.name = name
            self.step_seconds = 0.5
            self.rows_in = 10
            self.rows_out = 10
            self.inputs = []
            self.vec_batches = vec
            self.row_batches = row

    class _Scope:
        nodes = [
            _Node(0, "groupby", 3, 0),
            _Node(1, "join", 0, 2),
            _Node(2, "buffer", 1, 1),
            _Node(3, "output", 0, 0),
        ]
        epochs_run = 1

    prof = EpochProfiler(enabled=True, sample_every=1, top_n=10)
    snap = prof.sample(_Scope(), 1)
    paths = {op["name"]: op["path"] for op in snap["operators"]}
    assert paths["groupby"] == "columnar"
    assert paths["join"] == "row"
    assert paths["buffer"] == "mixed"
    assert paths["output"] is None
    rendered = render_snapshot(snap)
    assert "[columnar]" in rendered and "[mixed]" in rendered
    assert "bails" in snap


# ---------------------------------------------------------------------------
# native kernel parity (PR 14: split_deltas / freeze_scan / route_deltas)
# ---------------------------------------------------------------------------


def _native():
    from pathway_tpu import native

    mod = native.get()
    if mod is None or not hasattr(mod, "route_deltas"):
        pytest.skip("native core unavailable")
    return mod


@pytest.mark.parametrize("seed", range(3))
def test_native_split_deltas_parity(seed):
    import numpy as np

    nat = _native()
    rng = random.Random(seed)
    deltas = [
        (i, (rng.randrange(100), "s" + str(i % 3)), rng.choice([1, -1]))
        for i in range(50)
    ]
    mask = np.asarray([rng.random() < 0.5 for _ in deltas], np.uint8)
    kept, dropped = nat.split_deltas(deltas, mask)
    exp_kept = [d for d, m in zip(deltas, mask.tolist()) if m]
    exp_dropped = [d for d, m in zip(deltas, mask.tolist()) if not m]
    assert kept == exp_kept and dropped == exp_dropped
    with pytest.raises(ValueError, match="mask"):
        nat.split_deltas(deltas, np.ones(3, np.uint8))


@pytest.mark.parametrize("seed", range(4))
def test_native_freeze_scan_parity(seed):
    import numpy as np

    nat = _native()
    rng = random.Random(seed)
    is_int = seed % 2 == 0

    def mk(n):
        if is_int:
            return np.asarray(
                [rng.randrange(-100, 100) for _ in range(n)], np.int64
            )
        return np.asarray([rng.uniform(-100, 100) for _ in range(n)], np.float64)

    for wm0 in (None, 0 if is_int else 0.0):
        t = mk(60)
        thr = mk(60)
        kind = "q" if is_int else "d"
        mask, wm = nat.freeze_scan(kind, t, thr, wm0)
        # python reference — the FreezeNode row-path scan
        ref_wm = wm0
        ref_mask = bytearray(len(t))
        for i in range(len(t)):
            tv, thv = t[i].item(), thr[i].item()
            if ref_wm is not None and thv <= ref_wm:
                continue
            if ref_wm is None or tv > ref_wm:
                ref_wm = tv
            ref_mask[i] = 1
        assert bytes(mask) == bytes(ref_mask)
        assert wm == ref_wm and type(wm) is type(ref_wm)


@pytest.mark.parametrize("hash_none", [0, 1])
@pytest.mark.parametrize("n_dest", [2, 3, 7])
def test_native_route_deltas_parity(n_dest, hash_none):
    from pathway_tpu.engine.types import ERROR, hash_values, shard_to_worker

    nat = _native()
    rng = random.Random(n_dest * 10 + hash_none)
    deltas = []
    for i in range(120):
        k = rng.choice(
            [rng.randrange(50), "s" + str(rng.randrange(5)), None, True, 2**70]
        )
        if rng.random() < 0.05:
            k = ERROR
        deltas.append((rng.getrandbits(127), (k, i), rng.choice([1, -1])))
    out = nat.route_deltas(deltas, (0,), n_dest, hash_none)
    assert len(out) == n_dest
    exp = [[] for _ in range(n_dest)]
    for key, row, diff in deltas:
        v = row[0]
        if not hash_none and (v is None or v is ERROR):
            rk = key
        else:
            try:
                rk = hash_values((v,))
            except Exception:
                rk = key
        exp[shard_to_worker(rk, n_dest)].append((key, row, diff))
    assert out == exp


def test_native_route_deltas_matches_join_route():
    """End-to-end parity with JoinNode._route_jk + owner_of: the exchange
    fast path must agree with the per-row Python loop it replaces."""
    from pathway_tpu.engine.dataflow import JoinNode
    from pathway_tpu.engine.types import hash_values, shard_to_worker

    nat = _native()
    rng = random.Random(7)
    deltas = [
        (
            rng.getrandbits(127),
            (rng.randrange(10), None if rng.random() < 0.2 else "k%d" % (i % 7)),
            1,
        )
        for i in range(200)
    ]

    def key_fn(key, row):
        vals = (row[1],)
        if any(v is None for v in vals):
            return None
        return vals

    n = 4
    exp = [[] for _ in range(n)]
    for key, row, diff in deltas:
        rk = JoinNode._route_jk(key_fn, key, row)
        exp[shard_to_worker(rk, n)].append((key, row, diff))
    out = nat.route_deltas(deltas, (1,), n, 0)
    assert out == exp
